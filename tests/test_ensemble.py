"""Blockwise ensemble tests (ref: tests for dask_ml/ensemble/_blockwise.py)."""

import numpy as np
import pytest
from sklearn.linear_model import LinearRegression as SkLinear
from sklearn.linear_model import LogisticRegression as SkLogistic

from dask_ml_tpu.datasets import make_classification, make_regression
from dask_ml_tpu.ensemble import (
    BlockwiseVotingClassifier,
    BlockwiseVotingRegressor,
)
from dask_ml_tpu.parallel import ShardedArray, default_mesh


def test_voting_classifier_hard():
    X, y = make_classification(n_samples=400, n_features=8, random_state=0)
    clf = BlockwiseVotingClassifier(SkLogistic(max_iter=300)).fit(X, y)
    assert len(clf.estimators_) == default_mesh().devices.size
    pred = clf.predict(X)
    assert isinstance(pred, ShardedArray)
    assert clf.score(X, y) > 0.7
    with pytest.raises(AttributeError, match="soft"):
        clf.predict_proba(X)


def test_voting_classifier_soft():
    X, y = make_classification(n_samples=400, n_features=8, random_state=0)
    clf = BlockwiseVotingClassifier(
        SkLogistic(max_iter=300), voting="soft"
    ).fit(X, y)
    proba = clf.predict_proba(X).to_numpy()
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, atol=1e-6)
    assert clf.score(X, y) > 0.7


def test_voting_classifier_bad_voting():
    X, y = make_classification(n_samples=100, n_features=4, random_state=0)
    with pytest.raises(ValueError, match="voting"):
        BlockwiseVotingClassifier(SkLogistic(), voting="mean").fit(X, y)


def test_voting_regressor():
    X, y = make_regression(n_samples=400, n_features=8, random_state=0)
    reg = BlockwiseVotingRegressor(SkLinear()).fit(X, y)
    assert len(reg.estimators_) == default_mesh().devices.size
    assert reg.score(X, y) > 0.8
