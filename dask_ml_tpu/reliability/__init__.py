"""Reliability / chaos plane (ISSUE 11): deterministic fault injection
plus the runtime hardening that makes each injected fault survivable.

- ``faults``      — :class:`FaultPlan` / :func:`fault_point`: named
  host-side fault sites armed by ``config.fault_plan`` (off by default,
  zero overhead and jaxpr-byte-identical when off), firing by seeded
  invocation-index schedules so chaos runs replay exactly;
- ``stream_ckpt`` — fingerprint-keyed pass-granular checkpoint/resume
  for streamed GLM/SGD/Incremental fits (the Lloyd contract
  generalized; ``config.stream_checkpoint_path`` / ``_every``);
- ``supervisor``  — :class:`ReplicaSupervisor`: rebuilds dead fleet
  replicas off the serving path, warmed before they rejoin routing,
  under a bounded restart budget (``config.serving_supervise``).

The hardening the sites exercise lives where the faults strike:
bounded-backoff staging retry + the non-finite block policy in
``parallel/streaming.py``, the pass-barrier deadline
(:class:`~dask_ml_tpu.parallel.distributed.StreamSyncTimeout`) in
``parallel/distributed.py``, and the serving worker guard in
``serving/_server.py``.
"""

from __future__ import annotations

from .faults import (
    FAULT_KINDS,
    FAULT_SITES,
    FaultInjected,
    FaultPlan,
    InjectedCrash,
    InjectedIOError,
    NonFiniteBlock,
    StreamIORetriesExhausted,
    active_plan,
    fault_point,
    reset_plans,
)
from .stream_ckpt import StreamCheckpoint, stream_checkpoint
from .supervisor import ReplicaSupervisor

__all__ = [
    "FAULT_KINDS",
    "FAULT_SITES",
    "FaultInjected",
    "FaultPlan",
    "InjectedCrash",
    "InjectedIOError",
    "NonFiniteBlock",
    "ReplicaSupervisor",
    "StreamCheckpoint",
    "StreamIORetriesExhausted",
    "active_plan",
    "fault_point",
    "reset_plans",
    "status_block",
    "stream_checkpoint",
]

# counters the /status reliability block and the report CLI's
# reliability table surface (flat names; /metrics renders them with the
# _total suffix)
RELIABILITY_COUNTERS = (
    "faults_injected",
    "stream_retries",
    "stream_quarantined_blocks",
    "stream_checkpoint_saves",
    "stream_resumes",
    "serving_replica_restarts",
    "serving_replica_failures",
)


def status_block() -> dict:
    """The /status ``reliability`` block: the armed plan (if any) with
    per-site invocation/fired counts, plus the hardening counters —
    what an operator needs to answer "is chaos armed, and what has it
    hit so far"."""
    from ..config import get_config
    from ..observability._counters import counters_snapshot

    snap = counters_snapshot()
    counters = {
        k: v for k, v in snap.items()
        if k in RELIABILITY_COUNTERS or k.startswith("faults_injected_")
    }
    spec = get_config().fault_plan
    plan = active_plan() if spec else None
    return {
        "fault_plan": spec or None,
        "sites": plan.snapshot() if plan is not None else {},
        "counters": counters,
    }
