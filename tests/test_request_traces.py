"""Request trace plane (ISSUE 16): per-request lifecycle tracing
through the serving pipeline, tail sampling, exemplar histograms, the
/traces surface, reroute/shed/fault tagging, and the traffic
capture/replay round-trip.

The load-bearing assertions: stage stamps telescope exactly (the sum of
stage-pair durations IS complete - admit), a hammered traced server
pays ZERO post-warmup XLA compiles across a mid-run hot-swap, every
non-ok outcome is tail-sampled regardless of the slowest-p fraction,
and with ``obs_trace_sample=0`` no trace object is ever allocated
(the jaxpr-identity half of the contract lives in
``test_observability.py``)."""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from dask_ml_tpu import config, observability as obs
from dask_ml_tpu.observability import _requests as rtrace
from dask_ml_tpu.serving import (
    BucketLadder,
    FleetServer,
    ModelServer,
    RequestTimeout,
    ServerClosed,
    ServerOverloaded,
    SloShed,
)


@pytest.fixture(scope="module")
def logreg():
    """Two same-shape fitted models (the hot-swap pair) + host data."""
    from dask_ml_tpu.datasets import make_classification
    from dask_ml_tpu.linear_model import LogisticRegression

    X, y = make_classification(
        n_samples=600, n_features=12, n_informative=6, random_state=0
    )
    X2, y2 = make_classification(
        n_samples=600, n_features=12, n_informative=6, random_state=7
    )
    a = LogisticRegression(solver="lbfgs", max_iter=30).fit(X, y)
    b = LogisticRegression(solver="lbfgs", max_iter=30).fit(X2, y2)
    return a, b, X.to_numpy().astype(np.float32)


@pytest.fixture(autouse=True)
def _clean_plane():
    rtrace.traces_reset()
    yield
    rtrace.traces_reset()


def _ladder():
    return BucketLadder(8, 128, 2.0)


def _stage_order(trace):
    st = trace["stages"]
    return [st[s] for s in rtrace.STAGES if s in st]


# -- zero overhead when off --------------------------------------------------

def test_trace_plane_off_by_default(logreg):
    """obs_trace_sample=0 (the default): no trace object is ever
    allocated — the queue entries keep trace=None end to end and the
    plane's counters never move."""
    clf, _, Xh = logreg
    seen = []
    orig = rtrace.new_trace

    with ModelServer(clf, ladder=_ladder()) as srv:
        assert srv._trace_on is False
        srv.warmup()
        futs = [srv.submit(Xh[: 1 + i]) for i in range(4)]
        for f in futs:
            f.result(10)
    assert seen == [] and orig is rtrace.new_trace
    d = obs.traces_data()
    assert d["counts"] == {"started": 0, "completed": 0, "sampled": 0,
                           "captured": 0}
    assert d["traces"] == [] and d["stage_histograms"] == {}


# -- stage stamps ------------------------------------------------------------

def test_stages_telescope_and_tags(logreg):
    clf, _, Xh = logreg
    with config.set(obs_trace_sample=1.0):
        with ModelServer(clf, ladder=_ladder(),
                         methods=("predict", "predict_proba")) as srv:
            assert srv._trace_on is True
            srv.warmup()
            futs = [srv.submit(Xh[: 1 + (3 * i) % 40]) for i in range(8)]
            futs += [srv.submit(Xh[:5], method="predict_proba")
                     for _ in range(2)]
            for f in futs:
                f.result(10)
    d = obs.traces_data()
    assert d["counts"]["started"] == 10
    assert d["counts"]["completed"] == 10
    assert d["counts"]["sampled"] == 10        # p=1.0 keeps everything
    assert len(d["traces"]) == 10
    for t in d["traces"]:
        # every lifecycle stage stamped, in order
        assert set(t["stages"]) == set(rtrace.STAGES)
        order = _stage_order(t)
        assert order == sorted(order)
        # telescoping: stage-pair durations sum to the e2e exactly
        assert sum(t["durations"].values()) == pytest.approx(
            t["e2e_s"], abs=5e-5)
        assert t["outcome"] == "ok"
        # bucket is the COALESCED batch's ladder slot
        assert t["bucket"] in (8, 16, 32, 64, 128)
        assert t["version"] == 0
        assert t["method"] in ("predict", "predict_proba")
        assert t["trace_id"] >> 24 > 0         # pid-prefixed
    # per-stage exemplar histograms saw every completion
    hists = d["stage_histograms"]
    for name in ("queue_wait", "pack", "execute", "demux"):
        assert hists[name]["count"] == 10
        ex = [e for e in hists[name]["exemplars"] if e is not None]
        assert ex and all(isinstance(e, int) for e in ex)
        ids = {t["trace_id"] for t in d["traces"]}
        assert set(ex) <= ids


# -- the hammer: ragged concurrent traffic + mid-run hot-swap ---------------

def test_hammer_traced_hotswap_zero_compiles(logreg):
    """Concurrent ragged traffic with tracing ON, a hot-swap mid-run:
    every completed request's stages stay monotonic and sum to within
    5% (plus a small absolute floor) of its client-measured e2e, and
    the warmed server pays ZERO new XLA compiles."""
    clf, clf2, Xh = logreg
    rng = np.random.RandomState(3)
    sizes = [int(rng.randint(1, 100)) for _ in range(120)]
    measured = {}        # trace snapshot can't see client e2e: key by
    #                      (method, n_rows, order) is ambiguous — match
    #                      by trace_id via a submit-side registry
    lock = threading.Lock()
    errs = []

    with config.set(obs_trace_sample=1.0, obs_trace_keep=512):
        with ModelServer(clf, ladder=_ladder(), batch_window_ms=1.0) \
                as srv:
            srv.warmup()
            before = obs.counters_snapshot().get("recompiles", 0)

            def client(my_sizes):
                try:
                    for n in my_sizes:
                        t0 = time.perf_counter()
                        f = srv.submit(Xh[:n])
                        f.result(30)
                        e2e = time.perf_counter() - t0
                        with lock:
                            measured[len(measured)] = e2e
                except Exception as exc:   # pragma: no cover
                    errs.append(exc)

            threads = [threading.Thread(target=client,
                                        args=(sizes[c::4],))
                       for c in range(4)]
            for th in threads:
                th.start()
            # mid-run zero-recompile hot-swap (same shapes): wait for
            # real completions under v0, swap, let the rest drain
            deadline = time.monotonic() + 30
            while (obs.traces_data()["counts"]["completed"] < 10
                   and time.monotonic() < deadline):
                time.sleep(0.002)
            srv.swap_model(clf2)
            for th in threads:
                th.join(60)
            # a few post-swap requests pin v1 traffic deterministically
            for _ in range(3):
                srv.submit(Xh[:16]).result(30)
            after = obs.counters_snapshot().get("recompiles", 0)
    assert errs == []
    assert after - before == 0, \
        f"traced hammer paid {after - before} recompiles"
    d = obs.traces_data()
    assert d["counts"]["completed"] == len(sizes) + 3
    assert d["counts"]["sampled"] == len(sizes) + 3    # p=1.0
    client_e2e = sorted(measured.values())
    for t in d["traces"]:
        order = _stage_order(t)
        assert order == sorted(order), t
        dsum = sum(t["durations"].values())
        assert dsum == pytest.approx(t["e2e_s"], abs=1e-4)
        # the trace e2e is bounded by SOME client measurement: admit is
        # stamped at Request construction inside submit, complete right
        # after set_result — the client adds only call overhead, so the
        # slowest client e2e bounds every trace e2e (5% + 5ms slack)
        assert t["e2e_s"] <= client_e2e[-1] * 1.05 + 5e-3
    # both model versions served under tracing
    versions = {t["version"] for t in d["traces"]}
    assert versions == {0, 1}


# -- tail sampler ------------------------------------------------------------

def test_non_ok_outcomes_always_sampled(logreg):
    """Sheds, timeouts and (injected) errors are kept by the tail
    sampler at ANY sample fraction — here a tiny p that would almost
    never keep an ordinary completion."""
    clf, _, Xh = logreg
    with config.set(obs_trace_sample=0.01):
        # shed: a paused 2-deep queue overflows on the third submit
        with ModelServer(clf, ladder=_ladder(), max_queue=2) as srv:
            srv.warmup()
            srv.pause()
            held = [srv.submit(Xh[:4]) for _ in range(2)]
            with pytest.raises(ServerOverloaded):
                srv.submit(Xh[:4])
            srv.resume()
            for f in held:
                f.result(10)
        # timeout: requests expire while the worker is parked
        with ModelServer(clf, ladder=_ladder(), timeout_ms=30) as srv:
            srv.warmup()
            srv.pause()
            f = srv.submit(Xh[:4])
            time.sleep(0.1)
            srv.resume()
            with pytest.raises(RequestTimeout):
                f.result(10)
        # error: the chaos plane fails one batch inside _execute (the
        # worker re-applies the creator's config, so the plan armed
        # here is live on the worker thread)
        from dask_ml_tpu.reliability import faults
        from dask_ml_tpu.serving import ServingError

        faults.reset_plans()
        with config.set(fault_plan="serving_execute:crash@0"):
            with ModelServer(clf, ladder=_ladder()) as srv:
                srv.warmup()
                f = srv.submit(Xh[:4])
                with pytest.raises(ServingError):
                    f.result(10)
        faults.reset_plans()
    d = obs.traces_data()
    by_outcome = {}
    for t in d["traces"]:
        by_outcome.setdefault(t["outcome"], []).append(t)
    assert by_outcome.get("shed"), d["counts"]
    assert by_outcome.get("timeout"), d["counts"]
    assert by_outcome.get("error"), d["counts"]
    # the injected fault's batch is tagged
    assert all(t.get("fault_injected") for t in by_outcome["error"])
    # a shed trace never reached the worker: no queue_pop stamp
    assert "queue_pop" not in by_outcome["shed"][0]["stages"]


def test_tail_sampler_keeps_slowest_fraction(logreg):
    """At a small p most ordinary completions fold into the histograms
    WITHOUT being kept; the sampled set is the slow tail."""
    clf, _, Xh = logreg
    n = 150
    with config.set(obs_trace_sample=0.05):
        with ModelServer(clf, ladder=_ladder()) as srv:
            srv.warmup()
            # sequential round-trips: burst submits would queue behind
            # each other, every completion a new e2e max → all kept
            for _ in range(n):
                srv.submit(Xh[:4]).result(10)
    d = obs.traces_data()
    assert d["counts"]["completed"] == n
    # every completion folded into the per-stage histograms...
    assert d["stage_histograms"]["queue_wait"]["count"] == n
    # ...but only a fraction was kept with a full breakdown
    assert d["counts"]["sampled"] < n // 2


def test_trace_keep_bounds_retention(logreg):
    clf, _, Xh = logreg
    with config.set(obs_trace_sample=1.0, obs_trace_keep=5):
        with ModelServer(clf, ladder=_ladder()) as srv:
            srv.warmup()
            futs = [srv.submit(Xh[:4]) for _ in range(20)]
            for f in futs:
                f.result(10)
    d = obs.traces_data()
    assert d["counts"]["sampled"] == 20
    assert len(d["traces"]) == 5          # deque bound: newest kept


# -- fleet: reroute + SLO shed tagging --------------------------------------

def test_reroute_tags_surviving_replica_trace(logreg):
    """A replica dying between the health check and the put reroutes
    the request; the survivor's trace records the corpse's id."""
    clf, _, Xh = logreg
    with config.set(obs_trace_sample=1.0):
        fleet = FleetServer(clf, name="clf", replicas=2,
                            ladder=_ladder()).warmup()
        with fleet:
            # replica 0 refuses with the typed death error while still
            # ranking healthy (the race fleet.submit's failover covers)
            def _dead(X, method="predict"):
                raise ServerClosed("replica 0 died")

            fleet.replicas[0].submit = _dead
            y = fleet.predict(Xh[:6])
            assert y.shape == (6,)
    d = obs.traces_data()
    done = [t for t in d["traces"] if t["outcome"] == "ok"]
    assert done
    t = done[-1]
    assert t["rerouted_from"] == 0
    assert t["replica"] == 1
    assert set(t["stages"]) == set(rtrace.STAGES)


def test_slo_shed_trace_kept_and_tagged(logreg):
    clf, _, Xh = logreg
    with config.set(obs_trace_sample=1.0, serving_slo_ms=30.0):
        fleet = FleetServer(clf, name="clf", replicas=1,
                            ladder=_ladder(), batch_window_ms=1.0,
                            timeout_ms=0).warmup()
        with fleet:
            for _ in range(10):
                fleet.predict(Xh[:64])
            from dask_ml_tpu.serving._batching import Request

            for r in fleet.replicas:
                r.pause()
                for _ in range(13):
                    r._exec.observe("predict", 128, 0.5)
                for _ in range(8):
                    r._queue.put(Request(Xh[:100], "predict"))
            with pytest.raises(SloShed):
                fleet.submit(Xh[:100])
            for r in fleet.replicas:
                r._queue.drain_all()
                r.resume()
    d = obs.traces_data()
    shed = [t for t in d["traces"] if t["outcome"] == "slo_shed"]
    assert len(shed) == 1
    assert shed[0]["slo_shed"] is True
    assert shed[0]["n_rows"] == 100


# -- /traces endpoint --------------------------------------------------------

def test_traces_endpoint_serves_sampler_state(logreg):
    from dask_ml_tpu.observability import live

    clf, _, Xh = logreg
    live.stop_telemetry()
    with config.set(obs_trace_sample=1.0):
        with obs.TelemetryServer(port=0) as tsrv:
            with ModelServer(clf, ladder=_ladder()) as srv:
                srv.warmup()
                futs = [srv.submit(Xh[:4]) for _ in range(3)]
                for f in futs:
                    f.result(10)
            with urllib.request.urlopen(
                    f"{tsrv.url}/traces", timeout=5.0) as resp:
                assert resp.status == 200
                assert "json" in resp.headers["Content-Type"]
                body = json.loads(resp.read())
    assert body["counts"]["completed"] == 3
    assert len(body["traces"]) == 3
    assert body["stage_histograms"]["queue_wait"]["count"] == 3
    assert "exemplars" in body["stage_histograms"]["queue_wait"]
    live.metrics_reset()


def test_queue_wait_histogram_mirrors_to_live_registry(logreg):
    """The satellite family: serving_queue_wait_seconds{method,bucket}
    lands in the live registry (scraped on /metrics) while a telemetry
    server is up — fed from the trace timestamps."""
    from dask_ml_tpu.observability import live

    clf, _, Xh = logreg
    live.stop_telemetry()
    live.metrics_reset()
    with config.set(obs_trace_sample=1.0):
        with obs.TelemetryServer(port=0):
            with ModelServer(clf, ladder=_ladder()) as srv:
                srv.warmup()
                futs = [srv.submit(Xh[:4]) for _ in range(3)]
                for f in futs:
                    f.result(10)
            fams = {name for (name, labels) in
                    live.histograms_snapshot()}
            assert "serving_queue_wait_seconds" in fams
            assert "serving_pack_seconds" in fams
            assert "serving_demux_seconds" in fams
            key = [(name, labels) for (name, labels)
                   in live.histograms_snapshot()
                   if name == "serving_queue_wait_seconds"][0]
            assert dict(key[1])["method"] == "predict"
            text = live.render_prometheus()
            assert "serving_queue_wait_seconds_bucket" in text
            # exemplars stay OFF the text exposition (grammar-clean)
            assert "# {" not in text and "trace_id" not in text
    live.metrics_reset()


# -- capture / replay round-trip --------------------------------------------

def test_capture_roundtrip_replay(tmp_path, logreg):
    clf, _, Xh = logreg
    trace_dir = str(tmp_path / "t")
    with config.set(obs_trace_sample=1.0, trace_dir=trace_dir):
        with ModelServer(clf, ladder=_ladder(),
                         methods=("predict", "predict_proba")) as srv:
            srv.warmup()
            futs = [srv.submit(Xh[: 1 + i % 9]) for i in range(10)]
            futs += [srv.submit(Xh[:3], method="predict_proba")
                     for _ in range(4)]
            for f in futs:
                f.result(10)
    path = tmp_path / "t" / "trace.jsonl"
    records = obs.load_capture(str(path))
    assert len(records) == 14
    assert obs.traces_data()["counts"]["captured"] == 14
    # replay reproduces the recorded (method, rows) mix in order
    replayed = []
    out = obs.replay(records, lambda m, n: replayed.append((m, n)),
                     speed=1000.0)
    assert replayed == [(r["method"], r["n_rows"]) for r in records]
    assert out["requests"] == 14
    assert out["rows"] == sum(r["n_rows"] for r in records)
    assert out["by_method"] == {"predict": 10, "predict_proba": 4}
    assert out["rate_rps"] > 0
    # the sampled req_trace records rode the same file
    sampled = [json.loads(line) for line in open(path)
               if '"req_trace"' in line]
    assert len(sampled) == 14              # p=1.0
    assert all(s["stages"]["admit"] == 0.0 for s in sampled)


def test_replay_empty_and_corrupt_lines(tmp_path):
    p = tmp_path / "cap.jsonl"
    p.write_text('{"req_capture": true, "trace_id": 1, "method": "m", '
                 '"n_rows": 2, "t_unix": 5.0}\n'
                 'not json\n'
                 '{"other": true}\n')
    records = obs.load_capture(str(p))
    assert len(records) == 1
    out = obs.replay(records, lambda m, n: None)
    assert out["requests"] == 1 and out["rows"] == 2
    assert obs.replay([], lambda m, n: None)["requests"] == 0


# -- report CLI --------------------------------------------------------------

def _fake_trace(tid, pid, e2e, t_unix, method="predict", **tags):
    stages = {"admit": 0.0, "queue_pop": e2e * 0.4, "pack": e2e * 0.5,
              "dispatch": e2e * 0.55, "execute_done": e2e * 0.8,
              "demux": e2e * 0.9, "complete": e2e}
    durs = {"queue_wait": e2e * 0.4, "pack": e2e * 0.1,
            "dispatch": e2e * 0.05, "execute": e2e * 0.25,
            "demux": e2e * 0.1, "resolve": e2e * 0.1}
    return {"req_trace": True, "trace_id": tid, "pid": pid,
            "method": method, "n_rows": 4, "t_unix": t_unix,
            "e2e_s": e2e, "outcome": tags.pop("outcome", "ok"),
            "stages": stages, "durations": durs,
            "threads": {"admit": "MainThread", "worker": "w"}, **tags}


def test_report_slowest_table_and_merge():
    from dask_ml_tpu.observability.report import (
        build_report, merge_records, report_data, summarize_traces,
    )

    pid_a, pid_b = 11, 22
    a = [{"req_capture": True, "trace_id": (pid_a << 24) | i,
          "pid": pid_a, "method": "predict", "n_rows": 4,
          "t_unix": 100.0 + i} for i in range(3)]
    a += [_fake_trace((pid_a << 24) | 1, pid_a, 0.050, 100.0),
          _fake_trace((pid_a << 24) | 2, pid_a, 0.010, 101.0)]
    b = [_fake_trace((pid_b << 24) | 1, pid_b, 0.030, 100.5,
                     rerouted_from=0, replica=1)]
    merged = merge_records([a, b])
    tr = summarize_traces(merged)
    assert tr["sampled"] == 3
    # slowest first, across both processes' files
    assert [t["e2e_s"] for t in tr["traces"]] == [0.050, 0.030, 0.010]
    assert tr["capture"]["requests"] == 3
    assert tr["capture"]["by_method"] == {"predict": 3}
    data = report_data(merged)
    assert data["traces"]["sampled"] == 3
    json.dumps(data)                      # --json stays serializable
    text = build_report(merged, slowest=2)
    assert "traces (2 slowest of 3 sampled" in text
    assert "rerouted_from=0" in text
    assert "traffic capture" in text
    # --slowest 1 trims the table
    assert "traces (1 slowest of 3 sampled" in build_report(
        merged, slowest=1)


def test_report_cli_slowest_flag(tmp_path, capsys):
    from dask_ml_tpu.observability.report import main

    p = tmp_path / "tr.jsonl"
    with open(p, "w") as fh:
        for i in range(4):
            fh.write(json.dumps(_fake_trace(
                (9 << 24) | i, 9, 0.01 * (i + 1), 100.0 + i)) + "\n")
    assert main([str(p), "--slowest", "2"]) == 0
    out = capsys.readouterr().out
    assert "traces (2 slowest of 4 sampled" in out
    assert main([str(p), "--json"]) == 0
    data = json.loads(capsys.readouterr().out)
    assert data["traces"]["sampled"] == 4
    assert main([str(p), "--slowest"]) == 2        # missing count
    assert main([str(p), "--slowest", "x"]) == 2   # non-integer


def test_perfetto_flow_events_cross_threads(tmp_path):
    from dask_ml_tpu.observability.export import to_chrome_trace

    recs = [_fake_trace((11 << 24) | 1, 11, 0.040, 100.0),
            _fake_trace((22 << 24) | 1, 22, 0.020, 100.5)]
    trace = to_chrome_trace(recs)
    ev = trace["traceEvents"]
    slices = [e for e in ev if e.get("cat") == "request"
              and e["ph"] == "X"]
    flows = [e for e in ev if e.get("ph") in ("s", "f")]
    # 6 stage-pair slices per trace; one s + one f flow pair each
    assert len(slices) == 12
    assert len(flows) == 4
    starts = [e for e in flows if e["ph"] == "s"]
    ends = [e for e in flows if e["ph"] == "f"]
    assert {e["id"] for e in starts} == {(11 << 24) | 1, (22 << 24) | 1}
    assert {e["id"] for e in ends} == {e["id"] for e in starts}
    # the flow hops lanes: start on the admit thread, finish on worker
    for s in starts:
        f = [e for e in ends if e["id"] == s["id"]][0]
        assert s["tid"] != f["tid"]
    # two processes' MainThreads land on distinct lanes
    lanes = {e["args"]["name"] for e in ev if e["ph"] == "M"}
    assert "pid11.MainThread" in lanes and "pid22.MainThread" in lanes
    # queue_wait slice lanes on the admission thread
    qw = [e for e in slices if e["name"].endswith("queue_wait")]
    assert qw and all(e["dur"] > 0 for e in qw)


def test_traces_reset_isolates(logreg):
    clf, _, Xh = logreg
    with config.set(obs_trace_sample=1.0):
        with ModelServer(clf, ladder=_ladder()) as srv:
            srv.warmup()
            srv.submit(Xh[:4]).result(10)
    assert obs.traces_data()["counts"]["completed"] == 1
    obs.traces_reset()
    d = obs.traces_data()
    assert d["counts"]["completed"] == 0
    assert d["traces"] == [] and d["stage_histograms"] == {}
