"""Pairwise distances/kernels vs sklearn (the §4 parity contract)."""

import numpy as np
import pytest
import sklearn.metrics.pairwise as skpw

import dask_ml_tpu.metrics as dm


@pytest.fixture(scope="module")
def xy():
    rng = np.random.RandomState(0)
    return (rng.randn(60, 7).astype(np.float64),
            rng.randn(9, 7).astype(np.float64))


@pytest.mark.parametrize("metric", [
    "euclidean", "sqeuclidean", "manhattan", "cityblock", "l1", "l2",
    "cosine",
])
def test_pairwise_distances_parity(xy, metric):
    x, y = xy
    got = np.asarray(dm.pairwise_distances(x, y, metric=metric))
    sk_metric = metric
    want = skpw.pairwise_distances(x, y, metric=sk_metric)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_pairwise_distances_callable(xy):
    x, y = xy
    got = np.asarray(dm.pairwise_distances(x, y, metric=dm.euclidean_distances))
    want = skpw.euclidean_distances(x, y)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_pairwise_distances_bad_metric(xy):
    with pytest.raises(ValueError, match="unsupported metric"):
        dm.pairwise_distances(*xy, metric="nope")


@pytest.mark.parametrize("kernel,kwargs", [
    ("linear", {}),
    ("rbf", {"gamma": 0.3}),
    ("polynomial", {"degree": 2, "gamma": 0.5, "coef0": 1.0}),
    ("sigmoid", {"gamma": 0.1, "coef0": 0.5}),
])
def test_pairwise_kernels_parity(xy, kernel, kwargs):
    x, y = xy
    got = np.asarray(dm.pairwise_kernels(x, y, metric=kernel, **kwargs))
    want = skpw.pairwise_kernels(x, y, metric=kernel, **kwargs)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_argmin_min_parity(xy):
    x, y = xy
    labels, mins = dm.pairwise_distances_argmin_min(x, y)
    want_l, want_m = skpw.pairwise_distances_argmin_min(x, y)
    np.testing.assert_array_equal(np.asarray(labels), want_l)
    np.testing.assert_allclose(np.asarray(mins), want_m, rtol=1e-5, atol=1e-6)
