"""Execution plans (ISSUE 15): declare -> warm -> fit -> serve ->
scrape the plans table.

The ``dask_ml_tpu/plans`` subsystem is the ONE layer every compiled
specialization goes through — shape ladders (serving rows / sparse nnz
/ cohort slots), ``ProgramPlan.build()`` (cache keying, track_program
registration, donation wiring, compile_cache_dir arming) and the
process-wide ``WarmupRegistry``. This example walks the whole loop on
the newest plan client, GaussianNB:

1. DECLARE — the estimator's streamed fit is one ProgramPlan (a
   donated-carry per-block class-stats reducer) plus a GeometricLadder
   for block heights; that declaration lives in
   ``dask_ml_tpu/naive_bayes.py`` and is ~a page of code.
2. FIT (streamed) — ``Incremental(GaussianNB())`` streams host blocks
   through the plan-built program; pass 2 pays zero new XLA compiles.
3. SERVE (warmed) — ``ModelServer(fitted).warmup()`` walks the serving
   ladder through the WarmupRegistry; ragged traffic then mints zero
   compiles, and a second server over the same shapes warms for free
   (``plan_cache_hits``).
4. SCRAPE — the plans table (also on ``/status`` and in the report
   CLI) names which ladder rung minted each specialization.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from dask_ml_tpu import observability as obs
from dask_ml_tpu import plans
from dask_ml_tpu.naive_bayes import GaussianNB
from dask_ml_tpu.serving import BucketLadder, ModelServer
from dask_ml_tpu.wrappers import Incremental

n = int(os.environ.get("DASK_ML_TPU_EXAMPLE_N", 50_000))
d = 16
rng = np.random.RandomState(0)
half = n // 2
X = np.concatenate([rng.randn(half, d) + 1.5,
                    rng.randn(n - half, d) - 1.5]).astype(np.float32)
y = np.concatenate([np.zeros(half), np.ones(n - half)])
p = rng.permutation(n)
X, y = X[p], y[p]

# -- 2. streamed fit through the plan-built stats program -------------------
inc = Incremental(GaussianNB(), shuffle_blocks=True, random_state=0)
inc.fit(X, y)                                  # pass 1 mints the rungs
before = obs.counters_snapshot().get("recompiles", 0)
inc.partial_fit(X, y)                          # pass 2: warm caches only
after = obs.counters_snapshot().get("recompiles", 0)
nb = inc.estimator_
print(f"streamed GaussianNB: acc={nb.score(X, y):.3f}, "
      f"pass-2 recompiles={after - before} (contract: 0)")
assert after - before == 0

# -- 3. warmed serving through the WarmupRegistry ---------------------------
ladder = BucketLadder(8, 256, 2.0)
server = ModelServer(nb, methods=("predict", "predict_proba"),
                     ladder=ladder, batch_window_ms=1.0, timeout_ms=0)
server.warmup()
before = obs.counters_snapshot().get("recompiles", 0)
with server:
    r = np.random.RandomState(1)
    for _ in range(30):
        k = r.randint(1, 256)
        i = r.randint(0, n - k)
        server.predict(X[i:i + k])
after = obs.counters_snapshot().get("recompiles", 0)
print(f"served ragged traffic: recompiles={after - before} "
      "(contract: 0)")
assert after - before == 0

# a SECOND server over the same-shaped model: the plan build cache
# returns the same entry points, so its warmup is pure registry hits
before_hits = obs.counters_snapshot().get("plan_cache_hits", 0)
ModelServer(nb, methods=("predict", "predict_proba"),
            ladder=ladder).warmup()
hits = obs.counters_snapshot().get("plan_cache_hits", 0) - before_hits
print(f"second server warmup: {hits} plan cache hits, 0 fresh compiles")

# -- 4. the plans table -----------------------------------------------------
print("\nplans (program / plan / ladder / rungs / warmups / hits):")
for row in plans.plans_snapshot():
    if row["warmups"] or row["warm_hits"] or "nb" in row["program"]:
        print(f"  {row['program']:<38} {row['plan']:<12} "
              f"{row['ladder']:<14} {row['rungs']:<14} "
              f"{row['warmups']:>3} {row['warm_hits']:>3}")
