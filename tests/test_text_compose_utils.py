"""Text / compose / utils tests (ref: tests for feature_extraction,
compose, utils in the reference)."""

import numpy as np
import pandas as pd
import pytest
import scipy.sparse as sp

from dask_ml_tpu.compose import ColumnTransformer, make_column_transformer
from dask_ml_tpu.feature_extraction.text import (
    CountVectorizer,
    FeatureHasher,
    HashingVectorizer,
    to_sharded_dense,
)
from dask_ml_tpu.parallel import ShardedArray
from dask_ml_tpu.preprocessing import StandardScaler
from dask_ml_tpu.utils import (
    assert_estimator_equal,
    copy_learned_attributes,
    handle_zeros_in_scale,
)

DOCS = [
    "the quick brown fox", "jumps over the lazy dog",
    "the dog barks", "quick quick fox",
] * 5


def test_hashing_vectorizer_matches_sklearn():
    import sklearn.feature_extraction.text as sktext

    ours = HashingVectorizer(n_features=256).transform(DOCS)
    ref = sktext.HashingVectorizer(n_features=256).transform(DOCS)
    assert sp.issparse(ours)
    np.testing.assert_allclose(ours.toarray(), ref.toarray())


def test_hashing_to_sharded_dense():
    csr = HashingVectorizer(n_features=64).transform(DOCS)
    dense = to_sharded_dense(csr)
    assert isinstance(dense, ShardedArray)
    assert dense.shape == (len(DOCS), 64)


def test_feature_hasher():
    from sklearn.feature_extraction import FeatureHasher as SkFH

    data = [{"a": 1, "b": 2}, {"a": 3, "c": 1}] * 4
    ours = FeatureHasher(n_features=32).transform(data)
    ref = SkFH(n_features=32).transform(data)
    np.testing.assert_allclose(ours.toarray(), ref.toarray())


def test_count_vectorizer_auto_vocabulary():
    import sklearn.feature_extraction.text as sktext

    ours = CountVectorizer()
    got = ours.fit_transform(DOCS)
    ref = sktext.CountVectorizer().fit(DOCS)
    assert ours.vocabulary_ == ref.vocabulary_
    np.testing.assert_array_equal(
        got.toarray(), ref.transform(DOCS).toarray()
    )


def test_count_vectorizer_given_vocabulary():
    vocab = ["dog", "fox", "quick"]
    got = CountVectorizer(vocabulary=vocab).transform(DOCS)
    assert got.shape == (len(DOCS), 3)
    assert list(
        CountVectorizer(vocabulary=vocab).fit(DOCS).get_feature_names_out()
    ) == vocab


def test_column_transformer_sharded():
    X = np.random.RandomState(0).lognormal(size=(60, 4))
    sx = ShardedArray.from_array(X)
    ct = ColumnTransformer([
        ("scale", StandardScaler(), [0, 1]),
        ("keep", "passthrough", [2]),
    ])
    out = ct.fit_transform(sx)
    assert isinstance(out, ShardedArray)
    assert out.shape == (60, 3)
    got = out.to_numpy()
    np.testing.assert_allclose(got[:, 2], X[:, 2], rtol=1e-5)
    assert abs(got[:, 0].mean()) < 1e-4  # scaled
    # transform path matches fit_transform
    np.testing.assert_allclose(
        ct.transform(sx).to_numpy(), got, atol=1e-5
    )


def test_column_transformer_dataframe_remainder():
    df = pd.DataFrame({
        "a": [1.0, 2.0, 3.0, 4.0], "b": [2.0, 4.0, 6.0, 8.0],
        "c": [0.0, 1.0, 0.0, 1.0],
    })
    ct = ColumnTransformer(
        [("scale", StandardScaler(), ["a", "b"])], remainder="passthrough"
    )
    out = ct.fit_transform(df)
    assert out.shape == (4, 3)
    np.testing.assert_allclose(np.asarray(out)[:, 2], df["c"])
    assert "scale" in ct.named_transformers_


def test_make_column_transformer():
    ct = make_column_transformer(
        (StandardScaler(), [0]), ("passthrough", [1]),
        preserve_dataframe=False,
    )
    names = [name for name, _, _ in ct.transformers]
    assert len(names) == 2 and len(set(names)) == 2
    assert ct.preserve_dataframe is False


def test_column_transformer_bad_remainder():
    with pytest.raises(ValueError, match="remainder"):
        ColumnTransformer([], remainder="mean").fit_transform(
            np.zeros((3, 2))
        )


def test_assert_estimator_equal():
    from dask_ml_tpu.preprocessing import StandardScaler as Ours

    X = np.random.RandomState(0).randn(50, 3)
    a = Ours().fit(X)
    b = Ours().fit(X)
    assert_estimator_equal(a, b, rtol=1e-6)
    import sklearn.preprocessing as skpre

    c = skpre.StandardScaler().fit(X)
    assert_estimator_equal(a, c, exclude={"n_samples_seen_"},
                           rtol=1e-4, atol=1e-5)


def test_copy_learned_attributes():
    from sklearn.linear_model import LogisticRegression

    src = LogisticRegression(max_iter=200).fit(
        np.random.RandomState(0).randn(40, 3), np.arange(40) % 2
    )
    dst = LogisticRegression()
    copy_learned_attributes(src, dst)
    assert hasattr(dst, "coef_")


def test_handle_zeros_in_scale():
    np.testing.assert_array_equal(
        handle_zeros_in_scale(np.array([0.0, 2.0])), [1.0, 2.0]
    )


def test_check_chunks():
    import pytest

    from dask_ml_tpu.utils import check_chunks

    # integer = NUMBER of blocks (reference semantics), 100-row floor
    assert check_chunks(1000, 16, chunks=5) == (200, 16)
    assert check_chunks(1000, 16, chunks=50) == (100, 16)
    assert check_chunks(1000, 16, chunks=(50, 16)) == (50, 16)
    rows, cols = check_chunks(1000, 16)
    assert cols == 16 and 1 <= rows <= 1000
    with pytest.raises(AssertionError):
        check_chunks(1000, 16, chunks=(50, 8))  # column-chunking unsupported
    with pytest.raises(AssertionError):
        check_chunks(1000, 16, chunks="bad")


def test_add_intercept():
    from dask_ml_tpu.linear_model import add_intercept
    from dask_ml_tpu.parallel.sharded import ShardedArray

    X = ShardedArray.from_array(np.random.RandomState(0).randn(37, 4))
    out = add_intercept(X).to_numpy()
    assert out.shape == (37, 5)
    np.testing.assert_array_equal(out[:, 4], 1.0)
    np.testing.assert_allclose(out[:, :4], X.to_numpy(), rtol=1e-6)

    arr = add_intercept(np.zeros((3, 2)))
    np.testing.assert_array_equal(arr[:, 2], 1.0)


def test_count_vectorizer_df_semantics_match_sklearn(monkeypatch):
    """min_df/max_df/max_features apply to the MERGED vocabulary with
    global document frequencies (VERDICT r2 missing #6) — parity with
    sklearn on the concatenated corpus, across multiple blocks."""
    import sklearn.feature_extraction.text as sktext

    import dask_ml_tpu.feature_extraction.text as text_mod

    corpus = [
        "apple banana cherry", "apple banana", "apple cherry date",
        "banana cherry", "apple", "date elderberry fig",
        "fig grape apple", "banana grape", "cherry date fig grape",
        "apple banana cherry date", "elderberry", "grape fig",
    ]
    orig_blocks = text_mod._blocks
    monkeypatch.setattr(
        text_mod, "_blocks",
        lambda docs, block_size=3: orig_blocks(docs, 3),
    )
    for kw in (
        dict(min_df=2),
        dict(min_df=3),
        dict(max_df=0.5),
        dict(min_df=2, max_df=0.7),
        dict(max_features=4),
        dict(min_df=2, max_features=3),
        dict(min_df=0.1, max_df=0.9),
    ):
        ours = text_mod.CountVectorizer(**kw).fit(corpus)
        sk = sktext.CountVectorizer(**kw).fit(corpus)
        assert ours.vocabulary_ == sk.vocabulary_, kw
        # removed terms are exposed (sklearn 1.x dropped stop_words_)
        assert ours.stop_words_.isdisjoint(ours.vocabulary_), kw
        Xo = ours.transform(corpus)
        Xs = sk.transform(corpus)
        assert (Xo != Xs).nnz == 0, kw


def test_count_vectorizer_all_pruned_raises():
    from dask_ml_tpu.feature_extraction.text import CountVectorizer

    # threshold inversion: sklearn-parity error
    with pytest.raises(ValueError, match="max_df corresponds"):
        CountVectorizer(min_df=10).fit(["one two", "three four"])
    # every term unique and min_df=2: nothing survives pruning
    with pytest.raises(ValueError, match="no terms remain"):
        CountVectorizer(min_df=2).fit(
            ["one two", "three four", "five six", "seven eight"]
        )


@pytest.mark.slow
def test_sketched_quantiles_parity(monkeypatch):
    """Histogram-sketch quantiles within tolerance of exact (VERDICT r2
    missing #7). 3e5 rows exercises the identical kernel the >1M auto
    path runs (the sketch is row-count-oblivious); the dispatch boundary
    itself is tested by lowering the threshold."""
    from dask_ml_tpu.parallel import as_sharded
    from dask_ml_tpu.preprocessing import data as pdata
    from dask_ml_tpu.preprocessing.data import _masked_quantiles

    rng = np.random.RandomState(0)
    n = 300_000
    X = np.stack([
        rng.randn(n),
        rng.exponential(2.0, n),
        rng.uniform(-5, 5, n),
    ], axis=1).astype(np.float32)
    Xs = as_sharded(X)
    qs = [0.25, 0.5, 0.75]
    exact = np.asarray(_masked_quantiles(Xs, qs, sketch=False))
    sketch = np.asarray(_masked_quantiles(Xs, qs, sketch=True))
    # error bound: one bin width = (max-min)/4096 per column
    bin_w = (X.max(axis=0) - X.min(axis=0)) / 4096
    assert np.all(np.abs(sketch - exact) <= bin_w[None, :] + 1e-6)
    # auto dispatch flips from exact to sketch above the threshold
    monkeypatch.setattr(pdata, "_SKETCH_THRESHOLD", n)
    auto_at = np.asarray(_masked_quantiles(Xs, qs))  # n == threshold: exact
    np.testing.assert_allclose(auto_at, exact, atol=1e-6)
    monkeypatch.setattr(pdata, "_SKETCH_THRESHOLD", n - 1)
    auto_above = np.asarray(_masked_quantiles(Xs, qs))  # n > threshold
    np.testing.assert_allclose(auto_above, sketch, atol=1e-6)


def test_robust_scaler_sketch_matches_exact_at_scale():
    from dask_ml_tpu.parallel import as_sharded
    from dask_ml_tpu.preprocessing import RobustScaler

    rng = np.random.RandomState(1)
    X = (rng.randn(1_200_000, 2) * [2.0, 0.5] + [1.0, -3.0]).astype(
        np.float32
    )
    scaler = RobustScaler().fit(as_sharded(X))  # auto: sketch path
    import numpy as _np

    center_exact = _np.median(X, axis=0)
    scale_exact = (_np.percentile(X, 75, axis=0)
                   - _np.percentile(X, 25, axis=0))
    np.testing.assert_allclose(scaler.center_, center_exact, atol=2e-2)
    np.testing.assert_allclose(scaler.scale_, scale_exact, rtol=2e-2)
