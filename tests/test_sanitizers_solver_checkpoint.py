"""NaN/Inf sanitizers + solver-iteration checkpointing (VERDICT r2 #7,
SURVEY.md §5 rows 2-4): poisoned input must raise, not silently
"converge"; a killed long-running solve resumes mid-solve."""

import os

import numpy as np
import pytest

from dask_ml_tpu.parallel import as_sharded


@pytest.fixture(scope="module")
def poisoned():
    rng = np.random.RandomState(0)
    X = rng.randn(320, 6).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    Xbad = X.copy()
    Xbad[7, 3] = np.nan
    return X, Xbad, y


@pytest.mark.parametrize("solver", [
    "lbfgs", "newton", "gradient_descent", "admm",
])
def test_poisoned_input_raises_resident(poisoned, solver):
    from dask_ml_tpu.linear_model import LogisticRegression

    _, Xbad, y = poisoned
    with pytest.raises(FloatingPointError, match="non-finite"):
        LogisticRegression(solver=solver, max_iter=10).fit(
            as_sharded(Xbad), as_sharded(y)
        )


def test_poisoned_input_raises_streamed(poisoned, tmp_path):
    from dask_ml_tpu import config
    from dask_ml_tpu.linear_model import LogisticRegression

    _, Xbad, y = poisoned
    with config.set(stream_block_rows=100):
        with pytest.raises(FloatingPointError, match="non-finite"):
            LogisticRegression(solver="lbfgs", max_iter=10).fit(Xbad, y)


def test_poisoned_input_raises_kmeans(poisoned):
    from dask_ml_tpu.cluster import KMeans

    X, Xbad, _ = poisoned
    init = X[:3]
    with pytest.raises(FloatingPointError, match="non-finite"):
        KMeans(n_clusters=3, init=init, max_iter=10).fit(as_sharded(Xbad))


def test_clean_input_unaffected(poisoned):
    from dask_ml_tpu.linear_model import LogisticRegression

    X, _, y = poisoned
    clf = LogisticRegression(solver="lbfgs", max_iter=30).fit(
        as_sharded(X), as_sharded(y)
    )
    assert np.isfinite(clf.coef_).all()


def test_lbfgs_kill_and_resume(tmp_path, poisoned, monkeypatch):
    """Every-k-iteration checkpointing: a solve killed mid-run resumes
    from the last saved chunk and reaches the same answer as an
    uninterrupted solve."""
    from dask_ml_tpu.linear_model import LogisticRegression
    from dask_ml_tpu.utils import checkpoint as ckpt

    X, _, y = poisoned
    Xs, ys = as_sharded(X), as_sharded(y)
    path = str(tmp_path / "solver_ckpt")
    kw = dict(solver="lbfgs", max_iter=40, tol=0.0,
              solver_kwargs={"checkpoint_path": path,
                             "checkpoint_every": 10})

    # uninterrupted reference (no checkpointing)
    ref = LogisticRegression(solver="lbfgs", max_iter=40, tol=0.0).fit(
        Xs, ys
    )

    # kill after the 2nd chunk save (i.e. at iteration 20)
    real_save = ckpt.save_pytree
    saves = {"n": 0}

    def dying_save(p, tree, force=True):
        real_save(p, tree, force=force)
        saves["n"] += 1
        if saves["n"] == 2:
            raise KeyboardInterrupt("injected kill")

    monkeypatch.setattr(ckpt, "save_pytree", dying_save)
    with pytest.raises(KeyboardInterrupt):
        LogisticRegression(**kw).fit(Xs, ys)
    monkeypatch.setattr(ckpt, "save_pytree", real_save)
    assert os.path.exists(path)

    # resume: picks up at iteration 20, not zero
    clf = LogisticRegression(**kw).fit(Xs, ys)
    assert clf.solver_info_["resumed_from"] == 20
    assert clf.solver_info_["n_iter"] == 40
    np.testing.assert_allclose(clf.coef_, ref.coef_, rtol=1e-5, atol=1e-7)
    # a COMPLETED solve clears its checkpoint: re-fitting with different
    # params on the same path must not return the stale beta
    assert not os.path.exists(path)
    clf_c10 = LogisticRegression(solver="lbfgs", max_iter=40, tol=0.0,
                                 C=10.0, solver_kwargs=kw["solver_kwargs"]
                                 ).fit(Xs, ys)
    assert clf_c10.solver_info_["resumed_from"] == 0
    assert not np.allclose(clf_c10.coef_, clf.coef_)

    # fresh path: no resume
    kw2 = dict(kw)
    kw2["solver_kwargs"] = {"checkpoint_path": str(tmp_path / "other"),
                            "checkpoint_every": 10}
    clf2 = LogisticRegression(**kw2).fit(Xs, ys)
    assert clf2.solver_info_["resumed_from"] == 0
