"""Fit a device-resident LogisticRegression on sharded data.

Run anywhere: on a TPU VM this uses every chip of the slice; on a CPU
host set XLA_FLAGS=--xla_force_host_platform_device_count=8 to simulate
an 8-device mesh. (Equivalent dask-ml code needs a distributed cluster;
here the mesh IS the cluster.)
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


import numpy as np

N = int(os.environ.get("DASK_ML_TPU_EXAMPLE_N", 200_000))

from dask_ml_tpu import datasets
from dask_ml_tpu.linear_model import LogisticRegression
from dask_ml_tpu.model_selection import train_test_split
from dask_ml_tpu.preprocessing import StandardScaler

X, y = datasets.make_classification(
    n_samples=N, n_features=64, random_state=0
)  # a ShardedArray pair, row-sharded over every device
Xtr, Xte, ytr, yte = train_test_split(X, y, test_size=0.2, random_state=0)

scaler = StandardScaler()
Xtr = scaler.fit_transform(Xtr)
Xte = scaler.transform(Xte)

clf = LogisticRegression(solver="lbfgs", max_iter=100)
clf.fit(Xtr, ytr)  # one compiled while_loop; zero per-iteration host syncs
print("n_iter:", clf.n_iter_, "test accuracy:", clf.score(Xte, yte))
