"""Observability: structured JSONL metrics + profiling hooks.

Reference: dask's diagnostics/dashboard (SURVEY.md §5 tracing row —
``dask/diagnostics``, bokeh task stream). TPU equivalent: per-step JSONL
metric lines (loss, inertia, samples/s/chip) a controller can tail, and
thin wrappers over ``jax.profiler`` for TensorBoard/Perfetto traces.
"""

from __future__ import annotations

import contextlib
import json
import sys
import time

import jax


class MetricsLogger:
    """Append one JSON object per step to a file (or stdout)."""

    def __init__(self, path=None, extra=None):
        self.path = path
        self.extra = extra or {}
        self._fh = None
        self.t0 = time.time()

    def _handle(self):
        if self.path is None:
            return sys.stdout
        if self._fh is None:
            self._fh = open(self.path, "a")
        return self._fh

    def log(self, step=None, **metrics):
        rec = {"time": round(time.time() - self.t0, 6), **self.extra}
        if step is not None:
            rec["step"] = step
        rec.update(metrics)
        h = self._handle()
        h.write(json.dumps(rec) + "\n")
        h.flush()

    def close(self):
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


@contextlib.contextmanager
def fit_logger(component, **extra):
    """Per-fit MetricsLogger bound to ``config.metrics_path``; yields None
    (a no-op for callers that guard on it) when the knob is unset. This is
    how estimators/solvers wire per-step JSONL without every call site
    touching config (BASELINE.md measurement protocol)."""
    from ..config import get_config

    path = get_config().metrics_path
    if not path:
        yield None
        return
    logger = MetricsLogger(path, extra={"component": component, **extra})
    try:
        yield logger
    finally:
        logger.close()


def timed(fn, *args, **kwargs):
    """(result, seconds) with a block_until_ready barrier — the honest way
    to time an async-dispatch jax program."""
    t0 = time.perf_counter()
    out = fn(*args, **kwargs)
    out = jax.block_until_ready(out)
    return out, time.perf_counter() - t0


@contextlib.contextmanager
def profile_trace(log_dir):
    """jax.profiler trace context (view in TensorBoard / Perfetto)."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def start_profiler_server(port=9999):
    """Live-capture profiler endpoint (SURVEY.md §5:
    jax.profiler.start_server)."""
    return jax.profiler.start_server(port)
