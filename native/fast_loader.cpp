// Host-side data loader: multithreaded CSV -> float32 block parser.
//
// Role (SURVEY.md §2b native-code summary): the reference leans on
// NumPy/pandas C parsers inside dask tasks for ingest; the TPU build's
// one genuine native need is feeding the host->HBM streaming pipeline
// (parallel/streaming.py) faster than Python text parsing can. This
// library mmaps the file, splits it at newline boundaries into per-thread
// byte ranges, and parses rows into a caller-provided float32 buffer.
//
// Exposed via ctypes (no pybind11 in the image); compiled on demand by
// dask_ml_tpu/io/native.py with g++ -O3 -shared -fPIC.

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

struct Mapped {
    const char* data = nullptr;
    size_t size = 0;
    int fd = -1;
    bool ok() const { return data != nullptr; }
};

Mapped map_file(const char* path) {
    Mapped m;
    m.fd = open(path, O_RDONLY);
    if (m.fd < 0) return m;
    struct stat st;
    if (fstat(m.fd, &st) != 0 || st.st_size == 0) {
        close(m.fd);
        m.fd = -1;
        return m;
    }
    void* p = mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, m.fd, 0);
    if (p == MAP_FAILED) {
        close(m.fd);
        m.fd = -1;
        return m;
    }
    madvise(p, st.st_size, MADV_SEQUENTIAL);
    m.data = static_cast<const char*>(p);
    m.size = st.st_size;
    return m;
}

void unmap(Mapped& m) {
    if (m.data) munmap(const_cast<char*>(m.data), m.size);
    if (m.fd >= 0) close(m.fd);
}

// Count '\n'-terminated rows in [begin, end).
int64_t count_rows(const char* begin, const char* end) {
    int64_t n = 0;
    for (const char* p = begin; p < end; ++p)
        if (*p == '\n') ++n;
    if (end > begin && end[-1] != '\n') ++n;  // unterminated last row
    return n;
}

// Parse rows from [begin, end) into out (row-major, n_cols floats/row).
// Returns rows parsed, or -1 on malformed row (wrong column count).
int64_t parse_range(const char* begin, const char* end, int64_t n_cols,
                    float* out) {
    const char* p = begin;
    int64_t row = 0;
    while (p < end) {
        const char* line_end = static_cast<const char*>(
            memchr(p, '\n', end - p));
        if (!line_end) line_end = end;
        if (line_end > p) {  // skip empty lines
            int64_t col = 0;
            const char* q = p;
            while (q < line_end && col < n_cols) {
                char* next = nullptr;
                out[row * n_cols + col] = strtof(q, &next);
                if (next == q) return -1;  // not a number
                col++;
                q = next;
                while (q < line_end && (*q == ',' || *q == ' ' ||
                                        *q == '\t' || *q == '\r'))
                    ++q;
            }
            if (col != n_cols) return -1;
            ++row;
        }
        p = line_end + 1;
    }
    return row;
}

}  // namespace

extern "C" {

// Scan the file: returns row count, writes column count of the first row
// to *n_cols_out. Returns -1 on open failure, -2 on empty/invalid.
int64_t csv_dims(const char* path, int64_t* n_cols_out) {
    Mapped m = map_file(path);
    if (!m.ok()) return -1;
    // columns of first non-empty line = commas+1 (spaces also separate)
    const char* p = m.data;
    const char* end = m.data + m.size;
    while (p < end && *p == '\n') ++p;
    const char* line_end = static_cast<const char*>(
        memchr(p, '\n', end - p));
    if (!line_end) line_end = end;
    int64_t cols = 0;
    bool in_field = false;
    for (const char* q = p; q < line_end; ++q) {
        bool sep = (*q == ',' || *q == ' ' || *q == '\t' || *q == '\r');
        if (!sep && !in_field) { ++cols; in_field = true; }
        if (sep) in_field = false;
    }
    if (cols == 0) { unmap(m); return -2; }
    *n_cols_out = cols;
    int64_t rows = count_rows(p, end);
    unmap(m);
    return rows;
}

// Parse the whole file into out (preallocated n_rows*n_cols float32,
// row-major) using n_threads. Returns rows parsed, negative on error.
int64_t csv_parse_f32(const char* path, float* out, int64_t n_rows,
                      int64_t n_cols, int32_t n_threads) {
    Mapped m = map_file(path);
    if (!m.ok()) return -1;
    const char* begin = m.data;
    const char* end = m.data + m.size;
    if (n_threads < 1) n_threads = 1;
    if (n_threads > 64) n_threads = 64;

    // split into n_threads ranges aligned to newline boundaries
    std::vector<const char*> starts{begin};
    for (int t = 1; t < n_threads; ++t) {
        const char* guess = begin + (m.size * t) / n_threads;
        const char* nl = static_cast<const char*>(
            memchr(guess, '\n', end - guess));
        starts.push_back(nl ? nl + 1 : end);
    }
    starts.push_back(end);

    // row offsets per range (prefix counts) so threads write disjointly
    std::vector<int64_t> range_rows(n_threads);
    for (int t = 0; t < n_threads; ++t)
        range_rows[t] = count_rows(starts[t], starts[t + 1]);
    std::vector<int64_t> offsets(n_threads + 1, 0);
    for (int t = 0; t < n_threads; ++t)
        offsets[t + 1] = offsets[t] + range_rows[t];
    if (offsets[n_threads] > n_rows) {
        unmap(m);
        return -3;  // buffer too small
    }

    std::vector<int64_t> results(n_threads, 0);
    std::vector<std::thread> threads;
    for (int t = 0; t < n_threads; ++t) {
        threads.emplace_back([&, t] {
            results[t] = parse_range(starts[t], starts[t + 1], n_cols,
                                     out + offsets[t] * n_cols);
        });
    }
    for (auto& th : threads) th.join();
    unmap(m);
    int64_t total = 0;
    for (int t = 0; t < n_threads; ++t) {
        if (results[t] < 0) return -4;  // malformed row
        total += results[t];
    }
    return total;
}

}  // extern "C"
