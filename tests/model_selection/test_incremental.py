"""Adaptive search tests (ref: tests/model_selection/test_incremental.py,
test_hyperband.py, test_successive_halving.py)."""

import numpy as np
import pytest
from scipy.stats import loguniform
from sklearn.linear_model import SGDClassifier

from dask_ml_tpu.datasets import make_classification
from dask_ml_tpu.model_selection import (
    HyperbandSearchCV,
    IncrementalSearchCV,
    SuccessiveHalvingSearchCV,
)

PARAMS = {"alpha": loguniform(1e-5, 1e-1), "eta0": [0.01, 0.1, 0.5]}


def _sgd():
    return SGDClassifier(tol=None, penalty="l2", random_state=0,
                         learning_rate="constant")


@pytest.fixture(scope="module")
def data():
    return make_classification(n_samples=600, n_features=10, random_state=1)


def test_incremental_search(data):
    X, y = data
    search = IncrementalSearchCV(
        _sgd(), PARAMS, n_initial_parameters=8, max_iter=20,
        random_state=0, decay_rate=1.0,
    )
    search.fit(X, y, classes=[0.0, 1.0])
    assert 0.5 < search.best_score_ <= 1.0
    assert hasattr(search, "best_estimator_")
    assert len(search.cv_results_["params"]) == 8
    assert search.metadata_["n_models"] == 8
    # history bookkeeping
    assert all(
        {"model_id", "params", "partial_fit_calls", "score"} <= set(r)
        for r in search.history_
    )
    assert set(search.model_history_) == set(range(8))
    # decay actually dropped models: later survivors are few
    final_calls = search.cv_results_["partial_fit_calls"]
    assert final_calls.max() > final_calls.min()
    # post-fit API
    pred = search.predict(X)
    assert 0.0 <= search.score(X, y) <= 1.0
    np.testing.assert_array_equal(search.classes_, [0.0, 1.0])


def test_incremental_search_no_decay(data):
    X, y = data
    search = IncrementalSearchCV(
        _sgd(), PARAMS, n_initial_parameters=3, max_iter=5,
        decay_rate=None, random_state=0,
    )
    search.fit(X, y, classes=[0.0, 1.0])
    calls = search.cv_results_["partial_fit_calls"]
    assert (calls == 5).all()  # nobody dropped, everyone hits max_iter


def test_successive_halving(data):
    X, y = data
    search = SuccessiveHalvingSearchCV(
        _sgd(), PARAMS, n_initial_parameters=9, n_initial_iter=2,
        max_iter=30, aggressiveness=3, random_state=0,
    )
    search.fit(X, y, classes=[0.0, 1.0])
    calls = search.cv_results_["partial_fit_calls"]
    # 9 models at rung0 (2 calls); 3 promoted to 6; 1 promoted to 18
    assert (calls >= 2).all()
    assert sorted(calls)[-1] >= 18
    assert (calls == 2).sum() == 6  # two-thirds stopped at rung 0
    assert search.best_score_ > 0.5


def test_successive_halving_requires_n_initial_iter(data):
    X, y = data
    with pytest.raises(ValueError, match="n_initial_iter"):
        SuccessiveHalvingSearchCV(_sgd(), PARAMS).fit(X, y)


def test_hyperband(data):
    X, y = data
    search = HyperbandSearchCV(
        _sgd(), PARAMS, max_iter=9, aggressiveness=3, random_state=0,
    )
    meta_planned = search.metadata()
    search.fit(X, y, classes=[0.0, 1.0])
    assert search.best_score_ > 0.5
    assert search.metadata_["n_models"] == meta_planned["n_models"]
    brackets = {b["bracket"] for b in search.metadata_["brackets"]}
    assert brackets == {0, 1, 2}
    assert {r["bracket"] for r in search.history_} == {0, 1, 2}
    # cv_results_ merged across brackets with global ranks
    n = len(search.cv_results_["params"])
    assert n == search.metadata_["n_models"]
    assert search.cv_results_["rank_test_score"].min() == 1
    pred = search.predict(X)
    assert 0.0 <= search.score(X, y) <= 1.0


def test_hyperband_patience(data):
    X, y = data
    search = HyperbandSearchCV(
        _sgd(), PARAMS, max_iter=9, aggressiveness=3, random_state=0,
        patience=2, tol=1e-3,
    )
    search.fit(X, y, classes=[0.0, 1.0])
    assert search.best_score_ > 0.5


def test_inverse_decay_alias(data):
    """InverseDecaySearchCV is the explicit-name alias of the decaying
    IncrementalSearchCV (later dask-ml versions export both)."""
    from dask_ml_tpu.model_selection import (
        IncrementalSearchCV, InverseDecaySearchCV,
    )

    assert issubclass(InverseDecaySearchCV, IncrementalSearchCV)
    X, y = data
    s = InverseDecaySearchCV(
        SGDClassifier(random_state=0),
        {"alpha": [1e-4, 1e-3]}, n_initial_parameters="grid",
        decay_rate=1.0, max_iter=4, random_state=0,
    )
    s.fit(X, y, classes=[0.0, 1.0])
    assert s.best_score_ > 0.5
    assert len(s.cv_results_["params"]) == 2


@pytest.mark.slow
def test_device_solo_trials_run_on_submeshes():
    """Heterogeneous device candidates (multiclass SGD has no batch key)
    advance CONCURRENTLY on disjoint submeshes instead of serializing on
    one mesh (VERDICT r3 weak #3) — same placement rule as grid search."""
    from dask_ml_tpu.models.sgd import SGDClassifier as TpuSGD

    X, y = make_classification(n_samples=600, n_features=10, n_classes=3,
                               n_informative=6, random_state=2)
    search = IncrementalSearchCV(
        TpuSGD(random_state=0), {"alpha": [1e-5, 1e-4, 1e-3, 1e-2]},
        n_initial_parameters="grid", decay_rate=None, max_iter=4,
        random_state=0,
    )
    search.fit(X, y, classes=[0.0, 1.0, 2.0])
    recs = [r for r in search.history_ if r["executor"] == "submesh"]
    assert recs, "no trial took the submesh placement path"
    # concurrency proof: within one adaptive round, submesh trials ran on
    # more than one thread
    by_calls = {}
    for r in recs:
        by_calls.setdefault(r["partial_fit_calls"], set()).add(r["thread"])
    assert any(len(t) > 1 for t in by_calls.values())
    # and the search still converges to a sane result
    assert 0.4 < search.best_score_ <= 1.0
    assert search.best_estimator_.coef_.shape == (3, 10)
