"""Drift verify gate (ISSUE 7): a SUBPROCESS fit + serve with an
injected covariate shift must light up the quality plane end to end.

The child streams an SGD fit (attaching a per-feature training
profile), fronts it with a 1-replica FleetServer, and drives three
traffic phases:

1. CONTROL — requests drawn from the training distribution: the
   train-vs-serve drift score must stay BELOW the alert threshold
   (in-distribution traffic must not page anyone);
2. HOT SWAP — a second version publishes mid-run: the shadow canary
   scores the recent-traffic sample against BOTH versions through the
   warmed entry points (zero new XLA compiles), publishing per-version
   canary series;
3. SHIFT — requests mean-shifted by +3σ: the new version's
   ``drift_score`` must cross the threshold and ``drift_alerts_total``
   must increment.

The parent scrapes ``/metrics`` while the child lingers and asserts the
gauges/counters actually EXPOSED: >= 1 ``drift_score`` series over the
threshold, ``drift_alerts_total`` >= 1, and canary series for both
versions of the swap. Prints one JSON line; exit 0 = gate holds.
Run: ``python scripts/drift_smoke.py``.
"""

import json
import os
import re
import socket
import subprocess
import sys
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CHILD = r"""
import json, os, time
import numpy as np

from dask_ml_tpu import config, observability as obs
from dask_ml_tpu.models.sgd import SGDClassifier
from dask_ml_tpu.observability import drift
from dask_ml_tpu.serving import BucketLadder, FleetServer

rng = np.random.RandomState(0)
X = rng.randn(40_000, 8).astype(np.float32)
y = (X[:, 0] > 0).astype(np.float32)
y2 = (X[:, 1] > 0).astype(np.float32)   # a different concept: the
                                        # canary must see disagreement
with config.set(stream_block_rows=4096):
    a = SGDClassifier(max_iter=2, random_state=0).fit(X, y)
    b = SGDClassifier(max_iter=2, random_state=7).fit(X, y2)
assert a.training_profile_ and a.training_profile_["n_features"] == 8, \
    "streamed fit must attach a training profile"

verdict = {"ok": False}
threshold = config.get_config().obs_drift_threshold
fleet = FleetServer(a, name="clf", replicas=1,
                    ladder=BucketLadder(8, 128, 2.0),
                    batch_window_ms=0.5, timeout_ms=0).warmup()
with fleet:
    before = obs.counters_snapshot().get("recompiles", 0)
    # phase 1: control traffic from the training distribution (enough
    # requests that the worker's ~20 folds/s rate gate still samples
    # north of a thousand rows)
    for i in range(150):
        lo = (i * 60) % 30_000
        fleet.predict(X[lo:lo + 50])
    control = drift.compute()
    ctl = [r["psi"] for r in control if r["pair"] == "train_serve"]
    # phase 2: hot swap (shadow canary scores both versions)
    swapped_to = fleet.publish(b)
    # phase 3: mean-shifted traffic against the new version
    for i in range(150):
        lo = (i * 60) % 30_000
        fleet.predict(X[lo:lo + 50] + 3.0)
    shifted = drift.compute()
    sh = [r["psi"] for r in shifted
          if r["pair"] == "train_serve" and r["version"] == swapped_to]
    snap = obs.counters_snapshot()
    recompiles = snap.get("recompiles", 0) - before
    alerts = snap.get("drift_alerts", 0)
    canaries = drift.status_block()["canaries"]
    try:
        assert ctl and max(ctl) < threshold, \
            f"control drift {max(ctl) if ctl else None} >= {threshold}"
        assert sh and max(sh) > threshold, \
            f"shifted drift {max(sh) if sh else None} <= {threshold}"
        assert alerts >= 1, "no drift alert recorded"
        assert recompiles == 0, \
            f"{recompiles} post-warmup compiles (canary must be free)"
        assert canaries and canaries[0]["version_from"] == 1 \
            and canaries[0]["version_to"] == 2, canaries
        verdict.update(ok=True, control_max_psi=round(max(ctl), 4),
                       shifted_max_psi=round(max(sh), 3),
                       alerts=int(alerts), recompiles=int(recompiles),
                       canary_disagreement=canaries[0]["disagreement"])
    except AssertionError as exc:
        verdict["error"] = str(exc)
    print("DRIFT_DONE " + json.dumps(verdict), flush=True)
    # hold the exporter up so the parent's scrape cannot race the exit
    time.sleep(float(os.environ.get("DRIFT_SMOKE_LINGER", "20")))
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _get(url, timeout=2.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, resp.read().decode()


def main():
    out = {"ok": False}
    port = _free_port()
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "DASK_ML_TPU_OBS_HTTP_PORT": str(port),
           # every served row shadows + a fast monitor cadence: the
           # smoke must see the canary and the background scores
           "DASK_ML_TPU_OBS_SHADOW_FRACTION": "1.0",
           "DASK_ML_TPU_OBS_DRIFT_INTERVAL_S": "0.5"}
    child = subprocess.Popen(
        [sys.executable, "-c", CHILD], env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    base = f"http://127.0.0.1:{port}"
    deadline = time.time() + 180
    try:
        # 1) the child's own verdict (control low / shifted high /
        #    alert fired / zero compiles)
        verdict = None
        while time.time() < deadline:
            line = child.stdout.readline()
            if not line:
                break
            if line.startswith("DRIFT_DONE "):
                verdict = json.loads(line[len("DRIFT_DONE "):])
                break
        if verdict is None:
            if child.poll() is None:
                child.kill()
                child.wait(10)
            raise RuntimeError("child ended without a DRIFT_DONE line: "
                               + child.stderr.read()[-2000:])
        if not verdict.get("ok"):
            raise RuntimeError(f"drift gate failed in child: {verdict}")
        out.update(verdict)
        # 2) the quality plane is EXPOSED: drift gauges over threshold,
        #    the alert counter, and canary series for both versions
        _, text = _get(base + "/metrics")
        scores = {}
        for m in re.finditer(
                r'^dask_ml_tpu_drift_score\{([^}]*)\} (\S+)$', text,
                re.MULTILINE):
            scores[m.group(1)] = float(m.group(2))
        if not scores:
            raise RuntimeError("no drift_score series on /metrics")
        if max(scores.values()) <= 0.2:
            raise RuntimeError(
                f"no drift_score over threshold: {scores}"
            )
        m = re.search(r"^dask_ml_tpu_drift_alerts_total (\d+)", text,
                      re.MULTILINE)
        if not m or int(m.group(1)) < 1:
            raise RuntimeError("drift_alerts_total missing or zero")
        for version in ("1", "2"):
            if not re.search(
                    r'^dask_ml_tpu_canary_prediction_\w+\{[^}]*'
                    rf'version="{version}"', text, re.MULTILINE):
                raise RuntimeError(
                    f"no canary series for version {version} on /metrics"
                )
        # 3) /status carries the drift block
        _, body = _get(base + "/status")
        doc = json.loads(body)
        if not doc.get("drift", {}).get("scores"):
            raise RuntimeError("/status has no drift scores block")
        out.update(port=port, exposed_series=len(scores),
                   alerts_total=int(m.group(1)))
    except Exception as exc:
        out["ok"] = False
        out["error"] = f"{type(exc).__name__}: {exc}"
    finally:
        child.terminate()
        try:
            child.wait(10)
        except Exception:
            child.kill()
    print(json.dumps(out))
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
