"""KMeans tests (ref: tests/test_kmeans.py in the reference; sklearn is
the oracle per SURVEY.md §4)."""

import numpy as np
import pytest
from sklearn.cluster import KMeans as SkKMeans
from sklearn.metrics import adjusted_rand_score

from dask_ml_tpu.cluster import KMeans
from dask_ml_tpu.datasets import make_blobs


@pytest.fixture(scope="module")
def blobs():
    X, y = make_blobs(n_samples=500, n_features=5, centers=4, random_state=0,
                      cluster_std=0.8)
    return X, y


@pytest.mark.parametrize("init", ["k-means||", "k-means++", "random"])
def test_kmeans_recovers_blobs(blobs, init):
    X, y = blobs
    km = KMeans(n_clusters=4, init=init, random_state=0, max_iter=100).fit(X)
    assert km.cluster_centers_.shape == (4, 5)
    ari = adjusted_rand_score(y.to_numpy(), km.labels_.to_numpy())
    # random init has no restarts (n_init, as in the reference) and may hit
    # a local optimum; the smart inits must recover the blobs nearly exactly
    floor = 0.5 if init == "random" else 0.95
    assert ari > floor, f"init={init} ari={ari}"
    assert km.n_iter_ >= 1
    assert km.inertia_ > 0


def test_kmeans_inertia_close_to_sklearn(blobs):
    X, _ = blobs
    Xh = X.to_numpy()
    ours = KMeans(n_clusters=4, random_state=0, max_iter=200).fit(X)
    ref = SkKMeans(n_clusters=4, n_init=10, random_state=0).fit(Xh)
    assert ours.inertia_ <= ref.inertia_ * 1.05


def test_kmeans_explicit_init(blobs):
    X, _ = blobs
    init = X.to_numpy()[:4].copy()
    km = KMeans(n_clusters=4, init=init, max_iter=100).fit(X)
    assert km.inertia_ > 0


def test_kmeans_predict_transform_score(blobs):
    X, _ = blobs
    km = KMeans(n_clusters=4, random_state=0).fit(X)
    labels = km.predict(X)
    np.testing.assert_array_equal(labels.to_numpy(), km.labels_.to_numpy())
    d = km.transform(X).to_numpy()
    assert d.shape == (500, 4)
    np.testing.assert_array_equal(np.argmin(d, axis=1), labels.to_numpy())
    assert km.score(X) == pytest.approx(-km.inertia_, rel=1e-5)


def test_kmeans_numpy_input(blobs):
    X, _ = blobs
    km = KMeans(n_clusters=4, random_state=0).fit(X.to_numpy())
    assert km.cluster_centers_.shape == (4, 5)


def test_kmeans_errors(blobs):
    X, _ = blobs
    with pytest.raises(ValueError, match="n_clusters"):
        KMeans(n_clusters=501).fit(X)
    with pytest.raises(ValueError, match="Unknown init"):
        KMeans(init="bogus").fit(X)
    with pytest.raises(ValueError, match="init array"):
        KMeans(n_clusters=4, init=np.zeros((3, 5))).fit(X)


def test_kmeans_pallas_path_matches_xla(blobs):
    """Fused Pallas Lloyd (interpret mode on CPU) vs the XLA path."""
    X, _ = blobs
    init = X.to_numpy()[:4].copy()
    xla = KMeans(n_clusters=4, init=init, max_iter=50, use_pallas=False).fit(X)
    pls = KMeans(n_clusters=4, init=init, max_iter=50, use_pallas=True).fit(X)
    np.testing.assert_allclose(
        pls.cluster_centers_, xla.cluster_centers_, atol=1e-3
    )
    assert pls.inertia_ == pytest.approx(xla.inertia_, rel=1e-4)
    np.testing.assert_array_equal(
        pls.labels_.to_numpy(), xla.labels_.to_numpy()
    )


def test_fused_assign_update_parity():
    """Interpret-mode parity of the fused Pallas kernel (labels/mind/sums/
    counts/inertia) vs a NumPy reference, across padding and mask cases."""
    from dask_ml_tpu.ops.pallas_fused import fused_assign_update

    rng = np.random.RandomState(0)
    for n, d, k, nvalid in [(256, 8, 4, 256), (137, 7, 3, 130),
                            (1000, 13, 5, 900), (513, 3, 2, 500)]:
        x = rng.randn(n, d).astype(np.float32)
        mask = (np.arange(n) < nvalid).astype(np.float32)
        c = rng.randn(k, d).astype(np.float32)
        lab, mind, sums, counts, inertia = [
            np.asarray(v) for v in fused_assign_update(x, mask, c, interpret=True)
        ]
        # reference uses the same ||x||^2 - 2xc + ||c||^2 expansion so
        # f32 near-ties resolve identically
        d2 = (
            (x * x).sum(1)[:, None]
            - 2.0 * (x @ c.T)
            + (c * c).sum(1)[None, :]
        ).clip(min=0)
        lab_ref = d2.argmin(1)
        mind_ref = d2.min(1) * mask
        # argmin may legitimately differ on f32 near-ties (BLAS vs XLA
        # accumulation order); require the kernel's pick to be within
        # rounding noise of the row minimum instead of bit-equality
        np.testing.assert_allclose(
            d2[np.arange(n), lab], d2[np.arange(n), lab_ref],
            rtol=1e-5, atol=1e-4,
        )
        np.testing.assert_allclose(mind, mind_ref, rtol=1e-4, atol=1e-4)
        sums_ref = np.zeros((k, d), np.float32)
        np.add.at(sums_ref, lab_ref, x * mask[:, None])
        np.testing.assert_allclose(sums, sums_ref, rtol=1e-3, atol=1e-3)
        np.testing.assert_allclose(
            counts, np.bincount(lab_ref, weights=mask, minlength=k)
        )
        np.testing.assert_allclose(inertia, mind_ref.sum(), rtol=1e-4)


def test_k_means_functional(blobs):
    """Functional API parity: ref dask_ml/cluster/k_means.py::k_means."""
    from dask_ml_tpu.cluster import k_means

    X, _ = blobs
    centers, labels, inertia, n_iter = k_means(
        X, 4, init="random", random_state=0, max_iter=20, return_n_iter=True
    )
    assert centers.shape[1] == X.shape[1]
    assert centers.shape[0] == 4
    assert inertia > 0 and n_iter >= 1
    centers3 = k_means(X, 4, init="random", random_state=0, max_iter=20)
    assert len(centers3) == 3


def test_kmeans_score_is_negative_inertia(blobs):
    import sklearn.cluster as skc

    X, _ = blobs
    Xh = X.to_numpy() if hasattr(X, "to_numpy") else np.asarray(X)
    init = Xh[:4]
    ours = KMeans(n_clusters=4, init=init, max_iter=20, tol=0.0).fit(X)
    ref = skc.KMeans(n_clusters=4, init=init, n_init=1, max_iter=20,
                     tol=0.0).fit(Xh)
    # sklearn contract: score = -inertia of the assignment
    assert ours.score(X) == pytest.approx(-ours.inertia_, rel=1e-5)
    assert ours.inertia_ == pytest.approx(ref.inertia_, rel=1e-3)
