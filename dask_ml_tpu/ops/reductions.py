"""Masked global reductions over row-sharded arrays.

Reference equivalent: ``dask/array/reductions.py`` tree-reduce graphs
(SURVEY.md §2b row 1). Here each reduction is a ``jnp`` expression over the
global (padded) view; under ``jit`` with row sharding XLA lowers the sum to
a per-shard partial + ICI all-reduce — the same two-phase shape as dask's
tree-reduce, with zero scheduler/serialization overhead.

All functions take the padded data plus a row mask (1 = logical row,
0 = padding) so padding never biases a statistic.
"""

from __future__ import annotations

import jax.numpy as jnp


def masked_sum(x, mask, axis=0):
    """Sum over rows, ignoring padded rows. x: (n, ...), mask: (n,)."""
    return jnp.tensordot(mask, x, axes=(0, 0)) if axis == 0 and x.ndim > 1 else jnp.sum(
        x * _expand(mask, x), axis=axis
    )


def masked_mean(x, mask, n_rows):
    return masked_sum(x, mask) / n_rows


def masked_mean_var(x, mask, n_rows, ddof=0):
    """Numerically-stable mean/variance in one pass (two psums under jit)."""
    mean = masked_mean(x, mask, n_rows)
    centered = (x - mean) * _expand(mask, x)
    var = jnp.sum(centered * centered, axis=0) / max(n_rows - ddof, 1)
    return mean, var


def masked_min(x, mask, axis=0):
    big = jnp.asarray(jnp.inf, dtype=x.dtype)
    return jnp.min(jnp.where(_expand(mask, x) > 0, x, big), axis=axis)


def masked_max(x, mask, axis=0):
    small = jnp.asarray(-jnp.inf, dtype=x.dtype)
    return jnp.max(jnp.where(_expand(mask, x) > 0, x, small), axis=axis)


def masked_count_nonzero(x, mask):
    return jnp.tensordot(mask, (x != 0).astype(x.dtype), axes=(0, 0))


def _expand(mask, x):
    return mask.reshape(mask.shape + (1,) * (x.ndim - 1)).astype(x.dtype)
