"""Fleet verify gate (ISSUE 6): a SUBPROCESS 2-replica FleetServer under
ragged traffic with one hot-swap mid-run must

- pay ZERO XLA compiles after warmup (the swap rides
  ``CompiledBatchFn.swap_params`` — programs close over shapes, not
  values; the recompile counter is the witness);
- lose NO request across the swap (every submitted request resolves,
  and every answer matches one of the two published versions exactly);
- expose per-replica stats on ``/status`` (the fleet aggregate carries a
  ``replicas`` list; each replica labels its queue gauges);
- (ISSUE 16) after a supervised replica's worker dies with traced
  requests still queued, the supervisor's drain-and-requeue must tag
  every drained request's trace with the corpse's id
  (``rerouted_from``) — the requests complete on the rebuilt replica
  and their sampled traces prove where they came from.

The parent picks a free port, launches the child with
``DASK_ML_TPU_OBS_HTTP_PORT`` pointing at it, scrapes ``/status`` while
the fleet is up, and checks the child's own verdict line.

Prints one JSON line: {"ok": true, "requests": ..., "recompiles": 0,
"swapped_to": 2, ...}. Run: ``python scripts/fleet_smoke.py``
(exit 0 = gate holds).
"""

import json
import os
import socket
import subprocess
import sys
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CHILD = r"""
import json, os, threading, time
import numpy as np

from dask_ml_tpu import observability as obs
from dask_ml_tpu.datasets import make_classification
from dask_ml_tpu.linear_model import LogisticRegression
from dask_ml_tpu.serving import BucketLadder, FleetServer, ServingError

X, y = make_classification(n_samples=600, n_features=12,
                           n_informative=6, random_state=0)
X2, y2 = make_classification(n_samples=600, n_features=12,
                             n_informative=6, random_state=7)
a = LogisticRegression(solver="lbfgs", max_iter=30).fit(X, y)
b = LogisticRegression(solver="lbfgs", max_iter=30).fit(X2, y2)
Xh = X.to_numpy().astype(np.float32)
preds = {1: np.asarray(a.predict(Xh)), 2: np.asarray(b.predict(Xh))}

fleet = FleetServer(a, name="clf", replicas=2,
                    ladder=BucketLadder(8, 128, 2.0),
                    batch_window_ms=1.0, timeout_ms=0).warmup()
verdict = {"ok": False}
errs = []
N_CLIENTS = 3
# per-thread slots, summed after join: `sent[0] += 1` from several
# threads is a read-modify-write that can lose increments and flake
# the done == sent no-lost-request assertion
sent = [0] * N_CLIENTS
done = [0] * N_CLIENTS
stop = threading.Event()

def client(seed):
    rng = np.random.RandomState(seed)
    while not stop.is_set():
        n = rng.randint(1, 100)
        i = rng.randint(0, Xh.shape[0] - n)
        sent[seed] += 1
        try:
            got = fleet.predict(Xh[i:i + n])
        except ServingError as exc:        # a shed/timeout IS a lost
            errs.append(repr(exc))         # request for this gate
            continue
        if not any(np.array_equal(got, preds[v][i:i + n])
                   for v in (1, 2)):
            errs.append(f"mismatch at n={n} i={i}")
            continue
        done[seed] += 1

with fleet:
    before = obs.counters_snapshot().get("recompiles", 0)
    threads = [threading.Thread(target=client, args=(s,))
               for s in range(N_CLIENTS)]
    for t in threads:
        t.start()
    time.sleep(1.0)
    swapped_to = fleet.publish(b)          # ONE hot-swap mid-run
    time.sleep(1.0)
    stop.set()
    for t in threads:
        t.join()
    recompiles = obs.counters_snapshot().get("recompiles", 0) - before
    stats = fleet.stats()

    # phase 2 (ISSUE 16): a TRACED supervised fleet loses a worker with
    # requests still queued — the supervisor's requeue must tag every
    # drained request's trace with the corpse's replica id
    from dask_ml_tpu import config
    from dask_ml_tpu.serving import ServerClosed
    from dask_ml_tpu.serving._batching import fail_requests

    rerouted_ok = []
    with config.set(obs_trace_sample=1.0, serving_supervise=True,
                    serving_supervise_interval_s=0.05):
        fleet2 = FleetServer(a, name="clf2", replicas=2,
                             ladder=BucketLadder(8, 128, 2.0),
                             batch_window_ms=1.0, timeout_ms=0).warmup()
        with fleet2:
            doomed = fleet2.replicas[0]
            doomed.pause()
            futs = [doomed.submit(Xh[:16]) for _ in range(6)]

            def boom(first):
                # the in-hand request fails typed (the batch guard's
                # contract), then the worker thread dies mid-loop with
                # the remaining five still queued
                fail_requests([first],
                              ServerClosed("injected worker death"),
                              outcome="closed")
                raise RuntimeError("injected worker death")

            doomed._serve_guarded = boom
            doomed.resume()
            sacrificed = 0
            for f in futs:
                try:
                    got = f.result(120)
                    assert got.shape == (16,)
                except ServerClosed:
                    sacrificed += 1
        d = obs.traces_data()
        rerouted_ok = [t for t in d["traces"]
                       if t.get("rerouted_from") == 0
                       and t["outcome"] == "ok"]

    try:
        assert not errs, errs[:3]
        n_sent, n_done = sum(sent), sum(done)
        assert n_done == n_sent, (n_done, n_sent)
        assert n_sent >= 50, f"only {n_sent} requests — no real load"
        assert recompiles == 0, f"{recompiles} post-warmup compiles"
        assert swapped_to == 2 and stats["version"] == 2
        assert stats["swaps"] >= 1
        assert [p["version"] for p in stats["replicas"]] == [2, 2]
        assert sacrificed == 1, f"{sacrificed} sacrificed (wanted 1)"
        assert len(rerouted_ok) == 5, \
            f"{len(rerouted_ok)} drained requests traced rerouted_from=0"
        assert all(set(t["stages"]) >= {"admit", "queue_pop", "pack",
                                        "complete"}
                   for t in rerouted_ok)
        verdict.update(ok=True, requests=n_done,
                       recompiles=recompiles, swapped_to=swapped_to,
                       batches=stats["batches"],
                       rerouted_traced=len(rerouted_ok))
    except AssertionError as exc:
        verdict["error"] = str(exc)
    print("FLEET_DONE " + json.dumps(verdict), flush=True)
    # hold the fleet (and its /status registration) up so the parent's
    # scrape cannot race the exit
    time.sleep(float(os.environ.get("FLEET_SMOKE_LINGER", "20")))
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _get(url, timeout=2.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, resp.read().decode()


def main():
    out = {"ok": False}
    port = _free_port()
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "DASK_ML_TPU_OBS_HTTP_PORT": str(port)}
    child = subprocess.Popen(
        [sys.executable, "-c", CHILD], env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    base = f"http://127.0.0.1:{port}"
    deadline = time.time() + 180
    try:
        # 1) the exporter comes up with the fleet
        while True:
            try:
                status, body = _get(base + "/healthz")
                assert status == 200 and body == "ok\n"
                break
            except AssertionError:
                raise
            except Exception:
                if child.poll() is not None or time.time() > deadline:
                    if child.poll() is None:
                        child.kill()
                        child.wait(10)
                    raise RuntimeError(
                        "child exited or deadline passed before "
                        "/healthz answered: "
                        + child.stderr.read()[-2000:]
                    )
                time.sleep(0.05)
        # 2) /status must show the fleet aggregate WITH its per-replica
        #    breakdown while the fleet serves
        fleet_entry = None
        while time.time() < deadline:
            _, body = _get(base + "/status")
            doc = json.loads(body)
            fleets = [s for s in doc.get("serving", [])
                      if isinstance(s, dict) and "replicas" in s]
            if fleets and len(fleets[0]["replicas"]) == 2:
                fleet_entry = fleets[0]
                break
            if child.poll() is not None:
                raise RuntimeError(
                    "child exited before /status showed fleet stats"
                )
            time.sleep(0.05)
        if fleet_entry is None:
            raise RuntimeError("deadline: /status never showed a fleet "
                               "with 2 replicas")
        for p in fleet_entry["replicas"]:
            assert "replica" in p and "version" in p \
                and "queue_depth" in p, p
        # the /status registry block: what is serving, without
        # instrumenting application code (ISSUE 7 satellite)
        reg = doc.get("registry", {})
        assert "clf" in reg, f"/status registry block missing: {reg}"
        entry = reg["clf"]
        assert entry["current"] in entry["versions"], entry
        assert entry.get("t_publish") and entry.get("publisher"), entry
        # 3) the child's own verdict: zero compiles, zero lost requests
        verdict = None
        while time.time() < deadline:
            line = child.stdout.readline()
            if not line:
                break
            if line.startswith("FLEET_DONE "):
                verdict = json.loads(line[len("FLEET_DONE "):])
                break
        if verdict is None:
            raise RuntimeError("child ended without a FLEET_DONE line: "
                               + child.stderr.read()[-2000:])
        if not verdict.get("ok"):
            raise RuntimeError(f"fleet gate failed in child: {verdict}")
        out.update(verdict)
        out.update(port=port,
                   fleet_version=fleet_entry["version"],
                   healthy_replicas=fleet_entry["healthy_replicas"])
    except Exception as exc:
        out["ok"] = False
        out["error"] = f"{type(exc).__name__}: {exc}"
    finally:
        child.terminate()
        try:
            child.wait(10)
        except Exception:
            child.kill()
    print(json.dumps(out))
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
