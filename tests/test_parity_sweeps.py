"""Parity sweeps (VERDICT r2 #10 / SURVEY.md §4): results must be
invariant to input dtype and to the number of shards the data is chunked
over — the reference's chunk-count-invariance contract, with
``assert_estimator_equal`` as the comparator."""

import jax
import numpy as np
import pytest

from dask_ml_tpu.parallel.mesh import device_mesh, use_mesh
from dask_ml_tpu.parallel.sharded import ShardedArray
from dask_ml_tpu.utils.testing import assert_estimator_equal


@pytest.fixture(scope="module")
def data():
    rng = np.random.RandomState(0)
    X = rng.randn(400, 8) * np.linspace(3, 0.5, 8) + rng.randn(8)
    y = (X[:, 0] + 0.2 * rng.randn(400) > X[:, 0].mean()).astype(float)
    return X, y


def _fit_on_shards(make_est, X, y, n_shards):
    mesh = device_mesh(devices=jax.devices()[:n_shards])
    with use_mesh(mesh):
        Xs = ShardedArray.from_array(X.astype(np.float32), mesh=mesh)
        ys = (ShardedArray.from_array(y.astype(np.float32), mesh=mesh)
              if y is not None else None)
        est = make_est()
        est.fit(Xs) if ys is None else est.fit(Xs, ys)
    return est


_KM_INIT = np.random.RandomState(7).randn(3, 8).astype(np.float32)

SWEEP_CASES = [
    ("logreg", lambda: _import_est("LogisticRegression")(
        solver="lbfgs", max_iter=100), True,
     ["coef_", "intercept_", "classes_", "n_iter_"]),
    ("linreg", lambda: _import_est("LinearRegression")(
        solver="newton", max_iter=50), True, ["coef_", "intercept_"]),
    ("scaler", lambda: _import_est("StandardScaler")(), False,
     ["mean_", "var_", "scale_"]),
    ("pca", lambda: _import_est("PCA")(n_components=3, svd_solver="full"),
     False, ["components_", "explained_variance_", "mean_",
             "singular_values_"]),
    # fixed init: shard count must not change the Lloyd trajectory
    ("kmeans", lambda: _import_est("KMeans")(
        n_clusters=3, init=_KM_INIT, max_iter=10, tol=0.0), False,
     ["cluster_centers_", "inertia_"]),
    ("gnb", lambda: _import_est("GaussianNB")(), True,
     ["theta_", "var_", "class_prior_", "classes_"]),
    ("minmax", lambda: _import_est("MinMaxScaler")(), False,
     ["data_min_", "data_max_", "scale_", "min_"]),
    ("tsvd", lambda: _import_est("TruncatedSVD")(
        n_components=3, algorithm="tsqr"), False,
     ["components_", "singular_values_"]),
]


def _import_est(name):
    from dask_ml_tpu.cluster import KMeans
    from dask_ml_tpu.decomposition import PCA, TruncatedSVD
    from dask_ml_tpu.linear_model import LinearRegression, LogisticRegression
    from dask_ml_tpu.naive_bayes import GaussianNB
    from dask_ml_tpu.preprocessing import MinMaxScaler, StandardScaler

    return {"LogisticRegression": LogisticRegression,
            "LinearRegression": LinearRegression,
            "StandardScaler": StandardScaler, "PCA": PCA,
            "KMeans": KMeans, "GaussianNB": GaussianNB,
            "MinMaxScaler": MinMaxScaler, "TruncatedSVD": TruncatedSVD}[name]


@pytest.mark.parametrize("label,make_est,needs_y,attrs",
                         SWEEP_CASES, ids=[c[0] for c in SWEEP_CASES])
@pytest.mark.parametrize("n_shards", [1, 2, 8])
def test_chunk_count_invariance(data, label, make_est, needs_y, attrs,
                                n_shards):
    """Same data, 1 vs N shards: fitted attributes must agree — sharding
    is a layout, never a result change."""
    X, y = data
    ref = _fit_on_shards(make_est, X, y if needs_y else None, 4)
    alt = _fit_on_shards(make_est, X, y if needs_y else None, n_shards)
    assert_estimator_equal(
        alt, ref,
        exclude={"labels_", "solver_info_", "n_iter_"},
        rtol=2e-3, atol=2e-4,
    )


@pytest.mark.parametrize("dtype", [np.float64, np.float32, np.int32])
def test_dtype_invariance_glm(data, dtype):
    from dask_ml_tpu.linear_model import LogisticRegression
    from dask_ml_tpu.parallel import as_sharded

    X, y = data
    Xd = np.round(X * 100).astype(dtype) if dtype == np.int32 \
        else X.astype(dtype)
    clf = LogisticRegression(solver="lbfgs", max_iter=50).fit(
        as_sharded(Xd.astype(np.float32)), as_sharded(y)
    )
    ref = LogisticRegression(solver="lbfgs", max_iter=50).fit(
        as_sharded((Xd.astype(np.float64)).astype(np.float32)),
        as_sharded(y.astype(np.float64)),
    )
    np.testing.assert_allclose(clf.coef_, ref.coef_, rtol=1e-4, atol=1e-6)


@pytest.mark.parametrize("dtype", [np.float64, np.float32])
def test_dtype_invariance_scaler(data, dtype):
    from dask_ml_tpu.parallel import as_sharded
    from dask_ml_tpu.preprocessing import StandardScaler

    X, _ = data
    s = StandardScaler().fit(as_sharded(X.astype(dtype)))
    ref = StandardScaler().fit(as_sharded(X.astype(np.float64)))
    np.testing.assert_allclose(s.mean_, ref.mean_, rtol=1e-5)
    np.testing.assert_allclose(s.var_, ref.var_, rtol=1e-4)


# -- solver error paths ------------------------------------------------------

def test_solver_error_paths(data):
    from dask_ml_tpu.linear_model import LinearRegression, LogisticRegression
    from dask_ml_tpu.parallel import as_sharded

    X, y = data
    Xs, ys = as_sharded(X.astype(np.float32)), as_sharded(
        y.astype(np.float32))

    with pytest.raises(ValueError, match="Unknown solver"):
        LogisticRegression(solver="bogus").fit(Xs, ys)
    with pytest.raises(ValueError, match="Unknown penalty"):
        LogisticRegression(penalty="l3").fit(Xs, ys)
    for solver in ("lbfgs", "newton", "gradient_descent"):
        with pytest.raises(ValueError, match="smooth penalties only"):
            LogisticRegression(solver=solver, penalty="l1").fit(Xs, ys)
    with pytest.raises(ValueError):
        LogisticRegression().fit(Xs, as_sharded(
            y[:100].astype(np.float32)))  # length mismatch
    from dask_ml_tpu.utils.validation import check_is_fitted

    with pytest.raises(Exception):
        LinearRegression().predict(Xs)  # predict before fit


def test_underdetermined_newton_stays_finite():
    """n < d: the lstsq step keeps the Newton solve finite (min-norm)."""
    from dask_ml_tpu.linear_model import LinearRegression
    from dask_ml_tpu.parallel import as_sharded

    rng = np.random.RandomState(2)
    X = rng.randn(16, 32).astype(np.float32)
    y = (X @ rng.randn(32)).astype(np.float32)
    clf = LinearRegression(solver="newton", max_iter=10).fit(
        as_sharded(X), as_sharded(y)
    )
    assert np.isfinite(clf.coef_).all()
