"""Fleet-scope metrics federation: one registry over N fleet processes.

PR 17's :class:`~dask_ml_tpu.serving.federation.FederatedFleet` routes
requests over N fleet processes, each exposing its OWN ``/metrics`` /
``/status`` — the dask.distributed dashboard question ("what is the
fleet's p99 right now?") had no single answer. This module is that
answer: a :class:`MetricsFederator` RIDES the federation status poller
(it never starts a thread and never issues its own /status reads — the
PR 6 windowed-cursor lesson: a second reader of a consume-on-read
surface double-counts deltas, so the poller owns the one scrape per
interval and hands the cached doc to both consumers) and folds every
process's scraped telemetry into one fleet view:

- **counters sum** — process-cumulative counters add across the fleet
  (``dask_ml_tpu_fleet_serving_requests_total`` = the sum of every
  process's ``serving_requests``);
- **gauges get a ``{process=}`` label** — last-value signals (queue
  depth, replica health, fit progress) keep per-process identity;
- **histograms merge bucket-for-bucket** — every serving histogram
  shares the fixed 1-2-5 ``_hist.DEFAULT_BOUNDS`` ladder, so the fleet
  distribution is the EXACT bucket-wise sum (:meth:`Histogram.merge`)
  and fleet quantiles match pooling the raw observations to within one
  bucket width.

The merged families render on the ROUTER's own ``/metrics`` under a
``dask_ml_tpu_fleet_`` prefix (so they can never collide with — or
double-count against — the router's local families) plus a JSON block
on ``/status`` / ``/status/fleet``, via the provider hook the live
exporter exposes (``live.register_fleet_provider``). Dead processes'
series are DROPPED on the next ingest, never latched: each ingest
replaces the whole per-process doc set, so a killed process's gauges
vanish from the next scrape instead of freezing at their last value.

Fleet SLO burn-rate: with ``config.serving_slo_ms`` set, each process
counts ``serving_slo_violations``; the federator reads the fleet-wide
violation fraction per ingest window against the
:data:`SLO_BURN_BUDGET` error budget (the classic 1% — 99% of requests
inside the SLO). A window burning faster than budget (rate > 1) LATCHES
an alert: the alert ring survives the burn subsiding, because the
operator who looks an hour later must still see that it happened.

Zero-overhead contract: ``config.obs_fleet_federate`` off (the
default) builds no federator, registers no provider, and leaves the
router's exposition byte-identical; on, scraping stays pure host dicts
— no jax import, no XLA compile, no device sync anywhere here.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from ._hist import Histogram

__all__ = ["MetricsFederator", "SLO_BURN_BUDGET"]

# fleet error budget: the violation fraction at which burn rate reads
# 1.0 — the classic 99%-of-requests-inside-SLO target. A knob would be
# ceremony until a second budget exists; the constant is the contract.
SLO_BURN_BUDGET = 0.01

# alerts kept after they fire (latched: subsiding burn never clears
# them — only a fresh process / explicit reset does)
_ALERT_KEEP = 8


def _numeric(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


class MetricsFederator:
    """The fleet registry: ingests per-process ``/status`` docs (cached
    by the federation poller — ONE scrape per process per interval) and
    renders the merged fleet families.

    ``ingest(snapshots)`` takes ``[(process_id, doc_or_None), ...]``
    where ``doc`` is the process's full /status JSON (None = the
    process is dead this interval; its series drop immediately). The
    live exporter calls :meth:`render_lines` (Prometheus text lines
    appended to the router's /metrics) and :meth:`fleet_block` (the
    ``/status/fleet`` JSON) through the provider registration.
    """

    def __init__(self, name="model", slo_ms=0.0, min_interval_s=0.0,
                 budget=SLO_BURN_BUDGET):
        self.name = str(name)
        self._slo_ms = float(slo_ms)
        self._min_interval = float(min_interval_s)
        self._budget = float(budget)
        self._lock = threading.Lock()
        self._docs: dict[str, dict] = {}
        self._t_ingest = 0.0            # monotonic, throttle clock
        self._t_unix = None             # wall clock of last ingest
        self._scrape_s = None
        self._prev = None               # (violations, requests) totals
        self._burn = 0.0
        self._alerts: deque = deque(maxlen=_ALERT_KEEP)

    # -- ingest (rides the federation poller) -----------------------------
    def ingest(self, snapshots, scrape_s=None) -> bool:
        """Fold one poll interval's cached docs into the fleet view.
        Returns False when throttled by ``config.obs_fleet_poll_s`` —
        dead processes still drop immediately on a throttled tick (a
        stale latched series is exactly the failure mode this plane
        exists to kill)."""
        now = time.monotonic()
        with self._lock:
            if self._min_interval > 0 and self._t_ingest \
                    and now - self._t_ingest < self._min_interval:
                for pid, doc in snapshots:
                    if doc is None:
                        self._docs.pop(str(pid), None)
                return False
            self._t_ingest = now
            self._t_unix = time.time()
            if scrape_s is not None:
                self._scrape_s = float(scrape_s)
            # full replacement, not update: a process absent from this
            # interval's snapshot list (retired endpoint) drops too
            self._docs = {str(pid): doc for pid, doc in snapshots
                          if doc is not None}
            viol = req = 0
            for doc in self._docs.values():
                ctr = doc.get("counters") or {}
                v, r = ctr.get("serving_slo_violations"), \
                    ctr.get("serving_requests")
                if _numeric(v):
                    viol += int(v)
                if _numeric(r):
                    req += int(r)
            if self._prev is not None:
                # deltas clamped at 0: a process death makes the fleet
                # totals non-monotonic, which is attrition, not recovery
                dv = max(viol - self._prev[0], 0)
                dr = max(req - self._prev[1], 0)
                self._burn = (dv / dr) / self._budget if dr > 0 else 0.0
                if self._burn > 1.0:
                    # ONE creation point (ISSUE 20): the crossing is
                    # minted by the alert engine's ledger — at most one
                    # builtin:fleet_slo_burn firing per crossing — and
                    # the SAME record keeps this latched ring alive as
                    # the legacy fleet-block surface
                    from . import alerts as _obs_alerts

                    self._alerts.append(_obs_alerts.note_event(
                        "fleet_slo_burn", value=self._burn, meta={
                            "burn_rate": round(self._burn, 4),
                            "violations": dv,
                            "requests": dr,
                            "budget": self._budget,
                        }))
            self._prev = (viol, req)
        return True

    # -- merged views ------------------------------------------------------
    def _merged_locked(self):
        """(counters, gauges-by-family, hists-by-key) over the live
        docs. Caller holds ``_lock``; everything returned is fresh
        host data (no shared mutable state escapes)."""
        counters: dict[str, float] = {}
        gauges: dict[str, list] = {}
        hists: dict[tuple, Histogram] = {}
        for pid, doc in sorted(self._docs.items()):
            for k, v in (doc.get("counters") or {}).items():
                if _numeric(v):
                    counters[str(k)] = counters.get(str(k), 0) + v
            telem = doc.get("telemetry") or {}
            for name, labels, v in telem.get("gauges") or ():
                if not _numeric(v):
                    continue
                ls = tuple((str(k), str(val)) for k, val in labels)
                gauges.setdefault(str(name), []).append(
                    (ls + (("process", pid),), float(v))
                )
            for name, labels, snap in telem.get("histograms") or ():
                key = (str(name),
                       tuple((str(k), str(val)) for k, val in labels))
                h = hists.get(key)
                try:
                    if h is None:
                        hists[key] = h = Histogram(snap["bounds"])
                    h.merge(snap)
                except (ValueError, KeyError, TypeError):
                    # mismatched ladders / malformed doc: skip the
                    # series; a scrape must never 500 over one process
                    continue
        return counters, gauges, hists

    def render_lines(self) -> list:
        """Prometheus exposition lines for the merged fleet families,
        every family under ``dask_ml_tpu_fleet_`` (one TYPE line per
        family; a histogram family shadows a same-named gauge family,
        the live exporter's own rule)."""
        from .live import _PREFIX, _fmt, _labels_str, _merge_label, _san

        with self._lock:
            counters, gauges, hists = self._merged_locked()
            n_procs = len(self._docs)
            burn = self._burn
            n_alerts = len(self._alerts)
            scrape_s = self._scrape_s
        pre = f"{_PREFIX}fleet_"
        lines = []
        for name in sorted(counters):
            n = f"{pre}{_san(name)}_total"
            lines.append(f"# TYPE {n} counter")
            lines.append(f"{n} {_fmt(counters[name])}")
        hist_fams = {_san(name) for name, _ in hists}
        gauge_fams: dict[str, list] = {}
        for name, series in sorted(gauges.items()):
            if _san(name) not in hist_fams:
                gauge_fams[_san(name)] = series
        # the federator's own health gauges join the same family map so
        # a scraped gauge can never mint a duplicate TYPE line
        gauge_fams.setdefault("processes", []).append(((), n_procs))
        gauge_fams.setdefault("slo_burn_rate", []).append(((), burn))
        gauge_fams.setdefault("slo_alerts", []).append(((), n_alerts))
        if scrape_s is not None:
            gauge_fams.setdefault("scrape_seconds", []).append(
                ((), scrape_s))
        for name, series in gauge_fams.items():
            n = f"{pre}{name}"
            lines.append(f"# TYPE {n} gauge")
            for labels, v in series:
                lines.append(f"{n}{_labels_str(labels)} {_fmt(v)}")
        hist_by_fam: dict[str, list] = {}
        for (name, labels) in sorted(hists):
            hist_by_fam.setdefault(_san(name), []).append(
                (labels, hists[(name, labels)]))
        for fam, series in hist_by_fam.items():
            n = f"{pre}{fam}"
            lines.append(f"# TYPE {n} histogram")
            for labels, h in series:
                snap = h.snapshot()
                cum = 0
                for i, bound in enumerate(snap["bounds"]):
                    cum += snap["counts"][i]
                    lines.append(
                        f"{n}_bucket"
                        f"{_merge_label(labels, 'le', _fmt(bound))} {cum}"
                    )
                cum += snap["counts"][-1]
                lines.append(
                    f"{n}_bucket"
                    f"{_merge_label(labels, 'le', '+Inf')} {cum}"
                )
                ls = _labels_str(labels)
                lines.append(f"{n}_sum{ls} {_fmt(snap['sum'])}")
                lines.append(f"{n}_count{ls} {snap['count']}")
        return lines

    def fleet_block(self) -> dict:
        """The ``/status/fleet`` JSON: scraped processes, summed
        counters, merged histogram quantiles, and the SLO burn view
        with its latched alerts."""
        from .live import _labels_str

        with self._lock:
            counters, _, hists = self._merged_locked()
            pids = sorted(self._docs)
            burn = self._burn
            alerts = list(self._alerts)
            prev = self._prev
            scrape_s = self._scrape_s
            t_unix = self._t_unix
        hblock = {}
        for (name, labels), h in sorted(hists.items()):
            pct = h.percentiles((50, 99))
            hblock[f"{name}{_labels_str(labels)}"] = {
                "count": h.count,
                "sum": round(h.sum, 6),
                "p50": None if pct["p50"] != pct["p50"]
                else round(pct["p50"], 6),
                "p99": None if pct["p99"] != pct["p99"]
                else round(pct["p99"], 6),
            }
        return {
            "federation": self.name,
            "processes": pids,
            "n_scraped": len(pids),
            "counters": counters,
            "histograms": hblock,
            "slo": {
                "slo_ms": self._slo_ms,
                "budget": self._budget,
                "violations": prev[0] if prev else 0,
                "requests": prev[1] if prev else 0,
                "burn_rate": round(burn, 4),
                "alerts": alerts,
            },
            "scrape_seconds": scrape_s,
            "t_scrape_unix": round(t_unix, 3) if t_unix else None,
        }
