"""GaussianNB on sharded arrays.

Reference: ``dask_ml/naive_bayes.py`` (SURVEY.md §2a Naive Bayes row) —
per-class mean/var via masked reductions. Here the per-class statistics
are one jitted program (class masks × masked reductions, psum under
sharding) and the joint log-likelihood predict is a fused elementwise +
matmul program.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .base import BaseEstimator, ClassifierMixin, to_host
from .metrics import accuracy_score
from .parallel.sharded import ShardedArray
from .utils.validation import check_X_y, check_array, check_is_fitted

__all__ = ["GaussianNB"]


@jax.jit
def _class_stats(X, y, mask, classes):
    """Per-class count/mean/var in one pass. classes: (k,) values."""
    cmask = (y[None, :] == classes[:, None]).astype(X.dtype) * mask[None, :]
    counts = jnp.sum(cmask, axis=1)                      # (k,)
    sums = cmask @ X                                     # (k, d) on MXU
    means = sums / jnp.maximum(counts[:, None], 1.0)
    sq = cmask @ (X * X)
    var = sq / jnp.maximum(counts[:, None], 1.0) - means ** 2
    return counts, means, jnp.maximum(var, 0.0)


@jax.jit
def _joint_log_likelihood(X, theta, var, log_prior):
    # -0.5 * sum((x-mu)^2/var) - 0.5*sum(log 2 pi var) + log prior
    prec = 1.0 / var                                     # (k, d)
    x2 = (X * X) @ prec.T                                # (n, k)
    xm = X @ (theta * prec).T
    m2 = jnp.sum(theta * theta * prec, axis=1)
    quad = x2 - 2.0 * xm + m2[None, :]
    logdet = jnp.sum(jnp.log(2.0 * jnp.pi * var), axis=1)
    return -0.5 * (quad + logdet[None, :]) + log_prior[None, :]


class GaussianNB(ClassifierMixin, BaseEstimator):
    """Ref: dask_ml/naive_bayes.py::GaussianNB."""

    def __init__(self, priors=None, var_smoothing=1e-9):
        self.priors = priors
        self.var_smoothing = var_smoothing

    def fit(self, X, y):
        X, y = check_X_y(X, y, dtype=np.float32)
        mask = X.row_mask(X.dtype)
        classes = np.unique(y.to_numpy())
        counts, means, var = _class_stats(
            X.data, y.data, mask, jnp.asarray(classes, X.dtype)
        )
        # sklearn's numerical floor on variances
        from .ops.reductions import masked_mean_var

        _, gvar = masked_mean_var(X.data, mask, X.n_rows)
        eps = self.var_smoothing * float(jnp.max(gvar))
        self.classes_ = classes
        self.class_count_ = to_host(counts).astype(np.float64)
        self.theta_ = to_host(means).astype(np.float64)
        self.var_ = to_host(var).astype(np.float64) + eps
        if self.priors is not None:
            self.class_prior_ = np.asarray(self.priors, np.float64)
        else:
            self.class_prior_ = self.class_count_ / self.class_count_.sum()
        self.n_features_in_ = X.shape[1]
        return self

    def _jll(self, X):
        X = check_array(X, dtype=np.float32)
        return X, _joint_log_likelihood(
            X.data,
            jnp.asarray(self.theta_, X.dtype),
            jnp.asarray(self.var_, X.dtype),
            jnp.asarray(np.log(self.class_prior_), X.dtype),
        )

    def predict(self, X):
        check_is_fitted(self, "theta_")
        X, jll = self._jll(X)
        idx = to_host(jnp.argmax(jll, axis=1))[: X.n_rows]
        return self.classes_[idx]

    def predict_proba(self, X):
        check_is_fitted(self, "theta_")
        X, jll = self._jll(X)
        p = to_host(jax.nn.softmax(jll, axis=1))[: X.n_rows]
        return p

    def predict_log_proba(self, X):
        from .base import log_proba

        return log_proba(self.predict_proba(X))

    def score(self, X, y):
        y = y.to_numpy() if isinstance(y, ShardedArray) else np.asarray(y)
        return accuracy_score(y, self.predict(X))
