"""Classification metrics over (possibly sharded) arrays.

Reference: ``dask_ml/metrics/classification.py`` (SURVEY.md §2a Metrics
row) — blocked reductions with per-block sklearn kernels. Here each metric
is one jitted masked reduction; XLA inserts the psum when inputs are
sharded.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..parallel.sharded import ShardedArray, as_sharded


def _canon(y_true, y_pred, sample_weight=None):
    """Co-shard the pair (and sample_weight, padded alike); returns
    (a, b, weights, n) where weights = row-validity mask * sample_weight."""
    if isinstance(y_true, ShardedArray) or isinstance(y_pred, ShardedArray):
        mesh = (y_true.mesh if isinstance(y_true, ShardedArray) else y_pred.mesh)
        t = as_sharded(y_true, mesh=mesh)
        p = as_sharded(y_pred, mesh=mesh)
        w = t.row_mask()
        if sample_weight is not None:
            w = w * as_sharded(sample_weight, mesh=mesh).data
        return t.data, p.data, w, t.n_rows
    t = np.asarray(y_true)
    p = np.asarray(y_pred)
    w = np.ones(t.shape[0], np.float32)
    if sample_weight is not None:
        w = w * np.asarray(sample_weight)
    return t, p, w, t.shape[0]


def accuracy_score(y_true, y_pred, normalize=True, sample_weight=None):
    t, p, w, n = _canon(y_true, y_pred, sample_weight)
    hits = jnp.sum((t == p) * w)
    if not normalize:
        return float(hits)
    return float(hits / jnp.sum(w))


def log_loss(y_true, y_prob, eps=1e-15, sample_weight=None, labels=None):
    t, p, w, n = _canon(y_true, y_prob, sample_weight)
    p = jnp.clip(p, eps, 1.0 - eps)
    if p.ndim == 2 and p.shape[1] > 2:
        # multiclass: cross-entropy of the true-class probability, rows
        # renormalized as sklearn does. Column c of y_prob corresponds to
        # the c-th SORTED class — when a fold is missing a class that
        # inference is ambiguous, so (like sklearn) explicit labels are
        # required rather than silently misaligning columns
        if labels is not None:
            classes = np.sort(np.asarray(labels))
        else:
            host_t = (y_true.to_numpy() if isinstance(y_true, ShardedArray)
                      else np.asarray(y_true))
            classes = np.unique(host_t)
        if len(classes) != p.shape[1]:
            raise ValueError(
                f"y_true has {len(classes)} classes but y_prob has "
                f"{p.shape[1]} columns; pass labels= with every class"
            )
        p = p / jnp.sum(p, axis=1, keepdims=True)
        # cast on HOST: jnp.asarray(host_float64, ...) would request x64
        # and warn on every call in a scoring loop
        classes_d = jnp.asarray(classes.astype(np.dtype(str(t.dtype))))
        idx = jnp.clip(jnp.searchsorted(classes_d, t), 0, p.shape[1] - 1)
        # membership check: a y value absent from the classes (or falling
        # between them) must raise, not silently score a neighbor class
        ok = jnp.all((jnp.take(classes_d, idx) == t) | (w == 0))
        if not bool(ok):
            raise ValueError("y_true contains values not in labels")
        p_true = jnp.take_along_axis(p, idx[:, None], axis=1)[:, 0]
        ll = -jnp.log(jnp.clip(p_true, eps, 1.0))
        return float(jnp.sum(ll * w) / jnp.sum(w))
    if p.ndim == 2:  # (n, 2) probabilities: take class-1 column
        p = p[:, 1]
    # binary labels need not be 0/1 (e.g. {10, 20}): map the POSITIVE
    # (larger) class to 1 by a device min/max scan — one scalar fetch
    if labels is not None:
        lab = np.sort(np.asarray(labels))
        if len(lab) != 2:
            raise ValueError("binary y_prob needs exactly 2 labels")
        mn_h, mx_h = float(lab[0]), float(lab[1])
    else:
        valid = w > 0
        mn = jnp.min(jnp.where(valid, t, jnp.inf))
        mx = jnp.max(jnp.where(valid, t, -jnp.inf))
        mn_h, mx_h = float(mn), float(mx)
        if mn_h == mx_h:
            # single observed class: the 0/1 mapping is ambiguous and a
            # silent guess scores the WRONG class half the time
            raise ValueError(
                "y_true contains a single class; pass labels= to fix "
                "the class order"
            )
    ok = jnp.all((t == mn_h) | (t == mx_h) | (w == 0))
    if not bool(ok):
        raise ValueError("y_true contains values not in labels")
    if (mn_h, mx_h) != (0.0, 1.0):
        t = (t == mx_h).astype(jnp.float32)
    ll = -(t * jnp.log(p) + (1.0 - t) * jnp.log1p(-p))
    return float(jnp.sum(ll * w) / jnp.sum(w))
