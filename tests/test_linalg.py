"""Distributed linalg tests: TSQR, tall SVD, randomized SVD (SURVEY.md §7 B1).

Oracle = numpy.linalg on the gathered array, the same "small-data parity"
contract the reference uses with sklearn (SURVEY.md §4).
"""

import jax
import numpy as np
import pytest

from dask_ml_tpu.ops import linalg
from dask_ml_tpu.parallel import ShardedArray, default_mesh


def _sharded(n, d, seed=0, dtype=np.float32):
    x = np.random.RandomState(seed).randn(n, d).astype(dtype)
    return x, ShardedArray.from_array(x, default_mesh())


def test_tsqr_reconstruction_and_orthonormality():
    x, sx = _sharded(96, 6)
    q, r = linalg.tsqr(sx.data, sx.mesh)
    q, r = np.asarray(q), np.asarray(r)
    np.testing.assert_allclose(q @ r, x, atol=1e-4)
    np.testing.assert_allclose(q.T @ q, np.eye(6), atol=1e-4)
    assert np.allclose(r, np.triu(r))


def test_tsqr_with_zero_padding_rows():
    # padded rows are zero; Q rows stay zero and R is unaffected
    mesh = default_mesh()
    x = np.random.RandomState(3).randn(33, 4).astype(np.float32)
    sx = ShardedArray.from_array(x, mesh)
    q, r = linalg.tsqr(sx.data, mesh)
    q = np.asarray(q)
    np.testing.assert_allclose(q[:33] @ np.asarray(r), x, atol=1e-4)
    np.testing.assert_allclose(q[33:], 0.0, atol=1e-5)


def test_svd_tall_matches_numpy():
    x, sx = _sharded(128, 5)
    u, s, vt = linalg.svd_tall(sx.data, sx.mesh)
    s_np = np.linalg.svd(x, compute_uv=False)
    np.testing.assert_allclose(np.asarray(s), s_np, rtol=1e-4)
    rec = np.asarray(u) @ np.diag(np.asarray(s)) @ np.asarray(vt)
    np.testing.assert_allclose(rec, x, atol=1e-3)


@pytest.mark.slow
def test_randomized_svd_low_rank():
    rng = np.random.RandomState(0)
    base = rng.randn(200, 4) @ rng.randn(4, 16)
    x = base.astype(np.float32)
    sx = ShardedArray.from_array(x, default_mesh())
    u, s, vt = linalg.randomized_svd(
        sx.data, 4, jax.random.PRNGKey(0), sx.mesh, n_iter=4
    )
    s_np = np.linalg.svd(x, compute_uv=False)[:4]
    np.testing.assert_allclose(np.asarray(s), s_np, rtol=1e-3)
    rec = np.asarray(u) @ np.diag(np.asarray(s)) @ np.asarray(vt)
    np.testing.assert_allclose(rec, x, atol=2e-2)


def test_svd_flip_deterministic():
    x, sx = _sharded(64, 4, seed=5)
    u, s, vt = linalg.svd_tall(sx.data, sx.mesh)
    u2, vt2 = linalg.svd_flip(u, vt)
    u2, vt2 = np.asarray(u2), np.asarray(vt2)
    # flipped decomposition still reconstructs
    np.testing.assert_allclose(u2 @ np.diag(np.asarray(s)) @ vt2, x, atol=1e-3)
    # largest-|.| entry of each row of Vt is positive
    mx = np.argmax(np.abs(vt2), axis=1)
    assert (vt2[np.arange(4), mx] > 0).all()


def test_tsqr_fewer_rows_than_shards_per_block():
    """n barely above the shard count: per-shard blocks are extremely
    short; TSQR must still produce orthonormal Q and upper R."""
    mesh = default_mesh()
    shards = mesh.devices.size
    n, d = shards + 1, 3  # one shard gets 2 rows, rest get 1 (padded)
    rng = np.random.RandomState(0)
    Xs = ShardedArray.from_array(rng.randn(n, d).astype(np.float32))
    q, r = linalg.tsqr(Xs.data, mesh)
    qh, rh = np.asarray(q)[:n], np.asarray(r)
    np.testing.assert_allclose(qh @ rh, Xs.to_numpy(), atol=1e-4)
    np.testing.assert_allclose(qh.T @ qh, np.eye(d), atol=1e-4)


@pytest.mark.slow
def test_randomized_svd_components_near_rank():
    """k + oversampling exceeding d must clamp, and recover the full
    spectrum of an exactly low-rank matrix."""

    mesh = default_mesh()
    rng = np.random.RandomState(1)
    n, d, true_rank = 512, 12, 4
    A = (rng.randn(n, true_rank) @ rng.randn(true_rank, d)).astype(
        np.float32
    )
    Xs = ShardedArray.from_array(A)
    u, s, vt = linalg.randomized_svd(Xs.data, 8, jax.random.PRNGKey(0), mesh,
                              n_oversamples=10, n_iter=4)
    s = np.asarray(s)
    ref = np.linalg.svd(A.astype(np.float64), compute_uv=False)
    np.testing.assert_allclose(s[:true_rank], ref[:true_rank], rtol=1e-3)
    # spectrum beyond the true rank is numerically zero
    assert np.all(s[true_rank:] < ref[0] * 1e-4)


def test_svd_tall_single_column():
    mesh = default_mesh()
    rng = np.random.RandomState(2)
    x = rng.randn(256, 1).astype(np.float32)
    Xs = ShardedArray.from_array(x)
    u, s, vt = linalg.svd_tall(Xs.data, mesh)
    np.testing.assert_allclose(
        float(s[0]), np.linalg.norm(x), rtol=1e-4
    )
