"""End-to-end user journeys — the composed paths a dask-ml user actually
runs (ref: the reference's integration-style tests around pipelines and
searches; SURVEY.md §3.4 pipeline prefix sharing).

Each test walks a full chain, not one estimator: frame ingest →
preprocessing → device placement → (search over a Pipeline) → post-fit.
"""

import numpy as np
import pandas as pd
import pytest
from sklearn.pipeline import Pipeline

from dask_ml_tpu.compose import ColumnTransformer
from dask_ml_tpu.linear_model import LogisticRegression
from dask_ml_tpu.model_selection import GridSearchCV, train_test_split
from dask_ml_tpu.parallel import PartitionedFrame, ShardedArray, from_pandas
from dask_ml_tpu.preprocessing import (
    Categorizer, DummyEncoder, StandardScaler,
)


@pytest.fixture(scope="module")
def frame():
    rng = np.random.RandomState(0)
    n = 600
    df = pd.DataFrame({
        "x0": rng.randn(n),
        "x1": rng.rand(n) * 10,
        "city": rng.choice(["ams", "ber", "cdg"], n),
    })
    target = ((df["x0"] + 0.3 * df["x1"]
               + (df["city"] == "ams") + 0.3 * rng.randn(n)) > 2.0)
    return df, target.astype(np.float32).to_numpy()


@pytest.mark.slow
def test_frame_to_search_journey(frame):
    """frame → categorize → dummy → column-scale → device → GridSearchCV
    over a Pipeline → predict: every layer hands off to the next without
    manual conversion."""
    df, y = frame
    pf = from_pandas(df, npartitions=6)
    pf = Categorizer().fit(pf).transform(pf)
    feats = DummyEncoder().fit(pf).transform(pf)
    assert isinstance(feats, PartitionedFrame)
    ct = ColumnTransformer(
        [("num", StandardScaler(), ["x0", "x1"])], remainder="passthrough"
    )
    scaled = ct.fit_transform(feats)
    assert isinstance(scaled, PartitionedFrame)
    X = scaled.to_sharded()
    assert isinstance(X, ShardedArray)

    Xtr, Xte, ytr, yte = train_test_split(X, y, test_size=0.25,
                                          random_state=0)
    pipe = Pipeline([
        ("scale", StandardScaler()),
        ("clf", LogisticRegression(solver="lbfgs", max_iter=40)),
    ])
    search = GridSearchCV(pipe, {"clf__C": [0.1, 1.0]}, cv=2).fit(Xtr, ytr)
    assert search.best_score_ > 0.7
    pred = search.predict(Xte)
    pred = np.asarray(pred.to_numpy() if hasattr(pred, "to_numpy") else pred)
    assert pred.shape[0] == len(yte)
    acc = (pred == np.asarray(
        yte.to_numpy() if hasattr(yte, "to_numpy") else yte
    )).mean()
    assert acc > 0.75


def test_memmap_to_fit_journey(tmp_path):
    """disk memmap → streamed fit → streamed predict: the out-of-core
    chain with nothing materialized on device (BASELINE >HBM design)."""
    from dask_ml_tpu import config

    rng = np.random.RandomState(1)
    n, d = 6000, 8
    Xh = rng.randn(n, d).astype(np.float32)
    w = rng.randn(d).astype(np.float32)
    yh = (Xh @ w > 0).astype(np.float32)
    path = tmp_path / "X.f32"
    np.asarray(Xh).tofile(path)
    Xm = np.memmap(path, dtype=np.float32, mode="r", shape=(n, d))

    with config.set(stream_block_rows=1000):
        clf = LogisticRegression(solver="lbfgs", max_iter=40).fit(Xm, yh)
        proba = clf.predict_proba(Xm)
    resident = LogisticRegression(solver="lbfgs", max_iter=40).fit(Xh, yh)
    np.testing.assert_allclose(np.ravel(clf.coef_),
                               np.ravel(resident.coef_), atol=2e-2)
    assert proba.shape == (n, 2)
    assert ((proba[:, 1] > 0.5) == yh).mean() > 0.9
