"""Chunked synthetic datasets.

Reference: ``dask_ml/datasets.py`` (SURVEY.md §2a Datasets row) — per-block
sklearn generators with per-block seeds. Here blocks = shards: each shard's
rows are generated with a seed derived from (random_state, shard index) so
the dataset is deterministic for a given mesh size, then placed directly
onto the mesh — the TPU equivalent of "generate where the chunk lives".

The generators run sklearn on the host per shard (generation is not the hot
path); the returned ShardedArray is device-resident.
"""

from __future__ import annotations

import numpy as np
import sklearn.datasets as skdata

from .parallel.mesh import data_shards, resolve_mesh
from .parallel.sharded import ShardedArray

__all__ = ["make_classification", "make_regression", "make_blobs",
           "make_counts", "make_classification_df"]


def _per_shard(n_samples, mesh):
    s = data_shards(mesh)
    per = int(np.ceil(n_samples / s))
    sizes = [min(per, n_samples - i * per) for i in range(s)]
    return [max(sz, 0) for sz in sizes]


def _assemble(parts_X, parts_y, mesh):
    X = np.concatenate([p for p in parts_X if len(p)], axis=0)
    y = np.concatenate([p for p in parts_y if len(p)], axis=0)
    return (
        ShardedArray.from_array(X, mesh, dtype=np.float32),
        ShardedArray.from_array(y, mesh, dtype=np.float32),
    )


def _classification_parts(n_samples, n_features, n_informative, n_classes,
                          class_sep, flip_y, random_state, mesh,
                          class_weights=None):
    """Per-shard host blocks of the classification problem (shared by the
    array and DataFrame generators — the latter never touches the device)."""
    rs = np.random.RandomState(random_state)
    n_informative = min(n_informative, n_features)
    if n_informative == 0:
        # pure noise: no class signal (predictability=0 baselines)
        centers = np.zeros((n_classes, 0))
    else:
        if n_classes > 2 ** n_informative:
            raise ValueError(
                f"n_classes={n_classes} > 2**n_informative={2**n_informative} "
                "distinct hypercube vertices"
            )
        # distinct hypercube vertices per class (sampling with replacement
        # can hand two classes the same center → zero class signal).
        # NOT np.random.choice(pop, replace=False): that MATERIALIZES a
        # pop-sized permutation — 2**32 vertices is a ~34 GB allocation
        # that looks like a hang. sklearn's reservoir-style sampler
        # draws k distinct values from 2**62 without touching the pool.
        from sklearn.utils.random import sample_without_replacement

        chosen = np.asarray(
            sample_without_replacement(
                2 ** min(n_informative, 62), n_classes, random_state=rs
            ),
            dtype=np.int64,
        )
        bits = ((chosen[:, None] >> np.arange(min(n_informative, 62))) & 1)
        if n_informative > 62:  # pad extra dims with fixed signs
            bits = np.concatenate(
                [bits, np.ones((n_classes, n_informative - 62), int)], axis=1
            )
        centers = class_sep * (2.0 * bits - 1.0)
    perm = rs.permutation(n_features)
    seeds = rs.randint(0, 2**31 - 1, size=data_shards(mesh))
    Xs, ys = [], []
    for sz, seed in zip(_per_shard(n_samples, mesh), seeds):
        if sz <= 0:
            Xs.append(np.empty((0, n_features))); ys.append(np.empty((0,)))
            continue
        r = np.random.RandomState(int(seed))
        if class_weights is None:
            y = r.randint(0, n_classes, size=sz)
        else:
            y = r.choice(n_classes, size=sz, p=class_weights)
        X = r.normal(size=(sz, n_features))
        X[:, :n_informative] += centers[y]
        X = X[:, perm]
        flip = r.uniform(size=sz) < flip_y
        y = np.where(flip, r.randint(0, n_classes, size=sz), y)
        Xs.append(X); ys.append(y.astype(np.float64))
    return Xs, ys


def make_classification(n_samples=100, n_features=20, n_informative=5,
                        n_classes=2, class_sep=1.0, flip_y=0.01,
                        random_state=None, chunks=None, mesh=None):
    """Consistent global problem across shards: class centers (hypercube
    vertices in the informative subspace) and the feature permutation are
    drawn ONCE from random_state; shards draw only their rows. (The
    reference seeds sklearn's whole generator per block, so each block is
    a *different* problem — a known quirk we deliberately fix.)

    .. note:: seed-stream change — vertex selection now draws the class
       centers via sklearn's ``sample_without_replacement`` reservoir
       sampler instead of ``RandomState.choice`` (the old path
       materialized a ``2**n_informative``-sized permutation: a ~34 GB
       allocation at 32 informative features). Both are deterministic in
       ``random_state``, but they consume the seed stream differently,
       so a given seed selects DIFFERENT centers than it did before the
       change: snapshot tests pinning exact generated values (or
       metrics derived from them) will see fixtures move across this
       version boundary. Re-record such fixtures; distributional
       properties (separation, class balance) are unchanged."""
    mesh = resolve_mesh(mesh)
    Xs, ys = _classification_parts(
        n_samples, n_features, n_informative, n_classes, class_sep, flip_y,
        random_state, mesh,
    )
    return _assemble(Xs, ys, mesh)


def make_regression(n_samples=100, n_features=100, n_informative=10,
                    noise=0.0, bias=0.0, random_state=None, chunks=None,
                    mesh=None):
    """Fixed ground-truth coefficients across shards (see
    make_classification note on the reference's per-block quirk)."""
    mesh = resolve_mesh(mesh)
    rs = np.random.RandomState(random_state)
    n_informative = min(n_informative, n_features)
    coef = np.zeros(n_features)
    coef[rs.permutation(n_features)[:n_informative]] = 100.0 * rs.uniform(
        size=n_informative
    )
    seeds = rs.randint(0, 2**31 - 1, size=data_shards(mesh))
    Xs, ys = [], []
    for sz, seed in zip(_per_shard(n_samples, mesh), seeds):
        if sz <= 0:
            Xs.append(np.empty((0, n_features))); ys.append(np.empty((0,)))
            continue
        r = np.random.RandomState(int(seed))
        X = r.normal(size=(sz, n_features))
        y = X @ coef + bias
        if noise > 0:
            y = y + r.normal(scale=noise, size=sz)
        Xs.append(X); ys.append(y)
    return _assemble(Xs, ys, mesh)


def make_blobs(n_samples=100, n_features=2, centers=None, random_state=None,
               chunks=None, mesh=None, **kwargs):
    mesh = resolve_mesh(mesh)
    rs = np.random.RandomState(random_state)
    if centers is None:
        centers = 3
    if np.isscalar(centers):
        # fix center locations once so every shard draws from the same blobs
        centers = rs.uniform(-10, 10, size=(centers, n_features))
    seeds = rs.randint(0, 2**31 - 1, size=data_shards(mesh))
    Xs, ys = [], []
    for sz, seed in zip(_per_shard(n_samples, mesh), seeds):
        if sz <= 0:
            Xs.append(np.empty((0, n_features))); ys.append(np.empty((0,)))
            continue
        X, y = skdata.make_blobs(
            n_samples=sz, n_features=n_features, centers=centers,
            random_state=int(seed), **kwargs
        )
        Xs.append(X); ys.append(y)
    return _assemble(Xs, ys, mesh)


def make_classification_df(n_samples=100, n_features=20, predictability=0.1,
                           response_rate=0.5, random_state=None, chunks=None,
                           mesh=None, dates=None, **kwargs):
    """Classification data as (DataFrame, Series) with named feature columns
    (ref: ``dask_ml/datasets.py::make_classification_df``). Reference
    semantics: ``predictability`` is the FRACTION of informative features
    (n_informative = predictability * n_features) and ``response_rate`` the
    positive-class share. DataFrames live on host (TPU consumes arrays); an
    optional ``dates`` (start, end) pair adds a uniformly sampled ``date``
    column like the reference.
    """
    import pandas as pd

    n_classes = kwargs.pop("n_classes", 2)
    if not 0.0 <= predictability <= 1.0:
        raise ValueError(f"predictability must be in [0, 1], got {predictability}")
    if not 0.0 < response_rate <= 1.0:
        raise ValueError(f"response_rate must be in (0, 1], got {response_rate}")
    if n_classes == 1:
        weights = [1.0]
    elif n_classes == 2:
        weights = [1.0 - response_rate, response_rate]
    else:
        rest = (1.0 - response_rate) / (n_classes - 1)
        weights = [rest] * (n_classes - 1) + [response_rate]
    Xs, ys = _classification_parts(
        n_samples, n_features,
        kwargs.pop("n_informative", int(predictability * n_features)),
        n_classes,
        kwargs.pop("class_sep", 1.0),
        kwargs.pop("flip_y", 0.01),
        random_state, resolve_mesh(mesh),
        class_weights=weights,
    )
    if kwargs:
        raise TypeError(f"unsupported arguments: {sorted(kwargs)}")
    Xn = np.concatenate([p for p in Xs if len(p)], axis=0)
    yn = np.concatenate([p for p in ys if len(p)], axis=0)
    df = pd.DataFrame(Xn, columns=[f"feature_{i}" for i in range(n_features)])
    if dates is not None:
        start, end = pd.Timestamp(dates[0]), pd.Timestamp(dates[1])
        r = np.random.RandomState(random_state)
        offs = r.uniform(size=len(df)) * (end - start).value
        df.insert(0, "date", start + pd.to_timedelta(offs.astype(np.int64)))
    return df, pd.Series(yn.astype(np.int64), name="target")


def make_counts(n_samples=100, n_features=20, random_state=None, scale=1.0,
                chunks=None, mesh=None):
    """Poisson-target regression data (ref: dask_ml/datasets.py::make_counts)."""
    mesh = resolve_mesh(mesh)
    rs = np.random.RandomState(random_state)
    beta = rs.normal(0, 1, size=n_features) * scale / np.sqrt(n_features)
    seeds = rs.randint(0, 2**31 - 1, size=data_shards(mesh))
    Xs, ys = [], []
    for sz, seed in zip(_per_shard(n_samples, mesh), seeds):
        if sz <= 0:
            Xs.append(np.empty((0, n_features))); ys.append(np.empty((0,)))
            continue
        r = np.random.RandomState(int(seed))
        X = r.normal(0, 1, size=(sz, n_features))
        y = r.poisson(np.exp(X @ beta))
        Xs.append(X); ys.append(y.astype(np.float64))
    return _assemble(Xs, ys, mesh)
