"""Preprocessing parity vs sklearn (SURVEY.md §4 oracle pattern)."""

import numpy as np
import pytest
import sklearn.preprocessing as skpre

from dask_ml_tpu import preprocessing as pre

RNG = np.random.RandomState(42)
X = RNG.lognormal(size=(101, 4)).astype(np.float64)  # odd n → padding


def test_standard_scaler():
    ours = pre.StandardScaler().fit(X)
    ref = skpre.StandardScaler().fit(X)
    np.testing.assert_allclose(ours.mean_, ref.mean_, rtol=1e-4)
    np.testing.assert_allclose(ours.var_, ref.var_, rtol=1e-3)
    np.testing.assert_allclose(
        ours.transform(X).to_numpy(), ref.transform(X), atol=1e-4
    )
    back = ours.inverse_transform(ours.transform(X)).to_numpy()
    np.testing.assert_allclose(back, X, rtol=1e-3, atol=1e-4)


def test_standard_scaler_no_mean():
    ours = pre.StandardScaler(with_mean=False).fit(X)
    ref = skpre.StandardScaler(with_mean=False).fit(X)
    np.testing.assert_allclose(
        ours.transform(X).to_numpy(), ref.transform(X), rtol=1e-4
    )


def test_minmax_scaler():
    ours = pre.MinMaxScaler().fit(X)
    ref = skpre.MinMaxScaler().fit(X)
    np.testing.assert_allclose(ours.data_min_, ref.data_min_, rtol=1e-5)
    np.testing.assert_allclose(ours.data_max_, ref.data_max_, rtol=1e-5)
    np.testing.assert_allclose(
        ours.transform(X).to_numpy(), ref.transform(X), atol=1e-5
    )
    back = ours.inverse_transform(ours.transform(X)).to_numpy()
    np.testing.assert_allclose(back, X, rtol=1e-3, atol=1e-4)


def test_robust_scaler():
    ours = pre.RobustScaler().fit(X)
    ref = skpre.RobustScaler().fit(X)
    np.testing.assert_allclose(ours.center_, ref.center_, rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(ours.scale_, ref.scale_, rtol=1e-3)
    np.testing.assert_allclose(
        ours.transform(X).to_numpy(), ref.transform(X), atol=1e-3
    )


@pytest.mark.parametrize("dist", ["uniform", "normal"])
def test_quantile_transformer(dist):
    ours = pre.QuantileTransformer(n_quantiles=50, output_distribution=dist)
    ref = skpre.QuantileTransformer(n_quantiles=50, output_distribution=dist)
    t_ours = ours.fit_transform(X).to_numpy()
    t_ref = ref.fit_transform(X)
    assert abs(t_ours - t_ref).mean() < 0.02


def test_polynomial_features():
    ours = pre.PolynomialFeatures(degree=2).fit(X)
    ref = skpre.PolynomialFeatures(degree=2).fit(X)
    assert ours.n_output_features_ == ref.n_output_features_
    np.testing.assert_allclose(
        ours.transform(X).to_numpy(), ref.transform(X), rtol=1e-3, atol=1e-4
    )
    assert list(ours.get_feature_names_out()) == list(ref.get_feature_names_out())


def test_polynomial_interaction_only():
    ours = pre.PolynomialFeatures(degree=2, interaction_only=True,
                                  include_bias=False).fit(X)
    ref = skpre.PolynomialFeatures(degree=2, interaction_only=True,
                                   include_bias=False).fit(X)
    np.testing.assert_allclose(
        ours.transform(X).to_numpy(), ref.transform(X), rtol=1e-3, atol=1e-4
    )


def test_pipeline_scaler_logreg(xy_classification):
    """The B3 end-to-end slice: scale + fit + score on sharded data."""
    from dask_ml_tpu.linear_model import LogisticRegression

    Xc, y = xy_classification
    Xt = pre.StandardScaler().fit_transform(Xc)
    clf = LogisticRegression(solver="lbfgs", max_iter=300).fit(Xt, y)
    assert clf.score(Xt, y) > 0.85


def test_standard_scaler_large_offset_precision():
    """|mean| >> std in float32: the subtract-then-scale form keeps
    cancellation; a scale-then-shift rewrite rounds at the data's
    magnitude and produces garbage z-scores (timestamp-like features)."""
    rng = np.random.RandomState(0)
    # mean 1e7, std 1: x*(1/s) rounds at x's magnitude (~1.2 error per
    # z-score); (x - mean)/s cancels first and stays at ulp level. Exact
    # (f64) statistics are injected so the test isolates the TRANSFORM's
    # arithmetic from the f32 fit-stat estimation error.
    X32 = (1e7 + rng.randn(4000, 2)).astype(np.float32)
    X64 = X32.astype(np.float64)
    ref = skpre.StandardScaler().fit(X64)
    ours = pre.StandardScaler().fit(X32)
    ours.mean_, ours.var_, ours.scale_ = ref.mean_, ref.var_, ref.scale_
    got = ours.transform(X32).to_numpy()
    assert np.abs(got - ref.transform(X64)).max() < 0.05


@pytest.mark.slow
def test_quantile_transformer_subsample_and_random_state(monkeypatch):
    """subsample/random_state are honored (VERDICT r3 weak #5): a fit
    over n > subsample rows computes quantiles from a seeded uniform
    subsample (sklearn semantics), deterministic per seed and within
    tolerance of the exact-all-rows quantiles; and when the sample is
    itself past the sort threshold the sketch path engages."""
    rng = np.random.RandomState(0)
    Xb = rng.lognormal(size=(6000, 3)).astype(np.float32)
    exact = pre.QuantileTransformer(n_quantiles=100, subsample=None)
    exact.fit(Xb)
    a = pre.QuantileTransformer(n_quantiles=100, subsample=2000,
                                random_state=7).fit(Xb)
    b = pre.QuantileTransformer(n_quantiles=100, subsample=2000,
                                random_state=7).fit(Xb)
    np.testing.assert_array_equal(a.quantiles_, b.quantiles_)  # seeded
    # subsampled quantiles approximate the full-data quantiles
    spread = exact.quantiles_[-1] - exact.quantiles_[0]
    err = np.abs(a.quantiles_ - exact.quantiles_) / spread[None, :]
    assert np.median(err) < 0.05
    # the sampled fit still transforms close to sklearn's exact map
    t = a.transform(Xb).to_numpy()
    t_ref = skpre.QuantileTransformer(n_quantiles=100,
                                      subsample=None).fit_transform(Xb)
    assert abs(t - t_ref).mean() < 0.03
    # sample > sort threshold -> histogram sketch engages behind subsample
    from dask_ml_tpu.preprocessing import data as pdata

    calls = {}
    real = pdata._sketch_quantiles

    def spy(*args, **kw):
        calls["hit"] = True
        return real(*args, **kw)

    monkeypatch.setattr(pdata, "_SKETCH_THRESHOLD", 1999)
    monkeypatch.setattr(pdata, "_sketch_quantiles", spy)
    pre.QuantileTransformer(n_quantiles=100, subsample=2000,
                            random_state=7).fit(Xb)
    assert calls.get("hit")


def test_quantile_transformer_ignore_implicit_zeros_raises():
    Xb = np.random.RandomState(1).randn(50, 2).astype(np.float32)
    with pytest.raises(ValueError, match="sparse"):
        pre.QuantileTransformer(ignore_implicit_zeros=True).fit(Xb)
