"""Multi-host distributed runtime.

Reference: the ``distributed`` scheduler/worker/comm stack — TCP frames,
msgpack+pickle serialization, heartbeats (SURVEY.md §2b rows 4-5, §5 comm
row). TPU replacement: intra-slice communication is XLA collectives over
ICI compiled into programs (no serialization layer exists at all);
cross-host control is the JAX distributed runtime over DCN. This module
is the thin bring-up layer: ``initialize()`` wraps
``jax.distributed.initialize`` (no-op single-host), ``global_mesh`` spans
every process's devices, and small host-side control messages ride an
all-gather (``broadcast_host`` / ``barrier``) instead of a socket
protocol.

Single-host sessions exercise the same code paths (process_count == 1),
which is how the test suite covers it; a pod run only changes the
environment variables.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .mesh import device_mesh

_initialized = False


def initialize(coordinator_address=None, num_processes=None,
               process_id=None, local_device_ids=None):
    """Bring up the JAX distributed runtime (DCN control plane).

    No-op when single-process and no coordinator is configured — the same
    script runs on a laptop, one TPU VM, or every host of a pod slice.
    """
    global _initialized
    if _initialized:
        return
    if coordinator_address is None and num_processes is None and \
            "COORDINATOR_ADDRESS" not in __import__("os").environ:
        _initialized = True  # single-process mode
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
    )
    _initialized = True


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def is_coordinator() -> bool:
    """The host that runs search controllers (SURVEY.md §3.5: 'asyncio
    controller on host 0')."""
    return jax.process_index() == 0


def global_mesh(axis_names=("data",), shape=None):
    """Mesh over ALL processes' devices (ICI within a slice, DCN across:
    topology-ordered so the DCN hop is the outer factor of the data
    axis)."""
    return device_mesh(shape=shape, axis_names=axis_names,
                       devices=jax.devices(), topology_order=True)


def local_mesh(axis_names=("data",), shape=None):
    """Mesh over THIS process's devices only. Trials placed here never
    emit cross-host collectives, so different processes can run different
    programs concurrently — the placement unit for distributed
    hyperparameter search (SURVEY.md §3.5: 'trials pinned to
    hosts/mesh-subsets')."""
    return device_mesh(shape=shape, axis_names=axis_names,
                       devices=jax.local_devices(), topology_order=True)


def allgather_object(obj):
    """Gather one small picklable host object per process; every process
    receives the list ``[obj_from_proc_0, ..., obj_from_proc_{P-1}]``.
    Variable-size pickles ride the fixed-size device collective by
    padding to the max length (sizes exchanged first) — the control-plane
    result channel for distributed searches, replacing the reference's
    msgpack/pickle frames over TCP (SURVEY.md §5 comm row)."""
    import pickle

    if process_count() == 1:
        return [obj]
    buf = np.frombuffer(pickle.dumps(obj), np.uint8)
    sizes = allgather_host(np.array([buf.size], np.int32))[:, 0]
    padded = np.zeros(int(sizes.max()), np.uint8)
    padded[: buf.size] = buf
    stacked = allgather_host(padded)
    return [
        pickle.loads(stacked[i, : sizes[i]].tobytes())
        for i in range(len(sizes))
    ]


def allgather_host(value: np.ndarray) -> np.ndarray:
    """Gather a small host array from every process; returns the
    (n_processes, *shape) stack on all of them (shape/dtype must match
    across processes). The score-gather channel of distributed searches —
    replaces the reference's worker→scheduler result messages with one
    device-fabric collective.

    The payload rides the collective as raw bytes: ``jnp.asarray`` would
    silently downcast float64 (x64 disabled by default), and score merges
    must be bit-exact with the single-process run."""
    value = np.ascontiguousarray(value)
    if process_count() == 1:
        return value[None]
    from jax.experimental import multihost_utils

    buf = np.frombuffer(value.tobytes(), np.uint8)
    stacked = np.asarray(
        multihost_utils.process_allgather(jnp.asarray(buf), tiled=False)
    )
    return np.stack([
        np.frombuffer(stacked[i].tobytes(), value.dtype).reshape(value.shape)
        for i in range(stacked.shape[0])
    ])


def barrier(name="barrier"):
    """Cross-host sync point: a tiny psum over every device."""
    x = jnp.ones((jax.device_count(),))
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = global_mesh()
    y = jax.jit(
        lambda v: jnp.sum(v),
        in_shardings=NamedSharding(mesh, P("data")),
        out_shardings=NamedSharding(mesh, P()),
    )(x)
    return float(y)


def broadcast_host(value: np.ndarray, root: int = 0) -> np.ndarray:
    """Broadcast a small host array from the coordinator to all processes
    — replaces the reference's scheduler→worker control messages. Rides
    the device fabric (device_put + replication), not a socket."""
    if process_count() == 1:
        return np.asarray(value)
    from jax.experimental import multihost_utils

    return np.asarray(
        multihost_utils.broadcast_one_to_all(
            jnp.asarray(value), is_source=process_index() == root
        )
    )
