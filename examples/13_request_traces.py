"""Request tracing: per-request lifecycle stamps, tail sampling,
exemplars, and traffic capture/replay.

The drift plane (`examples/11`) watches the data; this example watches
the REQUEST — the unit a serving fleet is actually debugged by:

1. with ``obs_trace_sample`` on, every admitted request stamps its
   lifecycle (admit → queue_pop → pack → dispatch → execute_done →
   demux → complete) and the stage durations telescope exactly to the
   measured end-to-end latency;
2. the **tail sampler** keeps full breakdowns only for interesting
   traces (here: the rolling slowest 20% of ordinary completions),
   while EVERY completion folds into per-stage **exemplar histograms**
   — a scraped p99 links back to a concrete trace id;
3. a request served while an SLO is violated is ALWAYS kept, outcome
   tags and all — the trace an operator actually pages on;
4. with a trace sink configured, the admitted traffic lands as
   ``req_capture`` records that ``load_capture`` + ``replay``
   round-trip into a re-issued (method, rows, rate) mix.
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from dask_ml_tpu import config
from dask_ml_tpu.datasets import make_classification
from dask_ml_tpu.linear_model import LogisticRegression
from dask_ml_tpu.observability import load_capture, replay, traces_data, \
    traces_reset
from dask_ml_tpu.serving import BucketLadder, ModelServer

X, y = make_classification(n_samples=2_000, n_features=16,
                           n_informative=8, random_state=0)
clf = LogisticRegression(solver="lbfgs", max_iter=25).fit(X, y)
Xh = X.to_numpy().astype(np.float32)

traces_reset()
rng = np.random.RandomState(3)
capture_dir = tempfile.mkdtemp(prefix="req_traces_")

# 1+2) traced ragged traffic into a capture sink; sample the slowest 20%
with config.set(obs_trace_sample=0.2, obs_trace_keep=64,
                trace_dir=capture_dir):
    with ModelServer(clf, methods=("predict", "predict_proba"),
                     ladder=BucketLadder(8, 128, 2.0),
                     batch_window_ms=0.5, timeout_ms=0).warmup() as srv:
        for i in range(60):
            n_rows = int(rng.randint(1, 100))
            lo = int(rng.randint(0, Xh.shape[0] - n_rows))
            if i % 4 == 0:
                srv.predict_proba(Xh[lo:lo + n_rows])
            else:
                srv.predict(Xh[lo:lo + n_rows])

d = traces_data()
counts = d["counts"]
print(f"traced {counts['completed']} requests, tail-sampled "
      f"{counts['sampled']}, captured {counts['captured']}")

slowest = max(d["traces"], key=lambda t: t["e2e_s"])
stages = slowest["stages"]
print(f"slowest sampled trace {slowest['trace_id']:#x} "
      f"({slowest['method']}, {slowest['n_rows']} rows, "
      f"bucket {slowest['bucket']}):")
for name, dur in slowest["durations"].items():
    print(f"  {name:>10}  {dur * 1e6:9.1f} us")
assert abs(sum(slowest["durations"].values())
           - slowest["e2e_s"]) < 1e-5          # stages telescope
qw = d["stage_histograms"]["queue_wait"]
exemplar = next(e for e in reversed(qw["exemplars"]) if e is not None)
print(f"queue_wait histogram: {qw['count']} folds, top occupied "
      f"bucket's exemplar -> trace {exemplar:#x}")

# 3) an SLO violation is always kept, however unremarkable its latency
traces_reset()
with config.set(obs_trace_sample=0.01, serving_slo_ms=0.001):
    with ModelServer(clf, ladder=BucketLadder(8, 128, 2.0)).warmup() as srv:
        srv.predict(Xh[:24])
violated = [t for t in traces_data()["traces"] if t.get("slo_violation")]
assert violated and set(violated[0]["stages"]) == {
    "admit", "queue_pop", "pack", "dispatch", "execute_done", "demux",
    "complete"}
print(f"SLO-violating request kept at p=0.01 with a complete "
      f"breakdown (outcome {violated[0]['outcome']!r})")

# 4) the capture file round-trips into a replayed traffic mix
records = load_capture(os.path.join(capture_dir, "trace.jsonl"))
replayed = []
mix = replay(records, lambda m, n_rows: replayed.append((m, n_rows)),
             speed=1000.0)
assert mix["requests"] == 60 and len(replayed) == 60
print(f"replayed capture: {mix['requests']} requests, {mix['rows']} "
      f"rows, {mix['rate_rps']} req/s (1000x), mix {mix['by_method']}")

traces_reset()
print("request trace plane OK: telescoping stages, exemplar-linked "
      "histograms, always-kept SLO trouble, replayable capture")
