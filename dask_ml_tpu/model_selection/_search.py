"""Drop-in CV search: GridSearchCV / RandomizedSearchCV.

Reference: ``dask_ml/model_selection/_search.py`` + ``methods.py``
(SURVEY.md §2a, §3.4 call stack) — the ex-dask-searchcv engine that builds
ONE task graph for the whole search with two key optimizations:

1. ``CVCache``: each fold's train/test arrays extracted once, shared by
   every parameter combination. Here: folds are materialized once via
   ``take_rows`` (device gather) and reused across candidates.
2. Pipeline prefix sharing: identical (step, params, fold) subtrees get
   identical keys and are computed once. Here: an explicit memo dict keyed
   on (fold, prefix estimator-token chain) caches fitted pipeline
   prefixes AND their transformed output — same de-dup, no task graph
   (SURVEY.md §7: "de-dup via explicit controller memo").

Execution: candidates run as a host loop over jitted fits. Device
estimators share XLA compile cache across candidates (same shapes), which
is the jit-level analog of dask's task de-dup.
"""

from __future__ import annotations

import numbers

import numpy as np
from sklearn.model_selection import ParameterGrid, ParameterSampler

from ..base import BaseEstimator, clone
from ..metrics.scorer import check_scoring
from ..parallel.sharded import ShardedArray, take_rows
from ._normalize import estimator_token
from ._split import KFold


def _is_pipeline(est):
    return hasattr(est, "steps") and hasattr(est, "named_steps")


def check_cv(cv=None):
    if cv is None:
        return KFold(n_splits=5)
    if isinstance(cv, numbers.Integral):
        return KFold(n_splits=int(cv))
    if hasattr(cv, "split"):
        return cv
    raise ValueError(f"cannot interpret cv={cv!r}")


def _take(a, idx):
    if isinstance(a, ShardedArray):
        return take_rows(a, idx)
    return np.asarray(a)[idx]


class _CVCache:
    """Materialized folds, extracted once (ref methods.py::CVCache)."""

    def __init__(self, X, y, cv, cache=True):
        self.folds = []
        for train_idx, test_idx in cv.split(X, y):
            self.folds.append((
                _take(X, train_idx), _take(y, train_idx),
                _take(X, test_idx), _take(y, test_idx),
            ))


class _PrefixMemo:
    """Fitted-pipeline-prefix cache (ref: tokenized graph de-dup)."""

    def __init__(self):
        self._memo = {}
        self.hits = 0
        self.misses = 0

    def fit_pipeline(self, pipe, fold_id, X, y):
        """Fit a pipeline reusing cached fitted prefixes + transformed data."""
        key = (fold_id,)
        Xt = X
        fitted_steps = []
        n = len(pipe.steps)
        for i, (name, step) in enumerate(pipe.steps):
            key = key + (estimator_token(step),)
            last = i == n - 1
            if last:
                # final step fits on the (cached) transformed data
                cached = self._memo.get(key)
                if cached is None:
                    self.misses += 1
                    est = clone(step)
                    est.fit(Xt, y)
                    self._memo[key] = est
                else:
                    self.hits += 1
                    est = cached
                fitted_steps.append((name, est))
            else:
                cached = self._memo.get(key)
                if cached is None:
                    self.misses += 1
                    est = clone(step)
                    if hasattr(est, "fit_transform"):
                        Xt_new = est.fit_transform(Xt, y)
                    else:
                        Xt_new = est.fit(Xt, y).transform(Xt)
                    self._memo[key] = (est, Xt_new)
                else:
                    self.hits += 1
                    est, Xt_new = cached
                Xt = Xt_new
                fitted_steps.append((name, est))
        fitted = clone(pipe)
        fitted.steps = fitted_steps
        return fitted


class _BaseSearchCV(BaseEstimator):
    def __init__(self, estimator, scoring=None, cv=None, refit=True,
                 error_score="raise", return_train_score=False,
                 cache_cv=True, scheduler=None, n_jobs=-1):
        self.estimator = estimator
        self.scoring = scoring
        self.cv = cv
        self.refit = refit
        self.error_score = error_score
        self.return_train_score = return_train_score
        self.cache_cv = cache_cv
        self.scheduler = scheduler
        self.n_jobs = n_jobs

    def _candidates(self):
        raise NotImplementedError

    def fit(self, X, y=None, **fit_params):
        candidates = list(self._candidates())
        if not candidates:
            raise ValueError("no parameter candidates")
        cv = check_cv(self.cv)
        scorer = check_scoring(self.estimator, self.scoring)
        cache = _CVCache(X, y, cv, cache=self.cache_cv)
        memo = _PrefixMemo()
        n_folds = len(cache.folds)

        scores = np.full((len(candidates), n_folds), np.nan)
        train_scores = (
            np.full((len(candidates), n_folds), np.nan)
            if self.return_train_score else None
        )
        for ci, params in enumerate(candidates):
            for fi, (Xtr, ytr, Xte, yte) in enumerate(cache.folds):
                est = clone(self.estimator).set_params(**params)
                try:
                    if _is_pipeline(est):
                        est = memo.fit_pipeline(est, fi, Xtr, ytr)
                    else:
                        est.fit(Xtr, ytr, **fit_params)
                    scores[ci, fi] = scorer(est, Xte, yte)
                    if self.return_train_score:
                        train_scores[ci, fi] = scorer(est, Xtr, ytr)
                except Exception:
                    if self.error_score == "raise":
                        raise
                    scores[ci, fi] = self.error_score

        mean = scores.mean(axis=1)
        std = scores.std(axis=1)
        order = np.argsort(-mean, kind="stable")
        ranks = np.empty(len(candidates), np.int32)
        ranks[order] = np.arange(1, len(candidates) + 1)

        results = {
            "params": candidates,
            "mean_test_score": mean,
            "std_test_score": std,
            "rank_test_score": ranks,
        }
        for fi in range(n_folds):
            results[f"split{fi}_test_score"] = scores[:, fi]
        if self.return_train_score:
            results["mean_train_score"] = train_scores.mean(axis=1)
            results["std_train_score"] = train_scores.std(axis=1)
            for fi in range(n_folds):
                results[f"split{fi}_train_score"] = train_scores[:, fi]
        for key in sorted({k for p in candidates for k in p}):
            results[f"param_{key}"] = np.ma.masked_all(
                len(candidates), dtype=object
            )
            for ci, p in enumerate(candidates):
                if key in p:
                    results[f"param_{key}"][ci] = p[key]
        self.cv_results_ = results
        self.best_index_ = int(np.argmax(mean))
        self.best_score_ = float(mean[self.best_index_])
        self.best_params_ = candidates[self.best_index_]
        self.n_splits_ = n_folds
        self.scorer_ = scorer
        self.multimetric_ = False
        self._memo_stats = (memo.hits, memo.misses)

        if self.refit:
            est = clone(self.estimator).set_params(**self.best_params_)
            est.fit(X, y, **fit_params)
            self.best_estimator_ = est
        return self

    # -- delegation to best_estimator_ ------------------------------------
    def _check_refit(self, method):
        if not self.refit:
            raise AttributeError(
                f"{method} is only available when refit=True"
            )

    def predict(self, X):
        self._check_refit("predict")
        return self.best_estimator_.predict(X)

    def predict_proba(self, X):
        self._check_refit("predict_proba")
        return self.best_estimator_.predict_proba(X)

    def transform(self, X):
        self._check_refit("transform")
        return self.best_estimator_.transform(X)

    def decision_function(self, X):
        self._check_refit("decision_function")
        return self.best_estimator_.decision_function(X)

    def score(self, X, y=None):
        if hasattr(self, "scorer_") and self.scoring is not None:
            return self.scorer_(self.best_estimator_, X, y)
        self._check_refit("score")
        return self.best_estimator_.score(X, y)

    @property
    def classes_(self):
        return self.best_estimator_.classes_


class GridSearchCV(_BaseSearchCV):
    """Ref: dask_ml/model_selection/_search.py::GridSearchCV."""

    def __init__(self, estimator, param_grid, scoring=None, cv=None,
                 refit=True, error_score="raise", return_train_score=False,
                 cache_cv=True, scheduler=None, n_jobs=-1):
        super().__init__(estimator, scoring=scoring, cv=cv, refit=refit,
                         error_score=error_score,
                         return_train_score=return_train_score,
                         cache_cv=cache_cv, scheduler=scheduler,
                         n_jobs=n_jobs)
        self.param_grid = param_grid

    def _candidates(self):
        return ParameterGrid(self.param_grid)


class RandomizedSearchCV(_BaseSearchCV):
    """Ref: dask_ml/model_selection/_search.py::RandomizedSearchCV."""

    def __init__(self, estimator, param_distributions, n_iter=10,
                 random_state=None, scoring=None, cv=None, refit=True,
                 error_score="raise", return_train_score=False,
                 cache_cv=True, scheduler=None, n_jobs=-1):
        super().__init__(estimator, scoring=scoring, cv=cv, refit=refit,
                         error_score=error_score,
                         return_train_score=return_train_score,
                         cache_cv=cache_cv, scheduler=scheduler,
                         n_jobs=n_jobs)
        self.param_distributions = param_distributions
        self.n_iter = n_iter
        self.random_state = random_state

    def _candidates(self):
        return ParameterSampler(self.param_distributions, self.n_iter,
                                random_state=self.random_state)
