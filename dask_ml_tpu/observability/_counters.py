"""Runtime counter/gauge registry.

The signals a perf PR must be able to cite (ROADMAP north star:
hardware-speed hot paths): how many XLA recompiles a run paid, how many
bytes crossed the host↔device boundary, how much buffer reuse the
streamer achieved, and where device memory stands. Counters are a flat
``name -> number`` registry guarded by one lock; spans snapshot it at
open and emit the deltas at close, so every JSONL span record carries
the counters *it* caused.

Gating: ``config.obs_counters`` (env ``DASK_ML_TPU_OBS_COUNTERS``)
switches recording off entirely; the hot-path call sites cost one
config lookup + dict add, and nothing is ever traced into jitted code.

Recompile counting rides ``jax.monitoring``'s
``/jax/core/compile/backend_compile_duration`` event where the
installed jax exposes it; runtimes without ``jax.monitoring`` fall back
to :func:`count_recompiles`, which wraps a jitted entry point (the
``ops/`` jit entries use it) and counts compile-cache growth.
"""

from __future__ import annotations

import functools
import threading

import jax

_lock = threading.Lock()
_counters: dict[str, float] = {}


def counters_enabled() -> bool:
    from ..config import get_config

    return bool(get_config().obs_counters)


def counter_add(name: str, value=1) -> None:
    """Unconditional add — call sites that already paid the enabled()
    check (or tests building fixtures) use this directly."""
    with _lock:
        _counters[name] = _counters.get(name, 0) + value


def counters_snapshot() -> dict:
    with _lock:
        return dict(_counters)


def counters_reset() -> None:
    with _lock:
        _counters.clear()


def record_transfer(nbytes: int, direction: str = "h2d") -> None:
    """One host↔device transfer of ``nbytes`` (the block streamer calls
    this per device_put batch)."""
    if counters_enabled():
        counter_add(f"{direction}_bytes", int(nbytes))
        counter_add(f"{direction}_transfers", 1)


def record_donation(nbytes: int) -> None:
    """A donated buffer was reused in place of a fresh allocation."""
    if counters_enabled():
        counter_add("donated_bytes_reused", int(nbytes))
        counter_add("donated_buffers_reused", 1)


def record_plan_build(cached: bool = False) -> None:
    """One ProgramPlan build: ``plan_builds`` for a fresh tracked jit,
    ``plan_cache_hits`` when the process-wide build cache returned an
    existing entry point (the second client's free warmup)."""
    if counters_enabled():
        counter_add("plan_cache_hits" if cached else "plan_builds", 1)


def record_plan_warmup(hit: bool = False) -> None:
    """One WarmupRegistry event: ``plan_warmups`` for an executed warm
    call, ``plan_cache_hits`` for a skip (the key — and therefore the
    compile it would have minted — was already warm)."""
    if counters_enabled():
        counter_add("plan_cache_hits" if hit else "plan_warmups", 1)


def record_superblock(n_blocks: int) -> None:
    """One super-block dispatch covering ``n_blocks`` real streamed
    blocks — superblock_blocks / superblock_dispatches is the measured
    dispatch amortization (≈K); a pass's dispatches_per_pass lives on
    its ``streaming.superblock`` span record."""
    if counters_enabled():
        counter_add("superblock_dispatches", 1)
        counter_add("superblock_blocks", int(n_blocks))


def record_zero_copy(nbytes: int) -> None:
    """One streamed block staged as a zero-copy ALIAS of host memory
    (dlpack import on XLA:CPU) instead of a device_put copy —
    zero_copy_bytes is host memcpy traffic the staging path did NOT
    pay (the h2d_bytes counter only counts real copies)."""
    if counters_enabled():
        counter_add("zero_copy_bytes", int(nbytes))
        counter_add("zero_copy_blocks", 1)


def record_shard_staging(n_shards: int) -> None:
    """One batch-sharded staging assembly: ``n_shards`` per-shard host
    slabs were placed onto their own devices (ISSUE 9 data-parallel
    streaming) — shard_slab_puts / shard_staging_batches is the
    measured data-axis width of the streamed hot loop."""
    if counters_enabled():
        counter_add("shard_staging_batches", 1)
        counter_add("shard_slab_puts", int(n_shards))


def record_sparse_staging(n_blocks: int, nnz: int) -> None:
    """One bucketed-nnz sparse staging assembly (ISSUE 13): ``n_blocks``
    streamed blocks staged as device-resident COO triples carrying
    ``nnz`` real nonzeros — sparse_nnz_staged / sparse_blocks_staged is
    the measured per-block nnz, and its ratio against h2d_bytes shows
    the densify traffic the sparse path did NOT pay."""
    if counters_enabled():
        counter_add("sparse_blocks_staged", int(n_blocks))
        counter_add("sparse_nnz_staged", int(nnz))


def record_sparse_spill() -> None:
    """One served sparse batch whose nnz exceeded the warmed nnz-bucket
    ladder's top rung and spilled to the densified dense entry point
    (still zero new compiles — the dense (rows) bucket is warm)."""
    if counters_enabled():
        counter_add("serving_sparse_spills", 1)


def record_gspmd_reduce(nbytes: int) -> None:
    """Estimated cross-device reduce payload one implicit-GSPMD
    dispatch moved (today: the sharded streamed-ADMM block-local
    Newton, whose per-iteration Hessian/gradient partial sums XLA
    all-reduces over the row shards — ROADMAP 1(c)'s previously
    unmeasured traffic). An ANALYTIC payload estimate, not a NIC
    counter: it sizes what must cross the mesh at least once; with
    obs_programs on, the matching ``...admm_local.gspmd`` program row
    carries XLA's own measured bytes beside it."""
    if counters_enabled():
        counter_add("gspmd_reduce_bytes", int(nbytes))
        counter_add("gspmd_reduce_dispatches", 1)


def record_superblock_donation(nbytes: int) -> None:
    """A super-block scan's donated carry was handed back to XLA for
    in-place reuse (the accumulator/weights buffer never reallocates
    across the pass's dispatches)."""
    if counters_enabled():
        counter_add("superblock_donated_bytes", int(nbytes))
        counter_add("superblock_donations", 1)


# -- serving -----------------------------------------------------------------
# the online-inference registry slice (dask_ml_tpu/serving): admitted
# work, batching efficiency, and backpressure outcomes. Kept here so the
# report CLI and span counter-deltas see serving exactly like the fit
# counters.

_SERVING_DROP_COUNTERS = {
    "shed": "serving_shed",          # admission control refused entry
    "timeout": "serving_timeouts",   # deadline passed while queued
    "error": "serving_errors",       # batch execution raised
    "slo_shed": "serving_slo_shed",  # SLO admission predicted a miss
}


def record_serving_request(n_rows: int) -> None:
    """One admitted serving request of ``n_rows`` rows."""
    if counters_enabled():
        counter_add("serving_requests", 1)
        counter_add("serving_rows", int(n_rows))


def record_serving_batch(rows: int, bucket: int) -> None:
    """One executed micro-batch: ``rows`` real rows padded to the
    ``bucket`` rung — padding waste accumulates as serving_padded_rows /
    (serving_rows + serving_padded_rows)."""
    if counters_enabled():
        counter_add("serving_batches", 1)
        counter_add("serving_padded_rows", int(bucket - rows))


def record_serving_drop(kind: str) -> None:
    """A request resolved without a result; ``kind`` in
    {'shed', 'timeout', 'error'}."""
    if counters_enabled():
        counter_add(_SERVING_DROP_COUNTERS[kind], 1)


def record_serving_swap(rebuilt: bool = False) -> None:
    """One model hot-swap applied to a serving entry-point set.
    ``rebuilt=True`` marks the slow path — the new version's shapes did
    not match, so the entry points were recompiled instead of swapped
    (the zero-recompile contract intentionally does not cover it)."""
    if counters_enabled():
        counter_add("serving_swaps", 1)
        if rebuilt:
            counter_add("serving_swap_rebuilds", 1)


def record_serving_reroute() -> None:
    """A fleet request was rerouted off a failed/closed replica onto a
    surviving one."""
    if counters_enabled():
        counter_add("serving_reroutes", 1)


def record_registry_publish(rollback: bool = False) -> None:
    """One model version published to (or rolled back in) a
    ModelRegistry."""
    if counters_enabled():
        counter_add("registry_publishes", 1)
        if rollback:
            counter_add("registry_rollbacks", 1)


def record_drift_alert() -> None:
    """A drift score (train-vs-serve / window PSI, or a canary delta)
    crossed ``config.obs_drift_threshold`` — latched once per
    below→above crossing by the drift engine. The quality-plane burn
    signal a scraper alerts on (``dask_ml_tpu_drift_alerts_total``)."""
    if counters_enabled():
        counter_add("drift_alerts", 1)


def record_telemetry_series_dropped() -> None:
    """The live metric registry refused a NEW labeled series past
    ``config.obs_max_series`` (cardinality guard) — visible as
    ``telemetry_series_dropped_total``."""
    if counters_enabled():
        counter_add("telemetry_series_dropped", 1)


# -- reliability / chaos plane (dask_ml_tpu/reliability/) --------------------

def record_fault_injected(site: str, kind: str) -> None:
    """One armed fault fired at a named site (config.fault_plan) —
    ``faults_injected`` totals plus a per-site breakdown so a chaos
    run's /metrics shows WHERE the plan struck."""
    if counters_enabled():
        counter_add("faults_injected", 1)
        counter_add(f"faults_injected_{site}", 1)


def record_stream_retry() -> None:
    """One staging/reader IO failure absorbed by the bounded-backoff
    retry (config.stream_io_retries) — ``stream_retries_total`` on
    /metrics is the transient-IO burn signal."""
    if counters_enabled():
        counter_add("stream_retries", 1)


def record_stream_quarantine() -> None:
    """One streamed block quarantined by the non-finite policy
    (config.stream_nonfinite="quarantine"): its data zeroed and its
    valid-row count folded to 0 by the existing prefix-count mask."""
    if counters_enabled():
        counter_add("stream_quarantined_blocks", 1)


def record_stream_checkpoint(resume: bool = False) -> None:
    """One pass-granular stream checkpoint saved — or, with
    ``resume=True``, a killed streamed fit restored from one
    (``stream_resumes``)."""
    if counters_enabled():
        counter_add("stream_resumes" if resume
                    else "stream_checkpoint_saves", 1)


def record_replica_restart() -> None:
    """The replica supervisor rebuilt a dead fleet replica (fresh
    server at the registry's current version, warmed off the serving
    path, rejoined routing)."""
    if counters_enabled():
        counter_add("serving_replica_restarts", 1)


def record_replica_failure() -> None:
    """A replica exceeded its restart budget and degraded to permanent
    failover — the page-an-operator signal."""
    if counters_enabled():
        counter_add("serving_replica_failures", 1)


def record_scale_up() -> None:
    """The autoscaler ADDED a replica (SLO headroom predicted a miss
    under the up-band for the configured patience) — live /metrics:
    ``dask_ml_tpu_serving_scale_ups_total`` beside the
    ``serving_replicas`` gauge."""
    if counters_enabled():
        counter_add("serving_scale_ups", 1)


def record_scale_down() -> None:
    """The autoscaler RETIRED a replica (sustained headroom under the
    down-band); the victim drained gracefully and its gauge series were
    dropped."""
    if counters_enabled():
        counter_add("serving_scale_downs", 1)


def record_process_reroute() -> None:
    """The federation router re-issued a request on a different fleet
    PROCESS after its first choice died/refused mid-flight — the
    cross-process twin of ``serving_reroutes``."""
    if counters_enabled():
        counter_add("serving_process_reroutes", 1)


def record_process_failover() -> None:
    """The federation router marked a whole fleet process DOWN
    (connection refused / status poll dead) and stopped routing to it
    until it answers again."""
    if counters_enabled():
        counter_add("serving_process_failovers", 1)


def record_federation_publish() -> None:
    """One registry publish fanned out across the federation boundary
    (origin registry -> every remote fleet process)."""
    if counters_enabled():
        counter_add("federation_publishes", 1)


def record_serving_slo_violation() -> None:
    """A served request's end-to-end latency exceeded the configured
    ``serving_slo_ms`` — the request still SUCCEEDED (unlike the drop
    counters above); this is the SLO burn signal a scraper alerts on
    (live /metrics: ``dask_ml_tpu_serving_slo_violations_total``)."""
    if counters_enabled():
        counter_add("serving_slo_violations", 1)


# -- recompile tracking ------------------------------------------------------

_recompile_listener_installed = False


def _on_compile_duration(name, secs, **kw):
    # one backend_compile per (function, shape) specialization — exactly
    # the "how many recompiles did this run pay" signal
    if name.endswith("backend_compile_duration") and counters_enabled():
        counter_add("recompiles", 1)
        counter_add("compile_secs", float(secs))


def install_recompile_tracking() -> bool:
    """Register the jax.monitoring compile listener (idempotent).
    Returns False on jax builds without the monitoring API — callers
    then keep :func:`count_recompiles` wrappers live instead."""
    global _recompile_listener_installed
    if _recompile_listener_installed:
        return True
    try:
        from jax import monitoring

        monitoring.register_event_duration_secs_listener(
            _on_compile_duration
        )
        _recompile_listener_installed = True
        return True
    except Exception:
        return False


def count_recompiles(fn):
    """Fallback recompile counter for jitted entry points when
    ``jax.monitoring`` is unavailable: wrap the jitted callable and count
    compile-cache growth per call. Identity when the listener installed —
    the wrapper would double-count."""
    if install_recompile_tracking():
        return fn
    if not hasattr(fn, "_cache_size"):  # not a jitted callable
        return fn

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        before = fn._cache_size()
        out = fn(*args, **kwargs)
        grew = fn._cache_size() - before
        if grew > 0 and counters_enabled():
            counter_add("recompiles", grew)
        return out

    wrapped.__wrapped_jit__ = fn
    return wrapped


# -- gauges ------------------------------------------------------------------

def device_memory_gauges() -> dict:
    """Per-device memory stats as a flat gauge dict (empty on backends
    that report none — CPU). Polled, not accumulated: emit via
    :func:`log_counters` or a span ``add`` when a footprint snapshot
    matters."""
    out = {}
    for dev in jax.local_devices():
        try:
            stats = dev.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
            if key in stats:
                out[f"dev{dev.id}_{key}"] = int(stats[key])
    return out


def log_counters(logger, **extra) -> dict:
    """Emit one JSONL record holding the current counter snapshot plus
    device memory gauges; returns the snapshot. The report CLI reads the
    LAST such record as the run's totals."""
    snap = counters_snapshot()
    if logger is not None:
        logger.log(counters=True, **snap, **device_memory_gauges(),
                   **extra)
    return snap
