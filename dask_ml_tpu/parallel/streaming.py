"""Host→device block streaming for larger-than-HBM datasets.

Reference equivalent: dask's chunk scheduling — blocks materialize on
workers as tasks run (SURVEY.md §2b row 1). TPU design (SURVEY.md §7
design stance #1, "the heart of the system"): the working set lives in
host RAM (numpy / np.memmap); fixed-shape blocks are placed onto the mesh
with ``jax.device_put`` AHEAD of compute (device_put is async — issuing
the next transfer before consuming the current block overlaps DMA with
compute, the double-buffer pattern). A consumed block's HBM is released
when its Python reference drops at the next loop iteration, so peak
footprint is ≈ (prefetch + 1) blocks.

Blocks have a fixed padded shape (static shapes for jit); the final
partial block carries its logical row count and a mask.
"""

from __future__ import annotations

import numpy as np
import scipy.sparse as sp

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .mesh import (
    DATA_AXIS, MODEL_AXIS, data_shards, mesh_str, model_shards,
    resolve_mesh,
)


class SparseBlocks:
    """Row-concatenated view over a list of scipy sparse (CSR) blocks —
    the shape a blocked vectorizer naturally produces — WITHOUT the
    ``sp.vstack`` copy. Only supports what streaming needs: ``shape``,
    ``dtype`` and contiguous row-range densification.

    Ref: dask_ml/feature_extraction/text.py produces a dask array of CSR
    chunks; this is its host-side analog feeding BlockStream.
    """

    def __init__(self, blocks):
        blocks = [b.tocsr() if not sp.isspmatrix_csr(b) else b
                  for b in blocks]
        if not blocks:
            raise ValueError("SparseBlocks needs at least one block")
        d = blocks[0].shape[1]
        for b in blocks:
            if b.shape[1] != d:
                raise ValueError("blocks have inconsistent widths")
        self.blocks = blocks
        self.offsets = np.cumsum([0] + [b.shape[0] for b in blocks])
        self.shape = (int(self.offsets[-1]), d)
        self.dtype = blocks[0].dtype
        self.ndim = 2

    def tocsr(self):
        """Materialize as one CSR (O(nnz)) — for host consumers that
        need arbitrary row slicing (e.g. host-estimator block loops)."""
        return sp.vstack(self.blocks).tocsr()

    def slice_dense(self, lo, hi, dtype=np.float32):
        """Densify rows [lo, hi) — touches only the blocks they span."""
        if hi <= lo:
            return np.empty((0, self.shape[1]), dtype)
        i = int(np.searchsorted(self.offsets, lo, side="right") - 1)
        parts = []
        while lo < hi and i < len(self.blocks):
            b_lo, b_hi = self.offsets[i], self.offsets[i + 1]
            take = min(hi, b_hi) - lo
            parts.append(
                _csr_dense(self.blocks[i], lo - b_lo, lo - b_lo + take,
                           dtype)
            )
            lo += take
            i += 1
        return parts[0] if len(parts) == 1 \
            else np.concatenate(parts, axis=0)


def _is_sparse_source(a) -> bool:
    return sp.issparse(a) or isinstance(a, SparseBlocks)


def _n_rows_of(a) -> int:
    # len() raises on scipy sparse ("length is ambiguous")
    return int(a.shape[0]) if _is_sparse_source(a) else len(a)


def _csr_dense(a, lo, hi, dtype):
    """Densify CSR rows [lo, hi) straight into ``dtype`` — casting the
    nnz values first, so the transient is ONE dense block, not a
    float64 block plus its cast copy."""
    blk = a[lo:hi]
    if blk.dtype != dtype:
        blk = blk.astype(dtype)
    return blk.toarray()


def as_row_sliceable(a):
    """Normalize a sparse source to a row-sliceable form (CSR) ONCE —
    call this before a loop of ``_slice_dense`` calls; ``tocsr()`` is
    identity for CSR but O(nnz) for COO/CSC/BSR."""
    return a.tocsr() if sp.issparse(a) and not sp.isspmatrix_csr(a) else a


def as_row_indexable(a):
    """Normalize a sparse source to a form supporting fancy ROW
    indexing (``a[idx_array]``): scipy sparse → CSR; the
    ``SparseBlocks`` view (which only supports contiguous-range
    densify) materializes as one CSR. The single normalization point
    behind the search/split fold-extraction paths — sparse folds stay
    sparse, never densified."""
    a = as_row_sliceable(a)
    return a.tocsr() if isinstance(a, SparseBlocks) else a


def _slice_dense(a, lo, hi, dtype):
    """One host block of ``a`` as a dense array — the single densify
    point for sparse sources (O(block) host memory, never the corpus).
    Non-CSR sparse is converted defensively (COO/BSR cannot row-slice);
    loops should pre-normalize with ``as_row_sliceable``."""
    if isinstance(a, SparseBlocks):
        return a.slice_dense(lo, hi, dtype)
    if sp.issparse(a):
        return _csr_dense(a.tocsr(), lo, hi, dtype)
    return np.asarray(a[lo:hi], dtype=dtype)


class StreamBudgetExceeded(ValueError):
    """A streamed fit's PER-DEVICE staged super-block slab exceeds the
    simulated ``config.stream_device_byte_budget`` — the typed refusal
    (sibling of ``DenseBudgetExceeded``) that stands in for a real
    per-chip HBM OOM on CPU. The fix is a mesh with more shards on the
    axis that's over budget: a wide-d fit that a 1-D data mesh refuses
    fits once ``config.mesh_shape`` adds a model axis (X slabs then
    stage as (rows/D, d/M) tiles — per-device bytes flat in d)."""


class Block:
    """One streamed block: device data + logical row count."""

    __slots__ = ("arrays", "n_rows", "mask")

    def __init__(self, arrays, n_rows, mask):
        self.arrays = arrays
        self.n_rows = n_rows
        self.mask = mask


class SuperBlock:
    """K stacked streamed blocks: ONE dispatch's worth of data.

    ``arrays[i]`` is the stream's i-th array as a device
    ``(K, block_rows, ...)`` stack — or, in the CPU layout, a K-tuple
    of ``(block_rows, ...)`` device blocks (see ``superblock_unrolled``)
    — and ``counts`` the device ``(K,)`` int32 valid-row counts (a
    consumer derives each step's prefix mask from them). The FINAL
    super-block of a pass is padded to the same K — missing block slots
    carry ``counts == 0`` and all-zero data, so every dispatch compiles
    once — and ``n_blocks`` says how many slots are real. ``n_rows`` is
    the super-block's total valid rows.

    On a >1-device stream mesh (ISSUE 9) every array is BATCH-SHARDED
    over the mesh's "data" axis (each device owns a contiguous
    ``block_rows / D`` row slab of every block) and ``shard_counts``
    holds the device ``(D, K)`` per-shard valid-row counts — row ``s``
    lives on shard ``s``'s device, so a shard_map consumer reads its
    own ragged-tail counts locally (a block's trailing shards see 0).
    ``shard_counts`` is None on a single-device mesh."""

    __slots__ = ("arrays", "counts", "n_blocks", "n_rows",
                 "shard_counts")

    def __init__(self, arrays, counts, n_blocks, n_rows,
                 shard_counts=None):
        self.arrays = arrays
        self.counts = counts
        self.n_blocks = n_blocks
        self.n_rows = n_rows
        self.shard_counts = shard_counts


# XLA:CPU's dlpack import aliases host memory (zero-copy) only at
# >=64-byte alignment; below it the runtime silently copies — correct
# but pointless, so misaligned blocks keep the plain device_put path
_ZC_ALIGN = 64


def _dlpack_alias(a):
    """Import one host block into the runtime as a zero-copy ALIAS of
    its memory (XLA:CPU dlpack), or None when the import cannot be
    zero-copy (alignment / layout) or fails — callers then device_put a
    copy as before.

    Safety contract (why aliasing host memory is sound here): streamed
    data blocks are only ever READ by the consumers (input buffers are
    immutable to XLA unless donated, and no streamed kernel donates its
    data operands — only accumulator/weight carries), the block is
    either a view of a source array the stream holds alive for its own
    lifetime or a freshly allocated buffer the returned array's dlpack
    capsule keeps alive, and staging-ring slabs (which ARE refilled)
    never take this path. ``config.stream_zero_copy`` opts out for
    callers that mutate the source mid-fit."""
    if (a.ctypes.data % _ZC_ALIGN) or not a.flags["C_CONTIGUOUS"] \
            or a.nbytes == 0:
        return None
    try:
        if not a.flags.writeable:
            # numpy refuses dlpack export of readonly arrays (e.g.
            # mode="r" memmaps). XLA only reads the buffer, so re-wrap
            # the same memory writeable for the export alone. The
            # ctypes buffer owns NOTHING (from_address) — pin the
            # original view on it so the capsule chain
            # (jax.Array -> wrapper -> ctypes buf -> view -> mmap)
            # keeps the mapping alive for as long as the device array
            # exists, even if the caller drops the source mid-pass.
            import ctypes

            buf = (ctypes.c_byte * a.nbytes).from_address(a.ctypes.data)
            buf._keepalive = a
            src = np.frombuffer(buf, dtype=a.dtype).reshape(a.shape)
        else:
            src = a
        from jax import dlpack as _jdl

        return _jdl.from_dlpack(src)
    except Exception:
        return None


_PUT_ALIASES = None


def _device_put_aliases() -> bool:
    """One-time semantic probe: does this backend's ``device_put``
    alias (zero-copy) host numpy memory? Every backend in CI copies —
    but if one ever aliases, a reused staging buffer would be mutated
    under a still-queued consumer computation (block_until_ready only
    covers the transfer, not later reads of an aliased buffer), so the
    super-block ring switches to fresh per-super-block buffers there.
    The probe is the direct hazard: mutate the source after the put and
    see whether the device array changed."""
    global _PUT_ALIASES
    if _PUT_ALIASES is None:
        try:
            probe = np.zeros(8, np.float32)
            dev = jax.block_until_ready(jax.device_put(probe))
            probe[:] = 1.0
            _PUT_ALIASES = bool(float(np.asarray(dev)[0]) == 1.0)
        except Exception:
            _PUT_ALIASES = True  # cannot prove safety: assume aliasing
    return _PUT_ALIASES


def superblock_unrolled() -> bool:
    """Which super-block layout this backend wants. TPU/GPU: ONE
    stacked [K, block_rows, d] buffer consumed by a lax.scan — one DMA
    per super-block, and HBM scan slices are effectively free. XLA:CPU
    lowers each scan step's dynamic-slice of the stacked operand as a
    block-sized memcpy (measured ~2x the whole step's compute) and a
    stacked device_put as one single-threaded copy — there the executor
    keeps K separate block buffers (put as one pytree: transfers run
    concurrently, and full blocks stage as VIEWS with no host copy) and
    the kernels unroll the K-step chain inside the same single
    dispatch. Same math, same dispatch count, per-backend layout."""
    return jax.default_backend() == "cpu"


# auto block budget: bytes of ONE block's X on device. Fixed bytes (not a
# fraction of n) so an arbitrarily large memmap still streams in
# HBM-bounded blocks; peak device footprint ≈ (prefetch + 1) blocks.
_AUTO_BLOCK_BYTES = 256 << 20

# byte budget of ONE super-block (K stacked blocks) on device: caps the
# auto K and the K autotuner so super-blocking never defeats the HBM
# bound the per-block budget establishes (peak ≈ (prefetch + 1)
# super-blocks while a pass is in flight)
_SUPERBLOCK_BYTES = 512 << 20

# training-profile sample budget in VALUES (rows x features): the
# first-pass fold must stay a rounding error next to the pass compute
# at ANY design width
_PROFILE_VALUE_BUDGET = 1 << 20

# widest feature count the training profile sketches: past this the
# per-feature histogram matrix (d x ~80 int64 buckets) and the fold's
# O(block x d) temporaries stop being "free on the staging path" —
# wide/hashed feature spaces are served by the serving-side sketches'
# own cap instead
_PROFILE_MAX_FEATURES = 1024

# auto K: dispatch amortization saturates quickly — 8 blocks per
# dispatch removes ~7/8 of the per-block launch+sync overhead; beyond
# that the stacked buffer's footprint grows for single-digit-% returns
_AUTO_SUPERBLOCK_K = 8


def auto_block_rows(n_rows: int, row_bytes: int = 4) -> int:
    """Block size from config: ``stream_block_rows`` if set, else an
    HBM byte budget divided by the bytes-per-row of the streamed data."""
    from ..config import get_config

    br = get_config().stream_block_rows
    if br and br > 0:
        return int(br)
    return max(_AUTO_BLOCK_BYTES // max(int(row_bytes), 1), 1)


def grid_partition(n_pad: int, D: int) -> tuple[int, int]:
    """(n_blocks B, rows-per-block S) for ``n_pad`` rows on a D-way data
    axis: at least max(D, 8) blocks — the epoch must yield multiple
    minibatch steps even on a 1-device mesh (a D-only split would
    collapse a single-chip host fit to ONE gradient step per epoch) —
    with S rounded up to a multiple of D so a (B, S, d) block grid's row
    axis shards evenly. The one partition formula behind the fused-epoch
    grid, the Incremental wrapper's block loops, and the SGD host fit —
    device- and host-input fits of the same data train identical
    minibatches."""
    n_pad = max(n_pad, 1)
    target = max(D, 8)
    s = -(-n_pad // target)
    S = max(-(-s // D) * D, 1)
    return -(-n_pad // S), S


def resolve_stream_mesh(mesh=None):
    """The mesh a host-streamed fit runs over: an explicit ``mesh``
    wins; under a live multi-process runtime blocks are PROCESS-LOCAL
    data (they shard over this process's devices only — a global-mesh
    device_put asserts value equality across processes, and the
    cross-process merge is the consumer's explicit psum_host); else
    ``config.stream_mesh`` x ``config.mesh_shape`` pick the local
    device set and its 1-D/2-D shape (see ``mesh.stream_data_mesh`` —
    "Dx1" collapses to the plain 1-D mesh, "DxM" gives the 2-D
    ("data", "model") mesh). The ONE resolution point shared by
    ``BlockStream`` and ``fit_block_rows`` so block partitions,
    staging shardings and the lru'd scan-program mesh keys always
    agree — every BlockStream of a fit sees the SAME Mesh object."""
    if mesh is not None:
        return mesh
    from . import distributed as dist

    if dist.process_count() > 1:
        local = dist.local_mesh()
        from ..config import get_config

        n = int(get_config().stream_mesh)
        if n <= 0 or n >= local.devices.size:
            return local
        # config.stream_mesh still applies per process: N restricts to
        # the first N LOCAL devices, and stream_mesh=1 remains the
        # documented single-device escape hatch (the sharded flavor
        # never engages) even under a live multi-host runtime — the
        # exact environment where an un-validated path most needs an
        # opt-out
        from .mesh import device_mesh

        return device_mesh(devices=list(local.devices.flat)[:n])
    from .mesh import stream_data_mesh

    return stream_data_mesh()


def fit_block_rows(X, mesh=None) -> int:
    """Rows per block for an epoch-style fit over host data: the
    ``grid_partition`` size for the resolved mesh, capped by
    ``stream_plan``'s byte budget when X is a source that must stream in
    bounded dense blocks (sparse, memmap, configured block rows) — the
    ONE block-size policy shared by the SGD fit loop and
    ``Incremental._block_size``."""
    n = int(X.shape[0]) if hasattr(X, "shape") else len(X)
    D = max(data_shards(resolve_stream_mesh(mesh)), 1)
    S = max(grid_partition(-(-max(n, 1) // D) * D, D)[1], 1)
    budget = stream_plan(X)
    return S if budget is None else max(min(S, budget), 1)


def stream_plan(X) -> int | None:
    """Rows-per-block when ``X`` should be fitted out-of-core, else None.

    Streams when X is host-resident and either (a) an ``np.memmap`` —
    its backing file may exceed host AND device memory, so it must never
    be materialized whole — or (b) larger than a configured
    ``config.stream_block_rows``. Device-resident inputs (ShardedArray /
    jax.Array) always take the resident path.
    """
    from ..config import get_config

    if _is_sparse_source(X):
        # sparse ALWAYS streams: the device representation is dense, so
        # the only scalable bridge is one densified block at a time
        # (VERDICT r4 missing #2; ref text.py CSR chunks → per-block fit)
        n = X.shape[0]
        if n == 0:
            return None
        row_bytes = 4 * int(np.prod(X.shape[1:], dtype=np.int64) or 1)
        return min(auto_block_rows(n, row_bytes), n)
    if not isinstance(X, np.ndarray) or isinstance(X, np.generic):
        return None
    n = X.shape[0] if X.ndim else 0
    if n == 0:
        return None
    if isinstance(X, np.memmap):
        # blocks stream as float32 regardless of the memmap dtype
        row_bytes = 4 * int(np.prod(X.shape[1:], dtype=np.int64) or 1)
        return min(auto_block_rows(n, row_bytes), n)
    br = get_config().stream_block_rows
    if br and 0 < br < n:
        return br
    return None


class BlockStream:
    """Prefetched epoch iterator over host arrays.

    Parameters
    ----------
    arrays : tuple of host arrays (np.ndarray / np.memmap), equal length.
    block_rows : rows per block (rounded up to a multiple of the mesh's
        data-axis size); None reads ``config.stream_block_rows``, falling
        back to an HBM byte budget divided by the arrays' combined
        bytes-per-row.
    shuffle : shuffle block order each epoch (the reference's
        ``shuffle_blocks``); rows within a block keep locality.
    prefetch : transfers kept in flight ahead of compute (1 = classic
        double buffering); None reads ``config.stream_prefetch``.
    """

    def __init__(self, arrays, block_rows=None, mesh=None, shuffle=False,
                 seed=None, dtype=np.float32, prefetch=None,
                 profile=True, nonfinite=None):
        # stream_mesh / multi-process resolution lives in ONE place so
        # the data-parallel superblock flavor, the block partition and
        # the staging shardings can never disagree
        self.mesh = resolve_stream_mesh(mesh)
        # sparse sources normalize to CSR once: COO/BSR don't support
        # row slicing at all and CSC slices rows in O(nnz)
        self.arrays = tuple(
            a.tocsr() if sp.issparse(a) and not sp.isspmatrix_csr(a)
            else a
            for a in arrays
        )
        n = _n_rows_of(self.arrays[0])
        for a in self.arrays:
            if _n_rows_of(a) != n:
                raise ValueError("arrays have inconsistent lengths")
        self.n_rows = n
        # dense bytes-per-row of everything this stream puts on device —
        # sizes the auto block AND caps autotune growth at the same
        # byte budget (growth must not defeat the HBM bound)
        self._row_bytes = sum(
            4 * int(np.prod(a.shape[1:], dtype=np.int64) or 1)
            for a in self.arrays
        )
        if block_rows is None:
            block_rows = min(auto_block_rows(n, self._row_bytes), n)
        if prefetch is None:
            from ..config import get_config

            prefetch = get_config().stream_prefetch
        self.prefetch = max(int(prefetch), 1)
        shards = data_shards(self.mesh)
        self.block_rows = max(
            int(np.ceil(block_rows / shards)) * shards, shards
        )
        self.shuffle = shuffle
        self.rng = np.random.RandomState(seed)
        self.dtype = dtype
        self.n_blocks = int(np.ceil(n / self.block_rows))
        # 2-D mesh feature tiling (logical-axis rules, mesh.py): on a
        # ("data", "model") mesh ONLY the X position (arrays[0], dense,
        # ndim >= 2, d divisible by M — shard_map needs even tiles)
        # stages as (rows/D, d/M) per-device tiles; y/aux/masks and the
        # per-shard valid-row counts stay data-only (counts replicate
        # over "model" for free via P("data", None)). A non-tileable X
        # records the reason and stages data-only — the 1-D sharded
        # programs stay correct on a 2-D mesh (their specs name only
        # "data", so compute is model-replicated).
        m_shards = model_shards(self.mesh)
        self.model_tiled = False
        self.model_tile_reason = None
        if m_shards > 1:
            a0 = self.arrays[0]
            d0_tile = getattr(a0, "shape", (0,))[1] if getattr(
                a0, "ndim", 1) >= 2 else 0
            if _is_sparse_source(a0):
                self.model_tile_reason = "sparse-source"
            elif getattr(a0, "ndim", 1) != 2:
                self.model_tile_reason = "x-not-2d"
            elif d0_tile % m_shards:
                self.model_tile_reason = (
                    f"d-not-divisible({d0_tile}%{m_shards})"
                )
            else:
                self.model_tiled = True

        def _feat(i, a):
            return (MODEL_AXIS if i == 0 and self.model_tiled
                    else None,) + (None,) * (a.ndim - 2) \
                if a.ndim >= 2 else ()

        self._shardings = tuple(
            NamedSharding(self.mesh, P(*((DATA_AXIS,) + _feat(i, a))))
            for i, a in enumerate(self.arrays)
        )
        self._mask_sharding = NamedSharding(self.mesh, P(DATA_AXIS))
        # super-block stacks shard their ROW axis (axis 1); the block
        # axis is the scan axis and stays unsharded
        self._sb_shardings = tuple(
            NamedSharding(self.mesh,
                          P(*((None, DATA_AXIS) + _feat(i, a))))
            for i, a in enumerate(self.arrays)
        )
        self._counts_sharding = NamedSharding(self.mesh, P())
        # per-shard valid-row counts of the sharded superblock flavor:
        # a (D, K) matrix whose row s lives on shard s's device
        self._shard_counts_sharding = NamedSharding(
            self.mesh, P(DATA_AXIS, None)
        )
        # set by the K autotuner — and by the adaptive-search cohort
        # plane (ISSUE 14), which wants finer dispatch granularity than
        # a plain fit so each dispatch's slot RUNG can track the live
        # bracket instead of the round's widest moment
        self._superblock_k_override = None
        # device-resident sparse staging (ISSUE 13): when opted in
        # (config.stream_sparse) and the source stays under the density
        # threshold, a sparse X streams as bucketed-nnz COO triples
        # through _superblocks_sparse instead of densifying per block.
        # The plan (capacities, per-block nnz rungs, fallback reason)
        # is built ONCE here from indptr alone
        self.sparse_plan = None
        self.sparse_reason = None
        if any(_is_sparse_source(a) for a in self.arrays):
            from ..config import get_config as _gc

            _cfg = _gc()
            if not _cfg.stream_sparse:
                self.sparse_reason = "stream-sparse-off"
            elif not _is_sparse_source(self.arrays[0]) or any(
                    _is_sparse_source(a) for a in self.arrays[1:]):
                # only the X position streams sparse; sparse targets
                # have no kernel story
                self.sparse_reason = "sparse-operand-layout"
            else:
                from .sparse_stream import plan_sparse_stream

                plan = plan_sparse_stream(
                    self.arrays[0], self.block_rows,
                    data_shards(self.mesh),
                    float(_cfg.stream_sparse_max_density),
                )
                self.sparse_reason = plan.reason
                if plan.engaged:
                    self.sparse_plan = plan
        from ..config import ensure_compile_cache, get_config
        from ..observability.live import ensure_telemetry

        # reliability plane (ISSUE 11), captured once like _zero_copy:
        # bounded-backoff IO retry budget, the non-finite block policy,
        # and whether any fault plan is armed (the zero-overhead gate
        # for the staging-read fault site on the zero-copy view path)
        cfg_rel = get_config()
        self._io_retries = max(int(cfg_rel.stream_io_retries), 0)
        nf = (cfg_rel.stream_nonfinite if nonfinite is None
              else nonfinite)
        if nf not in ("off", "raise", "quarantine"):
            raise ValueError(
                f"stream_nonfinite={nf!r} is not supported; accepted: "
                "'off', 'raise', 'quarantine'"
            )
        self._nonfinite = nf
        # the plan SPEC is captured (not re-read per site): super-block
        # staging runs on a worker thread whose thread-local config does
        # not carry the creator's config.set overrides
        self._fault_spec = cfg_rel.fault_plan
        self._fault_armed = bool(self._fault_spec)

        # zero-copy staging (config.stream_zero_copy): on a
        # single-device XLA:CPU mesh, full-height aligned dense blocks
        # import as dlpack ALIASES of host memory instead of paying a
        # device_put memcpy — see _dlpack_alias for the safety
        # contract. Multi-device meshes keep the sharded put (an
        # aliased import is single-device), other backends have real
        # device memory to copy into.
        # ... and the one device must BE the process default device: a
        # dlpack import always lands on jax.devices()[0], so a stream
        # pinned to any other device (a virtual rank's submesh) would
        # stage its aliases onto the wrong chip
        self._zero_copy = bool(
            get_config().stream_zero_copy
            and jax.default_backend() == "cpu"
            and self.mesh.devices.size == 1
            and self.mesh.devices.flat[0] == jax.devices()[0]
        )

        # per-feature training profile (observability/sketch.py): the
        # staging path folds a strided row sample of the FIRST pass's
        # host slabs — pure numpy on buffers already in hand, so it can
        # never add a device sync or touch a jaxpr. Consumers attach the
        # snapshot to the fitted estimator (training_profile_); serving
        # scores live traffic against it (drift.py). `profile=False`
        # opts inference streams (streamed_map) out — a predict stream's
        # distribution is not a training profile.
        self.profile = None
        # WIDE sparse sources opt out: a hashed-text corpus is 2**16+
        # wide, and a per-feature sketch there is O(d * buckets) memory
        # (tens of MB) on a path whose whole point is O(block)
        # footprint. NARROW sparse (d <= _PROFILE_MAX_FEATURES) folds a
        # densified strided sample under the same per-VALUE budget as
        # dense streams — drift monitoring works on sparse fits that
        # can afford it, and the opt-out reason is on record
        sparse_src = any(_is_sparse_source(a) for a in self.arrays)
        d_prof = int(np.prod(
            getattr(self.arrays[0], "shape", (0, 1))[1:], dtype=np.int64
        ) or 1)
        self.profile_reason = None
        if sparse_src and d_prof > _PROFILE_MAX_FEATURES:
            self.profile_reason = f"sparse-wide(d={d_prof})"
        self._profile_enabled = bool(
            profile and get_config().obs_drift
            and self.profile_reason is None
        )
        # VALUE budget for the profile sample: bounds the fold cost per
        # fit regardless of dataset size AND width (the profile is a
        # uniform strided sample either way). A row budget alone let
        # wide designs blow the first-pass fold up proportionally to d
        # (d=128 folded 7.3M values, ~0.5s on the staging worker's
        # critical path — measured as a streamed-SGD throughput
        # regression); a value budget keeps the fold ~0.1s at any
        # width. 1M values = the old 64k rows at d=16.
        d0 = int(np.prod(
            getattr(self.arrays[0], "shape", (0, 1))[1:], dtype=np.int64
        ) or 1)
        budget_rows = max(_PROFILE_VALUE_BUDGET // max(d0, 1), 1024)
        self._profile_stride = max(
            int(np.ceil(self.n_rows / budget_rows)), 1
        )

        # streamed fits are the repeated-warmup-compile hot spot the
        # persistent compile cache exists for; apply the knob (no-op
        # when config.compile_cache_dir is unset)
        ensure_compile_cache()
        # ... and the long-running workload the live exporter exists
        # for: arm /metrics//status (no-op when obs_http_port is 0)
        ensure_telemetry()

    def _verify_native(self):
        """Which arrays the C++ readahead reader can serve, verified by
        comparing its block 0 against the numpy slice — catches sliced /
        re-offset memmap views whose .offset no longer describes them."""
        from ..io.native import NativeBlockReader, load_block_reader

        oks = []
        for a in self.arrays:
            ok = False
            if (type(a) is np.memmap and a.flags["C_CONTIGUOUS"]
                    and getattr(a, "filename", None) is not None
                    and load_block_reader() is not None):
                try:
                    # the offset/contiguity property is independent of
                    # block size: verify with a SMALL block instead of
                    # double-reading a full (possibly 256 MB) one.
                    # equal_nan: datasets with missing values must not
                    # silently lose the readahead path
                    vb = min(self.block_rows, len(a), 4096)
                    r = NativeBlockReader(a, vb)
                    blk = r.next()
                    ok = blk is not None and np.array_equal(
                        blk, np.asarray(a[: len(blk)]),
                        equal_nan=np.issubdtype(a.dtype, np.floating),
                    )
                    r.close()
                except Exception:
                    ok = False
            oks.append(ok)
        return oks

    def _native_readers(self):
        """Per-array readahead readers for a SEQUENTIAL pass (None where
        inapplicable); the reader thread pread()s blocks ahead of the
        consumer, overlapping disk latency with device transfer/compute
        (native/block_reader.cpp)."""
        if self.shuffle:
            return None
        if getattr(self, "_native_ok", None) is None:
            self._native_ok = self._verify_native()
        if not any(self._native_ok):
            return None
        from ..io.native import NativeBlockReader

        return [
            NativeBlockReader(a, self.block_rows) if ok else None
            for ok, a in zip(self._native_ok, self.arrays)
        ]

    def _profile_fold(self, blk, strided=False) -> None:
        """Fold one host X slab (valid rows only, pre-padding) into the
        training profile — first pass only (later passes re-stream the
        same rows), strided to the row budget, never raising into the
        stream. Called from the per-block path and the super-block
        staging worker alike (the sketch is thread-safe). ``strided``
        marks a sample the caller already strided (the sparse staging
        path densifies ONLY the sampled rows)."""
        if not self._profile_enabled or getattr(self, "_passes", 0):
            return
        try:
            if blk.ndim != 2 or blk.shape[0] == 0 \
                    or blk.shape[1] > _PROFILE_MAX_FEATURES:
                self._profile_enabled = (
                    blk.ndim == 2 and blk.shape[1] <= _PROFILE_MAX_FEATURES
                )
                return
            prof = self.profile
            if prof is None:
                from ..observability.sketch import FeatureSketch

                prof = self.profile = FeatureSketch(blk.shape[1])
            prof.fold(blk if strided else blk[:: self._profile_stride])
        except Exception:
            self._profile_enabled = False  # diagnostics never kill a fit

    def _profile_fold_sparse(self, a, lo, hi) -> None:
        """The sparse staging path's profile fold: densify ONLY the
        strided sample rows of [lo, hi) (the sparse path never builds a
        dense block, and narrow-sparse profiling must not reintroduce
        one) and fold them pre-strided. No-op when profiling is off
        (wide sparse keeps the recorded opt-out)."""
        if not self._profile_enabled or getattr(self, "_passes", 0):
            return
        try:
            step = self._profile_stride
            if sp.isspmatrix_csr(a):
                blk = np.asarray(a[lo:hi:step].toarray(), self.dtype)
            else:
                # SparseBlocks: scatter ONLY the strided rows' nonzeros
                # into the sample buffer — O(block nnz) work, O(sample)
                # dense memory, never the block_rows x d temp this path
                # exists to avoid
                from .sparse_stream import coo_rows

                data, cols, rows = coo_rows(a, lo, hi)
                sel = (rows % step) == 0
                n_s = -(-(hi - lo) // step)
                blk = np.zeros((n_s, a.shape[1]), self.dtype)
                np.add.at(blk, (rows[sel] // step, cols[sel]),
                          data[sel])
            self._profile_fold(blk, strided=True)
        except Exception:
            self._profile_enabled = False

    def profile_snapshot(self):
        """The training profile as a JSON-safe dict (None when profiling
        is off / nothing folded) — what fits attach as
        ``estimator.training_profile_``."""
        prof = self.profile
        return prof.to_dict() if prof is not None and prof.rows else None

    def _view_ok(self, a):
        # a full-height dense block whose dtype already matches can
        # skip host staging as a VIEW of the source — zero host copy
        # (np.memmap is an ndarray subclass, so sequential memmap
        # passes stage straight from the page cache)
        return (isinstance(a, np.ndarray)
                and not isinstance(a, np.generic)
                and a.dtype == self.dtype)

    def _zc_block_guarantee(self, a):
        """True when EVERY full-height block of ``a`` is guaranteed to
        import zero-copy: dtype matches (view staging), the source is
        C-contiguous, and both the base pointer and the per-block byte
        stride are 64-byte aligned (a block's offset is
        ``b * block_rows * strides[0]``). A dtype-match alone is NOT
        enough to reroute staging — a misaligned or non-contiguous
        source would lose the readahead/overlap machinery and then pay
        full copies on the consumer thread anyway."""
        return (self._view_ok(a)
                and a.flags["C_CONTIGUOUS"]
                and a.ctypes.data % _ZC_ALIGN == 0
                and (self.block_rows * a.strides[0]) % _ZC_ALIGN == 0)

    def _gate_readers_for_zero_copy(self, readers):
        """Null out (and close) readahead readers for arrays whose full
        blocks are GUARANTEED to stage as zero-copy aliases — the view
        path then pays neither the reader's copy-out nor a
        device_put. Arrays without the guarantee keep their reader."""
        if readers is None or not self._zero_copy:
            return readers
        for i, (r, a) in enumerate(zip(readers, self.arrays)):
            if r is not None and self._zc_block_guarantee(a):
                r.close()
                readers[i] = None
        return readers if any(r is not None for r in readers) else None

    @staticmethod
    def _disable_reader(readers, i):
        """A reader whose read failed mid-stream has an untrustworthy
        cursor (the failed ``next()`` may or may not have consumed its
        block) — drop it for the rest of the pass; reads fall back to
        POSITIONAL slices of the source, which are idempotent."""
        try:
            readers[i].close()
        except Exception:
            pass
        readers[i] = None

    def _retry_io(self, fn, what):
        """Run ``fn`` (an IDEMPOTENT staging step) with bounded
        exponential-backoff IO retry: OSError — a real disk/reader
        hiccup or an injected ``io`` fault — retries up to
        ``stream_io_retries`` times before raising the typed
        :class:`~dask_ml_tpu.reliability.StreamIORetriesExhausted`;
        :class:`InjectedCrash` (a modeled death, not a flaky read)
        propagates immediately."""
        import time as _time

        from ..observability import record_stream_retry
        from ..reliability import faults as _flt

        attempt = 0
        while True:
            try:
                return fn()
            except _flt.InjectedCrash:
                raise
            except OSError as exc:
                if attempt >= self._io_retries:
                    err = _flt.StreamIORetriesExhausted(
                        f"{what} still failing after {attempt + 1} "
                        f"attempt(s): {exc}"
                    )
                    try:
                        # opt-in incident hook (typed error, one
                        # module-global check when disarmed)
                        from ..observability import alerts as _obs_alerts

                        _obs_alerts.note_error(err, "stream_io")
                    except Exception:
                        pass
                    raise err from exc
                record_stream_retry()
                _time.sleep(min(0.02 * (2 ** attempt), 1.0))
                attempt += 1

    def _read_block_host(self, i, a, lo, hi, readers, out=None):
        """One host block read — dtype-cast dense rows [lo, hi) of
        array ``i`` — through the ``staging_read`` fault site with
        bounded exponential-backoff IO retry (``stream_io_retries``).
        With ``out`` the rows are written into ``out[:hi-lo]`` (the
        super-block slab path's single copy); else the block is
        returned (a source VIEW when dtype already matches)."""
        from ..observability import record_stream_retry
        from ..reliability import faults as _flt

        if readers is not None and readers[i] is not None:
            try:
                raw = _flt.fire_plan(self._fault_spec, "staging_read",
                                     readers[i].next())
                if out is not None:
                    out[: hi - lo] = raw
                    return None
                # copy out: the reader's ring buffer is reused, and
                # device_put reads the host buffer asynchronously
                return raw.astype(self.dtype, copy=True)
            except OSError:
                record_stream_retry()
                self._disable_reader(readers, i)

        def read():
            blk = _flt.fire_plan(
                self._fault_spec, "staging_read",
                _slice_dense(a, lo, hi, self.dtype)
            )
            if out is not None:
                out[: hi - lo] = blk
                return None
            return blk

        return self._retry_io(read,
                              f"staging read of rows [{lo}, {hi})")

    def _guard_block_host(self, outs, m):
        """Apply ``stream_nonfinite`` to one per-block staging result:
        returns (outs, m) unchanged, raises typed, or quarantines —
        data zeroed AND the valid-row count folded to 0, so the
        existing mask/prefix-count machinery drops the block with no
        shape change and no recompile."""
        if self._nonfinite == "off" or m == 0:
            return outs, m
        if all(bool(np.isfinite(np.asarray(o)[:m]).all()) for o in outs):
            return outs, m
        from ..reliability.faults import NonFiniteBlock

        if self._nonfinite == "raise":
            raise NonFiniteBlock(
                f"non-finite values in a streamed host block of {m} "
                "rows (config.stream_nonfinite='raise')"
            )
        from ..observability import record_stream_quarantine

        record_stream_quarantine()
        return [np.zeros_like(np.asarray(o)) for o in outs], 0

    def _block_host(self, b, readers=None):
        lo = b * self.block_rows
        hi = min(lo + self.block_rows, self.n_rows)
        m = hi - lo
        outs = []
        for i, a in enumerate(self.arrays):
            blk = self._read_block_host(i, a, lo, hi, readers)
            if i == 0:
                self._profile_fold(blk[:m])
            if m < self.block_rows:  # fixed shape: pad the tail block
                pad = [(0, self.block_rows - m)] + [(0, 0)] * (blk.ndim - 1)
                blk = np.pad(blk, pad)
            outs.append(blk)
        outs, m = self._guard_block_host(outs, m)
        mask = np.zeros(self.block_rows, self.dtype)
        mask[:m] = 1.0
        return outs, m, mask

    def _put(self, host_block):
        """Per-block device staging through the ``stream_put`` fault
        site, IO failures retried with the same bounded backoff as the
        reads (an injected transient fault must heal, not kill the
        pass)."""
        from ..reliability import faults as _flt

        def put():
            _flt.fire_plan(self._fault_spec, "stream_put")
            return self._put_impl(host_block)

        return self._retry_io(put, "device staging put")

    def _put_impl(self, host_block):
        outs, m, mask = host_block
        from ..observability import record_transfer, record_zero_copy

        dev = []
        copied = mask.nbytes
        for a, s in zip(outs, self._shardings):
            # full blocks reach here as source views (or fresh reader
            # copies); both are safe to alias — see _dlpack_alias
            zc = _dlpack_alias(a) if self._zero_copy else None
            if zc is not None:
                record_zero_copy(a.nbytes)
                dev.append(zc)
            else:
                copied += a.nbytes
                dev.append(jax.device_put(a, s))
        record_transfer(copied)
        return Block(tuple(dev), m,
                     jax.device_put(mask, self._mask_sharding))

    def __iter__(self):
        import time as _time

        order = np.arange(self.n_blocks)
        if self.shuffle:
            self.rng.shuffle(order)
        readers = None
        if not self.shuffle:
            try:
                readers = self._native_readers()
            except Exception:
                readers = None
        readers = self._gate_readers_for_zero_copy(readers)
        # per-pass overlap accounting (SURVEY §7 B0: the double buffer is
        # the heart of the system — measure it, don't assume it):
        #   host_s   — disk/densify/pad time building host blocks
        #   put_s    — host-side device_put issue time
        #   wait_s   — time the CONSUMER would stall: popped block's
        #              transfer not yet complete (overlap shortfall)
        #   consume_s— time the consumer held each block (its compute)
        stats = {"host_s": 0.0, "put_s": 0.0, "wait_s": 0.0,
                 "consume_s": 0.0, "n_blocks": int(self.n_blocks),
                 "block_rows": int(self.block_rows)}
        t_pass = _time.perf_counter()
        # k-deep prefetch: device_put is async, so issuing the next k
        # transfers before consuming the current block overlaps DMA with
        # compute (k=1 is the classic double buffer)
        from collections import deque

        pending = deque()
        from ..observability import span

        def pop():
            blk = pending.popleft()
            if measure_wait:
                t0 = _time.perf_counter()
                jax.block_until_ready(blk.arrays)
                stats["wait_s"] += _time.perf_counter() - t0
            return blk

        def emit(blk):
            # consume = wall time the generator is SUSPENDED at this
            # yield — exactly the consumer's per-block work
            t_y = _time.perf_counter()
            yield blk
            stats["consume_s"] += _time.perf_counter() - t_y

        # one span per pass: nests under the enclosing fit span and
        # carries the overlap stats + transfer-counter deltas at close
        with span("stream.pass") as sp:
            # the readiness sync serializes the host loop behind each
            # block's transfer, trading a little overlap for the wait_s
            # signal — only pay it when someone consumes the signal: a
            # recording sink (the span resolved one — bound fit logger
            # or configured trace/metrics path, where an unmeasured 0.0
            # would read as "perfectly overlapped") or an autotune pass
            # recording spans only: a span tracked solely for the
            # watchdog (sinkless, armed timeout) must not switch on the
            # readiness syncs — that would perturb the very timed runs
            # the watchdog observes
            measure_wait = sp.recording or getattr(
                self, "_autotune_pass", False
            )
            try:
                for b in order:
                    t0 = _time.perf_counter()
                    hb = self._block_host(b, readers)
                    t1 = _time.perf_counter()
                    stats["host_s"] += t1 - t0
                    pending.append(self._put(hb))
                    stats["put_s"] += _time.perf_counter() - t1
                    if len(pending) > self.prefetch:
                        yield from emit(pop())
                while pending:
                    yield from emit(pop())
            finally:
                stats["pass_s"] = _time.perf_counter() - t_pass
                self.stats = stats
                self._passes = getattr(self, "_passes", 0) + 1
                # the span record IS the per-pass JSONL record (via the
                # thread-bound fit logger or the configured trace sink);
                # `stream_pass` keys it for consumers and the report CLI.
                # n_rows: the pass's valid rows — the report derives
                # samples/s (and, with program tracking on, measured MFU
                # from the ctr_program_flops delta this span carries —
                # the consumer's compute runs while the generator is
                # suspended INSIDE this span)
                # passes_total (known inside epochs()) lets the live
                # plane derive an ETA from the pass clock — host ints
                tot = getattr(self, "_epochs_total", None)
                if tot:
                    sp.add(passes_total=int(tot))
                sp.add(stream_pass=self._passes, n_rows=int(self.n_rows),
                       **{k: (round(v, 6) if isinstance(v, float) else v)
                          for k, v in stats.items()})
                if readers:
                    for r in readers:
                        if r is not None:
                            r.close()

    def _maybe_grow_blocks(self):
        """Epoch-boundary block autotune: when a pass spends more HOST
        time preparing blocks (slice/densify/pad + put issue) than the
        consumer holds them, the per-block fixed costs dominate — double
        the block so fewer, larger transfers amortize them. wait_s is
        deliberately NOT part of the signal: under async dispatch the
        device's compute backlog surfaces as transfer wait, and growing
        blocks doesn't reduce bytes moved — it would misfire on
        compute-bound fits. Only between ``epochs()`` passes (per-block
        solver state like ADMM's never sees a resize), at most twice,
        and only when there are enough blocks that halving their count
        still keeps the mesh busy."""
        st = getattr(self, "stats", None)
        if st is None or self._passes > 2 or self.n_blocks < 16:
            return
        if self.sparse_plan is not None:
            # the sparse staging plan (capacities, per-shard nnz) is
            # keyed to the block partition — a mid-fit resize would
            # invalidate it
            return
        if not self._pass_data_bound(st):
            return
        shards = data_shards(self.mesh)
        # never grow past the byte budget that bounds device footprint
        # (a block already AT the budget stays there)
        budget_rows = max(_AUTO_BLOCK_BYTES // max(self._row_bytes, 1), 1)
        cap = min(int(np.ceil(self.n_rows / shards)) * shards,
                  max(budget_rows, self.block_rows))
        new_rows = min(self.block_rows * 2, cap)
        # a grown block must stay a SHARD MULTIPLE: the byte-budget cap
        # is not rounded, and the sharded superblock flavor's per-shard
        # staging/counts (block_rows / D exactly) require even division
        new_rows = max(new_rows // shards * shards, shards)
        if new_rows <= self.block_rows:
            return
        self.block_rows = new_rows
        self.n_blocks = int(np.ceil(self.n_rows / self.block_rows))

    def __len__(self):
        return self.n_blocks

    def epochs(self, n_epochs, autotune=None):
        if autotune is None:
            from ..config import get_config

            autotune = get_config().stream_autotune
        self._autotune_pass = bool(autotune)  # enables wait_s measuring
        self._epochs_total = int(n_epochs)    # pass spans carry it (ETA)
        try:
            for e in range(n_epochs):
                yield from self
                if autotune and e < n_epochs - 1:
                    self._maybe_grow_blocks()
        finally:
            self._autotune_pass = False
            self._epochs_total = None

    # -- super-block execution (ISSUE 3 tentpole) -------------------------
    # K fixed-shape blocks stack into one [K, block_rows, d] device
    # buffer; a consumer runs ONE jitted lax.scan per super-block with a
    # donated carry — one XLA dispatch per K blocks instead of K, no
    # host round-trip inside the scan.

    def resolve_superblock_k(self) -> int:
        """Blocks per super-block for this stream: the K autotuner's
        override, else ``config.superblock_k``, else the auto policy
        (8, capped by the pass length and the super-block byte budget).
        1 — the per-block path — when super-blocking is opted out or the
        source is sparse (ragged CSR densify slices stage per-block; the
        fixed staging ring would re-densify whole slabs)."""
        from ..config import get_config

        cfg = get_config()
        if not cfg.stream_superblock:
            return 1
        if any(_is_sparse_source(a) for a in self.arrays):
            if self.sparse_plan is None:
                return 1
            # device-resident sparse blocks stack like dense slabs; the
            # K byte budget reasons about the bucketed-nnz triples plus
            # the dense side arrays, not the n x d densification
            k = self._superblock_k_override or int(cfg.superblock_k)
            if k <= 0:
                k = _AUTO_SUPERBLOCK_K
            dense_bytes = sum(
                4 * int(np.prod(a.shape[1:], dtype=np.int64) or 1)
                for a in self.arrays[1:]
            ) * self.block_rows
            block_bytes = max(
                self.sparse_plan.block_bytes() + dense_bytes, 1
            )
            budget_k = max(_SUPERBLOCK_BYTES // block_bytes, 1)
            return int(max(min(k, self.n_blocks, budget_k), 1))
        k = self._superblock_k_override or int(cfg.superblock_k)
        if k <= 0:
            k = _AUTO_SUPERBLOCK_K
        block_bytes = max(self.block_rows * self._row_bytes, 1)
        budget_k = max(_SUPERBLOCK_BYTES // block_bytes, 1)
        return int(max(min(k, self.n_blocks, budget_k), 1))

    def use_superblocks(self) -> bool:
        """True when a fused-scan consumer should take the super-block
        path (K > 1); False falls back to the per-block loop."""
        return self.resolve_superblock_k() > 1

    def sb_data_shards(self) -> int:
        """Data-axis shards of this stream's mesh — the D the sharded
        superblock flavor (shard_map + psum scan programs) runs over.
        1 means the single-device programs run untouched (their jaxprs
        stay byte-identical to the pre-mesh feature)."""
        return max(data_shards(self.mesh), 1)

    def sb_model_shards(self) -> int:
        """Model-axis shards the X super-blocks actually TILE over —
        M of the 2-D flavor. 1 on 1-D meshes AND whenever the X
        position couldn't tile (sparse / non-2-D / d not divisible:
        see ``model_tile_reason``), so consumers can branch on this
        one number."""
        return model_shards(self.mesh) if self.model_tiled else 1

    def sb_sharded(self) -> bool:
        """True when super-blocks stage device-sharded (over "data",
        "model", or both) and consumers should run their
        shard_map/psum scan flavor."""
        return self.sb_data_shards() > 1 or self.sb_model_shards() > 1

    def sb_sparse(self) -> bool:
        """True when super-blocks stage as device-resident bucketed-nnz
        sparse slabs (``SuperBlock.arrays[0]`` is a SparseSlab) and
        consumers should run their ``superblock.sparse.*`` flavor."""
        return self.sparse_plan is not None and self.use_superblocks()

    def _shard_counts_of(self, counts):
        """(D, K) per-shard valid-row counts: shard s owns rows
        [s*Sd, (s+1)*Sd) of every block (Sd = block_rows / D — the
        stream rounds block_rows to a shard multiple), so a ragged
        tail block fills shard 0..j and pads the rest with ZERO
        counts, exactly like the ragged final super-block pads its
        missing block slots."""
        D = self.sb_data_shards()
        sd = self.block_rows // D
        return np.clip(
            counts[None, :].astype(np.int64)
            - np.arange(D, dtype=np.int64)[:, None] * sd,
            0, sd,
        ).astype(np.int32)

    def _check_device_budget(self, k):
        """Enforce ``config.stream_device_byte_budget`` (0 = off): the
        bytes ONE device holds for a staged super-block — K blocks x
        its (block_rows/D) row slab x its (d/M when the X position
        tiles, else d) feature tile, per array, at the 4-byte staging
        dtype — must fit the simulated budget, else the fit refuses
        typed (``StreamBudgetExceeded``) instead of letting a wide-d
        1-D fit blow past per-chip HBM on real hardware."""
        from ..config import get_config

        budget = int(get_config().stream_device_byte_budget)
        if budget <= 0:
            return
        D = self.sb_data_shards()
        M = self.sb_model_shards()
        per_dev = 0
        for i, a in enumerate(self.arrays):
            feat = int(np.prod(
                getattr(a, "shape", (0,))[1:], dtype=np.int64) or 1)
            if i == 0 and self.model_tiled:
                feat = -(-feat // M)
            per_dev += int(k) * (self.block_rows // D) * feat * 4
        if per_dev > budget:
            raise StreamBudgetExceeded(
                f"staged super-block needs {per_dev} bytes per device "
                f"(K={k}, block_rows={self.block_rows}, mesh "
                f"{mesh_str(self.mesh)}), over the simulated "
                f"stream_device_byte_budget={budget}. Shard the "
                "over-budget axis: set config.mesh_shape to a 2-D "
                "'DxM' so X stages as (rows/D, d/M) per-device tiles "
                "(per-device bytes flat in d), or lower superblock_k / "
                "stream_block_rows."
            )

    def _put_sharded(self, a, sharding):
        """One batch-sharded ``jax.Array`` from PER-SHARD host slabs,
        each placed onto its own device (the overlapped staging worker
        issues the D per-device transfers together — one slab, one
        device, no runtime-side splitting of a monolithic host
        buffer). Slabs of a C-contiguous source whose shard boundary
        falls on a row boundary are zero-copy VIEWS until the transfer
        reads them."""
        from ..observability import record_shard_staging
        from ..reliability.faults import fire_plan

        fire_plan(self._fault_spec, "stream_put_sharded")
        imap = sharding.devices_indices_map(a.shape)
        devs = list(imap)
        slabs = [np.ascontiguousarray(a[imap[dv]]) for dv in devs]
        parts = jax.device_put(slabs, devs)
        record_shard_staging(len(devs))
        return jax.make_array_from_single_device_arrays(
            a.shape, sharding, parts
        )

    def _sb_ring(self, k):
        """Fixed ring of host staging slabs, one slab set per in-flight
        transfer: super-block i+1 is assembled and its device_put issued
        while the consumer still scans super-block i (the double-buffer
        pattern lifted one level). A slot is refilled only after its
        previous transfer is confirmed complete — device_put reads the
        host buffer asynchronously, and overwriting a buffer mid-read
        would corrupt the transfer."""
        shape_key = (k, self.block_rows)
        ring = getattr(self, "_ring", None)
        if ring is not None and self._ring_key == shape_key:
            return ring
        n_slots = self.prefetch + 2
        ring = [self._sb_slot(k) for _ in range(n_slots)]
        self._ring = ring
        self._ring_key = shape_key
        return ring

    def _guard_sb_block(self, slot, parts, j, m, counts, unroll):
        """Apply ``stream_nonfinite`` to one staged super-block slot:
        a non-finite block either raises typed or quarantines — data
        zeroed and ``counts[j]`` folded to 0, exactly the shape the
        ragged-final-super-block padding already compiles for (no new
        program, no recompile; the scan's masked prefix-count drops
        it). No-op at the default policy."""
        if self._nonfinite == "off" or m == 0:
            return
        n_arr = len(self.arrays)
        pieces = ([parts[i][j] for i in range(n_arr)] if unroll
                  else [slot["bufs"][i][j] for i in range(n_arr)])
        if all(bool(np.isfinite(np.asarray(p)[:m]).all())
               for p in pieces):
            return
        from ..reliability.faults import NonFiniteBlock

        if self._nonfinite == "raise":
            raise NonFiniteBlock(
                f"non-finite values in streamed super-block slot {j} "
                f"({m} rows; config.stream_nonfinite='raise')"
            )
        from ..observability import record_stream_quarantine

        counts[j] = 0
        for i in range(n_arr):
            slot["bufs"][i][j] = 0
            if unroll:
                # a view / zero-copy alias can't be zeroed in place —
                # swap the slot's zeroed staging buffer in instead
                parts[i][j] = slot["bufs"][i][j]
        record_stream_quarantine()

    def _sb_slot(self, k):
        return {
            "bufs": [
                np.zeros((k, self.block_rows) + a.shape[1:], self.dtype)
                for a in self.arrays
            ],
            "counts": np.zeros(k, np.int32),
            "dev": None,
        }

    def superblocks(self, order=None):
        """One prefetched pass over K-stacked super-blocks.

        ``order`` (default: all blocks once, shuffled when the stream
        shuffles) is the sequence of block indices the consumer's scan
        steps through — block j of super-block i is ``order[i*K + j]``.
        An explicit ``order`` may be any length and revisit blocks (the
        adaptive-search cohort plane streams each round's block-step
        TIMELINE through here, ISSUE 14). The final super-block pads
        missing slots with zero counts so every dispatch has the
        identical [K, block_rows, d] shape."""
        if self.sparse_plan is not None:
            # device-resident sparse staging (ISSUE 13): bucketed-nnz
            # COO triples instead of densified slabs, same dispatch /
            # counts / sharding contract
            yield from self._superblocks_sparse(order)
            return
        import time as _time

        from ..observability import (record_superblock,
                                     record_transfer, record_zero_copy,
                                     span)

        k = self.resolve_superblock_k()
        if order is None:
            order = np.arange(self.n_blocks)
            if self.shuffle:
                self.rng.shuffle(order)
        order = np.asarray(order, np.int64)
        n_sb = max(int(np.ceil(len(order) / k)), 1)
        sequential = bool(
            len(order) == self.n_blocks
            and np.array_equal(order, np.arange(self.n_blocks))
        )
        readers = None
        if sequential:
            try:
                readers = self._native_readers()
            except Exception:
                readers = None
        ring = self._sb_ring(k)
        unroll = superblock_unrolled()
        D = self.sb_data_shards()
        sharded = self.sb_sharded()
        self._check_device_budget(k)
        stats = {"host_s": 0.0, "put_s": 0.0, "wait_s": 0.0,
                 "consume_s": 0.0, "n_blocks": int(len(order)),
                 "block_rows": int(self.block_rows),
                 "superblock_k": int(k),
                 "sb_shards": int(D),
                 "sb_model_shards": int(self.sb_model_shards()),
                 # pass-span mesh tag: the 2-D shape the report CLI /
                 # /status render as "DxM"
                 "mesh": mesh_str(self.mesh),
                 "dispatches_per_pass": int(n_sb)}
        t_pass = _time.perf_counter()
        from collections import deque

        pending = deque()

        view_ok = self._view_ok

        readers = self._gate_readers_for_zero_copy(readers)

        def fill(slot, blocks):
            """Assemble ``blocks`` (block indices) into host parts:
            the slot's stacked slabs (scan layout) or per-block host
            buffers/views (unrolled layout). Returns (parts, counts)."""
            if slot["dev"] is not None:
                # the slot's previous transfer must have committed
                # before its host buffer is rewritten
                jax.block_until_ready(slot["dev"])
                slot["dev"] = None
            counts = slot["counts"]
            counts[:] = 0
            parts = [[] for _ in self.arrays] if unroll else None
            for j, b in enumerate(blocks):
                lo = int(b) * self.block_rows
                hi = min(lo + self.block_rows, self.n_rows)
                m = hi - lo
                counts[j] = m
                for i, a in enumerate(self.arrays):
                    buf = slot["bufs"][i]
                    from_reader = (readers is not None
                                   and readers[i] is not None)
                    if (unroll and not from_reader
                            and m == self.block_rows and view_ok(a)):
                        if i == 0:
                            self._profile_fold(a[lo:hi])
                        # with a fault plan armed the view read runs
                        # through the staging_read site (which may
                        # return a poisoned COPY — never the source);
                        # unarmed, the pristine zero-copy view path is
                        # untouched
                        blk = self._read_block_host(i, a, lo, hi, None) \
                            if self._fault_armed else a[lo:hi]
                        if self._zero_copy:
                            # source view -> zero-copy alias now, ON
                            # the staging thread; put() passes the
                            # already-imported array through
                            dev = _dlpack_alias(blk)
                            if dev is not None:
                                record_zero_copy(blk.nbytes)
                                parts[i].append(dev)
                                continue
                        parts[i].append(blk)
                        continue
                    self._read_block_host(i, a, lo, hi, readers,
                                          out=buf[j])
                    if i == 0:
                        self._profile_fold(buf[j, :m])
                    if m < self.block_rows:
                        buf[j, m:] = 0
                    if unroll:
                        parts[i].append(buf[j])
                self._guard_sb_block(slot, parts, j, m, counts, unroll)
            for i in range(len(self.arrays)):
                for j in range(len(blocks), k):
                    slot["bufs"][i][j] = 0
                    if unroll:
                        parts[i].append(slot["bufs"][i][j])
            return (parts if unroll else slot["bufs"]), counts

        shard_counts_of = self._shard_counts_of

        def put(slot, parts, counts, n_real):
            if sharded:
                # data-parallel staging (ISSUE 9): each array becomes a
                # batch-sharded jax.Array assembled from per-shard host
                # slabs placed onto their own device — the consumer's
                # shard_map scan then reads purely local rows and pays
                # ONE psum per super-block for its reducers
                if unroll:
                    nbytes = sum(b.nbytes for p in parts for b in p)
                    record_transfer(nbytes + counts.nbytes)
                    dev = tuple(
                        tuple(self._put_sharded(b, self._shardings[i])
                              for b in p)
                        for i, p in enumerate(parts)
                    )
                else:
                    record_transfer(
                        sum(b.nbytes for b in parts) + counts.nbytes
                    )
                    dev = tuple(
                        self._put_sharded(b, s)
                        for b, s in zip(parts, self._sb_shardings)
                    )
                counts_d = jax.device_put(counts, self._counts_sharding)
                shard_d = self._put_sharded(
                    shard_counts_of(counts), self._shard_counts_sharding
                )
                slot["dev"] = dev + (counts_d, shard_d)
                return SuperBlock(dev, counts_d, n_real,
                                  int(counts[:n_real].sum()),
                                  shard_counts=shard_d)
            if unroll:
                nbytes = sum(b.nbytes for p in parts for b in p
                             if not isinstance(b, jax.Array))
                record_transfer(nbytes + counts.nbytes)
                # ONE pytree device_put per array: the K block
                # transfers are issued together (concurrent copies — a
                # single stacked put is one serial memcpy on CPU).
                # Blocks the staging thread already imported zero-copy
                # (jax.Array entries) pass straight through; the
                # leftovers (ragged tail, padding slots, unaligned
                # arrays) are put individually — they are the small
                # minority whenever aliasing is on at all
                dev = tuple(
                    tuple(jax.device_put(
                        p, [self._shardings[i]] * len(p)
                    )) if not any(isinstance(b, jax.Array) for b in p)
                    else tuple(
                        b if isinstance(b, jax.Array)
                        else jax.device_put(b, self._shardings[i])
                        for b in p
                    )
                    for i, p in enumerate(parts)
                )
            else:
                record_transfer(
                    sum(b.nbytes for b in parts) + counts.nbytes
                )
                dev = tuple(
                    jax.device_put(b, s)
                    for b, s in zip(parts, self._sb_shardings)
                )
            counts_d = jax.device_put(counts, self._counts_sharding)
            slot["dev"] = dev + (counts_d,)
            return SuperBlock(dev, counts_d, n_real,
                              int(counts[:n_real].sum()))

        def produce(i):
            """Stage + transfer super-block i (runs on the ONE staging
            worker thread, so slot and reader order stay sequential):
            assembly and device_put of super-block i+1 proceed while
            the consumer's scan over super-block i runs — on backends
            whose device_put is a synchronous host copy (CPU) the
            thread is what makes the overlap real."""
            blocks = order[i * k:(i + 1) * k]
            # aliasing backends (device_put zero-copies host memory, see
            # _device_put_aliases) can never see a REUSED staging buffer
            # — a queued consumer computation would read the refill
            slot = self._sb_slot(k) if _device_put_aliases() \
                else ring[i % len(ring)]
            t0 = _time.perf_counter()
            parts, counts = fill(slot, blocks)
            t1 = _time.perf_counter()
            stats["host_s"] += t1 - t0
            sb = put(slot, parts, counts, len(blocks))
            stats["put_s"] += _time.perf_counter() - t1
            return sb

        def pop():
            fut = pending.popleft()
            # the consumer's true stall: staging/transfer not done yet
            t0 = _time.perf_counter()
            sb = fut.result()
            if measure_wait:
                jax.block_until_ready(sb.arrays)
            stats["wait_s"] += _time.perf_counter() - t0
            return sb

        def emit(sb):
            # the superblock dispatch boundary fault site: a "crash"
            # arm here aborts the consumer MID-PASS — the in-process
            # stand-in for a killed fit that the pass-granular
            # checkpoint/resume machinery recovers from
            from ..reliability.faults import fire_plan

            fire_plan(self._fault_spec, "superblock_dispatch")
            record_superblock(sb.n_blocks)
            t_y = _time.perf_counter()
            yield sb
            stats["consume_s"] += _time.perf_counter() - t_y

        # when every array's staging is guaranteed (near-)free — its
        # full blocks alias zero-copy, or its per-block bytes are so
        # small the copy is noise — the background staging worker has
        # nothing real to overlap, and the per-pass executor spin-up,
        # future hand-offs, and GIL ping-pong between the two threads
        # cost more than they hide (~30% of a steady-state CPU pass at
        # bench shapes). Stage inline there; keep the worker wherever a
        # real memcpy/densify/device_put pipeline exists to overlap
        # (non-contiguous or misaligned sources, dtype conversion).
        def _cheap_to_stage(a):
            if self._zc_block_guarantee(a):
                return True
            row_bytes = 4 * int(np.prod(a.shape[1:], dtype=np.int64)
                                or 1)
            return row_bytes * self.block_rows <= (1 << 20)

        inline = self._zero_copy and all(
            _cheap_to_stage(a) for a in self.arrays
        )

        class _Done:
            __slots__ = ("v",)

            def __init__(self, v):
                self.v = v

            def result(self):
                return self.v

        if inline:
            staging = None
            submit = lambda fn, i: _Done(fn(i))  # noqa: E731
        else:
            from concurrent.futures import ThreadPoolExecutor

            staging = ThreadPoolExecutor(max_workers=1)
            submit = staging.submit
        with span("streaming.superblock") as sp:
            # recording spans only: a span tracked solely for the
            # watchdog (sinkless, armed timeout) must not switch on the
            # readiness syncs — that would perturb the very timed runs
            # the watchdog observes
            measure_wait = sp.recording or getattr(
                self, "_autotune_pass", False
            )
            try:
                for i in range(n_sb):
                    pending.append(submit(produce, i))
                    if len(pending) > self.prefetch:
                        yield from emit(pop())
                while pending:
                    yield from emit(pop())
            finally:
                if staging is not None:
                    staging.shutdown(wait=True)
                stats["pass_s"] = _time.perf_counter() - t_pass
                self.stats = stats
                self._passes = getattr(self, "_passes", 0) + 1
                # n_rows: valid rows this pass's `order` actually covered
                # (a partial-order pass must not claim the whole dataset)
                pass_rows = int(sum(
                    min((int(b) + 1) * self.block_rows, self.n_rows)
                    - int(b) * self.block_rows
                    for b in order
                ))
                tot = getattr(self, "_epochs_total", None)
                if tot:
                    sp.add(passes_total=int(tot))
                sp.add(stream_pass=self._passes,
                       dispatches=int(n_sb), n_rows=pass_rows,
                       **{key: (round(v, 6) if isinstance(v, float) else v)
                          for key, v in stats.items()})
                if readers:
                    for r in readers:
                        if r is not None:
                            r.close()
                # process-spanning pass barrier (multi-host streaming):
                # every process streams the same pass sequence, so the
                # sync matches up; behind the runtime capability probe —
                # a backend that cannot span processes makes this a
                # no-op instead of a crash
                from . import distributed as dist

                if dist.process_count() > 1:
                    dist.sync_stream_pass("superblock_pass")

    def superblock_epochs(self, n_epochs, autotune=None):
        """Epoch iterator over super-blocks (the superblocks() analog of
        :meth:`epochs`): shuffle redraws per pass, and opt-in autotune
        may grow the blocks AND the K between passes (each resize
        recompiles the consumer's scan once)."""
        if autotune is None:
            from ..config import get_config

            autotune = get_config().stream_autotune
        self._autotune_pass = bool(autotune)
        self._epochs_total = int(n_epochs)
        try:
            for e in range(n_epochs):
                yield from self.superblocks()
                if autotune and e < n_epochs - 1:
                    self._maybe_grow_blocks()
                    self._maybe_grow_superblock()
        finally:
            self._autotune_pass = False
            self._epochs_total = None

    def _pass_data_bound(self, st):
        """Was the last pass limited by data movement? Per-block passes
        compare the generator's staging time against the consumer's
        hold time (the original signal). Super-block passes stage on a
        BACKGROUND worker — host_s/put_s there are overlapped busy
        time, not consumer cost, and consume_s is mostly async dispatch
        issue — so the signal is the consumer's measured STALL: wait_s
        above 10% of the pass."""
        if "superblock_k" in st:
            return st.get("wait_s", 0.0) > 0.1 * max(
                st.get("pass_s", 0.0), 1e-9
            )
        return st["host_s"] + st["put_s"] > st["consume_s"]

    def _maybe_grow_superblock(self):
        """Epoch-boundary K autotune, alongside ``_maybe_grow_blocks``:
        when the consumer still stalls on staged data at the current K,
        double K so one scan amortizes more blocks and staging batches
        further ahead. Unlike block growth this never changes the
        minibatch partition (results are identical at any K); it is
        still opt-in-only because a resize recompiles the scan, and
        steady-state passes must stay at zero recompiles. Capped by the
        super-block byte budget and the pass length."""
        st = getattr(self, "stats", None)
        if st is None or "superblock_k" not in st:
            return
        if not self._pass_data_bound(st):
            return
        k = int(st["superblock_k"])
        block_bytes = max(self.block_rows * self._row_bytes, 1)
        cap = int(max(min(self.n_blocks,
                          _SUPERBLOCK_BYTES // block_bytes), 1))
        new_k = min(k * 2, cap)
        if new_k > k:
            self._superblock_k_override = new_k

    # -- device-resident sparse staging (ISSUE 13 tentpole) ---------------
    # A sparse X stages as fixed-shape bucketed-nnz COO triples
    # (data/cols/rows padded to the plan's capacity) stacked K-deep —
    # the sparse twin of the dense super-block path: same fixed host
    # ring, same overlapped staging worker, same counts/shard_counts
    # and dispatch contract, O(nnz) staged bytes instead of O(S * d).

    def _sp_ring(self, k):
        plan = self.sparse_plan
        D = self.sb_data_shards()
        width = plan.cap * D
        shape_key = ("sparse", k, self.block_rows, width)
        ring = getattr(self, "_sparse_ring", None)
        if ring is not None and self._sparse_ring_key == shape_key:
            return ring
        n_slots = self.prefetch + 2

        def slot():
            return {
                "data": np.zeros((k, width), np.float32),
                "cols": np.zeros((k, width), np.int32),
                "rows": np.zeros((k, width), np.int32),
                "bufs": [
                    np.zeros((k, self.block_rows) + a.shape[1:],
                             self.dtype)
                    for a in self.arrays[1:]
                ],
                "counts": np.zeros(k, np.int32),
                "dev": None,
            }

        ring = [slot() for _ in range(n_slots)]
        self._sparse_ring = ring
        self._sparse_ring_key = shape_key
        self._sparse_slot_fn = slot
        return ring

    def _guard_sparse_slot(self, slot, j, m, counts):
        """``stream_nonfinite`` for one sparse-staged slot: non-finite
        VALUES (the dense side arrays are checked too) raise typed or
        quarantine — data zeroed, count folded to 0, no shape change."""
        if self._nonfinite == "off" or m == 0:
            return
        finite = bool(np.isfinite(slot["data"][j]).all()) and all(
            bool(np.isfinite(buf[j, :m]).all()) for buf in slot["bufs"]
        )
        if finite:
            return
        from ..reliability.faults import NonFiniteBlock

        if self._nonfinite == "raise":
            raise NonFiniteBlock(
                f"non-finite values in streamed sparse super-block slot "
                f"{j} ({m} rows; config.stream_nonfinite='raise')"
            )
        from ..observability import record_stream_quarantine

        counts[j] = 0
        slot["data"][j] = 0
        slot["cols"][j] = 0
        slot["rows"][j] = 0
        for buf in slot["bufs"]:
            buf[j] = 0
        record_stream_quarantine()

    def _superblocks_sparse(self, order=None):
        """The sparse flavor of :meth:`superblocks`: one prefetched pass
        of K-stacked bucketed-nnz slabs. Identical stats keys, span
        record, fault sites, counts semantics and (on a >1-shard mesh)
        per-shard staging + ``shard_counts`` — consumers see
        ``SuperBlock.arrays[0]`` as a :class:`SparseSlab` and select
        their ``superblock.sparse.*`` scan programs."""
        import time as _time
        from collections import deque

        from ..observability import (record_sparse_staging,
                                     record_superblock, record_transfer,
                                     span)
        from ..reliability import faults as _flt
        from .sparse_stream import SparseSlab, pack_block

        plan = self.sparse_plan
        k = self.resolve_superblock_k()
        if order is None:
            order = np.arange(self.n_blocks)
            if self.shuffle:
                self.rng.shuffle(order)
        order = np.asarray(order, np.int64)
        n_sb = max(int(np.ceil(len(order) / k)), 1)
        ring = self._sp_ring(k)
        D = self.sb_data_shards()
        sharded = D > 1
        sd = self.block_rows // D
        cap = plan.cap
        sp_sharding = NamedSharding(
            self.mesh, P(None, DATA_AXIS) if sharded else P()
        )
        stats = {"host_s": 0.0, "put_s": 0.0, "wait_s": 0.0,
                 "consume_s": 0.0, "n_blocks": int(len(order)),
                 "block_rows": int(self.block_rows),
                 "superblock_k": int(k),
                 "sb_shards": int(D),
                 "sb_model_shards": 1,
                 "mesh": mesh_str(self.mesh),
                 "dispatches_per_pass": int(n_sb),
                 "sparse_cap": int(cap)}
        t_pass = _time.perf_counter()
        pending = deque()

        def fill(slot, blocks):
            if slot["dev"] is not None:
                jax.block_until_ready(slot["dev"])
                slot["dev"] = None
            counts = slot["counts"]
            counts[:] = 0
            nnz = 0
            X = self.arrays[0]
            for j, b in enumerate(blocks):
                lo = int(b) * self.block_rows
                hi = min(lo + self.block_rows, self.n_rows)
                m = hi - lo
                counts[j] = m

                def pack():
                    _flt.fire_plan(self._fault_spec, "staging_read")
                    return pack_block(
                        X, lo, hi, D, sd, cap, slot["data"][j],
                        slot["cols"][j], slot["rows"][j],
                    )

                nnz += self._retry_io(
                    pack, f"sparse staging read of rows [{lo}, {hi})"
                )
                self._profile_fold_sparse(X, lo, hi)
                for i, a in enumerate(self.arrays[1:], start=1):
                    buf = slot["bufs"][i - 1]
                    self._read_block_host(i, a, lo, hi, None,
                                          out=buf[j])
                    if m < self.block_rows:
                        buf[j, m:] = 0
                self._guard_sparse_slot(slot, j, m, counts)
            for j in range(len(blocks), k):
                slot["data"][j] = 0
                slot["cols"][j] = 0
                slot["rows"][j] = 0
                for buf in slot["bufs"]:
                    buf[j] = 0
            return nnz

        def put(slot, counts, n_real, nnz):
            nbytes = (slot["data"].nbytes + slot["cols"].nbytes
                      + slot["rows"].nbytes
                      + sum(b.nbytes for b in slot["bufs"])
                      + counts.nbytes)
            record_transfer(nbytes)
            record_sparse_staging(n_real, nnz)
            if sharded:
                triple = tuple(
                    self._put_sharded(slot[name], sp_sharding)
                    for name in ("data", "cols", "rows")
                )
                dense_d = tuple(
                    self._put_sharded(buf, self._sb_shardings[i + 1])
                    for i, buf in enumerate(slot["bufs"])
                )
                counts_d = jax.device_put(counts, self._counts_sharding)
                shard_d = self._put_sharded(
                    self._shard_counts_of(counts),
                    self._shard_counts_sharding,
                )
            else:
                def putp():
                    _flt.fire_plan(self._fault_spec, "stream_put")
                    t = tuple(
                        jax.device_put(slot[name], sp_sharding)
                        for name in ("data", "cols", "rows")
                    )
                    dd = tuple(
                        jax.device_put(buf, self._sb_shardings[i + 1])
                        for i, buf in enumerate(slot["bufs"])
                    )
                    return t, dd, jax.device_put(
                        counts, self._counts_sharding
                    )

                triple, dense_d, counts_d = self._retry_io(
                    putp, "sparse device staging put"
                )
                shard_d = None
            slab = SparseSlab(*triple, n_rows=sd,
                              n_features=plan.n_features, shards=D,
                              cap=cap)
            slot["dev"] = triple + dense_d + (counts_d,)
            return SuperBlock((slab,) + dense_d, counts_d, n_real,
                              int(counts[:n_real].sum()),
                              shard_counts=shard_d)

        def produce(i):
            blocks = order[i * k:(i + 1) * k]
            slot = self._sparse_slot_fn() if _device_put_aliases() \
                else ring[i % len(ring)]
            t0 = _time.perf_counter()
            nnz = fill(slot, blocks)
            t1 = _time.perf_counter()
            stats["host_s"] += t1 - t0
            sb = put(slot, slot["counts"], len(blocks), nnz)
            stats["put_s"] += _time.perf_counter() - t1
            return sb

        def pop():
            fut = pending.popleft()
            t0 = _time.perf_counter()
            sb = fut.result()
            if measure_wait:
                jax.block_until_ready(
                    (sb.arrays[0].data,) + sb.arrays[1:]
                )
            stats["wait_s"] += _time.perf_counter() - t0
            return sb

        def emit(sb):
            _flt.fire_plan(self._fault_spec, "superblock_dispatch")
            record_superblock(sb.n_blocks)
            t_y = _time.perf_counter()
            yield sb
            stats["consume_s"] += _time.perf_counter() - t_y

        from concurrent.futures import ThreadPoolExecutor

        staging = ThreadPoolExecutor(max_workers=1)
        with span("streaming.superblock") as sp_:
            measure_wait = sp_.recording or getattr(
                self, "_autotune_pass", False
            )
            try:
                for i in range(n_sb):
                    pending.append(staging.submit(produce, i))
                    if len(pending) > self.prefetch:
                        yield from emit(pop())
                while pending:
                    yield from emit(pop())
            finally:
                staging.shutdown(wait=True)
                stats["pass_s"] = _time.perf_counter() - t_pass
                self.stats = stats
                self._passes = getattr(self, "_passes", 0) + 1
                pass_rows = int(sum(
                    min((int(b) + 1) * self.block_rows, self.n_rows)
                    - int(b) * self.block_rows
                    for b in order
                ))
                tot = getattr(self, "_epochs_total", None)
                if tot:
                    sp_.add(passes_total=int(tot))
                sp_.add(stream_pass=self._passes,
                        dispatches=int(n_sb), n_rows=pass_rows,
                        **{key: (round(v, 6) if isinstance(v, float)
                                 else v)
                           for key, v in stats.items()})
                from . import distributed as dist

                if dist.process_count() > 1:
                    dist.sync_stream_pass("superblock_pass")

    def sparse_block_put(self, b):
        """Stage ONE block as a single-slab sparse triple plus the
        dense side arrays — the grad-accum micro path's per-block
        staging (single-device placement; the grad-accum flavor merges
        on host). Returns (SparseSlab, dense device arrays, mask, m)."""
        from .sparse_stream import SparseSlab, pack_block

        plan = self.sparse_plan
        cap = plan.cap1
        lo = int(b) * self.block_rows
        hi = min(lo + self.block_rows, self.n_rows)
        m = hi - lo
        data = np.zeros(cap, np.float32)
        cols = np.zeros(cap, np.int32)
        rows = np.zeros(cap, np.int32)
        pack_block(self.arrays[0], lo, hi, 1, self.block_rows, cap,
                   data, cols, rows)
        dense = []
        for i, a in enumerate(self.arrays[1:], start=1):
            blk = self._read_block_host(i, a, lo, hi, None)
            if m < self.block_rows:
                pad = [(0, self.block_rows - m)] \
                    + [(0, 0)] * (blk.ndim - 1)
                blk = np.pad(blk, pad)
            dense.append(blk)
        mask = np.zeros(self.block_rows, self.dtype)
        mask[:m] = 1.0
        devs = jax.device_put([data, cols, rows] + dense + [mask],
                              NamedSharding(self.mesh, P()))
        slab = SparseSlab(*devs[:3], n_rows=self.block_rows,
                          n_features=plan.n_features, shards=1, cap=cap)
        return slab, tuple(devs[3:-1]), devs[-1], m


def streamed_map(X, block_rows, fn):
    """Map ``fn(block) -> host array (block_valid_rows, ...)`` over X's
    blocks and concatenate — the one stream→compute→host pattern shared by
    every streamed inference path (GLM decision values, KMeans labels /
    distances, PCA scores). ``fn`` receives the padded device block; its
    output is sliced to the block's logical rows here."""
    from ..config import get_config

    # inference streams must keep row alignment: quarantining (dropping)
    # a block would silently misalign the concatenated output against
    # the input rows, so the quarantine policy hardens to "raise" here
    nf = get_config().stream_nonfinite
    outs = []
    for blk in BlockStream((X,), block_rows=block_rows, profile=False,
                           nonfinite="raise" if nf != "off" else "off"):
        outs.append(np.asarray(fn(blk))[: blk.n_rows])
    return np.concatenate(outs, axis=0)
