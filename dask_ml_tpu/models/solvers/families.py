"""GLM families: Normal, Logistic, Poisson.

Reference equivalent: ``dask_glm/families.py`` (SURVEY.md §2b row 6), which
hand-codes loglike/gradient/hessian per family for dask arrays. TPU-native
design: each family is just a pointwise loss + inverse link as pure jax
functions; gradients and Hessian weights come from autodiff / closed forms
and fuse into the surrounding XLA program — no hand-written gradient graphs.

``pointwise(eta, y)`` is the per-row negative log-likelihood (up to a
y-only constant); the global objective is the mask-weighted mean, so padded
rows contribute nothing.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


class Normal:
    name = "normal"

    @staticmethod
    def pointwise(eta, y):
        return 0.5 * (eta - y) ** 2

    @staticmethod
    def mean(eta):  # inverse link
        return eta

    @staticmethod
    def hess_weight(eta, y):
        return jnp.ones_like(eta)


class Logistic:
    name = "logistic"

    @staticmethod
    def pointwise(eta, y):
        # log(1 + e^eta) - y*eta, stable via softplus
        return jax.nn.softplus(eta) - y * eta

    @staticmethod
    def mean(eta):
        return jax.nn.sigmoid(eta)

    @staticmethod
    def hess_weight(eta, y):
        p = jax.nn.sigmoid(eta)
        return p * (1.0 - p)


class Poisson:
    name = "poisson"

    @staticmethod
    def pointwise(eta, y):
        return jnp.exp(eta) - y * eta

    @staticmethod
    def mean(eta):
        return jnp.exp(eta)

    @staticmethod
    def hess_weight(eta, y):
        return jnp.exp(eta)


FAMILIES = {f.name: f for f in (Normal, Logistic, Poisson)}


def get_family(name: str):
    if name not in FAMILIES:
        raise ValueError(f"Unknown family {name!r}; options: {sorted(FAMILIES)}")
    return FAMILIES[name]
