"""Batched (vmapped) trial execution — the TPU replacement for the
reference's concurrent model futures (``dask_ml/model_selection/
_incremental.py::_fit`` async controller, SURVEY.md §3.5): N homogeneous
models advance in ONE jitted step over a stacked weight pytree, and the
search data plane stays device-resident for device-native estimators."""

import numpy as np
import pytest

import jax.numpy as jnp

from dask_ml_tpu.linear_model import SGDClassifier, SGDRegressor
from dask_ml_tpu.model_selection import IncrementalSearchCV
from dask_ml_tpu.models.sgd import _sgd_step_many
from dask_ml_tpu.parallel import as_sharded
from dask_ml_tpu.parallel.sharded import ShardedArray


def test_one_step_advances_eight_models():
    """One _sgd_step_many call == one XLA program advancing 8 models."""
    rng = np.random.RandomState(0)
    n, d, N = 256, 8, 8
    X = jnp.asarray(rng.randn(n, d).astype(np.float32))
    beta = rng.randn(d).astype(np.float32)
    y = jnp.asarray((rng.rand(n) < 1 / (1 + np.exp(-X @ beta))).astype(
        np.float32))
    mask = jnp.ones((n,), jnp.float32)
    W = jnp.zeros((N, d + 1), jnp.float32)
    lrs = jnp.asarray(np.linspace(0.05, 0.5, N), jnp.float32)
    alphas = jnp.asarray(np.logspace(-5, -1, N), jnp.float32)
    ones = jnp.ones((N,), jnp.float32)
    W2, losses = _sgd_step_many(
        X, y, mask, jnp.float32(n), W, lrs, alphas, ones, 0 * ones, ones,
        "log_loss",
    )
    assert W2.shape == (N, d + 1) and losses.shape == (N,)
    # every model moved, and different lrs → different weights
    assert (np.abs(np.asarray(W2)).sum(axis=1) > 0).all()
    norms = np.linalg.norm(np.asarray(W2), axis=1)
    assert len(np.unique(np.round(norms, 6))) == N


def test_batched_step_matches_single_steps():
    """vmapped cohort step ≡ N independent partial_fit calls."""
    rng = np.random.RandomState(1)
    X = rng.randn(200, 6).astype(np.float32)
    y = (rng.rand(200) < 0.5).astype(np.float32)
    etas = [0.05, 0.1, 0.2, 0.4]

    singles = []
    for eta in etas:
        m = SGDClassifier(eta0=eta, learning_rate="constant")
        m.partial_fit(X, y, classes=[0.0, 1.0])
        m.partial_fit(X, y)
        singles.append(m.coef_.ravel())

    cohort = [SGDClassifier(eta0=eta, learning_rate="constant")
              for eta in etas]
    for m in cohort:
        m._batch_prepare({"classes": [0.0, 1.0]})
    keys = {m._batch_key() for m in cohort}
    assert len(keys) == 1  # homogeneous: one cohort, one program
    SGDClassifier._batched_partial_fit(cohort, X, y)
    SGDClassifier._batched_partial_fit(cohort, X, y)
    SGDClassifier._batch_publish(cohort, X.shape[1])
    for single, m in zip(singles, cohort):
        np.testing.assert_allclose(single, m.coef_.ravel(), rtol=1e-5)


def test_batched_score_matches_single_scores():
    rng = np.random.RandomState(2)
    X = rng.randn(300, 5).astype(np.float32)
    y = (rng.rand(300) < 0.5).astype(np.float64)
    cohort = [SGDClassifier(eta0=e, learning_rate="constant")
              for e in (0.1, 0.3)]
    for m in cohort:
        m.partial_fit(X, y, classes=[0.0, 1.0])
    batched = SGDClassifier._batched_score_default(cohort, X, y)
    for i, m in enumerate(cohort):
        assert batched[i] == pytest.approx(m.score(X, y), abs=1e-6)

    reg = [SGDRegressor(eta0=e, learning_rate="constant")
           for e in (0.01, 0.05)]
    yr = (X @ rng.randn(5)).astype(np.float32)
    for m in reg:
        m.partial_fit(X, yr)
    batched = SGDRegressor._batched_score_default(reg, X, yr)
    for i, m in enumerate(reg):
        assert batched[i] == pytest.approx(m.score(X, yr), abs=1e-5)


def test_search_uses_batched_cohorts(xy_classification):
    """History records carry batch_size ≥ 8: the whole cohort advanced in
    shared jitted steps, not a sequential model-at-a-time loop."""
    X, y = xy_classification
    search = IncrementalSearchCV(
        SGDClassifier(learning_rate="constant"),
        {"eta0": [0.05, 0.1, 0.2, 0.4], "alpha": [1e-4, 1e-3]},
        n_initial_parameters="grid", decay_rate=None, max_iter=5,
        random_state=0,
    )
    search.fit(X, y, classes=[0.0, 1.0])
    first_round = [r for r in search.history_
                   if r["partial_fit_calls"] == 1]
    assert len(first_round) == 8
    assert all(r["batch_size"] == 8 for r in first_round)
    assert search.best_score_ > 0.6


@pytest.mark.slow
def test_search_data_plane_stays_on_device(xy_classification, monkeypatch):
    """VERDICT r1 weak #4: no full-dataset device→host copy when the
    input is a ShardedArray and the estimator is device-native."""
    X, y = xy_classification
    Xs, ys = as_sharded(X.astype(np.float32)), as_sharded(
        y.astype(np.float32))

    calls = []
    orig = ShardedArray.to_numpy

    def spy(self):
        calls.append(self.n_rows)
        return orig(self)

    monkeypatch.setattr(ShardedArray, "to_numpy", spy)
    search = IncrementalSearchCV(
        SGDClassifier(learning_rate="constant"),
        {"eta0": [0.1, 0.2]}, n_initial_parameters="grid",
        decay_rate=None, max_iter=3, random_state=0,
    )
    search.fit(Xs, ys, classes=[0.0, 1.0])
    # the (n, d) training data must never round-trip through host; only
    # small scoring/publish pulls are allowed
    assert not any(c >= len(X) for c in calls), calls
    assert search.best_score_ > 0.5


def test_heterogeneous_cohorts_split():
    """Different losses cannot share a program: separate batch keys."""
    a = SGDClassifier(loss="log_loss")
    b = SGDClassifier(loss="hinge")
    a._batch_prepare({"classes": [0, 1]})
    b._batch_prepare({"classes": [0, 1]})
    assert a._batch_key() != b._batch_key()


def test_host_solo_trials_run_concurrently(xy_classification):
    """VERDICT r2 weak #1: non-batchable (host sklearn) trials advance
    through a thread pool, not a strictly sequential loop — placement
    evidence lands in history_ (executor/thread fields)."""
    from sklearn.linear_model import SGDClassifier as SkSGD

    X, y = xy_classification
    search = IncrementalSearchCV(
        SkSGD(tol=None), {"alpha": [1e-5, 1e-4, 1e-3, 1e-2]},
        n_initial_parameters="grid", decay_rate=None, max_iter=3,
        random_state=0,
    )
    search.fit(X, y, classes=[0.0, 1.0])
    threaded = [r for r in search.history_ if r["executor"] == "threads"]
    assert threaded, search.history_[:2]
    assert len({r["thread"] for r in threaded}) > 1  # real concurrency
    assert search.best_score_ > 0.5


def test_cursor_diverged_device_models_progress(xy_classification):
    """Device-protocol models whose block cursors diverged fall out of
    the vmapped cohort but still make progress (sequential singleton
    groups — the safe path on one shared mesh)."""
    from dask_ml_tpu.model_selection._incremental import fit as ctrl_fit
    from dask_ml_tpu.metrics.scorer import check_scoring

    X, y = xy_classification
    X = X.astype(np.float32)
    y = y.astype(np.float32)
    blocks = [(X[i::4], y[i::4]) for i in range(4)]

    calls_seen = []

    def hook(info):
        calls_seen.append({m: r[-1]["partial_fit_calls"]
                           for m, r in info.items()})
        rounds = len(calls_seen)
        if rounds == 1:
            return {0: 1, 1: 2}  # diverge the cursors
        if rounds <= 3:
            return {0: 1, 1: 1}  # both advance, cursors stay diverged
        return {}

    def factory(params):
        return SGDClassifier(tol=1e-3, **params)

    scorer = check_scoring(SGDClassifier(), None)
    info, models, meta, history = ctrl_fit(
        factory, [{"eta0": 0.1}, {"eta0": 0.2}], blocks,
        X[:100], y[:100], scorer, hook,
        fit_params={"classes": [0.0, 1.0]},
    )
    # cursors diverged after round 2 and both models kept advancing
    assert meta[0]["block_cursor"] != meta[1]["block_cursor"]
    assert meta[0]["partial_fit_calls"] == 4  # 1 initial + 1 + 1 + 1
    assert meta[1]["partial_fit_calls"] == 5  # 1 initial + 2 + 1 + 1
    # diverged device models advanced as sequential singletons
    late = [r for r in history if r["partial_fit_calls"] >= 4]
    assert late and all(r["batch_size"] == 1 for r in late)
    assert all(r["executor"] == "sequential" for r in late)


@pytest.mark.slow
def test_cohort_fused_calls_match_loop():
    """A cohort round's n_calls block steps fused into one scan program
    (_batched_fused_calls) produce the SAME weights and lr clocks as
    the per-call _batched_partial_fit loop, including ragged last
    blocks and mixed lr schedules."""
    import numpy as np

    from dask_ml_tpu.models.sgd import SGDClassifier
    from dask_ml_tpu.parallel import as_sharded
    from dask_ml_tpu.parallel.sharded import take_rows

    rng = np.random.RandomState(1)
    n, d = 1300, 7
    X = rng.randn(n, d).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    Xs, ys = as_sharded(X), as_sharded(y)
    blocks = []
    for s in range(0, n, 400):
        idx = np.arange(s, min(s + 400, n))
        blocks.append((take_rows(Xs, idx), take_rows(ys, idx)))

    def cohort():
        ms = [SGDClassifier(alpha=a, random_state=0, learning_rate=lr)
              for a, lr in [(1e-4, "invscaling"), (1e-2, "optimal")]]
        for m in ms:
            m._batch_prepare({"classes": np.array([0.0, 1.0])})
        return ms

    loop = cohort()
    for b in blocks:
        SGDClassifier._batched_partial_fit(loop, *b)
    SGDClassifier._batch_publish(loop, d)
    fused = cohort()
    SGDClassifier._batched_fused_calls(fused, blocks)
    SGDClassifier._batch_publish(fused, d)
    for l, f in zip(loop, fused):
        np.testing.assert_allclose(f.coef_, l.coef_, atol=1e-6)
        np.testing.assert_allclose(f.intercept_, l.intercept_, atol=1e-6)
        assert l._t == f._t
