"""Adaptive incremental hyperparameter search.

Reference: ``dask_ml/model_selection/_incremental.py`` (SURVEY.md §2a
adaptive row, §3.5 call stack): an async controller over distributed
futures submits ``partial_fit``/``score`` block-by-block and adaptively
stops/keeps models via an ``additional_calls`` hook.

TPU mapping (SURVEY.md §3.5): the controller is a synchronous host loop
(trials are pinned work, not stolen futures); models train one data block
per call and are scored on a held-out split. The ``additional_calls``
protocol is preserved exactly: it receives ``{model_id: [history
records]}`` and returns ``{model_id: n_more_partial_fit_calls}`` — an
empty dict (or all-zero dict) stops the search. SuccessiveHalving and
Hyperband reuse this engine, as in the reference.
"""

from __future__ import annotations

import os
import threading
import time

import numpy as np
from sklearn.model_selection import ParameterSampler

from ..base import BaseEstimator, clone
from ..metrics.scorer import check_scoring
from ..parallel.sharded import ShardedArray
from ..utils.validation import data_fingerprint as _data_fingerprint
from ._split import train_test_split


def _to_host(a):
    from ..parallel.streaming import _is_sparse_source

    if _is_sparse_source(a):
        return a  # sparse stays sparse (np.asarray would mangle it)
    return a.to_numpy() if isinstance(a, ShardedArray) else np.asarray(a)


def _blocks_of(X, y, n_blocks, block_rows=None):
    """Row blocks = the unit of one partial_fit call.

    Device-resident data plane (VERDICT r1 #5): when X is a ShardedArray
    the blocks are extracted ON DEVICE via ``take_rows`` (a sharded
    gather) and stay there — no full-dataset device→host→device
    round-trip before training, which at BASELINE scale would be a
    TB-size copy. Host inputs keep host blocks (streamed to device per
    step, as the reference streams blocks to workers). ``block_rows``
    pins the exact block height (the streamed cohort plane passes its
    stream partition so solo fallbacks train the SAME minibatches the
    superblock scans do)."""
    if isinstance(X, ShardedArray):
        from ..parallel.sharded import take_rows

        ys = y if isinstance(y, ShardedArray) else None
        n = X.n_rows
        bs = int(block_rows) if block_rows \
            else max(int(np.ceil(n / n_blocks)), 1)
        out = []
        for i in range(0, n, bs):
            idx = np.arange(i, min(i + bs, n))
            if not idx.size:
                continue
            yb = take_rows(ys, idx) if ys is not None \
                else np.asarray(y)[idx]
            out.append((take_rows(X, idx), yb))
        return out
    from ..parallel.streaming import as_row_sliceable

    Xh, yh = _to_host(X), _to_host(y)
    Xh = as_row_sliceable(Xh)  # sparse: CSR slices, no densify
    n = int(Xh.shape[0])
    bs = int(block_rows) if block_rows \
        else max(int(np.ceil(n / n_blocks)), 1)
    return [(Xh[i:i + bs], yh[i:i + bs]) for i in range(0, n, bs)
            if int(Xh[i:i + bs].shape[0])]


def _supports_batch(model) -> bool:
    return hasattr(type(model), "_batched_partial_fit") and \
        hasattr(model, "_batch_key")


def host_view_estimator(est):
    """Replace any device-array attributes with host numpy so the model
    pickles across the process-gather channel (and stays usable — every
    consumer re-coerces with jnp.asarray)."""
    import jax

    from ..base import to_host

    if est is None:
        return est
    for k, v in list(vars(est).items()):
        if isinstance(v, jax.Array):
            setattr(est, k, to_host(v))
    return est


# Hyperband distributes whole brackets across processes; the SHA fits it
# runs per bracket must NOT additionally distribute their candidates (the
# peers are busy with other brackets — a nested allgather would deadlock).
# Thread-local, not a module global: virtual process ranks are threads of
# ONE process, and rank A leaving its bracket must not re-enable
# distribution under rank B's still-running SHA.
import contextlib
import threading

_dist_state = threading.local()


def _dist_is_disabled():
    return getattr(_dist_state, "disabled", False)


@contextlib.contextmanager
def disable_process_distribution():
    prev = getattr(_dist_state, "disabled", False)
    _dist_state.disabled = True
    try:
        yield
    finally:
        _dist_state.disabled = prev


class _StreamCohortPlane:
    """The streamed superblock data plane for adaptive-search cohort
    rounds (ISSUE 14 tentpole): instead of keeping train blocks
    device-resident and dispatching the search's own cohort scans
    (HBM-capped, blind to the stream mesh, densifying sparse corpora),
    a round advances ALL surviving batchable candidates through ONE
    ``BlockStream`` superblock pass — the same staging ring, mesh
    sharding, bucketed-nnz sparse format and fused Pallas flavors every
    streamed fit already rides. The plane owns:

    - the block PARTITION (``fit_block_rows`` — the same formula the
      streamed SGD/Incremental fits use, so a search trains the same
      minibatches a plain streamed fit of the winner would);
    - one lazily-built ``BlockStream`` per cohort batch key (the stream
      needs the cohort's y encoding), reused across every round so the
      staging ring and compiled scans stay warm;
    - one staged validation HOLDOUT per key (dense device slab or
      packed sparse COO triple), scored in one batched dispatch per
      round;
    - ``n_slots`` — the search's total candidate count, the FIXED pad
      of the stacked cohort carry: bracket halving reuses the one
      compiled scan via the slot mask instead of recompiling at each
      surviving N.

    ``config.search_stream=False`` restores the device-resident cohort
    path on the SAME partition (the honest A/B bench.py records)."""

    def __init__(self, X_train, y_train, X_test, y_test, n_slots):
        from ..parallel.streaming import BlockStream, fit_block_rows

        self.X, self.y = X_train, y_train
        self.X_test, self.y_test = X_test, y_test
        self.n_slots = int(n_slots)
        n = int(X_train.shape[0])
        self.block_rows = int(fit_block_rows(X_train))
        self.n_blocks = max(int(np.ceil(n / self.block_rows)), 1)
        self._streams = {}
        self._holdouts = {}
        self.stats = {"rounds": 0, "dispatches": 0, "shards": 1,
                      "sparse": False, "fused": False,
                      "fused_reason": None}
        # probe: the hot loop must actually superblock this source at
        # this partition (a sparse corpus that fell back to per-block
        # densify, or stream_superblock off, keeps the device plane)
        probe = BlockStream((X_train,), block_rows=self.block_rows,
                            profile=False)
        self.engaged = bool(
            probe.block_rows == self.block_rows
            and probe.use_superblocks()
        )
        self.reason = None if self.engaged else (
            probe.sparse_reason or "per-block-path"
        )

    @staticmethod
    def eligible(estimator, X_train):
        """The stream PARTITION (and, with ``config.search_stream`` on,
        the streamed execution plane) serves single-process searches
        over HOST-resident X with a streamed-cohort-capable estimator;
        the device-resident plane keeps everything else. A bracket SHA
        running under ``disable_process_distribution`` (multi-process
        Hyperband stripes whole brackets across processes) counts as
        single-process: it fits on its local mesh, and its stream
        resolves to exactly that mesh — BASELINE config 5's
        trials-parallel-across-hosts shape with every bracket riding
        the streamed plane. The knob is deliberately NOT part of this
        check — with it off the search keeps the stream partition but
        executes rounds through the device-resident cohort machinery,
        so the two paths train identical minibatches and their scores
        are comparable."""
        from ..parallel import distributed as _dist

        return (hasattr(type(estimator), "_streamed_cohort_round")
                and not isinstance(X_train, ShardedArray)
                and (_dist.process_count() == 1 or _dist_is_disabled()))

    def stream_for(self, key, model):
        """The (cached) training BlockStream for cohort batch key
        ``key`` — built on first use because the stream stages the
        ENCODED targets (the key pins the class set)."""
        stream = self._streams.get(key)
        if stream is None:
            from ..parallel.streaming import BlockStream

            y_enc = np.asarray(
                model._encode_y(np.asarray(self.y)), np.float32
            )
            stream = BlockStream((self.X, y_enc),
                                 block_rows=self.block_rows,
                                 shuffle=False, profile=False)
            # finer dispatch granularity than a plain streamed fit
            # (~4 super-blocks per full pass): a Hyperband round's
            # timeline mixes wide early steps with narrow survivor
            # tails, and each dispatch picks its slot RUNG from the
            # union of active candidates — coarse super-blocks would
            # drag the whole round onto the widest rung. The byte
            # budget in resolve_superblock_k still caps K
            stream._superblock_k_override = max(
                2, -(-self.n_blocks // 4)
            )
            self._streams[key] = stream
        return stream

    def holdout_for(self, key, cls, model):
        holdout = self._holdouts.get(key)
        if holdout is None:
            holdout = cls._cohort_holdout(self.X_test, self.y_test,
                                          model)
            self._holdouts[key] = holdout
        return holdout

    def note_round(self, info):
        """Fold one cohort round's engagement record into the plane's
        stats (surfaced on ``search.metadata_["stream"]`` so smoke
        suites assert engagement instead of trusting the gates)."""
        self.stats["rounds"] += 1
        self.stats["dispatches"] += int(info.get("dispatches", 0))
        self.stats["shards"] = int(info.get("shards", 1))
        self.stats["sparse"] = bool(info.get("sparse", False))
        self.stats["fused"] = bool(info.get("fused", False))
        self.stats["fused_reason"] = info.get("fused_reason")

    def snapshot(self):
        return {"streamed": True, "n_blocks": int(self.n_blocks),
                "block_rows": int(self.block_rows),
                "n_slots": int(self.n_slots), **self.stats}


def fit(model_factory, params_list, train_blocks, X_test, y_test, scorer,
        additional_calls, fit_params=None, patience=False, tol=1e-3,
        max_iter=None, prefix="", verbose=False, checkpoint=None,
        ckpt_token=None, hook_state=None, scoring_is_default=False,
        trial_tags=None, stream_plane=None):
    """Core controller entry: opens the per-fit JSONL sink (closed even on
    error) around the actual controller loop in :func:`_fit`."""
    from ..observability import fit_logger, span

    with span("fit", component="adaptive_search", prefix=prefix,
              n_models=len(params_list)), \
            fit_logger("adaptive_search", prefix=prefix) as logger:
        return _fit(model_factory, params_list, train_blocks, X_test,
                    y_test, scorer, additional_calls, fit_params=fit_params,
                    patience=patience, tol=tol, max_iter=max_iter,
                    prefix=prefix, verbose=verbose, checkpoint=checkpoint,
                    ckpt_token=ckpt_token, hook_state=hook_state,
                    scoring_is_default=scoring_is_default, logger=logger,
                    trial_tags=trial_tags, stream_plane=stream_plane)


def _fit(model_factory, params_list, train_blocks, X_test, y_test, scorer,
         additional_calls, fit_params=None, patience=False, tol=1e-3,
         max_iter=None, prefix="", verbose=False, checkpoint=None,
         ckpt_token=None, hook_state=None, scoring_is_default=False,
         logger=None, trial_tags=None, stream_plane=None):
    """Core controller (ref: _incremental.py::_fit). Returns
    (info, models, history).

    ``checkpoint`` (utils.checkpoint.SearchCheckpoint, optional) persists
    (history, meta, models, active set, hook state) after every adaptive
    round; an INTERRUPTED search whose saved identity token matches
    ``ckpt_token`` resumes at round granularity instead of restarting
    (SURVEY.md §5 — capability the reference lacks: its killed searches
    lose all model futures). A checkpoint is cleared on successful
    completion, so finished searches never leak state into new ones.
    ``hook_state`` is a (get, set) pair persisting the adaptive hook's
    schedule position (e.g. SHA's rung) alongside the controller state.
    """
    fit_params = fit_params or {}
    models = {}
    meta = {}
    history = []
    info = {}
    start = time.time()
    n_blocks = len(train_blocks)
    # Multi-process candidate distribution (SURVEY.md §3.5 'trials pinned
    # to hosts'): model mid is OWNED by process (mid % n_proc); each
    # round every process trains/scores only its models, then one
    # object-allgather merges the round's records so the adaptive
    # decisions (additional_calls, patience, budget caps) are computed
    # identically everywhere from identical info.
    from ..parallel import distributed as _dist

    n_proc = 1 if _dist_is_disabled() else _dist.process_count()
    pid = _dist.process_index() if n_proc > 1 else 0
    placement_mesh = None
    if n_proc > 1:
        # per-process partial model state is not round-resumable
        checkpoint = None
        ckpt_token = None
        # owned candidates run on THIS process's local-device mesh: a
        # device estimator would otherwise dispatch global-mesh
        # collectives its peers (busy with their own candidates) never
        # enter — a silent deadlock (same placement rule as Hyperband's
        # bracket distribution)
        from ..parallel.distributed import local_mesh

        placement_mesh = local_mesh()

    def _owned(mid):
        return n_proc == 1 or mid % n_proc == pid

    pending = []  # this round's records, exchanged at the round barrier

    def sync_round(exc=None):
        if n_proc == 1:
            if exc is not None:
                raise exc
            return
        from ..parallel.distributed import allgather_object

        payload = {
            "records": list(pending),
            "meta": {mid: {k: meta[mid][k] for k in
                           ("partial_fit_calls", "block_cursor", "score")}
                     for mid in meta if _owned(mid)},
            "error": None if exc is None else repr(exc),
        }
        pending.clear()
        parts = allgather_object(payload)
        if exc is not None:
            raise exc
        bad = [p["error"] for p in parts if p["error"] is not None]
        if bad:
            raise RuntimeError(
                f"peer process failed during distributed adaptive "
                f"search: {bad}"
            )
        merged = [r for p in parts for r in p["records"]]
        merged.sort(key=lambda r: (r["partial_fit_calls"], r["model_id"]))
        for rec in merged:
            history.append(rec)
            info[rec["model_id"]].append(rec)
        for p in parts:
            for mid, m in p["meta"].items():
                meta[mid].update(m)

    def run_round(requests):
        """One adaptive round: local execution of the owned share (on the
        local mesh under multi-process), then the record exchange — a
        failure anywhere fails every process fast instead of hanging
        peers in the allgather."""
        import contextlib

        from ..observability import span
        from ..parallel.mesh import use_mesh

        placement = (use_mesh(placement_mesh) if placement_mesh is not None
                     else contextlib.nullcontext())
        try:
            with span("search.round", round=round_idx,
                      n_trials=len(requests),
                      n_calls=sum(requests.values())), placement:
                run_requests(requests)
        except Exception as e:
            sync_round(e)
            raise
        sync_round()
    round_idx = 0
    active = None

    restored = checkpoint.load() if checkpoint is not None else None
    if restored is not None and restored.get("token") == ckpt_token \
            and ckpt_token is not None:
        round_idx = restored["round"]
        history = restored["history"]
        meta = restored["meta"]
        models = restored["models"]
        active = set(restored["active"])
        # keep history timestamps monotonic across the restart
        start = time.time() - restored.get("elapsed", 0.0)
        if hook_state is not None and restored.get("hook") is not None:
            hook_state[1](restored["hook"])
        info = {mid: [r for r in history if r["model_id"] == mid]
                for mid in models}
    else:
        restored = None

    def save_round():
        if checkpoint is None:
            return
        checkpoint.save_round(round_idx, history, meta, models, extra={
            "token": ckpt_token,
            "active": sorted(active) if active is not None else sorted(models),
            "hook": hook_state[0]() if hook_state is not None else None,
            "elapsed": time.time() - start,
        })

    if restored is None:
        for mid, params in enumerate(params_list):
            models[mid] = model_factory(params)
            meta[mid] = {
                "model_id": mid, "params": params, "partial_fit_calls": 0,
                "score": None, "block_cursor": 0,
            }
            info[mid] = []

    def record_scores(mids, scores, fit_time, score_time,
                      executor="sequential"):
        for mid, score in zip(mids, scores):
            m = meta[mid]
            m["score"] = float(score)
            record = {
                "model_id": mid,
                "params": m["params"],
                "partial_fit_calls": m["partial_fit_calls"],
                "partial_fit_time": fit_time,
                "score": float(score),
                "score_time": score_time,
                "elapsed_wall_time": time.time() - start,
                "batch_size": len(mids),
                "executor": executor,
                "thread": threading.get_ident(),
                "owner": pid,
            }
            if n_proc > 1:
                pending.append(record)
            else:
                history.append(record)
                info[mid].append(record)
            if logger is not None:
                tags = trial_tags(mid) if trial_tags is not None else {}
                logger.log(step=m["partial_fit_calls"], model_id=mid,
                           partial_fit_calls=m["partial_fit_calls"],
                           score=float(score), batch_size=len(mids),
                           partial_fit_time=fit_time,
                           score_time=score_time, **tags)

    def train_one(mid, n_calls, executor="sequential", blocks=None,
                  test=None):
        """``blocks``/``test`` override the shared data plane when a
        trial runs on a submesh with pre-placed copies."""
        import scipy.sparse as sp

        m = meta[mid]
        model = models[mid]
        device_model = type(model).__module__.startswith("dask_ml_tpu")
        t0 = time.time()
        for i in range(n_calls):
            Xb, yb = (blocks[i] if blocks is not None
                      else train_blocks[m["block_cursor"] % n_blocks])
            if sp.issparse(Xb) and device_model:
                # device estimators' per-block partial_fit takes dense
                # operands; a solo trial that fell out of the streamed
                # cohort densifies ONE block at a time (host sklearn
                # estimators consume the CSR natively)
                Xb = Xb.toarray()
            model.partial_fit(Xb, yb, **fit_params)
            m["block_cursor"] += 1
            m["partial_fit_calls"] += 1
        fit_time = time.time() - t0
        t0 = time.time()
        Xt, yt = test if test is not None else (X_test, y_test)
        score = scorer(model, Xt, yt)
        score_time = time.time() - t0
        record_scores([mid], [score], fit_time, score_time,
                      executor=executor)

    # per-submesh test-split copies, keyed by the submesh's device ids;
    # rebuilt only when the round's partition changes
    _submesh_test_cache = {}

    def run_dev_solo(dev_solo):
        """Device-native solo trials on DISJOINT submeshes (VERDICT r3
        weak #3): the same placement rule grid search uses
        (_search.py::_submeshes) applied to the incremental controller —
        k heterogeneous device candidates run concurrently, each
        entirely inside its own submesh, so their XLA collectives can
        never interleave on shared devices. Trained weights are pulled
        to host after each wave (host_view_estimator): model state must
        not stay pinned to a submesh, because the NEXT round may place
        the model on a different mesh."""
        from concurrent.futures import ThreadPoolExecutor

        from ..parallel.mesh import use_mesh

        if not dev_solo:
            return
        device_plane = isinstance(train_blocks[0][0], ShardedArray)
        if device_plane:
            parent = train_blocks[0][0].mesh
        elif placement_mesh is not None:
            parent = placement_mesh
        else:
            from ..parallel.mesh import resolve_mesh

            parent = resolve_mesh(None)
        if len(dev_solo) <= 1 or parent.devices.size < 2:
            for mid, n_calls in dev_solo:
                train_one(mid, n_calls)
                # the invariant below holds on EVERY path: weights go
                # back to host so a later round may re-place the model
                host_view_estimator(models[mid])
            return
        from ._search import _submeshes

        subs = _submeshes(parent, len(dev_solo))
        if not device_plane:
            # host blocks: each trial checks a submesh out; concurrent
            # host->device placement is safe (same rule as grid search's
            # pure-host-folds branch)
            import queue as _queue

            free = _queue.SimpleQueue()
            for s in subs:
                free.put(s)

            def on_submesh(mid, n_calls):
                sub = free.get()
                try:
                    with use_mesh(sub):
                        train_one(mid, n_calls, executor="submesh")
                    host_view_estimator(models[mid])
                finally:
                    free.put(sub)

            with ThreadPoolExecutor(max_workers=len(subs)) as pool:
                futures = [pool.submit(on_submesh, mid, n_calls)
                           for mid, n_calls in dev_solo]
                for f in futures:
                    f.result()
            return
        # device plane: reshard each trial's round blocks + one test copy
        # per submesh DEVICE-TO-DEVICE on the parent mesh BEFORE trials
        # launch (parent-mesh programs in flight during submesh trials
        # can deadlock on shared devices), then run the wave concurrently
        import jax as _jx

        from ..parallel.sharded import reshard

        def _reshard_pair(pair, sub):
            Xb, yb = pair
            return (
                reshard(Xb, sub) if isinstance(Xb, ShardedArray) else Xb,
                reshard(yb, sub) if isinstance(yb, ShardedArray) else yb,
            )

        keys = {tuple(d.id for d in s.devices.reshape(-1)) for s in subs}
        if set(_submesh_test_cache) != keys:
            _submesh_test_cache.clear()
        S = len(subs)
        for w0 in range(0, len(dev_solo), S):
            wave = dev_solo[w0:w0 + S]
            prepared = []
            for j, (mid, n_calls) in enumerate(wave):
                sub = subs[j]
                cur = meta[mid]["block_cursor"]
                blks = [
                    _reshard_pair(train_blocks[(cur + i) % n_blocks], sub)
                    for i in range(n_calls)
                ]
                key = tuple(d.id for d in sub.devices.reshape(-1))
                if key not in _submesh_test_cache:
                    _submesh_test_cache[key] = _reshard_pair(
                        (X_test, y_test), sub
                    )
                prepared.append((mid, n_calls, sub, blks,
                                 _submesh_test_cache[key]))
            _jx.block_until_ready([
                a.data for _, _, _, blks, test in prepared
                for pair in (list(blks) + [test]) for a in pair
                if isinstance(a, ShardedArray)
            ])

            def on_sub(mid, n_calls, sub, blks, test):
                with use_mesh(sub):
                    train_one(mid, n_calls, executor="submesh",
                              blocks=blks, test=test)
                host_view_estimator(models[mid])

            with ThreadPoolExecutor(max_workers=len(wave)) as pool:
                futures = [pool.submit(on_sub, *args) for args in prepared]
                for f in futures:
                    f.result()

    def train_cohort(mids, n_calls):
        """Advance a homogeneous cohort: each of the n_calls steps is ONE
        jitted vmapped program over the stacked weight pytree — the TPU
        replacement for the reference's N concurrent model futures
        (ref _incremental.py::_fit async controller, SURVEY.md §3.5)."""
        cohort = [models[mid] for mid in mids]
        cls = type(cohort[0])
        t0 = time.time()
        fused = n_calls > 1 and hasattr(cls, "_batched_fused_calls")
        if fused:
            # the round's n_calls block steps collapse into ONE scan
            # program (same updates and lr clocks as the call loop).
            # Blocks are deduplicated — a multi-epoch rung revisits them
            # through the order operand — and the stack must fit
            # alongside the dataset (one block at a time otherwise).
            cursor = meta[mids[0]]["block_cursor"]
            idxs = [(cursor + i) % n_blocks for i in range(n_calls)]
            uniq = sorted(set(idxs))
            pos = {j: k for k, j in enumerate(uniq)}
            stack_bytes = sum(
                train_blocks[j][0].data.nbytes for j in uniq
                if isinstance(train_blocks[j][0], ShardedArray)
            )
            from ..wrappers import _device_headroom_bytes

            fused = _device_headroom_bytes(
                stack_bytes, train_blocks[uniq[0]][0]
            )
        if fused:
            cls._batched_fused_calls(
                cohort, [train_blocks[j] for j in uniq],
                order=[pos[j] for j in idxs],
            )
            for mid in mids:
                meta[mid]["block_cursor"] += n_calls
                meta[mid]["partial_fit_calls"] += n_calls
        else:
            for _ in range(n_calls):
                cursor = meta[mids[0]]["block_cursor"] % n_blocks
                Xb, yb = train_blocks[cursor]
                cls._batched_partial_fit(cohort, Xb, yb)
                for mid in mids:
                    meta[mid]["block_cursor"] += 1
                    meta[mid]["partial_fit_calls"] += 1
        cls._batch_publish(cohort, train_blocks[0][0].shape[1])
        fit_time = time.time() - t0
        t0 = time.time()
        if scoring_is_default and hasattr(cls, "_batched_score_default"):
            scores = cls._batched_score_default(cohort, X_test, y_test)
        else:
            scores = [scorer(m, X_test, y_test) for m in cohort]
        score_time = time.time() - t0
        # per-model share of the cohort's wall time: summing history_
        # timings then matches actual wall clock whether models advanced
        # solo or batched (batch_size recovers the cohort total)
        record_scores(mids, scores, fit_time / len(mids),
                      score_time / len(mids), executor="vmapped")

    def train_cohort_streamed(key, ent):
        """Advance every batchable candidate sharing ``key`` through
        ONE streamed superblock pass (ISSUE 14 tentpole): the round's
        requests — heterogeneous ``n_calls`` included — compress onto
        one block-step timeline (two models at the same absolute call
        index share the step; per-model activity masks pick who
        advances), so the data is read from host once per round
        regardless of candidate count, and each model still trains on
        exactly the blocks its own ``partial_fit`` loop would have."""
        mids = [mid for mid, _ in ent]
        cohort = [models[mid] for mid in mids]
        cls = type(cohort[0])
        t0 = time.time()
        stream = stream_plane.stream_for(key, cohort[0])
        nb = stream_plane.n_blocks
        starts = {mid: meta[mid]["block_cursor"] for mid in mids}
        timeline = sorted({starts[mid] + j
                           for mid, nc in ent for j in range(nc)})
        step_of = {t: s for s, t in enumerate(timeline)}
        order = np.asarray([t % nb for t in timeline], np.int64)
        act = np.zeros((len(timeline), len(mids)), np.float32)
        for i, (mid, nc) in enumerate(ent):
            for j in range(nc):
                act[step_of[starts[mid] + j], i] = 1.0
        info_round = cls._streamed_cohort_round(
            cohort, stream, order, act, stream_plane.n_slots,
            # first streamed round of the search: warm the whole slot
            # rung ladder so later bracket shrinks stay at zero compiles
            warm=stream_plane.stats["rounds"] == 0,
        )
        for mid, nc in ent:
            meta[mid]["block_cursor"] += nc
            meta[mid]["partial_fit_calls"] += nc
        fit_time = time.time() - t0
        t0 = time.time()
        if scoring_is_default and hasattr(cls, "_cohort_holdout_scores"):
            holdout = stream_plane.holdout_for(key, cls, cohort[0])
            scores = cls._cohort_holdout_scores(
                cohort, holdout, stream_plane.n_slots
            )
        else:
            scores = [scorer(m, X_test, y_test) for m in cohort]
        score_time = time.time() - t0
        stream_plane.note_round(info_round)
        record_scores(mids, scores, fit_time / len(mids),
                      score_time / len(mids), executor="streamed")

    def run_requests(requests):
        """Execute {mid: n_calls>0}: cohort-batch everything batchable,
        grouped by (batch key, n_calls, block cursor)."""
        solo, groups = [], {}
        for mid, n_calls in requests.items():
            if not _owned(mid):
                continue
            model = models[mid]
            key = None
            if _supports_batch(model):
                model._batch_prepare(fit_params)
                key = model._batch_key()
            if key is None:
                solo.append((mid, n_calls))
            else:
                gk = (key, n_calls, meta[mid]["block_cursor"] % n_blocks)
                groups.setdefault(gk, []).append(mid)
        # Solo trials: RAW HOST estimators (sklearn et al — nothing from
        # this package) run through a thread pool: their partial_fit/
        # score is host compute, so threads genuinely overlap. Device
        # estimators — batched-protocol models that fell out of a
        # cohort, IncrementalPCA, wrappers — run concurrently on
        # DISJOINT submeshes (run_dev_solo): concurrent XLA programs are
        # safe exactly when they share no devices.
        def _is_host_model(m):
            return not type(m).__module__.startswith("dask_ml_tpu")

        dev_solo = [(m, n) for m, n in solo if not _is_host_model(models[m])]
        host_solo = [(m, n) for m, n in solo if _is_host_model(models[m])]
        run_dev_solo(dev_solo)
        if len(host_solo) > 1:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(
                max_workers=min(8, len(host_solo))
            ) as pool:
                futures = [
                    pool.submit(train_one, mid, n_calls, "threads")
                    for mid, n_calls in host_solo
                ]
                for f in futures:
                    f.result()
        else:
            for mid, n_calls in host_solo:
                train_one(mid, n_calls)
        if stream_plane is not None and groups:
            # streamed cohort plane (ISSUE 14): merge every batchable
            # group with the same key — heterogeneous (n_calls, cursor)
            # combinations ride the SAME pass via per-model step masks,
            # so a Hyperband round's whole bracket union is one stream
            by_key = {}
            for (key, n_calls, _cursor), mids in groups.items():
                by_key.setdefault(key, []).extend(
                    (mid, n_calls) for mid in mids
                )
            for key, ent in sorted(
                by_key.items(), key=lambda kv: min(m for m, _ in kv[1])
            ):
                train_cohort_streamed(key, sorted(ent))
            return
        for (key, n_calls, _cursor), mids in sorted(
            groups.items(), key=lambda kv: kv[1][0]
        ):
            if len(mids) == 1 and n_calls == 1:
                train_one(mids[0], n_calls)
            else:
                # a SINGLE batchable model asked for several calls still
                # takes the cohort path: its n_calls block steps fuse
                # into one scan program (super-block execution of the
                # partial_fit driver) instead of n_calls dispatches
                train_cohort(mids, n_calls)

    # first round: one call each (skipped when resuming a checkpoint)
    if restored is None:
        run_round({mid: 1 for mid in models})
        round_idx = 1
        active = set(models)
        save_round()

    while active:
        instructions = additional_calls(
            {mid: info[mid] for mid in active}
        )
        instructions = {
            mid: c for mid, c in instructions.items() if mid in active
        }
        active = set(instructions)
        if not instructions or all(c == 0 for c in instructions.values()):
            break
        requests = {}
        for mid, n_calls in instructions.items():
            if n_calls <= 0:
                continue
            if patience and len(info[mid]) > patience:
                recent = [r["score"] for r in info[mid][-patience:]]
                if max(recent) < info[mid][-patience - 1]["score"] + tol:
                    # plateaued: retire so the hook stops asking for it
                    active.discard(mid)
                    continue
            if max_iter is not None and (
                meta[mid]["partial_fit_calls"] + n_calls > max_iter
            ):
                n_calls = max_iter - meta[mid]["partial_fit_calls"]
                if n_calls <= 0:
                    active.discard(mid)
                    continue
            requests[mid] = n_calls
        if not requests:
            break  # every requested model was retired; nothing can advance
        run_round(requests)
        round_idx += 1
        save_round()

    if checkpoint is not None:
        checkpoint.clear()  # completed: never resume into a new search
    if n_proc > 1:
        # every process receives every trained model (small: weights +
        # params), so best_estimator_ and post-fit delegation work
        # identically everywhere
        from ..parallel.distributed import allgather_object

        parts = allgather_object({
            mid: host_view_estimator(models[mid])
            for mid in models if _owned(mid)
        })
        for part in parts:
            models.update(part)
    return info, models, meta, history


class BaseIncrementalSearchCV(BaseEstimator):
    """Shared plumbing of the futures-style searches."""

    def __init__(self, estimator, parameters, n_initial_parameters=10,
                 test_size=None, patience=False, tol=1e-3, max_iter=100,
                 random_state=None, scoring=None, verbose=False, prefix=""):
        self.estimator = estimator
        self.parameters = parameters
        self.n_initial_parameters = n_initial_parameters
        self.test_size = test_size
        self.patience = patience
        self.tol = tol
        self.max_iter = max_iter
        self.random_state = random_state
        self.scoring = scoring
        self.verbose = verbose
        self.prefix = prefix

    # -- hooks overridden by subclasses -----------------------------------
    def _n_initial(self):
        return self.n_initial_parameters

    def _additional_calls(self, info):
        raise NotImplementedError

    def _reset_hook(self):
        """Reset adaptive-schedule state at the start of each fit."""

    def _hook_state(self):
        """Schedule position persisted with checkpoints (e.g. SHA rung)."""
        return {}

    def _trial_tags(self, mid):
        """Extra JSONL fields attached to model ``mid``'s telemetry
        records (Hyperband tags the bracket)."""
        return {}

    def _set_hook_state(self, state):
        for k, v in state.items():
            setattr(self, k, v)

    def _sample_params(self, n):
        return list(ParameterSampler(
            self.parameters, n, random_state=self.random_state
        ))

    def fit(self, X, y=None, **fit_params):
        from ..parallel import distributed as _dist

        if _dist.process_count() > 1 and not _dist_is_disabled():
            if isinstance(X, ShardedArray) or isinstance(y, ShardedArray):
                raise ValueError(
                    "multi-process adaptive search requires host-resident "
                    "X/y (each process loads its copy and trains a "
                    "disjoint candidate subset)"
                )
            if self.random_state is None:
                raise ValueError(
                    "multi-process adaptive search requires a fixed "
                    "random_state: every process must derive the "
                    "IDENTICAL train/test split and candidate sample"
                )
            self._dist_stats = (_dist.process_index(), _dist.process_count())
        test_size = self.test_size
        if test_size is None:
            test_size = 0.15
        # _split_random_state decouples the SPLIT seed from the SAMPLING
        # seed: Hyperband's multi-process bracket SHAs sample with
        # random_state + s but must split identically to the
        # single-process interleaved fit (one shared split), or results
        # would diverge by process count
        split_seed = getattr(self, "_split_random_state", self.random_state)
        X_train, X_test, y_train, y_test = train_test_split(
            X, y, test_size=test_size, random_state=split_seed
        )
        scorer_raw = check_scoring(self.estimator, self.scoring)
        # Device-resident data plane for estimators whose partial_fit
        # consumes device blocks (the batched-trial protocol implies it):
        # blocks and test split stay as ShardedArrays — no full-dataset
        # host round-trip (VERDICT r1 #5). Everything else (raw sklearn,
        # host-only partial_fit like IncrementalPCA) keeps the host plane,
        # as the reference streams blocks to workers.
        est_device = _supports_batch(self.estimator)
        if not est_device:
            X_train, y_train = _to_host(X_train), _to_host(y_train)
            X_test, y_test = _to_host(X_test), _to_host(y_test)
        params_list = self._sample_params(self._n_initial())
        from ..config import get_config
        from ..parallel.mesh import data_shards, resolve_mesh
        from ..parallel.streaming import _is_sparse_source

        # Streamed cohort plane (ISSUE 14): single-process searches over
        # host X with a streamed-cohort-capable estimator take the
        # STREAM partition (fit_block_rows — the same minibatches a
        # plain streamed fit trains), and by default execute each round
        # as one BlockStream superblock pass. config.search_stream=False
        # keeps the partition but runs the device-resident cohort
        # machinery over it — the honest A/B the bench records.
        stream_plane = None
        stream_partition = _StreamCohortPlane.eligible(
            self.estimator, X_train
        )
        if stream_partition:
            plane = _StreamCohortPlane(X_train, y_train, X_test, y_test,
                                       n_slots=len(params_list))
            if plane.engaged and get_config().search_stream:
                stream_plane = plane
            n_blocks = plane.n_blocks
            blocks = _blocks_of(X_train, y_train, n_blocks,
                                block_rows=plane.block_rows)
            if _is_sparse_source(X_train) and stream_plane is None:
                raise ValueError(
                    "adaptive search over a sparse X needs the streamed "
                    "cohort plane (the device-resident cohort path would "
                    "densify the corpus); it did not engage: "
                    f"{plane.reason if not plane.engaged else 'config.search_stream=False'}. "
                    "Enable config.stream_sparse/search_stream or "
                    "densify explicitly within the dense byte budget."
                )
        else:
            if est_device and _is_sparse_source(X_train):
                raise ValueError(
                    "adaptive search over a sparse X requires a "
                    "single-process, host-resident streamed cohort "
                    "plane (multi-process searches and device-resident "
                    "inputs keep the dense data plane)"
                )
            n_blocks = (
                data_shards(X.mesh) if isinstance(X, ShardedArray)
                else data_shards(resolve_mesh(None))
            )
            blocks = _blocks_of(X_train, y_train, n_blocks)

        def factory(params):
            return clone(self.estimator).set_params(**params)

        self._reset_hook()
        from ..config import get_config

        ckpt_dir = get_config().checkpoint_dir
        checkpoint = None
        ckpt_token = None
        # random_state=None draws a fresh split every run, so resume is
        # impossible (the split cannot be reproduced) — no checkpoint is
        # created AT ALL: writing unresumable state every round is pure
        # overhead and a shared-directory hazard (ADVICE r1 #2).
        if ckpt_dir and self.random_state is not None:
            import hashlib

            from ..utils.checkpoint import SearchCheckpoint
            from ._normalize import _token_piece, estimator_token

            # identity token: a stale checkpoint from a different search
            # (estimator, candidate params, data CONTENT + shape, split,
            # budget) must NOT be resumed — it would relabel old models
            # with new params or leak a different split's training rows
            # into test scores. The content fingerprint (ADVICE r1 #1)
            # catches same-shape-different-data: a handful of sample rows
            # is hashed, so it costs one tiny device fetch at most.
            ckpt_token = hashlib.sha1("|".join([
                type(self).__name__, self.prefix,
                estimator_token(self.estimator),
                _token_piece(params_list),
                str(getattr(X, "shape", np.shape(X))),
                _data_fingerprint(X), _data_fingerprint(y),
                str(len(blocks)), str(self.max_iter),
                str(self.patience), str(self.tol),
                str(self.random_state), str(test_size),
            ]).encode()).hexdigest()
            # per-search directory: another search of the same class must
            # not overwrite or clear this search's resumable state
            sub = "-".join(
                p for p in (type(self).__name__, self.prefix,
                            ckpt_token[:12])
                if p
            )
            checkpoint = SearchCheckpoint(os.path.join(ckpt_dir, sub))

        info, models, meta, history = fit(
            factory, params_list, blocks, X_test, y_test, scorer_raw,
            self._additional_calls, fit_params=fit_params,
            patience=self.patience, tol=self.tol, max_iter=self.max_iter,
            prefix=self.prefix, verbose=self.verbose, checkpoint=checkpoint,
            ckpt_token=ckpt_token,
            hook_state=(self._hook_state, self._set_hook_state),
            scoring_is_default=self.scoring is None,
            trial_tags=self._trial_tags, stream_plane=stream_plane,
        )

        self.history_ = history
        self.model_history_ = info
        n_models = len(params_list)
        scores = np.array([
            info[mid][-1]["score"] if info[mid] else np.nan
            for mid in range(n_models)
        ])
        calls = np.array([meta[mid]["partial_fit_calls"]
                          for mid in range(n_models)])
        order = np.argsort(-scores, kind="stable")
        ranks = np.empty(n_models, np.int32)
        ranks[order] = np.arange(1, n_models + 1)
        results = {
            "params": params_list,
            "test_score": scores,
            "mean_test_score": scores,
            "rank_test_score": ranks,
            "model_id": np.arange(n_models),
            "partial_fit_calls": calls,
        }
        for key in sorted({k for p in params_list for k in p}):
            results[f"param_{key}"] = np.ma.masked_all(n_models, dtype=object)
            for ci, p in enumerate(params_list):
                if key in p:
                    results[f"param_{key}"][ci] = p[key]
        self.cv_results_ = results
        self.best_index_ = int(np.nanargmax(scores))
        self.best_score_ = float(scores[self.best_index_])
        self.best_params_ = params_list[self.best_index_]
        self.best_estimator_ = models[self.best_index_]
        self.n_splits_ = 1
        self.multimetric_ = False
        self.scorer_ = scorer_raw
        self.metadata_ = {
            "n_models": n_models,
            "partial_fit_calls": int(calls.sum()),
            # the streamed-plane engagement record (ISSUE 14): which
            # execution plane the cohort rounds rode, how many
            # superblock dispatches the whole search paid, and the
            # mesh/sparse/fused composition — smoke suites assert on
            # this instead of trusting the gates
            "stream": (stream_plane.snapshot() if stream_plane is not None
                       else {"streamed": False}),
        }
        return self

    # -- post-fit delegation ----------------------------------------------
    def predict(self, X):
        return self.best_estimator_.predict(_to_host(X))

    def predict_proba(self, X):
        return self.best_estimator_.predict_proba(_to_host(X))

    def decision_function(self, X):
        return self.best_estimator_.decision_function(_to_host(X))

    def score(self, X, y=None):
        return self.scorer_(self.best_estimator_, _to_host(X), _to_host(y))

    @property
    def classes_(self):
        return self.best_estimator_.classes_


class IncrementalSearchCV(BaseIncrementalSearchCV):
    """Ref: dask_ml/model_selection/_incremental.py::IncrementalSearchCV —
    inverse-decay model dropping: after scoring event k, keep the top
    ``n_initial / (1 + decay_rate * k)`` models and give each one more
    partial_fit call; ``decay_rate=None`` keeps all models to max_iter."""

    def __init__(self, estimator, parameters, n_initial_parameters=10,
                 decay_rate=1.0, test_size=None, patience=False, tol=1e-3,
                 fits_per_score=1, max_iter=100, random_state=None,
                 scoring=None, verbose=False, prefix=""):
        super().__init__(estimator, parameters,
                         n_initial_parameters=n_initial_parameters,
                         test_size=test_size, patience=patience, tol=tol,
                         max_iter=max_iter, random_state=random_state,
                         scoring=scoring, verbose=verbose, prefix=prefix)
        self.decay_rate = decay_rate
        self.fits_per_score = fits_per_score
        self._step = 0

    def _reset_hook(self):
        # re-fitting the same instance must restart the decay schedule
        self._step = 0

    def _hook_state(self):
        return {"_step": self._step}

    def _n_initial(self):
        if self.n_initial_parameters == "grid":
            from sklearn.model_selection import ParameterGrid

            return len(ParameterGrid(self.parameters))
        return self.n_initial_parameters

    def _sample_params(self, n):
        if self.n_initial_parameters == "grid":
            from sklearn.model_selection import ParameterGrid

            return list(ParameterGrid(self.parameters))
        return super()._sample_params(n)

    def _additional_calls(self, info):
        self._step += 1
        scores = {mid: recs[-1]["score"] for mid, recs in info.items()}
        calls = {mid: recs[-1]["partial_fit_calls"]
                 for mid, recs in info.items()}
        if self.decay_rate is None:
            keep = list(scores)
        else:
            n_keep = max(
                1, int(self._n_initial() / (1 + self.decay_rate * self._step))
            )
            keep = sorted(scores, key=scores.get, reverse=True)[:n_keep]
        out = {}
        for mid in keep:
            if calls[mid] >= self.max_iter:
                out[mid] = 0
            else:
                out[mid] = self.fits_per_score
        if all(v == 0 for v in out.values()):
            return {mid: 0 for mid in out}
        return out


class InverseDecaySearchCV(IncrementalSearchCV):
    """Explicit-name alias used in later dask-ml versions."""
