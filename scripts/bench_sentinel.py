"""Bench regression sentinel: gate verify.sh on the recorded BENCH
history.

Compares the LATEST ``BENCH_r*.json`` round against per-metric budget
floors seeded from the reference round (``BENCH_r05.json`` by default,
the earliest available otherwise) and fails (exit 1) on any >20%
regression — the "throughput quietly rotted" failure mode the numeric
test suite cannot see.

Rules:

- throughput-like metrics (samples/s, rows/s, iterations/s — anything
  whose unit is not seconds) must stay >= floor = reference * (1 - tol);
- latency-like metrics (unit "s": c_grid_search_seconds,
  randomized_svd_seconds, hyperband_seconds) must stay <= reference *
  (1 + tol);
- a metric is only compared when BOTH rounds measured it on the SAME
  backend with a non-null value — a CPU-fallback round is not a
  regression of a TPU round, it's a different machine;
- error/null entries in the latest round for metrics the reference
  measured (same-backend) are reported but only WARN: a flaky secondary
  config must not hard-fail verify, the throughput floors do that.

Env knobs: ``BENCH_SENTINEL_TOL`` (default 0.20),
``BENCH_SENTINEL_REF`` (default r05).
"""

import glob
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOL = float(os.environ.get("BENCH_SENTINEL_TOL", "0.20"))
REF_ROUND = os.environ.get("BENCH_SENTINEL_REF", "r05")


def _load(path):
    try:
        with open(path) as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


def _tail_metrics(tail):
    """Recover metric entries from a TRUNCATED stdout tail: the driver
    keeps only the last ~2000 chars of bench.py's output, which cuts the
    headline open-brace but leaves the extra_metrics entries as complete
    ``{"metric": ...}`` objects — raw_decode each occurrence."""
    dec = json.JSONDecoder()
    out = {}
    for m in re.finditer(r'\{"metric"', tail or ""):
        try:
            obj, _ = dec.raw_decode(tail, m.start())
        except ValueError:
            continue
        if isinstance(obj, dict) and "metric" in obj:
            out[obj["metric"]] = obj
    return out


def _rounds():
    """(usable rounds, all round numbers on disk). A round that yields
    no metrics at all is still REPORTED via the second set — the newest
    round silently producing nothing is itself the failure mode this
    gate exists for."""
    out = {}
    on_disk = set()
    for path in glob.glob(os.path.join(REPO, "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if m:
            on_disk.add(int(m.group(1)))
        data = _load(path)
        if not (m and isinstance(data, dict)):
            continue
        # the driver wraps bench.py's JSON line as {"parsed": {...}}
        # (null when the line outgrew the driver's tail buffer); a raw
        # bench doc carries "metric" at top level — accept both, and
        # fall back to recovering entries from the truncated tail
        doc = data.get("parsed") if isinstance(data.get("parsed"),
                                               dict) else (
            data if "metric" in data else None)
        if doc is None:
            recovered = _tail_metrics(data.get("tail"))
            if recovered:
                doc = {"metric": None,
                       "extra_metrics": list(recovered.values())}
        if isinstance(doc, dict):
            out[int(m.group(1))] = (path, doc)
    return out, on_disk


def _metrics(doc):
    """Flatten a bench doc into {metric: {"value", "unit", "backend"}}
    (headline + extra_metrics; error entries keep value=None)."""
    out = {}
    for entry in [doc] + list(doc.get("extra_metrics") or []):
        if not isinstance(entry, dict) or not entry.get("metric"):
            continue
        out[entry["metric"]] = {
            "value": entry.get("value"),
            "unit": entry.get("unit", ""),
            "backend": entry.get("backend"),
        }
    return out


def main():
    rounds, on_disk = _rounds()
    if not on_disk:
        print("bench sentinel: no BENCH_r*.json recorded yet — skipping")
        return 0
    if not rounds or max(on_disk) > max(rounds):
        # the newest round on disk yielded NO metrics (hung/killed bench
        # with nothing recoverable) — exactly the silent-rot failure
        # this gate exists to catch; gating an older round as "latest"
        # would report OK over it
        print(
            f"  SENTINEL FAIL BENCH_r{max(on_disk):02d}.json exists but "
            "yields no metrics (bench hung or was killed?) — the newest "
            "round cannot be gated", file=sys.stderr,
        )
        return 1
    ref_num = None
    m = re.match(r"r(\d+)$", REF_ROUND)
    if m and int(m.group(1)) in rounds:
        ref_num = int(m.group(1))
    else:
        ref_num = min(rounds)
    latest_num = max(rounds)
    ref_path, ref_doc = rounds[ref_num]
    latest_path, latest_doc = rounds[latest_num]
    if latest_num == ref_num:
        print(f"bench sentinel: only the reference round "
              f"(r{ref_num:02d}) exists — nothing newer to gate")
        return 0
    ref = _metrics(ref_doc)
    latest = _metrics(latest_doc)
    # metrics the reference round predates (e.g. the fleet section) seed
    # their floor from the EARLIEST round that measured them — a new
    # metric becomes gated the round after it first records, instead of
    # staying floorless until someone rewrites the reference
    seeded = {}
    for num in sorted(rounds):
        if num == latest_num:
            break
        for name, entry in _metrics(rounds[num][1]).items():
            if name not in ref and name not in seeded \
                    and entry["value"] is not None:
                seeded[name] = (entry, num)
    for name, (entry, num) in seeded.items():
        ref[name] = entry
        print(f"bench sentinel: {name} floor seeded from r{num:02d} "
              "(absent from the reference round)")
    failures, warnings_, checked = [], [], 0
    for name, r in sorted(ref.items()):
        rv = r["value"]
        if rv is None or not isinstance(rv, (int, float)) or rv <= 0:
            continue
        cur = latest.get(name)
        if cur is None:
            # absent entirely (crashed bench section, truncated tail) —
            # the common partial-rot mode; surface it, don't skip it
            warnings_.append(
                f"{name}: measured in r{ref_num:02d} but ABSENT from "
                f"r{latest_num:02d}"
            )
            continue
        if cur["value"] is None:
            if cur.get("backend") in (None, r["backend"]):
                warnings_.append(
                    f"{name}: measured in r{ref_num:02d} but null/error "
                    f"in r{latest_num:02d}"
                )
            continue
        if cur["backend"] != r["backend"]:
            continue  # different machine class: not comparable
        cv = cur["value"]
        checked += 1
        lower_is_better = r["unit"] == "s"
        if lower_is_better:
            budget = rv * (1.0 + TOL)
            if cv > budget:
                failures.append(
                    f"{name}: {cv:.4g}s vs budget {budget:.4g}s "
                    f"(reference r{ref_num:02d}={rv:.4g}s, "
                    f"+{(cv / rv - 1) * 100:.1f}%)"
                )
        else:
            floor = rv * (1.0 - TOL)
            if cv < floor:
                failures.append(
                    f"{name}: {cv:.4g} vs floor {floor:.4g} "
                    f"(reference r{ref_num:02d}={rv:.4g}, "
                    f"{(cv / rv - 1) * 100:.1f}%)"
                )
    print(f"bench sentinel: r{latest_num:02d} vs r{ref_num:02d} floors, "
          f"{checked} comparable metrics, tol {TOL:.0%}")
    for w in warnings_:
        print(f"  WARN {w}")
    if failures:
        for f in failures:
            print(f"  SENTINEL FAIL {f}", file=sys.stderr)
        return 1
    print("bench sentinel OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
