"""GridSearchCV execution knobs (VERDICT r1 weak #8): scheduler/n_jobs/
cache_cv are behavior, not decoration — concurrent candidates run on
disjoint mesh subsets (SURVEY.md §3.4/§3.5 trial placement)."""

import numpy as np
import pytest

from dask_ml_tpu.linear_model import LogisticRegression
from dask_ml_tpu.model_selection import GridSearchCV
from dask_ml_tpu.model_selection._search import _submeshes
from dask_ml_tpu.parallel import default_mesh

GRID = {"C": [0.1, 1.0, 10.0]}


def _search(**kw):
    # cv=2: both folds share one shape, so concurrent submesh trials
    # exercise the placement machinery without an extra XLA compile per
    # distinct fold shape (the behavior under test is identical)
    return GridSearchCV(
        LogisticRegression(solver="lbfgs", max_iter=15),
        GRID, cv=2, **kw,
    )


@pytest.fixture(scope="module")
def seq_search(xy_classification):
    # ONE synchronous reference search shared by every comparison test
    # (a single CPU runs each fit serially; recomputing the identical
    # reference per test dominated this file's runtime)
    X, y = xy_classification
    return _search(scheduler="synchronous").fit(X, y)


@pytest.mark.slow
def test_threaded_matches_synchronous(xy_classification, seq_search):
    X, y = xy_classification
    par = _search(n_jobs=4).fit(X, y)  # default scheduler: threads
    np.testing.assert_allclose(
        seq_search.cv_results_["mean_test_score"],
        par.cv_results_["mean_test_score"], rtol=1e-5,
    )
    assert seq_search.best_params_ == par.best_params_


@pytest.mark.slow
def test_threaded_sharded_input(xy_classification, seq_search):
    from dask_ml_tpu.parallel import as_sharded

    X, y = xy_classification
    Xs, ys = as_sharded(X.astype(np.float32)), as_sharded(
        y.astype(np.float32))
    par = _search(n_jobs=2).fit(Xs, ys)
    np.testing.assert_allclose(
        par.cv_results_["mean_test_score"],
        seq_search.cv_results_["mean_test_score"], rtol=1e-4,
    )


def test_n_jobs_one_is_sequential(xy_classification):
    X, y = xy_classification
    s = _search(n_jobs=1).fit(X, y)
    assert s.best_score_ > 0.6


def test_invalid_scheduler_raises(xy_classification):
    X, y = xy_classification
    with pytest.raises(ValueError, match="scheduler"):
        _search(scheduler="distributed").fit(X, y)
    with pytest.raises(ValueError, match="n_jobs"):
        _search(n_jobs=0).fit(X, y)


def test_cache_cv_false_same_results(xy_classification, seq_search):
    X, y = xy_classification
    off = _search(cache_cv=False, scheduler="synchronous").fit(X, y)
    np.testing.assert_allclose(
        seq_search.cv_results_["mean_test_score"],
        off.cv_results_["mean_test_score"], rtol=1e-5,
    )


def test_submesh_partition_disjoint():
    mesh = default_mesh()
    n = mesh.devices.size
    if n < 2:
        pytest.skip("needs multi-device mesh")
    subs = _submeshes(mesh, 4)
    seen = set()
    for s in subs:
        ids = {d.id for d in s.devices.reshape(-1)}
        assert not (ids & seen)  # disjoint: programs can't share devices
        seen |= ids
    assert len(seen) <= n
