"""Chunked synthetic datasets.

Reference: ``dask_ml/datasets.py`` (SURVEY.md §2a Datasets row) — per-block
sklearn generators with per-block seeds. Here blocks = shards: each shard's
rows are generated with a seed derived from (random_state, shard index) so
the dataset is deterministic for a given mesh size, then placed directly
onto the mesh — the TPU equivalent of "generate where the chunk lives".

The generators run sklearn on the host per shard (generation is not the hot
path); the returned ShardedArray is device-resident.
"""

from __future__ import annotations

import numpy as np
import sklearn.datasets as skdata

from .parallel.mesh import data_shards, resolve_mesh
from .parallel.sharded import ShardedArray


def _per_shard(n_samples, mesh):
    s = data_shards(mesh)
    per = int(np.ceil(n_samples / s))
    sizes = [min(per, n_samples - i * per) for i in range(s)]
    return [max(sz, 0) for sz in sizes]


def _assemble(parts_X, parts_y, mesh):
    X = np.concatenate([p for p in parts_X if len(p)], axis=0)
    y = np.concatenate([p for p in parts_y if len(p)], axis=0)
    return (
        ShardedArray.from_array(X, mesh, dtype=np.float32),
        ShardedArray.from_array(y, mesh, dtype=np.float32),
    )


def make_classification(n_samples=100, n_features=20, random_state=None,
                        chunks=None, mesh=None, **kwargs):
    mesh = resolve_mesh(mesh)
    rs = np.random.RandomState(random_state)
    seeds = rs.randint(0, 2**31 - 1, size=data_shards(mesh))
    Xs, ys = [], []
    for sz, seed in zip(_per_shard(n_samples, mesh), seeds):
        if sz <= 0:
            Xs.append(np.empty((0, n_features))); ys.append(np.empty((0,)))
            continue
        X, y = skdata.make_classification(
            n_samples=sz, n_features=n_features, random_state=int(seed), **kwargs
        )
        Xs.append(X); ys.append(y)
    return _assemble(Xs, ys, mesh)


def make_regression(n_samples=100, n_features=100, random_state=None,
                    chunks=None, mesh=None, **kwargs):
    mesh = resolve_mesh(mesh)
    rs = np.random.RandomState(random_state)
    seeds = rs.randint(0, 2**31 - 1, size=data_shards(mesh))
    Xs, ys = [], []
    for sz, seed in zip(_per_shard(n_samples, mesh), seeds):
        if sz <= 0:
            Xs.append(np.empty((0, n_features))); ys.append(np.empty((0,)))
            continue
        X, y = skdata.make_regression(
            n_samples=sz, n_features=n_features, random_state=int(seed), **kwargs
        )
        Xs.append(X); ys.append(y)
    return _assemble(Xs, ys, mesh)


def make_blobs(n_samples=100, n_features=2, centers=None, random_state=None,
               chunks=None, mesh=None, **kwargs):
    mesh = resolve_mesh(mesh)
    rs = np.random.RandomState(random_state)
    if centers is None:
        centers = 3
    if np.isscalar(centers):
        # fix center locations once so every shard draws from the same blobs
        centers = rs.uniform(-10, 10, size=(centers, n_features))
    seeds = rs.randint(0, 2**31 - 1, size=data_shards(mesh))
    Xs, ys = [], []
    for sz, seed in zip(_per_shard(n_samples, mesh), seeds):
        if sz <= 0:
            Xs.append(np.empty((0, n_features))); ys.append(np.empty((0,)))
            continue
        X, y = skdata.make_blobs(
            n_samples=sz, n_features=n_features, centers=centers,
            random_state=int(seed), **kwargs
        )
        Xs.append(X); ys.append(y)
    return _assemble(Xs, ys, mesh)


def make_counts(n_samples=100, n_features=20, random_state=None, scale=1.0,
                chunks=None, mesh=None):
    """Poisson-target regression data (ref: dask_ml/datasets.py::make_counts)."""
    mesh = resolve_mesh(mesh)
    rs = np.random.RandomState(random_state)
    beta = rs.normal(0, 1, size=n_features) * scale / np.sqrt(n_features)
    seeds = rs.randint(0, 2**31 - 1, size=data_shards(mesh))
    Xs, ys = [], []
    for sz, seed in zip(_per_shard(n_samples, mesh), seeds):
        if sz <= 0:
            Xs.append(np.empty((0, n_features))); ys.append(np.empty((0,)))
            continue
        r = np.random.RandomState(int(seed))
        X = r.normal(0, 1, size=(sz, n_features))
        y = r.poisson(np.exp(X @ beta))
        Xs.append(X); ys.append(y.astype(np.float64))
    return _assemble(Xs, ys, mesh)
