"""HyperbandSearchCV.

Reference: ``dask_ml/model_selection/_hyperband.py`` (SURVEY.md §2a, §3.5
call stack): computes Hyperband brackets from (max_iter, aggressiveness)
and runs a SuccessiveHalving schedule per bracket. Like the reference,
all brackets are INTERLEAVED through one shared controller fit (VERDICT
r3 missing #4): every adaptive round advances the union of live
candidates across brackets, so cohort batching and submesh placement mix
brackets and an early-stopped bracket frees budget for live ones instead
of serializing behind them. With the streamed cohort plane (ISSUE 14,
``config.search_stream``), an interleaved round over host X is ONE
``BlockStream`` superblock pass: the brackets' heterogeneous
``n_calls`` fold onto a single block-step timeline with per-model
activity masks, so one data pass trains the whole bracket union. Under
multi-process, whole brackets are striped across processes (each an
independent SHA sweep on its local mesh, itself riding the streamed
plane on that mesh) — the cross-host unit stays coarse while the
intra-process execution interleaves.
"""

from __future__ import annotations

import math

import numpy as np
from sklearn.model_selection import ParameterSampler

from ..base import clone
from ._incremental import (
    BaseIncrementalSearchCV, disable_process_distribution,
    host_view_estimator,
)
from ._successive_halving import SuccessiveHalvingSearchCV


def _brackets(max_iter, eta):
    """Hyperband bracket table: [(bracket, n_models, n_initial_iter)]."""
    s_max = int(math.floor(math.log(max_iter, eta)))
    B = (s_max + 1) * max_iter
    out = []
    for s in range(s_max, -1, -1):
        n = int(math.ceil(B / max_iter * (eta ** s) / (s + 1)))
        r = max(1, int(max_iter * (eta ** -s)))
        out.append((s, n, r))
    return out


class HyperbandSearchCV(BaseIncrementalSearchCV):
    """Ref: _hyperband.py::HyperbandSearchCV."""

    def __init__(self, estimator, parameters, max_iter=81, aggressiveness=3,
                 patience=False, tol=1e-3, test_size=None, random_state=None,
                 scoring=None, verbose=False, prefix=""):
        super().__init__(estimator, parameters,
                         test_size=test_size, patience=patience, tol=tol,
                         max_iter=max_iter, random_state=random_state,
                         scoring=scoring, verbose=verbose, prefix=prefix)
        self.max_iter = max_iter
        self.aggressiveness = aggressiveness

    def metadata(self):
        """Expected work BEFORE fitting (ref: HyperbandSearchCV.metadata)."""
        brackets = _brackets(self.max_iter, self.aggressiveness)
        bracket_info = []
        total_models = 0
        total_calls = 0
        for s, n, r in brackets:
            calls = self._bracket_calls(n, r)
            bracket_info.append({
                "bracket": s, "n_models": n,
                "partial_fit_calls": calls,
            })
            total_models += n
            total_calls += calls
        return {
            "n_models": total_models,
            "partial_fit_calls": total_calls,
            "brackets": bracket_info,
        }

    def _bracket_calls(self, n, r):
        eta = self.aggressiveness
        calls = n * r
        while True:
            # successive rungs: top n/eta models train to min(r*eta,
            # max_iter) — the same cap the SHA controller applies
            # (_successive_halving.py next_target), so the estimate counts
            # the final partial rung and the survivor's run to max_iter
            nk = max(1, math.floor(n / eta))
            rk = min(r * eta, self.max_iter)
            if rk == r:
                break
            calls += nk * (rk - r)
            n, r = nk, rk
        return calls

    # -- interleaved single-process schedule (controller hooks) -----------
    def _n_initial(self):
        return sum(n for _, n, _ in _brackets(self.max_iter,
                                              self.aggressiveness))

    def _sample_params(self, n):
        # per-bracket draws with the SAME seeds the sequential-bracket
        # (and multi-process) path uses, so the candidate sets agree.
        # ParameterSampler TRUNCATES small discrete spaces, so the
        # realized per-bracket counts are recorded for _reset_hook's
        # model-id ranges (assuming the nominal bracket sizes would
        # misalign every bracket after a truncated one).
        out = []
        self._sampled_counts = []
        for s, nb, _r in _brackets(self.max_iter, self.aggressiveness):
            seed = (None if self.random_state is None
                    else self.random_state + s)
            drawn = list(ParameterSampler(self.parameters, nb,
                                          random_state=seed))
            self._sampled_counts.append(len(drawn))
            out.extend(drawn)
        return out

    def _reset_hook(self):
        # model-id ranges per bracket + each bracket's SHA rung position
        self._bounds = []
        self._rungs = {}
        off = 0
        counts = getattr(self, "_sampled_counts", None)
        for i, (s, nb, r) in enumerate(
            _brackets(self.max_iter, self.aggressiveness)
        ):
            size = counts[i] if counts is not None else nb
            self._bounds.append((s, off, off + size, r))
            self._rungs[s] = 0
            off += size

    def _hook_state(self):
        return {"_rungs": dict(self._rungs)}

    def _bracket_of(self, mid):
        for s, lo, hi, _r in self._bounds:
            if lo <= mid < hi:
                return s
        return None

    def _trial_tags(self, mid):
        """Per-trial telemetry tag: which Hyperband bracket this model
        belongs to (``_bounds`` exists once ``_reset_hook`` ran; the
        multi-process path runs per-bracket SHAs whose prefix already
        names the bracket)."""
        if getattr(self, "_bounds", None):
            return {"bracket": self._bracket_of(mid)}
        return {}

    def _additional_calls(self, info):
        """One SHA step PER BRACKET over that bracket's live candidates,
        merged into a single round request — the round-robin interleave
        (ref _hyperband.py: all brackets submitted to one scheduler)."""
        eta = self.aggressiveness
        out = {}
        for s, lo, hi, r in self._bounds:
            binfo = {mid: recs for mid, recs in info.items()
                     if lo <= mid < hi}
            if not binfo:
                continue
            scores = {mid: recs[-1]["score"] for mid, recs in binfo.items()}
            calls = {mid: recs[-1]["partial_fit_calls"]
                     for mid, recs in binfo.items()}
            target = min(r * (eta ** self._rungs[s]), self.max_iter)
            pending = {mid: target - calls[mid]
                       for mid in scores if calls[mid] < target}
            if pending:
                out.update(pending)
                continue
            n_keep = max(1, math.floor(len(scores) / eta))
            keep = sorted(scores, key=scores.get, reverse=True)[:n_keep]
            self._rungs[s] += 1
            next_target = min(r * (eta ** self._rungs[s]), self.max_iter)
            promote = {mid: next_target - calls[mid] for mid in keep}
            out.update({mid: c for mid, c in promote.items() if c > 0})
        return out

    def _fit_interleaved(self, X, y, **fit_params):
        super().fit(X, y, **fit_params)
        # bracket annotations on the merged controller outputs
        for rec in self.history_:
            rec["bracket"] = self._bracket_of(rec["model_id"])
        res = self.cv_results_
        res["bracket"] = np.asarray([
            self._bracket_of(mid) for mid in res["model_id"]
        ])
        meta_brackets = []
        for s, lo, hi, _r in self._bounds:
            sel = (res["model_id"] >= lo) & (res["model_id"] < hi)
            meta_brackets.append({
                "bracket": s, "n_models": int(sel.sum()),
                "partial_fit_calls": int(
                    res["partial_fit_calls"][sel].sum()
                ),
            })
        self.metadata_["brackets"] = meta_brackets
        return self

    def fit(self, X, y=None, **fit_params):
        rng_seed = self.random_state
        brackets = _brackets(self.max_iter, self.aggressiveness)

        # Multi-process: brackets are independent SHA sweeps, so each
        # process runs a strided share on its local-device mesh and the
        # per-bracket payloads (history, results, best model) merge via
        # one object-allgather — BASELINE configs[4] 'trials parallel
        # across TPU hosts' (SURVEY.md §3.5). Single-process: one
        # interleaved controller fit over all brackets.
        from ..parallel import distributed as _dist

        n_proc = _dist.process_count()
        if n_proc == 1:
            return self._fit_interleaved(X, y, **fit_params)
        from ..parallel.sharded import ShardedArray

        if isinstance(X, ShardedArray) or isinstance(y, ShardedArray):
            raise ValueError(
                "multi-process Hyperband requires host-resident X/y "
                "(each process loads its copy and runs a disjoint "
                "bracket subset)"
            )
        from ..parallel.distributed import local_mesh
        from ..parallel.mesh import use_mesh

        placement_mesh = local_mesh()
        self._dist_stats = (_dist.process_index(), n_proc)

        payloads = {}
        local_exc = None
        for bi, (s, n, r) in enumerate(brackets):
            if bi % n_proc != _dist.process_index():
                continue
            sha = SuccessiveHalvingSearchCV(
                clone(self.estimator), self.parameters,
                n_initial_parameters=n, n_initial_iter=r,
                max_iter=self.max_iter, aggressiveness=self.aggressiveness,
                test_size=self.test_size, patience=self.patience,
                tol=self.tol,
                random_state=None if rng_seed is None else rng_seed + s,
                scoring=self.scoring, verbose=self.verbose,
                prefix=f"{self.prefix}bracket={s}",
            )
            # SPLIT with the shared seed (sampling stays rng_seed + s):
            # the single-process interleaved fit scores every bracket on
            # one split, and a 1-host vs N-host run of the same search
            # must produce the same scores
            sha._split_random_state = rng_seed
            try:
                # bracket-level distribution: the inner SHA must not also
                # distribute its candidates (peers run OTHER brackets)
                with disable_process_distribution(), \
                        use_mesh(placement_mesh):
                    sha.fit(X, y, **fit_params)
            except Exception as e:
                # hold the failure: peers must learn about it through the
                # gather below instead of blocking in it forever
                local_exc = e
                break
            payloads[bi] = {
                "s": s,
                "history": sha.history_,
                "model_history": sha.model_history_,
                "results": dict(sha.cv_results_),
                "best_score": sha.best_score_,
                "best_params": sha.best_params_,
                "best_estimator": host_view_estimator(sha.best_estimator_),
            }

        from ..parallel.distributed import allgather_object

        parts = allgather_object({
            "payloads": {} if local_exc is not None else payloads,
            "error": None if local_exc is None else repr(local_exc),
        })
        if local_exc is not None:
            raise local_exc
        bad = [p["error"] for p in parts if p["error"] is not None]
        if bad:
            raise RuntimeError(
                f"peer process failed during distributed Hyperband: {bad}"
            )
        payloads = {}
        for part in parts:
            payloads.update(part["payloads"])

        self.history_ = []
        self.model_history_ = {}
        all_results = []
        best = (-np.inf, None, None, None)  # score, params, est, bracket
        meta_brackets = []
        offset = 0
        for bi in range(len(brackets)):
            p = payloads[bi]
            s = p["s"]
            for rec in p["history"]:
                rec = dict(rec)
                rec["bracket"] = s
                rec["model_id"] = rec["model_id"] + offset
                self.history_.append(rec)
            for mid, recs in p["model_history"].items():
                self.model_history_[mid + offset] = recs
            res = p["results"]
            n_models = len(res["params"])
            res["bracket"] = np.full(n_models, s)
            res["model_id"] = res["model_id"] + offset
            all_results.append(res)
            meta_brackets.append({
                "bracket": s, "n_models": n_models,
                "partial_fit_calls": int(res["partial_fit_calls"].sum()),
            })
            if p["best_score"] > best[0]:
                best = (p["best_score"], p["best_params"],
                        p["best_estimator"], s)
            offset += n_models

        # merge bracket cv_results_
        keys = set().union(*(r.keys() for r in all_results))
        merged = {}
        for k in keys:
            vals = [
                r.get(k, np.ma.masked_all(len(r["params"]), dtype=object))
                for r in all_results
            ]
            if k == "params":
                merged[k] = [p for r in all_results for p in r["params"]]
            elif isinstance(vals[0], np.ma.MaskedArray):
                merged[k] = np.ma.concatenate(vals)
            else:
                merged[k] = np.concatenate(vals)
        scores = merged["test_score"]
        order = np.argsort(-scores, kind="stable")
        ranks = np.empty(len(scores), np.int32)
        ranks[order] = np.arange(1, len(scores) + 1)
        merged["rank_test_score"] = ranks
        self.cv_results_ = merged

        self.best_score_ = float(best[0])
        self.best_params_ = best[1]
        self.best_estimator_ = best[2]
        self.best_index_ = int(np.argmax(scores))
        self.scorer_ = None
        from ..metrics.scorer import check_scoring

        self.scorer_ = check_scoring(self.estimator, self.scoring)
        self.multimetric_ = False
        self.metadata_ = {
            "n_models": sum(b["n_models"] for b in meta_brackets),
            "partial_fit_calls": sum(
                b["partial_fit_calls"] for b in meta_brackets
            ),
            "brackets": meta_brackets,
        }
        return self
