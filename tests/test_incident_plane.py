"""The incident plane (ISSUE 20): alert rule grammar, firing/resolved
state machines, event routing from the drift/fleet/watchdog latches,
black-box incident capture (rate limit, retention, atomicity), deep
profiling fallbacks, report/endpoint surfaces, and the zero-overhead
contract."""

import json
import os
import threading
import time

import numpy as np
import pytest

from dask_ml_tpu import config
from dask_ml_tpu.observability import alerts, incidents, live
from dask_ml_tpu.observability._counters import (
    counter_add,
    counters_reset,
)


@pytest.fixture(autouse=True)
def _clean_plane():
    yield
    alerts.reset()
    incidents.reset()
    counters_reset()
    live.metrics_reset()


# -- rule grammar ------------------------------------------------------------

def test_parse_rules_grammar():
    rules = alerts.parse_rules(
        "serving_slo_violations:rate>5/60s, drift_score_max:gauge>0.2;"
        "fit_eta_seconds:gauge>1800, recompiles:counter>=10"
    )
    assert [r.kind for r in rules] == ["rate", "gauge", "gauge",
                                       "counter"]
    r = rules[0]
    assert (r.metric, r.op, r.threshold, r.window_s) == \
        ("serving_slo_violations", ">", 5.0, 60.0)
    assert rules[1].window_s is None
    assert rules[3].op == ">="


def test_parse_rules_empty_and_builtin_are_no_rules():
    assert alerts.parse_rules("") == []
    assert alerts.parse_rules("builtin") == []
    assert alerts.parse_rules(" builtin , ") == []


@pytest.mark.parametrize("bad", [
    "nocolon",
    "x:bogus>1",
    "x:rate>1",          # rate needs a window
    "x:gauge>1/30s",     # windows are rate-only
    "x:rate>1/0s",       # window must be positive
    "x:gauge!1",
    "x:gauge>abc",
])
def test_parse_rules_typed_rejection_lists_accepted_forms(bad):
    with pytest.raises(alerts.AlertRuleError) as ei:
        alerts.parse_rules(bad)
    msg = str(ei.value)
    # the rejection is self-documenting: the full accepted-forms
    # vocabulary rides every error
    assert "accepted forms" in msg
    assert "rate" in msg and "gauge" in msg and "builtin" in msg
    assert isinstance(ei.value, ValueError)


# -- state machines (driven tick-by-tick, no ticker thread) ------------------

def _engine(spec, interval=1.0):
    rules = alerts.parse_rules(spec)
    return alerts.AlertEngine(rules, interval)


def test_gauge_rule_fires_and_resolves_with_hysteresis():
    eng = _engine("my_gauge:gauge>0.5")
    now = time.time()
    live.gauge_set("my_gauge", 0.9)
    out = eng.tick(now)
    assert [(r.name.split(":")[0], tr) for r, tr in out] == \
        [("my_gauge", "firing")]
    assert eng.rows()[0]["state"] == "firing"
    # one clean tick is NOT enough (hysteresis) ...
    live.gauge_set("my_gauge", 0.1)
    assert eng.tick(now + 1) == []
    assert eng.rows()[0]["state"] == "firing"
    # ... the second clean tick resolves
    out = eng.tick(now + 2)
    assert [tr for _, tr in out] == ["resolved"]
    assert eng.rows()[0]["state"] == "ok"
    assert eng.rows()[0]["fired"] == 1


def test_gauge_rule_worst_series_and_no_data():
    eng = _engine("g:gauge>1.0, h:gauge<0.0")
    now = time.time()
    # absent families = no data = no firing
    assert eng.tick(now) == []
    # worst series for the op direction: any one series breaching fires
    live.gauge_set("g", 0.5, (("shard", "a"),))
    live.gauge_set("g", 2.0, (("shard", "b"),))
    live.gauge_set("h", 0.5)
    out = eng.tick(now + 1)
    assert [r.metric for r, _ in out] == ["g"]


def test_rate_rule_first_sample_is_baseline():
    """Counter totals from BEFORE the engine armed can never fire a
    rate rule — the post-warmup-recompiles semantics."""
    counter_add("ev_total", 100)   # pre-arm history
    eng = _engine("ev_total:rate>2/10s")
    now = time.time()
    assert eng.tick(now) == []      # baseline sample, no verdict
    assert eng.tick(now + 1) == []  # no delta
    counter_add("ev_total", 5)
    out = eng.tick(now + 2)
    assert [tr for _, tr in out] == ["firing"]
    # the window slides: once the bump ages out, two clean ticks resolve
    assert eng.tick(now + 14) == []
    out = eng.tick(now + 15)
    assert [tr for _, tr in out] == ["resolved"]


def test_counter_rule_absolute_total():
    eng = _engine("boom:counter>=3")
    now = time.time()
    counter_add("boom", 2)
    assert eng.tick(now) == []
    counter_add("boom", 1)
    assert [tr for _, tr in eng.tick(now + 1)] == ["firing"]


def test_event_rule_fires_on_note_event_and_ages_out(tmp_path):
    with config.set(obs_alert_rules="builtin", obs_alert_interval_s=60):
        eng = alerts.ensure_engine()
        assert eng is not None
        rec = alerts.note_event("watchdog_stall", value=4.2,
                                meta={"span": "fit"})
        assert rec["event"] == "watchdog_stall"
        data = alerts.alerts_data()
        assert data["armed"] and \
            "builtin:watchdog_stall" in data["firing"]
        assert data["transitions"][-1]["state"] == "firing"
        # firing transitions increment the counter + set the gauge
        from dask_ml_tpu.observability._counters import counters_snapshot

        assert counters_snapshot().get("alerts_fired") == 1
        key = ("alerts_firing", (("rule", "builtin:watchdog_stall"),))
        assert live.gauges_snapshot()[key] == 1.0
        # a fresh event while firing refreshes the clock, no re-fire
        alerts.note_event("watchdog_stall", value=5.0)
        assert counters_snapshot().get("alerts_fired") == 1
        # age-based auto-resolve: EVENT_RESOLVE_TICKS intervals without
        # a fresh event
        out = eng.tick(now=time.time() + 60 * 10)
        assert [tr for _, tr in out] == ["resolved"]
        assert live.gauges_snapshot()[key] == 0.0


def test_events_ledger_records_without_engine():
    """The crossing ledger is always on — drift/fleet/watchdog events
    land even with no engine armed (the old private-deque role)."""
    assert alerts.engine() is None
    rec = alerts.note_event("drift", value=0.4, meta={"model": "m"})
    assert alerts.events("drift")[-1] is rec
    assert alerts.events("fleet_slo_burn") == []


def test_note_error_is_inert_by_default_and_routes_when_armed():
    alerts.note_error(ValueError("x"), "serving_execute")
    assert alerts.events("typed_error") == []   # disarmed: no ledger spam
    with config.set(obs_alert_rules="builtin", obs_alert_interval_s=60):
        alerts.ensure_engine()
        alerts.note_error(ValueError("boom"), "serving_execute")
        evs = alerts.events("typed_error")
        assert evs and evs[-1]["error"] == "ValueError"
        assert "builtin:typed_error" in alerts.alerts_data()["firing"]


def test_engine_transitions_emit_jsonl_and_capture(tmp_path):
    trace = str(tmp_path / "tr")
    idir = str(tmp_path / "inc")
    with config.set(trace_dir=trace, incident_dir=idir,
                    obs_alert_interval_s=60):
        eng = alerts.ensure_engine()   # incident_dir alone arms built-ins
        assert eng is not None
        alerts.note_event("fleet_slo_burn", value=2.5,
                          meta={"burn_rate": 2.5})
        recs = [json.loads(line)
                for line in open(os.path.join(trace, "trace.jsonl"))]
        al = [r for r in recs if r.get("alert")]
        assert al and al[-1]["rule"] == "builtin:fleet_slo_burn" \
            and al[-1]["state"] == "firing"
        # the firing transition captured one bundle
        files = [n for n in os.listdir(idir)
                 if n.startswith("incident_") and n.endswith(".json")]
        assert len(files) == 1
        inc = [r for r in [json.loads(line) for line in
                           open(os.path.join(trace, "trace.jsonl"))]
               if r.get("incident")]
        assert inc and inc[-1]["reason"] == "alert:builtin:fleet_slo_burn"


# -- source wiring (dedupe: one crossing = one event) ------------------------

def test_drift_canary_crossing_routes_through_ledger():
    from dask_ml_tpu.observability import drift

    rng = np.random.RandomState(0)
    old = rng.randn(400)
    new = old + 10.0   # wildly disagreeing versions
    with config.set(obs_drift_threshold=0.05):
        verdict = drift.record_canary("m", 1, 2, "predict", old, new)
    assert verdict["disagreement"] > 0.05
    evs = alerts.events("drift")
    assert len(evs) == 1 and evs[0]["pair"] == "canary"
    drift.reset()


def test_fleet_burn_latch_routes_through_ledger_same_record():
    from dask_ml_tpu.observability.fleet import MetricsFederator

    fed = MetricsFederator("f")
    doc1 = {"counters": {"serving_slo_violations": 0,
                         "serving_requests": 100}}
    doc2 = {"counters": {"serving_slo_violations": 50,
                         "serving_requests": 200}}
    fed.ingest([("p0", doc1)])
    fed.ingest([("p0", doc2)])       # 50/100 violations >> 1% budget
    assert len(fed._alerts) == 1
    evs = alerts.events("fleet_slo_burn")
    assert len(evs) == 1
    # the SAME object serves both surfaces — one crossing, one record
    assert fed._alerts[0] is evs[0]
    assert fed._alerts[0]["burn_rate"] > 1.0


def test_watchdog_stall_feeds_the_ledger():
    from dask_ml_tpu.observability import span
    from dask_ml_tpu.observability._watchdog import Watchdog

    wd = Watchdog(timeout_s=0.05, poll_s=0.02)
    with wd:
        with span("stalling"):
            deadline = time.time() + 5
            while not alerts.events("watchdog_stall"):
                assert time.time() < deadline, "no stall event"
                time.sleep(0.02)
    evs = alerts.events("watchdog_stall")
    assert evs and evs[-1]["span"] == "stalling"


# -- incident capture --------------------------------------------------------

def _arm(tmp_path, **kw):
    return config.set(incident_dir=str(tmp_path / "inc"), **kw)


def test_capture_bundle_contents_and_rate_limit(tmp_path):
    with _arm(tmp_path):
        path = incidents.capture_incident("test", rule="r1",
                                          meta={"k": "v"})
        assert path and os.path.exists(path)
        bundle = json.load(open(path))
        for key in ("open_spans", "recent_spans", "traces", "counters",
                    "gauges", "histograms", "programs",
                    "device_memory", "fault_plan", "alerts",
                    "watchdog_stalls", "config"):
            assert key in bundle, key
        assert bundle["reason"] == "test" and bundle["rule"] == "r1"
        assert bundle["meta"] == {"k": "v"}
        assert len(bundle["config"]["fingerprint"]) == 64
        assert bundle["config"]["values"]["incident_keep"] == 16
        # second capture inside the window: refused, counted
        assert incidents.capture_incident("again") is None
        from dask_ml_tpu.observability._counters import counters_snapshot

        snap = counters_snapshot()
        assert snap.get("incidents_captured") == 1
        assert snap.get("incidents_rate_limited") == 1
        # force bypasses the limit
        p2 = incidents.capture_incident("forced", force=True)
        assert p2 and p2 != path
        data = incidents.incidents_data()
        assert [c["reason"] for c in data["captured"]] == ["test",
                                                           "forced"]


def test_capture_disabled_without_dir(tmp_path):
    assert incidents.capture_incident("x") is None
    assert incidents.incidents_data()["captured"] == []


def test_retention_evicts_oldest(tmp_path):
    with _arm(tmp_path, incident_keep=2):
        paths = [incidents.capture_incident(f"r{i}", force=True)
                 for i in range(4)]
        idir = str(tmp_path / "inc")
        left = sorted(n for n in os.listdir(idir)
                      if n.startswith("incident_")
                      and n.endswith(".json"))
        assert len(left) == 2
        # the SURVIVORS are the newest two
        assert os.path.basename(paths[-1]) in left
        assert os.path.basename(paths[0]) not in left


def test_load_bundles_skips_unparseable(tmp_path):
    with _arm(tmp_path):
        incidents.capture_incident("good", force=True)
        idir = str(tmp_path / "inc")
        with open(os.path.join(idir, "incident_9999_bad.json"),
                  "w") as f:
            f.write("{truncated")
        rows = incidents.load_bundles(idir)
        assert len(rows) == 2
        assert rows[0].get("reason") == "good"
        assert "error" in rows[1]
    assert "error" in incidents.load_bundles("/nonexistent/dir")[0]


def test_config_fingerprint_tracks_knobs():
    fp1, _ = incidents.config_fingerprint()
    with config.set(incident_keep=3):
        fp2, values = incidents.config_fingerprint()
    assert fp1 != fp2 and values["incident_keep"] == 3
    fp3, _ = incidents.config_fingerprint()
    assert fp3 == fp1


# -- deep profiling ----------------------------------------------------------

def test_deep_profile_noop_with_reason_off_tpu(tmp_path):
    import jax

    if jax.default_backend() == "tpu":
        pytest.skip("asserts the off-TPU fallback")
    with _arm(tmp_path):
        out = incidents.deep_profile(1)
    assert out["profiled"] is False
    assert "TPU" in out["reason"]
    assert out["backend"] == jax.default_backend()


def test_deep_profile_rejects_bad_seconds(tmp_path):
    with _arm(tmp_path):
        assert incidents.deep_profile(0)["profiled"] is False
        assert incidents.deep_profile("nan-ish")["profiled"] is False
        assert incidents.deep_profile(-3)["profiled"] is False


# -- report / endpoint surfaces ----------------------------------------------

def test_report_summaries_from_transition_records():
    from dask_ml_tpu.observability.report import (
        render_report,
        report_data,
    )

    records = [
        {"alert": True, "rule": "r1", "kind": "rate", "metric": "m",
         "state": "firing", "value": 7, "t_unix": 100.0},
        {"alert": True, "rule": "r1", "kind": "rate", "metric": "m",
         "state": "resolved", "value": 0, "t_unix": 160.0},
        {"alert": True, "rule": "r2", "kind": "gauge", "metric": "g",
         "state": "firing", "value": 0.9, "t_unix": 200.0},
        {"incident": True, "path": "/tmp/i.json", "reason": "alert:r1",
         "rule": "r1", "t_unix": 101.0},
    ]
    data = report_data(records)
    al = data["alerts"]
    assert al["firing"] == ["r2"]
    by_rule = {r["rule"]: r for r in al["rules"]}
    assert by_rule["r1"]["state"] == "ok" and by_rule["r1"]["fired"] == 1
    assert by_rule["r2"]["state"] == "firing"
    assert data["incidents"][0]["reason"] == "alert:r1"
    text = render_report(data)
    assert "alerts (rules engine)" in text
    assert "incidents (black-box bundles)" in text
    assert "r2" in text and "alert:r1" in text


def test_report_prefers_status_snapshot_blocks():
    from dask_ml_tpu.observability.report import (
        summarize_alerts,
        summarize_incidents,
    )

    snap = {"armed": True, "rules": [{"rule": "x", "state": "firing"}],
            "firing": ["x"], "transitions": []}
    records = [
        {"alert": True, "rule": "old", "state": "firing", "t_unix": 1},
        {"alerts": snap},
        {"incidents": [{"path": "p", "reason": "r", "rule": None,
                        "t_unix": 2}]},
    ]
    assert summarize_alerts(records) is snap
    assert summarize_incidents(records)[0]["path"] == "p"


def test_report_cli_incidents_flag(tmp_path, capsys):
    from dask_ml_tpu.observability.report import main

    with _arm(tmp_path):
        incidents.capture_incident("cli-test", force=True)
    idir = str(tmp_path / "inc")
    assert main(["--incidents", idir]) == 0
    out = capsys.readouterr().out
    assert "incident bundles" in out and "cli-test" in out
    # --json rides the same object
    assert main(["--incidents", idir, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["incident_bundles"][0]["reason"] == "cli-test"
    assert doc["incident_bundles"][0]["counters"] is not None


def test_status_and_alerts_endpoint(tmp_path):
    import urllib.request

    with config.set(obs_alert_rules="builtin", obs_alert_interval_s=60,
                    incident_dir=str(tmp_path / "inc")):
        alerts.ensure_engine()
        alerts.note_event("watchdog_stall", value=1.0)
        doc = live.status_data()
        assert doc["alerts"]["armed"]
        assert "builtin:watchdog_stall" in doc["alerts"]["firing"]
        assert doc["incidents"]["captured"], "capture-on-firing missing"
        # the same blocks ride report_data as synthetic records — no
        # second serialization path
        assert doc["report"]["alerts"] is not None
        assert doc["report"]["alerts"]["firing"] == \
            doc["alerts"]["firing"]
        assert doc["report"]["incidents"] == \
            doc["incidents"]["captured"]
        with live.TelemetryServer(port=0) as srv:
            with urllib.request.urlopen(srv.url + "/alerts",
                                        timeout=5) as resp:
                adoc = json.loads(resp.read().decode())
        assert adoc["armed"] and adoc["rules"]
        assert adoc["events"][-1]["event"] == "watchdog_stall"


def test_export_lanes_alert_and_incident_instants():
    from dask_ml_tpu.observability.export import to_chrome_trace

    records = [
        {"span": "fit", "span_id": 1, "parent_id": None, "depth": 0,
         "time": 1.0, "t_unix": 101.0, "wall_s": 0.5,
         "thread": "MainThread"},
        {"alert": True, "rule": "r1", "state": "firing", "value": 3,
         "time": 1.2, "t_unix": 101.2, "thread": "MainThread"},
        {"alert": True, "rule": "r1", "state": "resolved", "value": 0,
         "time": 1.3, "t_unix": 101.3, "thread": "MainThread"},
        {"incident": True, "reason": "alert:r1", "path": "/tmp/x.json",
         "time": 1.25, "t_unix": 101.25, "thread": "MainThread"},
    ]
    trace = to_chrome_trace(records)
    names = [e["name"] for e in trace["traceEvents"]
             if e.get("ph") == "i"]
    assert "alert firing: r1" in names
    assert "incident: alert:r1" in names
    # resolved transitions stay off the timeline
    assert not any("resolved" in n for n in names)


# -- zero-overhead contract --------------------------------------------------

def test_incident_plane_adds_nothing_when_disabled():
    """Default config: no engine object, no ticker thread, no capture
    ring growth — and the streamed-SGD scan kernel's jaxpr stays
    byte-identical across an arm/disarm cycle of the full plane (the
    engine is host dicts + one thread; nothing of it exists inside
    jit)."""
    import jax
    import jax.numpy as jnp

    from dask_ml_tpu.models.sgd import _sgd_sb_scan
    from dask_ml_tpu.observability._programs import unwrap

    def scan_jaxpr():
        body = unwrap(_sgd_sb_scan)
        K, S, d = 2, 8, 3
        return str(jax.make_jaxpr(
            lambda W, Xs, ys, c, lrs: body(
                W, Xs, ys, c, lrs, 1e-4, 1.0, 0.0, 1.0, "hinge", None
            )
        )(jnp.zeros(d + 1), jnp.zeros((K, S, d)), jnp.zeros((K, S)),
          jnp.zeros(K, jnp.int32), jnp.zeros(K)))

    assert alerts.engine() is None
    assert alerts.ensure_engine() is None      # "" knobs: stays None
    assert not [t for t in threading.enumerate()
                if t.name == "dask-ml-tpu-alerts"]
    baseline = scan_jaxpr()
    with config.set(obs_alert_rules="builtin", obs_alert_interval_s=60):
        eng = alerts.ensure_engine()
        assert eng is not None and eng._thread.is_alive()
        assert scan_jaxpr() == baseline
    alerts.stop_engine()
    assert not [t for t in threading.enumerate()
                if t.name == "dask-ml-tpu-alerts"]
    assert scan_jaxpr() == baseline


def test_bad_rule_spec_raises_into_the_arming_caller():
    with config.set(obs_alert_rules="totally:wrong>"):
        with pytest.raises(alerts.AlertRuleError):
            live.ensure_telemetry()
    assert alerts.engine() is None
