"""Ref: dask_ml/ensemble/__init__.py."""
from ._blockwise import BlockwiseVotingClassifier, BlockwiseVotingRegressor

__all__ = ["BlockwiseVotingClassifier", "BlockwiseVotingRegressor"]
