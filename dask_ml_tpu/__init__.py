"""dask_ml_tpu — a TPU-native distributed ML framework with the
capabilities of dask-ml (see SURVEY.md for the blueprint).

Layout:
- ``parallel/`` — mesh/sharding substrate
- ``ops/``      — reductions, distributed linalg, pairwise kernels
- ``models/``   — estimator implementations + GLM solver library
- ``utils/``    — validation helpers
- sklearn-parity namespaces currently importable: ``linear_model``,
  ``preprocessing``, ``metrics``, ``datasets`` (more land per
  SURVEY.md §7's build plan).
"""

__version__ = "0.1.0"
