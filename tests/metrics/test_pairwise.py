"""Pairwise distances/kernels vs sklearn (the §4 parity contract)."""

import numpy as np
import pytest
import sklearn.metrics.pairwise as skpw

import dask_ml_tpu.metrics as dm


@pytest.fixture(scope="module")
def xy():
    rng = np.random.RandomState(0)
    return (rng.randn(60, 7).astype(np.float64),
            rng.randn(9, 7).astype(np.float64))


@pytest.mark.parametrize("metric", [
    "euclidean", "sqeuclidean", "manhattan", "cityblock", "l1", "l2",
    "cosine",
])
def test_pairwise_distances_parity(xy, metric):
    x, y = xy
    got = np.asarray(dm.pairwise_distances(x, y, metric=metric))
    sk_metric = metric
    want = skpw.pairwise_distances(x, y, metric=sk_metric)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_pairwise_distances_callable(xy):
    x, y = xy
    got = np.asarray(dm.pairwise_distances(x, y, metric=dm.euclidean_distances))
    want = skpw.euclidean_distances(x, y)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_pairwise_distances_bad_metric(xy):
    with pytest.raises(ValueError, match="unsupported metric"):
        dm.pairwise_distances(*xy, metric="nope")


@pytest.mark.parametrize("kernel,kwargs", [
    ("linear", {}),
    ("rbf", {"gamma": 0.3}),
    ("polynomial", {"degree": 2, "gamma": 0.5, "coef0": 1.0}),
    ("sigmoid", {"gamma": 0.1, "coef0": 0.5}),
])
def test_pairwise_kernels_parity(xy, kernel, kwargs):
    x, y = xy
    got = np.asarray(dm.pairwise_kernels(x, y, metric=kernel, **kwargs))
    want = skpw.pairwise_kernels(x, y, metric=kernel, **kwargs)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_argmin_min_parity(xy):
    x, y = xy
    labels, mins = dm.pairwise_distances_argmin_min(x, y)
    want_l, want_m = skpw.pairwise_distances_argmin_min(x, y)
    np.testing.assert_array_equal(np.asarray(labels), want_l)
    np.testing.assert_allclose(np.asarray(mins), want_m, rtol=1e-5, atol=1e-6)


def test_public_metrics_accept_sharded_and_slice_padding():
    """Public metrics functions take ShardedArray X and return exactly
    len(X) rows — padding must never leak (ref contract:
    dask_ml/metrics/pairwise.py returns len(X)-row dask arrays)."""
    from dask_ml_tpu import metrics as m
    from dask_ml_tpu.parallel import as_sharded

    rng = np.random.RandomState(0)
    x = rng.randn(101, 7).astype(np.float32)  # odd count forces padding
    yc = rng.randn(5, 7).astype(np.float32)
    xs = as_sharded(x)
    assert xs.padded_shape[0] > 101  # padding actually present

    for fn, ref in [
        (m.euclidean_distances, skpw.euclidean_distances),
        (m.manhattan_distances, skpw.manhattan_distances),
        (m.cosine_distances, skpw.cosine_distances),
        (m.rbf_kernel, skpw.rbf_kernel),
        (m.linear_kernel, skpw.linear_kernel),
    ]:
        out = np.asarray(fn(xs, yc))
        assert out.shape[0] == 101, fn.__name__
        np.testing.assert_allclose(out, ref(x, yc), rtol=1e-4, atol=1e-4)

    labels, mins = m.pairwise_distances_argmin_min(xs, yc)
    wl, wm = skpw.pairwise_distances_argmin_min(x, yc)
    assert len(labels) == 101 and len(mins) == 101
    np.testing.assert_array_equal(np.asarray(labels), wl)
    np.testing.assert_allclose(np.asarray(mins), wm, rtol=1e-4, atol=1e-4)

    out = np.asarray(m.pairwise_distances(xs, yc))
    assert out.shape == (101, 5)
    out = np.asarray(m.pairwise_kernels(xs, yc, metric="rbf"))
    assert out.shape == (101, 5)


def test_pairwise_y_none_and_keyword():
    """sklearn/dask-ml contract: Y=None means X-vs-X; Y passes by keyword."""
    from dask_ml_tpu import metrics as m

    rng = np.random.RandomState(0)
    x = rng.randn(20, 4)
    # f32 device math vs sklearn's f64: near-zero distances carry
    # expansion-cancellation noise ~sqrt(eps_f32)
    np.testing.assert_allclose(
        np.asarray(m.pairwise_distances(x)), skpw.pairwise_distances(x),
        rtol=1e-4, atol=2e-3,
    )
    np.testing.assert_allclose(
        np.asarray(m.euclidean_distances(x)), skpw.euclidean_distances(x),
        rtol=1e-4, atol=2e-3,
    )
    yc = rng.randn(3, 4)
    np.testing.assert_allclose(
        np.asarray(m.rbf_kernel(x, Y=yc)), skpw.rbf_kernel(x, Y=yc),
        rtol=1e-5,
    )


def test_pairwise_distances_argmin_matches_sklearn():
    import sklearn.metrics as skm

    from dask_ml_tpu.metrics import pairwise_distances_argmin

    rng = np.random.RandomState(3)
    X = rng.randn(80, 5).astype(np.float32)
    Y = rng.randn(9, 5).astype(np.float32)
    got = np.asarray(pairwise_distances_argmin(X, Y))
    np.testing.assert_array_equal(got, skm.pairwise_distances_argmin(X, Y))
