"""Pallas TPU kernels for the hot ops.

SURVEY.md §2b row 7: the reference's inner-loop math is sklearn's Cython
``pairwise_distances_argmin_min`` called per block; §7 B1 plans a "Pallas
fused distance-argmin". This kernel goes further than fusing distance +
argmin: one pass over X computes the assignment AND accumulates the
centroid sums/counts/inertia — the entire data touch of a Lloyd iteration
— so X streams through VMEM exactly once per iteration. The XLA fallback
path reads X twice (distance matmul + segment_sum) and materializes the
(n, k) distance matrix; here only (tile, k) lives on-chip.

Layout notes (pallas_guide.md): distances via the MXU matmul
``x @ c.T`` with f32 accumulation; accumulator outputs revisit the same
block every grid step (constant index_map) with @pl.when(first) init —
TPU grids are sequential, so accumulation is race-free.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _pick_tile(n):
    for t in (1024, 512, 256, 128, 64, 32, 16, 8):
        if n % t == 0:
            return t
    return n


def _assign_update_kernel(x_ref, m_ref, c_ref, labels_ref, mind_ref,
                          sums_ref, counts_ref, inertia_ref):
    i = pl.program_id(0)
    x = x_ref[:]                       # (tile, d)
    m = m_ref[:]                       # (tile, 1)
    c = c_ref[:]                       # (k, d)
    k = c.shape[0]
    # ||x||^2 - 2 x.c + ||c||^2 ; the matmul rides the MXU, epilogue fuses
    xc = jax.lax.dot_general(
        x, c, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                   # (tile, k)
    d2 = (
        jnp.sum(x * x, axis=1, keepdims=True)
        - 2.0 * xc
        + jnp.sum(c * c, axis=1)[None, :]
    )
    d2 = jnp.maximum(d2, 0.0)
    labels = jnp.argmin(d2, axis=1).astype(jnp.int32)
    mind = jnp.min(d2, axis=1)
    labels_ref[:] = labels
    mind_ref[:] = mind * m[:, 0]

    onehot = (
        labels[:, None] == jax.lax.broadcasted_iota(jnp.int32, (1, k), 1)
    ).astype(jnp.float32) * m           # (tile, k), padding rows zeroed

    @pl.when(i == 0)
    def _init():
        sums_ref[:] = jnp.zeros_like(sums_ref)
        counts_ref[:] = jnp.zeros_like(counts_ref)
        inertia_ref[:] = jnp.zeros_like(inertia_ref)

    sums_ref[:] += jax.lax.dot_general(
        onehot, x, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                   # (k, d) MXU accumulation
    counts_ref[:] += jnp.sum(onehot, axis=0, keepdims=True)
    inertia_ref[:] += jnp.sum(mind * m[:, 0]).reshape(1, 1)


@functools.partial(jax.jit, static_argnames=("interpret",))
def fused_assign_update(x, mask, centers, interpret=False):
    """One Lloyd-iteration data pass over a (per-device) block.

    x: (n, d), mask: (n,) row validity, centers: (k, d).
    Returns (labels (n,) int32, min_d2 (n,), sums (k, d), counts (k,),
    inertia scalar) — caller psums the last three across shards.
    """
    n, d = x.shape
    k = centers.shape[0]
    tile = _pick_tile(n)
    grid = (n // tile,)
    labels, mind, sums, counts, inertia = pl.pallas_call(
        _assign_update_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile, d), lambda i: (i, 0)),
            pl.BlockSpec((tile, 1), lambda i: (i, 0)),
            pl.BlockSpec((k, d), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((tile,), lambda i: (i,)),
            pl.BlockSpec((tile,), lambda i: (i,)),
            pl.BlockSpec((k, d), lambda i: (0, 0)),
            pl.BlockSpec((1, k), lambda i: (0, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n,), jnp.int32),
            jax.ShapeDtypeStruct((n,), jnp.float32),
            jax.ShapeDtypeStruct((k, d), jnp.float32),
            jax.ShapeDtypeStruct((1, k), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x.astype(jnp.float32), mask.astype(jnp.float32)[:, None],
      centers.astype(jnp.float32))
    return labels, mind, sums, counts[0], inertia[0, 0]
