"""Classification metrics over (possibly sharded) arrays.

Reference: ``dask_ml/metrics/classification.py`` (SURVEY.md §2a Metrics
row) — blocked reductions with per-block sklearn kernels. Here each metric
is one jitted masked reduction; XLA inserts the psum when inputs are
sharded.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..parallel.sharded import ShardedArray, as_sharded


def _canon(y_true, y_pred, sample_weight=None):
    """Co-shard the pair (and sample_weight, padded alike); returns
    (a, b, weights, n) where weights = row-validity mask * sample_weight."""
    if isinstance(y_true, ShardedArray) or isinstance(y_pred, ShardedArray):
        mesh = (y_true.mesh if isinstance(y_true, ShardedArray) else y_pred.mesh)
        t = as_sharded(y_true, mesh=mesh)
        p = as_sharded(y_pred, mesh=mesh)
        w = t.row_mask()
        if sample_weight is not None:
            w = w * as_sharded(sample_weight, mesh=mesh).data
        return t.data, p.data, w, t.n_rows
    t = np.asarray(y_true)
    p = np.asarray(y_pred)
    w = np.ones(t.shape[0], np.float32)
    if sample_weight is not None:
        w = w * np.asarray(sample_weight)
    return t, p, w, t.shape[0]


def accuracy_score(y_true, y_pred, normalize=True, sample_weight=None):
    t, p, w, n = _canon(y_true, y_pred, sample_weight)
    hits = jnp.sum((t == p) * w)
    if not normalize:
        return float(hits)
    return float(hits / jnp.sum(w))


def log_loss(y_true, y_prob, eps=1e-15, sample_weight=None):
    t, p, w, n = _canon(y_true, y_prob, sample_weight)
    p = jnp.clip(p, eps, 1.0 - eps)
    if p.ndim == 2:  # (n, 2) probabilities: take class-1 column
        p = p[:, 1]
    ll = -(t * jnp.log(p) + (1.0 - t) * jnp.log1p(-p))
    return float(jnp.sum(ll * w) / jnp.sum(w))
