#!/usr/bin/env bash
# Repo verify: lint + the ROADMAP.md tier-1 test command, verbatim.
#
#   scripts/verify.sh          # lint, then the full tier-1 suite
#   scripts/verify.sh --lint   # lint only (fast pre-commit gate)

cd "$(dirname "$0")/.." || exit 1

# -- lint: shard_map must come from the compat shim --------------------------
# `from jax import shard_map` only exists on jax >= 0.6; the direct
# import once took down all 33 tier-1 test collections. Everything goes
# through dask_ml_tpu/_compat.py.
bad=$(grep -rn --include='*.py' -E 'from jax import .*shard_map|jax\.shard_map\b|jax\.experimental\.shard_map|from jax\.experimental import .*shard_map' \
      dask_ml_tpu tests examples bench.py scripts 2>/dev/null \
      | grep -v 'dask_ml_tpu/_compat.py')
if [ -n "$bad" ]; then
    echo "LINT FAIL: import shard_map from dask_ml_tpu._compat, not jax:"
    echo "$bad"
    exit 1
fi
echo "lint OK: no direct jax shard_map imports outside _compat.py"

# -- lint: the serving package must never import from tests/ -----------------
# (a production subsystem reaching into test fixtures would make the
# test tree a runtime dependency)
bad=$(grep -rn --include='*.py' -E '^[[:space:]]*(from[[:space:]]+tests|import[[:space:]]+tests)\b' \
      dask_ml_tpu/serving 2>/dev/null)
if [ -n "$bad" ]; then
    echo "LINT FAIL: dask_ml_tpu/serving must not import from tests/:"
    echo "$bad"
    exit 1
fi
echo "lint OK: serving package imports nothing from tests/"

# -- lint: every public config knob must be documented in README -------------
# (the config table is the operator's contract; a knob that ships
# undocumented is how obs_programs' extra-AOT-compile surprise happened)
knobs=$(grep -E '^    [a-z][a-z0-9_]*: ' dask_ml_tpu/config.py \
        | sed -E 's/^ +([a-z0-9_]+):.*/\1/')
missing=""
for k in $knobs; do
    if ! grep -q "$k" README.md; then
        missing="$missing $k"
    fi
done
if [ -n "$missing" ]; then
    echo "LINT FAIL: config knobs missing from the README config table:"
    echo "   $missing"
    exit 1
fi
echo "lint OK: every config.py knob is documented in README.md"

if [ "${1:-}" = "--lint" ]; then
    exit 0
fi

# -- bench sentinel: recorded-round regression gate (ISSUE 4) ----------------
# the latest BENCH_r*.json family must hold its per-metric budget floors
# (seeded from r05): >20% throughput loss / slowdown on a comparable
# backend fails verify before any throughput number quietly rots.
if ! python scripts/bench_sentinel.py; then
    echo "VERIFY FAIL: bench sentinel (recorded-round regression)"
    exit 1
fi

# -- perf smoke: super-block dispatch collapse (ISSUE 3) ---------------------
# streamed-SGD at smoke scale: fails when dispatches_per_pass exceeds
# ceil(n_blocks / superblock_k) + 1 or when passes after the first pay
# any new XLA compiles — the regressions throughput numbers hide.
if ! timeout -k 10 300 env JAX_PLATFORMS=cpu python scripts/perf_smoke.py; then
    echo "VERIFY FAIL: super-block perf smoke"
    exit 1
fi

# -- live-scrape gate (ISSUE 5): a subprocess streamed fit with
# obs_http_port set must answer /healthz 200 and expose >=1 histogram
# series + >=1 fit progress gauge on /metrics WHILE it runs.
if ! timeout -k 10 300 python scripts/live_smoke.py; then
    echo "VERIFY FAIL: live telemetry scrape gate"
    exit 1
fi

# -- multichip dryrun (8 virtual CPU devices): the sharded lbfgs/ADMM
# paths must run AND record a flight-recorder trace the report CLI can
# render (spans + programs tables) — asserted inside the script.
if ! timeout -k 10 300 python scripts/multichip_dryrun.py; then
    echo "VERIFY FAIL: multichip dryrun (sharded paths + recorded trace)"
    exit 1
fi

# -- fleet gate (ISSUE 6): a subprocess 2-replica fleet under ragged
# traffic with one hot-swap mid-run must pay zero post-warmup compiles,
# lose no request across the swap, and show per-replica stats on /status.
if ! timeout -k 10 300 python scripts/fleet_smoke.py; then
    echo "VERIFY FAIL: serving fleet gate (hot-swap / replicas / status)"
    exit 1
fi

# -- drift gate (ISSUE 7): a subprocess fit + serve with an injected
# mean-shifted request stream must push drift_score over threshold and
# increment drift_alerts_total while an in-distribution control stream
# stays below; a mid-run hot swap must publish canary series for both
# versions — all with zero post-warmup compiles.
if ! timeout -k 10 300 python scripts/drift_smoke.py; then
    echo "VERIFY FAIL: drift gate (quality observability)"
    exit 1
fi

# -- chaos gate (ISSUE 11): a subprocess streamed fit SIGKILLed mid-pass
# must auto-resume to 1e-6 parity; an injected staging IOError must be
# retried (counters visible on /metrics) with a bit-identical result;
# a replica killed under ragged traffic must be supervisor-rebuilt with
# zero lost requests and zero post-rewarm XLA compiles.
if ! timeout -k 10 500 python scripts/chaos_smoke.py; then
    echo "VERIFY FAIL: chaos gate (fault injection / resume / supervision)"
    exit 1
fi

# -- federation gate (ISSUE 17): TWO subprocess fleet processes behind
# one router; SIGKILL the currently-preferred process mid-traffic — zero
# lost admitted requests (survivor traces carry rerouted_from_process),
# the next publish re-converges the survivor to the control registry's
# version with zero post-warmup compiles; a replayed burst must fire a
# plans-warm autoscale scale-up while holding its SLO verdict.
if ! timeout -k 10 500 python scripts/federation_smoke.py; then
    echo "VERIFY FAIL: federation gate (routing / failover / autoscale)"
    exit 1
fi

# -- incident gate (ISSUE 20): a subprocess fleet with an injected
# fault_plan SLO breach must close detect -> snapshot -> artifact:
# /alerts transitions firing -> resolved, EXACTLY ONE rate-limited
# incident bundle lands (open spans + counter/histogram snapshots +
# programs table), zero post-warmup XLA compiles, POST /profile answers
# the off-TPU no-op-with-reason, and a SIGKILL mid-capture-loop never
# publishes a truncated bundle (the save_host atomic-publish contract).
if ! timeout -k 10 500 python scripts/incident_smoke.py; then
    echo "VERIFY FAIL: incident gate (alerts / capture / profiling)"
    exit 1
fi

# -- serving suite (fast, targeted): the online-inference subsystem gates
# the same as lint — a broken server should fail verify in ~1min, before
# the full tier-1 wait. timeout-wrapped like tier-1: a hung serving
# worker must not block verify forever.
if ! timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest \
      tests/test_serving.py tests/test_fleet.py -q -p no:cacheprovider \
      -p no:xdist -p no:randomly; then
    echo "VERIFY FAIL: serving tests"
    exit 1
fi

# -- tier-1 (ROADMAP.md, verbatim) -------------------------------------------
set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c); exit $rc
