"""Multi-host distributed runtime.

Reference: the ``distributed`` scheduler/worker/comm stack — TCP frames,
msgpack+pickle serialization, heartbeats (SURVEY.md §2b rows 4-5, §5 comm
row). TPU replacement: intra-slice communication is XLA collectives over
ICI compiled into programs (no serialization layer exists at all);
cross-host control is the JAX distributed runtime over DCN. This module
is the thin bring-up layer: ``initialize()`` wraps
``jax.distributed.initialize`` (no-op single-host), ``global_mesh`` spans
every process's devices, and small host-side control messages ride an
all-gather (``broadcast_host`` / ``barrier``) instead of a socket
protocol.

Single-host sessions exercise the same code paths (process_count == 1),
which is how the test suite covers it; a pod run only changes the
environment variables.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .mesh import device_mesh

_initialized = False


def initialize(coordinator_address=None, num_processes=None,
               process_id=None, local_device_ids=None):
    """Bring up the JAX distributed runtime (DCN control plane).

    No-op when single-process and no coordinator is configured — the same
    script runs on a laptop, one TPU VM, or every host of a pod slice.
    """
    global _initialized
    if _initialized:
        return
    if coordinator_address is None and num_processes is None and \
            "COORDINATOR_ADDRESS" not in __import__("os").environ:
        _initialized = True  # single-process mode
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
    )
    _initialized = True


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def is_coordinator() -> bool:
    """The host that runs search controllers (SURVEY.md §3.5: 'asyncio
    controller on host 0')."""
    return jax.process_index() == 0


def global_mesh(axis_names=("data",), shape=None):
    """Mesh over ALL processes' devices (ICI within a slice, DCN across)."""
    return device_mesh(shape=shape, axis_names=axis_names,
                       devices=jax.devices())


def barrier(name="barrier"):
    """Cross-host sync point: a tiny psum over every device."""
    x = jnp.ones((jax.device_count(),))
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = global_mesh()
    y = jax.jit(
        lambda v: jnp.sum(v),
        in_shardings=NamedSharding(mesh, P("data")),
        out_shardings=NamedSharding(mesh, P()),
    )(x)
    return float(y)


def broadcast_host(value: np.ndarray, root: int = 0) -> np.ndarray:
    """Broadcast a small host array from the coordinator to all processes
    — replaces the reference's scheduler→worker control messages. Rides
    the device fabric (device_put + replication), not a socket."""
    if process_count() == 1:
        return np.asarray(value)
    from jax.experimental import multihost_utils

    return np.asarray(
        multihost_utils.broadcast_one_to_all(
            jnp.asarray(value), is_source=process_index() == root
        )
    )
