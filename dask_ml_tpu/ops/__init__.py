from . import linalg, pairwise, reductions
