"""Hyperband/SuccessiveHalving depth tests: bracket math vs the published
Hyperband table, metadata()/metadata_ consistency, sklearn- and
device-estimator integration (ref: dask_ml/model_selection/_hyperband.py,
SURVEY.md §3.5)."""

import numpy as np
import pytest
from sklearn.datasets import make_classification
from sklearn.linear_model import SGDClassifier

from dask_ml_tpu.model_selection import (
    HyperbandSearchCV,
    SuccessiveHalvingSearchCV,
)
from dask_ml_tpu.model_selection._hyperband import _brackets


def test_bracket_table_81_3():
    """The canonical (max_iter=81, eta=3) table from Li et al. 2016."""
    assert _brackets(81, 3) == [
        (4, 81, 1), (3, 34, 3), (2, 15, 9), (1, 8, 27), (0, 5, 81)
    ]


def test_bracket_table_27_3():
    assert _brackets(27, 3) == [(3, 27, 1), (2, 12, 3), (1, 6, 9), (0, 4, 27)]


@pytest.fixture(scope="module")
def data():
    X, y = make_classification(
        n_samples=600, n_features=10, n_informative=6, random_state=0
    )
    return X, y


@pytest.mark.parametrize("max_iter", [9, 10])  # power and non-power of eta
def test_metadata_matches_actual_work(data, max_iter):
    """Pre-fit metadata() must predict the realized partial_fit_calls
    exactly when patience is off (reference parity: metadata vs metadata_).
    max_iter=10 exercises the capped final rung (min(r*eta, max_iter))."""
    X, y = data
    h = HyperbandSearchCV(
        SGDClassifier(tol=1e-3), {"alpha": np.logspace(-4, -1, 30)},
        max_iter=max_iter, random_state=0,
    )
    pre = h.metadata()
    h.fit(X, y, classes=[0, 1])
    assert pre["n_models"] == h.metadata_["n_models"]
    assert pre["partial_fit_calls"] == h.metadata_["partial_fit_calls"]
    for b_pre, b_post in zip(pre["brackets"], h.metadata_["brackets"]):
        assert b_pre["bracket"] == b_post["bracket"]
        assert b_pre["n_models"] == b_post["n_models"]
        assert b_pre["partial_fit_calls"] == b_post["partial_fit_calls"]


def test_hyperband_with_sklearn_estimator(data):
    X, y = data
    h = HyperbandSearchCV(
        SGDClassifier(tol=1e-3), {"alpha": np.logspace(-5, 0, 30)},
        max_iter=9, random_state=0,
    )
    h.fit(X, y, classes=[0, 1])
    assert h.best_score_ > 0.7
    assert set(h.best_params_) == {"alpha"}
    # cv_results_ structural parity
    res = h.cv_results_
    n = len(res["params"])
    for key in ("test_score", "rank_test_score", "model_id",
                "partial_fit_calls", "bracket", "param_alpha"):
        assert len(res[key]) == n, key
    assert res["rank_test_score"].min() == 1
    # history records every scoring event with the reference's fields
    rec = h.history_[0]
    for field in ("model_id", "params", "partial_fit_calls", "score",
                  "elapsed_wall_time", "bracket"):
        assert field in rec, field
    # model_history_ groups records per model
    assert set(h.model_history_) == set(res["model_id"])
    # post-fit API delegates to best_estimator_
    assert h.predict(X[:10]).shape == (10,)
    assert 0 <= h.score(X, y) <= 1


@pytest.mark.slow
def test_hyperband_with_device_sgd(data):
    """Device-resident SGD (models/sgd.py) under the adaptive search,
    with classes passed through fit params (sklearn contract)."""
    from dask_ml_tpu.linear_model import SGDClassifier as DevSGD

    X, y = data
    h = HyperbandSearchCV(
        DevSGD(), {"eta0": [0.001, 0.01, 0.1, 1.0]},
        max_iter=4, aggressiveness=2, random_state=0,
    )
    h.fit(X.astype(np.float32), y.astype(np.float32), classes=[0.0, 1.0])
    assert h.best_score_ > 0.6


def test_device_sgd_partial_fit_requires_classes(data):
    from dask_ml_tpu.linear_model import SGDClassifier as DevSGD

    X, y = data
    with pytest.raises(ValueError, match="classes"):
        DevSGD().partial_fit(X[:50].astype(np.float32),
                             y[:50].astype(np.float32))


def test_sha_promotes_best(data):
    X, y = data
    sha = SuccessiveHalvingSearchCV(
        SGDClassifier(tol=1e-3, random_state=0),
        {"alpha": np.logspace(-4, -1, 20)},
        n_initial_parameters=8, n_initial_iter=1, max_iter=9,
        aggressiveness=3, random_state=0,
    )
    sha.fit(X, y, classes=[0, 1])
    calls = sha.cv_results_["partial_fit_calls"]
    # halving structure: survivors trained strictly longer; exactly one
    # model reaches the full budget, the middle rung holds eta^-1 of the
    # initial population (8 -> 2 -> 1 with eta=3 including the survivor)
    assert calls.max() > calls.min()
    assert (calls == calls.max()).sum() == 1
    assert (calls > calls.min()).sum() == 2
    # the reported best is the argmax of final scores (reference behavior:
    # best-by-score over ALL models, not necessarily the longest-trained)
    assert sha.best_index_ == int(np.nanargmax(sha.cv_results_["test_score"]))
    assert sha.best_score_ >= np.nanmax(sha.cv_results_["test_score"]) - 1e-12


def test_reproducible_with_random_state(data):
    X, y = data
    kw = dict(max_iter=4, aggressiveness=2, random_state=7)
    h1 = HyperbandSearchCV(SGDClassifier(tol=1e-3, random_state=0),
                           {"alpha": np.logspace(-4, -1, 10)}, **kw)
    h2 = HyperbandSearchCV(SGDClassifier(tol=1e-3, random_state=0),
                           {"alpha": np.logspace(-4, -1, 10)}, **kw)
    h1.fit(X, y, classes=[0, 1])
    h2.fit(X, y, classes=[0, 1])
    assert h1.best_params_ == h2.best_params_
    assert h1.best_score_ == h2.best_score_


def test_brackets_interleave_through_one_controller(data):
    """All brackets advance through ONE shared controller fit (VERDICT r3
    missing #4): history shows bracket records interleaved round-robin,
    not one bracket completing before the next starts — while the total
    work still matches the pre-fit estimate exactly."""
    X, y = data
    h = HyperbandSearchCV(
        SGDClassifier(tol=None, random_state=0),
        {"alpha": [1e-5, 1e-4, 1e-3, 1e-2], "eta0": [0.01, 0.1, 0.5]},
        max_iter=9, aggressiveness=3, random_state=0,
    )
    h.fit(X, y, classes=[0.0, 1.0])
    seq = [r["bracket"] for r in h.history_]
    assert set(seq) == {b["bracket"] for b in h.metadata_["brackets"]}
    # interleave evidence: some bracket reappears AFTER another bracket's
    # records (a sequential-bracket run produces contiguous runs only)
    first_last = {}
    for i, b in enumerate(seq):
        first_last.setdefault(b, [i, i])[1] = i
    spans = sorted(first_last.values())
    assert any(a2 > b1 for (_, a2), (b1, _) in zip(spans, spans[1:])), seq
    assert h.metadata()["partial_fit_calls"] == \
        h.metadata_["partial_fit_calls"]
