"""BlockTransformer: stateless per-block function application.

Reference: ``dask_ml/preprocessing/_block_transformer.py`` (SURVEY.md §2a
encoders row). Here "per block" is the whole sharded array under one jit
when the function is jax-traceable (XLA fuses it); host numpy is the
fallback for non-traceable functions.
"""

from __future__ import annotations

import numpy as np

from ..base import BaseEstimator, TransformerMixin
from ..parallel.sharded import ShardedArray, as_sharded


class BlockTransformer(TransformerMixin, BaseEstimator):
    """Ref: _block_transformer.py::BlockTransformer."""

    def __init__(self, func, validate=False, **kw_args):
        self.func = func
        self.validate = validate
        self.kw_args = kw_args

    def fit(self, X, y=None):
        return self

    def transform(self, X, y=None):
        kwargs = self.kw_args or {}
        if isinstance(X, ShardedArray):
            try:
                out = self.func(X.data, **kwargs)
                return ShardedArray(out, X.n_rows, X.mesh)
            except Exception:
                out = self.func(X.to_numpy(), **kwargs)
                return as_sharded(np.asarray(out), mesh=X.mesh)
        return self.func(X, **kwargs)
