"""Back-compat shim: the observability subsystem grew into the
``dask_ml_tpu.observability`` package (span tracing, counters, report
CLI). Every name that ever lived here re-exports from there — including
the module-global ``_active_loggers`` sink registry, which external
code (bench.py, tests) binds directly."""

from ..observability import *  # noqa: F401,F403
from ..observability import (  # noqa: F401
    _active_lock,
    _active_loggers,
)
from ..observability._metrics import _jit_step_cb  # noqa: F401
