"""Pallas TPU kernels for the hot ops.

SURVEY.md §2b row 7: the reference's inner-loop math is sklearn's Cython
``pairwise_distances_argmin_min`` called per block; §7 B1 plans a "Pallas
fused distance-argmin". This kernel goes further than fusing distance +
argmin: one pass over X computes the assignment AND accumulates the
centroid sums/counts — the entire data touch of a Lloyd iteration — so X
streams through VMEM exactly once per iteration. The XLA fallback path
reads X twice (distance matmul + segment_sum) and materializes the (n, k)
distance matrix; here only (tile, k) lives on-chip.

Layout notes (pallas_guide.md + Mosaic lowering constraints verified on a
real v5e chip):

- distances via the MXU matmul ``x @ c.T`` with f32 accumulation;
- every intermediate stays RANK-2 — Mosaic's vector layouts cannot
  relayout rank-1 values produced by cross-lane reductions ("Offset
  change" errors), so argmin is an iota-min with ``keepdims=True``,
  center norms arrive precomputed as a (1, k) operand, and the scalar
  inertia sum happens in XLA on the kernel's masked min-distance output;
- accumulator outputs revisit the same block every grid step (constant
  index_map) with @pl.when(first) init — TPU grids are sequential, so
  accumulation is race-free;
- rows are padded to a 128-multiple tile (Mosaic minor-tiling), with the
  mask zeroing padded rows out of every statistic.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _pick_tile(n):
    """Row tile for the grid. Mosaic requires output blocks to be
    multiples of the minor tiling (128), so tiles are always
    128-multiples and callers pad n up to a tile multiple."""
    if n <= 1024:
        return -(-n // 128) * 128  # single grid step, ≤127 padded rows
    return 1024 if n % 1024 == 0 else 512


_GLM_TILE_BUDGET = 4 * 1024 * 1024  # x-block bytes kept well under VMEM


def _budget_tile(n, cost):
    """Shrink the row tile until ``cost(tile)`` fits the VMEM budget
    (128-row Mosaic floor); None when nothing fits — the ONE copy of
    the halve-until-budget rule for every GLM kernel gate."""
    tile = _pick_tile(n)
    while tile > 128 and cost(tile) > _GLM_TILE_BUDGET:
        tile //= 2
    tile = max(tile, 128)
    return tile if cost(tile) <= _GLM_TILE_BUDGET else None


def glm_tile(n, d, itemsize):
    """Row tile for the GLM kernel bounded by BOTH n and the x-block's
    VMEM footprint; None when even a 128-row tile of a very wide design
    would blow the budget — callers then keep the XLA loss (its matmuls
    tile the feature dim freely)."""
    return _budget_tile(n, lambda t: t * d * itemsize)


def _assign_update_kernel(x_ref, m_ref, c_ref, c2_ref, labels_ref, mind_ref,
                          sums_ref, counts_ref):
    i = pl.program_id(0)
    x = x_ref[:]                       # (tile, d)
    m = m_ref[:]                       # (tile, 1)
    c = c_ref[:]                       # (k, d)
    c2 = c2_ref[:]                     # (1, k) precomputed ||c||^2
    k = c.shape[0]
    # ||x||^2 - 2 x.c + ||c||^2 ; the matmul rides the MXU, epilogue fuses
    xc = jax.lax.dot_general(
        x, c, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                   # (tile, k)
    d2 = jnp.sum(x * x, axis=1, keepdims=True) - 2.0 * xc + c2
    d2 = jnp.maximum(d2, 0.0)
    mind = jnp.min(d2, axis=1, keepdims=True)          # (tile, 1)
    iota = jax.lax.broadcasted_iota(
        jnp.int32, (x.shape[0], k), 1
    ).astype(jnp.float32)
    # first-occurrence argmin, all rank-2: min over lanes of iota where
    # the distance achieves the row minimum
    labf = jnp.min(jnp.where(d2 <= mind, iota, float(k)), axis=1,
                   keepdims=True)                       # (tile, 1)
    labels_ref[:] = labf.astype(jnp.int32)
    mind_ref[:] = mind * m

    onehot = (iota == labf).astype(jnp.float32) * m     # (tile, k)

    @pl.when(i == 0)
    def _init():
        sums_ref[:] = jnp.zeros_like(sums_ref)
        counts_ref[:] = jnp.zeros_like(counts_ref)

    sums_ref[:] += jax.lax.dot_general(
        onehot, x, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                   # (k, d) MXU accumulation
    counts_ref[:] += jnp.sum(onehot, axis=0, keepdims=True)


def _lloyd_stats_kernel(x_ref, nv_ref, c_ref, c2_ref, sums_ref, counts_ref,
                        inertia_ref, *, tile):
    i = pl.program_id(0)
    x = x_ref[:]                       # (tile, d)
    c = c_ref[:]                       # (k, d)
    c2 = c2_ref[:]                     # (1, k)
    k = c.shape[0]
    # row validity from the GLOBAL row index (valid rows are a prefix of
    # the padded array by construction) — no (n, 1) mask operand, whose
    # T(8,128) layout would pad 128× in HBM
    row_ids = jax.lax.broadcasted_iota(jnp.int32, (x.shape[0], 1), 0) \
        + i * tile
    m = (row_ids < nv_ref[0, 0]).astype(jnp.float32)    # (tile, 1) VMEM
    xc = jax.lax.dot_general(
        x, c, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    d2 = jnp.sum(x * x, axis=1, keepdims=True) - 2.0 * xc + c2
    d2 = jnp.maximum(d2, 0.0)
    mind = jnp.min(d2, axis=1, keepdims=True)
    iota = jax.lax.broadcasted_iota(
        jnp.int32, (x.shape[0], k), 1
    ).astype(jnp.float32)
    labf = jnp.min(jnp.where(d2 <= mind, iota, float(k)), axis=1,
                   keepdims=True)
    onehot = (iota == labf).astype(jnp.float32) * m

    @pl.when(i == 0)
    def _init():
        sums_ref[:] = jnp.zeros_like(sums_ref)
        counts_ref[:] = jnp.zeros_like(counts_ref)
        inertia_ref[:] = jnp.zeros_like(inertia_ref)

    sums_ref[:] += jax.lax.dot_general(
        onehot, x, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    counts_ref[:] += jnp.sum(onehot, axis=0, keepdims=True)
    inertia_ref[:] += jnp.sum(mind * m, axis=0, keepdims=True)


@functools.partial(jax.jit, static_argnames=("interpret",))
def fused_lloyd_stats(x, n_valid, centers, interpret=False):
    """Lloyd-iteration statistics WITHOUT per-row outputs: returns only
    (sums (k, d), counts (k,), inertia scalar). The full kernel's
    per-row labels/min-d2 outputs are (n, 1) arrays whose TPU tiled
    layout T(8,128) pads them 128× in HBM (~512 B/row) — at 10⁷+ rows
    that alone OOMs the chip, and the Lloyd loop never reads them. Row
    validity rides in as one scalar (valid rows are a prefix of the
    padded block)."""
    n, d = x.shape
    k = centers.shape[0]
    x = x.astype(jnp.float32)
    centers = centers.astype(jnp.float32)
    tile = _pick_tile(n)
    n_pad = -(-n // tile) * tile
    if n_pad != n:
        x = jnp.pad(x, ((0, n_pad - n), (0, 0)))
    grid = (n_pad // tile,)
    c2 = jnp.sum(centers * centers, axis=1)[None, :]
    nv = jnp.asarray(n_valid, jnp.int32).reshape(1, 1)
    sums, counts, inertia = pl.pallas_call(
        functools.partial(_lloyd_stats_kernel, tile=tile),
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile, d), lambda i: (i, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
            pl.BlockSpec((k, d), lambda i: (0, 0)),
            pl.BlockSpec((1, k), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((k, d), lambda i: (0, 0)),
            pl.BlockSpec((1, k), lambda i: (0, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((k, d), jnp.float32),
            jax.ShapeDtypeStruct((1, k), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x, nv, centers, c2)
    return sums, counts[0], inertia[0, 0]


def _tile_mask(x, nv_ref, i, tile):
    """Per-tile prefix-validity mask from the global row index vs the
    scalar valid-row count — shared by every GLM kernel."""
    row_ids = jax.lax.broadcasted_iota(jnp.int32, (x.shape[0], 1), 0) \
        + i * tile
    return (row_ids < nv_ref[0, 0]).astype(jnp.float32)  # (tile, 1)


def _glm_eta_terms(x, yv, b, family):
    """eta (matvec at x's dtype so bf16 rides the MXU at bf16 rate, f32
    accum — solvers._smooth_loss's contract) plus the family's pointwise
    NLL / residual. Family formulas come from
    models/solvers/families.py — pure jnp ops that lower inside the
    kernel, so the Pallas and XLA losses cannot diverge."""
    eta = jax.lax.dot_general(
        x, b.astype(x.dtype), (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                   # (tile, 1)
    from ..models.solvers.families import get_family

    fam = get_family(family)
    per = fam.pointwise(eta, yv)
    resid = fam.mean(eta) - yv
    return fam, eta, per, resid


def _glm_value_grad_kernel(x_ref, y_ref, nv_ref, b_ref, loss_ref, grad_ref,
                           *, tile, family):
    """One X pass computing Σ pointwise-NLL AND Σ ∂NLL/∂β.

    The XLA path reads X twice per value_and_grad (forward matvec +
    gradient matmul) — at GLM arithmetic intensity the fit is HBM-bound,
    so this halves the data traffic of every solver iteration. Same
    layout rules as the Lloyd kernels: rank-2 everywhere, validity from
    the global row index vs one scalar, accumulators revisited with a
    constant index_map (sequential TPU grid: race-free)."""
    i = pl.program_id(0)
    x = x_ref[:]                       # (tile, d) — f32 or bf16
    yv = y_ref[:]                      # (tile, 1) f32
    b = b_ref[:]                       # (1, d) f32
    m = _tile_mask(x, nv_ref, i, tile)
    _, _, per, resid = _glm_eta_terms(x, yv, b, family)

    @pl.when(i == 0)
    def _init():
        loss_ref[:] = jnp.zeros_like(loss_ref)
        grad_ref[:] = jnp.zeros_like(grad_ref)

    loss_ref[:] += jnp.sum(per * m, axis=0, keepdims=True)
    grad_ref[:] += jax.lax.dot_general(
        (resid * m).astype(x.dtype), x, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                   # (1, d) f32 accumulation


@functools.partial(jax.jit, static_argnames=("family", "interpret"))
def fused_glm_value_grad(x, n_valid, y, beta, family, interpret=False):
    """(Σ pointwise-NLL, Σ ∂/∂β (d,)) of one (per-device) block in ONE
    data pass. ``beta`` is f32 (d,); ``y`` f32 (n,); row validity is the
    scalar prefix count ``n_valid`` (GLM padding is trailing per shard).
    Callers psum both outputs across shards and add the penalty/mean
    scaling in XLA."""
    n, d = x.shape
    y = y.astype(jnp.float32)
    beta = beta.astype(jnp.float32)
    tile = glm_tile(n, d, x.dtype.itemsize)
    if tile is None:
        raise ValueError(
            f"design too wide for the fused GLM kernel VMEM budget "
            f"(d={d}); use the XLA loss (use_pallas=False)"
        )
    n_pad = -(-n // tile) * tile
    if n_pad != n:
        x = jnp.pad(x, ((0, n_pad - n), (0, 0)))
        y = jnp.pad(y, (0, n_pad - n))
    grid = (n_pad // tile,)
    nv = jnp.asarray(n_valid, jnp.int32).reshape(1, 1)
    loss, grad = pl.pallas_call(
        functools.partial(_glm_value_grad_kernel, tile=tile,
                          family=family),
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile, d), lambda i: (i, 0)),
            pl.BlockSpec((tile, 1), lambda i: (i, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
            jax.ShapeDtypeStruct((1, d), jnp.float32),
        ],
        interpret=interpret,
    )(x, y[:, None], nv, beta[None, :])
    return loss[0, 0], grad[0]


def _glm_vgh_kernel(x_ref, y_ref, nv_ref, b_ref, loss_ref, grad_ref,
                    hess_ref, *, tile, family):
    """Newton's whole data touch in one X pass: Σ NLL, Σ ∂/∂β, AND the
    Σ XᵀWX Gauss-Newton Hessian — the XLA path reads X ~3x per
    iteration (forward, gradient, weighted Hessian matmul)."""
    i = pl.program_id(0)
    x = x_ref[:]                       # (tile, d)
    yv = y_ref[:]                      # (tile, 1)
    b = b_ref[:]                       # (1, d)
    m = _tile_mask(x, nv_ref, i, tile)
    fam, eta, per, resid = _glm_eta_terms(x, yv, b, family)
    w = fam.hess_weight(eta, yv) * m                    # (tile, 1)

    @pl.when(i == 0)
    def _init():
        loss_ref[:] = jnp.zeros_like(loss_ref)
        grad_ref[:] = jnp.zeros_like(grad_ref)
        hess_ref[:] = jnp.zeros_like(hess_ref)

    loss_ref[:] += jnp.sum(per * m, axis=0, keepdims=True)
    grad_ref[:] += jax.lax.dot_general(
        (resid * m).astype(x.dtype), x, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    xw = x * w.astype(x.dtype)
    hess_ref[:] += jax.lax.dot_general(
        xw, x, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                   # (d, d)


def glm_newton_tile(n, d, itemsize):
    """Row tile for the Newton kernel: budget covers the x block, the
    weighted copy, and the (d, d) Hessian accumulator."""
    return _budget_tile(n, lambda t: 2 * t * d * itemsize + d * d * 4)


@functools.partial(jax.jit, static_argnames=("family", "interpret"))
def fused_glm_value_grad_hess(x, n_valid, y, beta, family,
                              interpret=False):
    """(Σ NLL, Σ ∂/∂β (d,), Σ XᵀWX (d, d)) of one block in ONE pass —
    the per-shard Newton statistics; callers psum all three."""
    n, d = x.shape
    y = y.astype(jnp.float32)
    beta = beta.astype(jnp.float32)
    tile = glm_newton_tile(n, d, x.dtype.itemsize)
    if tile is None:
        raise ValueError(
            f"design too wide for the fused Newton kernel (d={d}); use "
            "the XLA path (use_pallas=False)"
        )
    n_pad = -(-n // tile) * tile
    if n_pad != n:
        x = jnp.pad(x, ((0, n_pad - n), (0, 0)))
        y = jnp.pad(y, (0, n_pad - n))
    grid = (n_pad // tile,)
    nv = jnp.asarray(n_valid, jnp.int32).reshape(1, 1)
    loss, grad, hess = pl.pallas_call(
        functools.partial(_glm_vgh_kernel, tile=tile, family=family),
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile, d), lambda i: (i, 0)),
            pl.BlockSpec((tile, 1), lambda i: (i, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((d, d), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
            jax.ShapeDtypeStruct((1, d), jnp.float32),
            jax.ShapeDtypeStruct((d, d), jnp.float32),
        ],
        interpret=interpret,
    )(x, y[:, None], nv, beta[None, :])
    return loss[0, 0], grad[0], hess


def _glm_multi_value_grad_kernel(x_ref, yc_ref, nv_ref, b_ref, loss_ref,
                                 grad_ref, *, tile, family):
    """Multi-target twin of ``_glm_value_grad_kernel``: ONE X pass
    serves all C one-vs-rest problems. ``yc_ref`` holds class codes;
    per-class 0/1 targets derive in-kernel from an iota compare, eta is
    one (tile, C) MXU matmul against the stacked B, and the (C, d)
    gradient accumulates with a second MXU contraction."""
    i = pl.program_id(0)
    x = x_ref[:]                       # (tile, d)
    yc = yc_ref[:]                     # (tile, 1) f32 codes
    B = b_ref[:]                       # (C, d) f32
    C = B.shape[0]
    row_ids = jax.lax.broadcasted_iota(jnp.int32, (x.shape[0], 1), 0) \
        + i * tile
    m = (row_ids < nv_ref[0, 0]).astype(jnp.float32)    # (tile, 1)
    eta = jax.lax.dot_general(
        x, B.astype(x.dtype), (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                   # (tile, C)
    iota = jax.lax.broadcasted_iota(
        jnp.int32, (x.shape[0], C), 1
    ).astype(jnp.float32)
    yv = (iota == yc).astype(jnp.float32)               # (tile, C)
    from ..models.solvers.families import get_family

    fam = get_family(family)
    per = fam.pointwise(eta, yv) * m
    resid = (fam.mean(eta) - yv) * m

    @pl.when(i == 0)
    def _init():
        loss_ref[:] = jnp.zeros_like(loss_ref)
        grad_ref[:] = jnp.zeros_like(grad_ref)

    loss_ref[:] += jnp.sum(per, axis=0, keepdims=True).sum(
        axis=1, keepdims=True
    )
    grad_ref[:] += jax.lax.dot_general(
        resid.astype(x.dtype), x, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                   # (C, d)


def glm_multi_tile(n, d, n_classes, itemsize):
    """Row tile for the multi-target kernel bounded by the combined
    VMEM footprint of the x block, the (tile, C) intermediates, and the
    two (C, d) operands; None when no 128-row tile fits."""
    return _budget_tile(n, lambda t: (
        t * d * itemsize + t * n_classes * 4 * 3 + 2 * n_classes * d * 4
    ))


@functools.partial(jax.jit, static_argnames=("family", "interpret"))
def fused_glm_multi_value_grad(x, n_valid, y_codes, B, family,
                               interpret=False):
    """(Σ pointwise-NLL over classes+rows, Σ ∂/∂B (C, d)) of one block
    in ONE data pass — the reference analog would be C separate
    dask-glm objective evaluations. ``y_codes`` holds class indices
    0..C-1 (f32); callers psum both outputs across shards."""
    n, d = x.shape
    C = B.shape[0]
    y_codes = y_codes.astype(jnp.float32)
    B = B.astype(jnp.float32)
    tile = glm_multi_tile(n, d, C, x.dtype.itemsize)
    if tile is None:
        raise ValueError(
            f"design too wide for the fused multi-target GLM kernel "
            f"(d={d}, C={C}); use the stacked XLA path"
        )
    n_pad = -(-n // tile) * tile
    if n_pad != n:
        x = jnp.pad(x, ((0, n_pad - n), (0, 0)))
        y_codes = jnp.pad(y_codes, (0, n_pad - n), constant_values=-1.0)
    grid = (n_pad // tile,)
    nv = jnp.asarray(n_valid, jnp.int32).reshape(1, 1)
    loss, grad = pl.pallas_call(
        functools.partial(_glm_multi_value_grad_kernel, tile=tile,
                          family=family),
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile, d), lambda i: (i, 0)),
            pl.BlockSpec((tile, 1), lambda i: (i, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
            pl.BlockSpec((C, d), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
            pl.BlockSpec((C, d), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
            jax.ShapeDtypeStruct((C, d), jnp.float32),
        ],
        interpret=interpret,
    )(x, y_codes[:, None], nv, B)
    return loss[0, 0], grad


# ---------------------------------------------------------------------------
# streamed super-block kernels (ISSUE 8 tentpole): the per-block bodies
# the donated-carry super-block scans call INSTEAD of their XLA flavors
# when `config.pallas_stream` is on, the backend is a real TPU, and the
# block shape fits the grid/VMEM rules below. Each kernel is ONE VMEM
# pass over its block — objective AND gradient (AND Hessian) from a
# single X read, where the XLA flavors read X two to three times
# (forward matvec + autodiff backward + weighted Hessian matmul). Row
# validity is the streamed block's prefix count (SuperBlock.counts),
# exactly the scalar the resident kernels already take. ``mxu`` casts
# the matmul operands to bf16 in VMEM (f32 accumulation — the
# config.dtype="auto" TPU path); everything else stays f32.
# ---------------------------------------------------------------------------


def stream_tile(S, cost):
    """Largest 128-multiple tile that DIVIDES the streamed block height
    and fits the VMEM budget; None when the height isn't a 128-multiple
    or nothing fits. Streamed kernels cannot pad: a pad inside the
    consumer's scan would copy the block in HBM on every step, which is
    exactly the traffic the fusion removes — callers fall back to the
    XLA flavor instead (``use_stream_kernels`` gates on this)."""
    if S <= 0 or S % 128:
        return None
    for t in (1024, 512, 256, 128):
        if S % t == 0 and cost(t) <= _GLM_TILE_BUDGET:
            return t
    return None


def sgd_stream_tile(S, d, itemsize=4):
    return stream_tile(S, lambda t: t * d * itemsize)


def glm_stream_tile(S, d, kind, itemsize=4):
    """Tile for the streamed GLM ``kind`` reducer; the vgh budget also
    covers the weighted copy and the (d, d) Hessian accumulator."""
    if kind == "vgh":
        return stream_tile(
            S, lambda t: 2 * t * d * itemsize + d * d * 4
        )
    return stream_tile(S, lambda t: t * d * itemsize)


def kmeans_stream_tile(S, d, k, itemsize=4):
    return stream_tile(
        S, lambda t: t * d * itemsize + t * k * 4 + 2 * k * d * 4
    )


def glm_multi_stream_tile(S, d, n_classes, itemsize=4):
    """Tile for the streamed multi-target GLM reducers: the x block,
    the three (tile, C) intermediates (eta / targets / residual), and
    the two (C, d) weight/gradient operands."""
    return stream_tile(S, lambda t: (
        t * d * itemsize + t * n_classes * 4 * 3 + 2 * n_classes * d * 4
    ))


def sgd_many_stream_tile(S, d, n_models, itemsize=4):
    """Tile for the multi-weight streamed SGD kernel (multiclass OvR
    rows, a batched-trial cohort, or a search cohort's slot stack —
    the streamed cohort scans gate at the FULL padded slot count, so a
    tile that fits the top rung fits every narrower one): same
    footprint shape as the multi-target GLM reducer."""
    return glm_multi_stream_tile(S, d, n_models, itemsize)


def stream_kernel_mode(backend=None):
    """(use, interpret) for the fused streamed kernel family: opted in
    (config.pallas_stream, default on) AND a real TPU backend —
    compiled Mosaic kernels, interpret False. Off-TPU the fused bodies
    only run when ``config.pallas_stream_interpret`` additionally opts
    into the Pallas interpreter (CI parity / dry-run benches);
    otherwise the XLA flavors run unchanged — with the knobs off their
    jaxprs are byte-identical to the pre-feature programs."""
    from ..config import get_config

    cfg = get_config()
    if not cfg.pallas_stream:
        return False, False
    if backend is None:
        backend = jax.default_backend()
    if backend == "tpu":
        return True, False
    return (True, True) if cfg.pallas_stream_interpret else (False, False)


def use_stream_kernels(backend=None):
    """The auto-gate for the fused streamed kernel family — see
    :func:`stream_kernel_mode` (this keeps the historical bool shape
    for callers that don't care about interpret mode)."""
    return stream_kernel_mode(backend)[0]


# the fused-flavor audit vocabulary lives HERE and only here — the GLM
# and SGD flavor selectors both record these strings in
# solver_info_["fused_stream_reason"], and tpu_smoke/README compare
# them literally, so a renamed reason must change in exactly one place

def stream_mode_reason():
    """Why the fused streamed kernels are off for this process (knob or
    backend), or None when :func:`stream_kernel_mode` says go."""
    from ..config import get_config

    if not get_config().pallas_stream:
        return "pallas-stream-off"
    return None if stream_kernel_mode()[0] else "off-TPU"


def stream_tile_reason(S_local, tile):
    """Why a tile gate refused the per-shard slab of ``S_local`` rows
    (None when ``tile`` was accepted)."""
    if tile is not None:
        return None
    return "non-128-mult shard rows" if S_local % 128 else "vmem-budget"


def _mxu_cast(a, mxu):
    return a if mxu is None else a.astype(mxu)


def sgd_objective_terms(eta, yv, loss):
    """(pointwise loss, dloss/deta) for the SGD losses — the ONE
    definition shared by the fused step kernel and any epilogue, so the
    Pallas and autodiff (models/sgd.py::_sgd_update_one) objectives
    cannot diverge. ``eta``/``yv`` rank-2."""
    if loss == "log_loss":
        per = jax.nn.softplus(eta) - yv * eta
        resid = jax.nn.sigmoid(eta) - yv
    elif loss == "hinge":
        sign = 2.0 * yv - 1.0
        margins = sign * eta
        per = jnp.maximum(0.0, 1.0 - margins)
        resid = -sign * (margins < 1.0).astype(jnp.float32)
    elif loss == "squared_error":
        diff = eta - yv
        per = 0.5 * diff * diff
        resid = diff
    else:  # pragma: no cover - validated upstream
        raise ValueError(f"unknown SGD loss {loss!r}")
    return per, resid


def _sgd_grad_kernel(x_ref, y_ref, nv_ref, w_ref, b0_ref, loss_ref,
                     gw_ref, gb_ref, *, tile, loss, mxu):
    """Σ pointwise-loss, Σ ∂/∂coef, Σ ∂/∂intercept of one streamed
    block in ONE X pass (the XLA step reads X twice: forward matvec +
    autodiff backward). Same layout rules as every kernel here: rank-2
    throughout, prefix-count validity, constant-index accumulators on
    the sequential TPU grid."""
    i = pl.program_id(0)
    x = x_ref[:]                        # (tile, d) f32
    yv = y_ref[:]                       # (tile, 1) f32
    w = w_ref[:]                        # (1, d) f32 coef row
    b0 = b0_ref[:]                      # (1, 1) intercept*iflag
    m = _tile_mask(x, nv_ref, i, tile)
    xd = _mxu_cast(x, mxu)
    eta = jax.lax.dot_general(
        xd, w.astype(xd.dtype), (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) + b0                              # (tile, 1)
    per, resid = sgd_objective_terms(eta, yv, loss)
    rm = resid * m

    @pl.when(i == 0)
    def _init():
        loss_ref[:] = jnp.zeros_like(loss_ref)
        gw_ref[:] = jnp.zeros_like(gw_ref)
        gb_ref[:] = jnp.zeros_like(gb_ref)

    loss_ref[:] += jnp.sum(per * m, axis=0, keepdims=True)
    gw_ref[:] += jax.lax.dot_general(
        rm.astype(xd.dtype), xd, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                   # (1, d)
    gb_ref[:] += jnp.sum(rm, axis=0, keepdims=True)


def fused_sgd_block_grad(x, n_valid, y, w_ext, iflag, loss,
                         mxu=None, interpret=False):
    """(Σ pointwise-loss, Σ ∂/∂w (d+1,)) of one streamed block in ONE
    X pass. ``w_ext`` is the (d+1,) weight vector (last entry the
    intercept); ``iflag`` zeroes the intercept's contribution exactly
    like the XLA step. Raw sums — the caller divides by n_valid and
    adds the l2/prox terms (models/sgd.py's epilogue). Traced inside
    the consumer's scan: shapes must already satisfy
    ``sgd_stream_tile`` (no padding here, by design)."""
    S, d = x.shape
    tile = sgd_stream_tile(S, d, x.dtype.itemsize)
    grid = (S // tile,)
    nv = jnp.asarray(n_valid, jnp.int32).reshape(1, 1)
    b0 = (w_ext[-1] * iflag).astype(jnp.float32).reshape(1, 1)
    loss_sum, gw, gb = pl.pallas_call(
        functools.partial(_sgd_grad_kernel, tile=tile, loss=loss,
                          mxu=mxu),
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile, d), lambda i: (i, 0)),
            pl.BlockSpec((tile, 1), lambda i: (i, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
            jax.ShapeDtypeStruct((1, d), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x, y[:, None], nv, w_ext[None, :-1], b0)
    grad = jnp.concatenate([gw[0], gb[0]])
    return loss_sum[0, 0], grad


def _glm_stream_kernel(x_ref, y_ref, nv_ref, b_ref, b0_ref, *outs,
                       tile, family, kind, mxu):
    """Streamed-GLM reducer body: ``kind`` picks which sums accumulate
    (val: loss; vg: + gradient; vgh: + Gauss-Newton Hessian pieces).
    The intercept rides as the (1, 1) ``b0`` operand and its gradient/
    Hessian border accumulate as separate outputs — the caller
    assembles the bordered (d+1, d+1) form in XLA, identical to
    ``_block_val_grad_hess``'s ``jnp.block``."""
    i = pl.program_id(0)
    x = x_ref[:]                        # (tile, d)
    yv = y_ref[:]                       # (tile, 1)
    b = b_ref[:]                        # (1, d)
    b0 = b0_ref[:]                      # (1, 1)
    m = _tile_mask(x, nv_ref, i, tile)
    xd = _mxu_cast(x, mxu)
    eta = jax.lax.dot_general(
        xd, b.astype(xd.dtype), (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) + b0
    from ..models.solvers.families import get_family

    fam = get_family(family)
    per = fam.pointwise(eta, yv)

    @pl.when(i == 0)
    def _init():
        for o in outs:
            o[:] = jnp.zeros_like(o)

    loss_ref = outs[0]
    loss_ref[:] += jnp.sum(per * m, axis=0, keepdims=True)
    if kind == "val":
        return
    resid = (fam.mean(eta) - yv) * m
    grad_ref, gb_ref = outs[1], outs[2]
    grad_ref[:] += jax.lax.dot_general(
        resid.astype(xd.dtype), xd, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                   # (1, d)
    gb_ref[:] += jnp.sum(resid, axis=0, keepdims=True)
    if kind == "vg":
        return
    hess_ref, col_ref, wsum_ref = outs[3], outs[4], outs[5]
    w = fam.hess_weight(eta, yv) * m
    xw = xd * w.astype(xd.dtype)
    hess_ref[:] += jax.lax.dot_general(
        xw, xd, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                   # (d, d)
    col_ref[:] += jnp.sum(xw.astype(jnp.float32), axis=0, keepdims=True)
    wsum_ref[:] += jnp.sum(w, axis=0, keepdims=True)


def fused_glm_stream(kind, x, n_valid, y, beta, family, intercept,
                     mxu=None, interpret=False):
    """One streamed block's ``kind`` sums in ONE X pass, matching the
    XLA block kernels in models/solvers/streamed.py:

    - "val":  Σ pointwise-NLL (scalar)
    - "vg":   (Σ NLL, Σ ∂/∂beta) — beta is (d+1,) when ``intercept``
    - "vgh":  (Σ NLL, Σ ∂/∂beta, Σ bordered Gauss-Newton Hessian)

    Raw sums over valid rows (prefix count ``n_valid``); the streamed
    objective's epilogue adds mean scaling and penalties exactly as for
    the XLA flavors."""
    S, d_ext = x.shape[0], x.shape[1]
    beta = beta.astype(jnp.float32)
    tile = glm_stream_tile(S, d_ext, kind, x.dtype.itemsize)
    nv = jnp.asarray(n_valid, jnp.int32).reshape(1, 1)
    if intercept:
        b, b0 = beta[None, :-1], beta[-1].reshape(1, 1)
    else:
        b, b0 = beta[None, :], jnp.zeros((1, 1), jnp.float32)
    d = b.shape[1]
    out_specs = [pl.BlockSpec((1, 1), lambda i: (0, 0))]
    out_shape = [jax.ShapeDtypeStruct((1, 1), jnp.float32)]
    if kind != "val":
        out_specs += [pl.BlockSpec((1, d), lambda i: (0, 0)),
                      pl.BlockSpec((1, 1), lambda i: (0, 0))]
        out_shape += [jax.ShapeDtypeStruct((1, d), jnp.float32),
                      jax.ShapeDtypeStruct((1, 1), jnp.float32)]
    if kind == "vgh":
        out_specs += [pl.BlockSpec((d, d), lambda i: (0, 0)),
                      pl.BlockSpec((1, d), lambda i: (0, 0)),
                      pl.BlockSpec((1, 1), lambda i: (0, 0))]
        out_shape += [jax.ShapeDtypeStruct((d, d), jnp.float32),
                      jax.ShapeDtypeStruct((1, d), jnp.float32),
                      jax.ShapeDtypeStruct((1, 1), jnp.float32)]
    outs = pl.pallas_call(
        functools.partial(_glm_stream_kernel, tile=tile, family=family,
                          kind=kind, mxu=mxu),
        grid=(S // tile,),
        in_specs=[
            pl.BlockSpec((tile, d), lambda i: (i, 0)),
            pl.BlockSpec((tile, 1), lambda i: (i, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(x, y[:, None], nv, b, b0)
    loss = outs[0][0, 0]
    if kind == "val":
        return (loss,)
    grad = outs[1][0]
    if intercept:
        grad = jnp.concatenate([grad, outs[2][0]])
    if kind == "vg":
        return loss, grad
    hess, col, wsum = outs[3], outs[4][0], outs[5]
    if intercept:
        hess = jnp.block([
            [hess, col[:, None]],
            [col[None, :], wsum],
        ])
    return loss, grad, hess


def _glm_multi_stream_kernel(x_ref, yc_ref, nv_ref, b_ref, b0_ref, *outs,
                             tile, family, kind, mxu):
    """Streamed multi-target GLM reducer body: ONE X pass serves all C
    one-vs-rest problems of a streamed block. Class codes ride in as a
    (tile, 1) operand and per-class 0/1 targets derive in-kernel from an
    iota compare (the streamed twin of ``_glm_multi_value_grad_kernel``,
    plus the streamed contracts: prefix-count validity, intercept as the
    (1, C) ``b0`` operand with its gradient a separate output, no
    padding)."""
    i = pl.program_id(0)
    x = x_ref[:]                        # (tile, d)
    yc = yc_ref[:]                      # (tile, 1) f32 class codes
    B = b_ref[:]                        # (C, d) f32
    b0 = b0_ref[:]                      # (1, C)
    C = B.shape[0]
    m = _tile_mask(x, nv_ref, i, tile)
    xd = _mxu_cast(x, mxu)
    eta = jax.lax.dot_general(
        xd, B.astype(xd.dtype), (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) + b0                              # (tile, C)
    iota = jax.lax.broadcasted_iota(
        jnp.int32, (x.shape[0], C), 1
    ).astype(jnp.float32)
    yv = (iota == yc).astype(jnp.float32)
    from ..models.solvers.families import get_family

    fam = get_family(family)
    per = fam.pointwise(eta, yv) * m

    @pl.when(i == 0)
    def _init():
        for o in outs:
            o[:] = jnp.zeros_like(o)

    outs[0][:] += jnp.sum(per, axis=0, keepdims=True).sum(
        axis=1, keepdims=True
    )
    if kind == "val":
        return
    resid = (fam.mean(eta) - yv) * m
    grad_ref, gb_ref = outs[1], outs[2]
    grad_ref[:] += jax.lax.dot_general(
        resid.astype(xd.dtype), xd, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                   # (C, d)
    gb_ref[:] += jnp.sum(resid, axis=0, keepdims=True)   # (1, C)


def fused_glm_multi_stream(kind, x, n_valid, y_codes, B, family,
                           intercept, mxu=None, interpret=False):
    """One streamed block's multi-target ``kind`` sums in ONE X pass —
    the fused flavor of ``_block_val_multi`` / ``_block_val_grad_multi``
    (kinds "val" and "vg"; the per-class Hessian stack stays XLA). ``B``
    is (C, d+1) when ``intercept`` (last column the intercepts); raw
    sums over valid rows, shapes must satisfy
    ``glm_multi_stream_tile``."""
    S = x.shape[0]
    d_full = x.shape[1]
    B = B.astype(jnp.float32)
    C = B.shape[0]
    tile = glm_multi_stream_tile(S, d_full, C, x.dtype.itemsize)
    nv = jnp.asarray(n_valid, jnp.int32).reshape(1, 1)
    if intercept:
        Bm, b0 = B[:, :-1], B[:, -1][None, :]
    else:
        Bm, b0 = B, jnp.zeros((1, C), jnp.float32)
    d = Bm.shape[1]
    out_specs = [pl.BlockSpec((1, 1), lambda i: (0, 0))]
    out_shape = [jax.ShapeDtypeStruct((1, 1), jnp.float32)]
    if kind != "val":
        out_specs += [pl.BlockSpec((C, d), lambda i: (0, 0)),
                      pl.BlockSpec((1, C), lambda i: (0, 0))]
        out_shape += [jax.ShapeDtypeStruct((C, d), jnp.float32),
                      jax.ShapeDtypeStruct((1, C), jnp.float32)]
    outs = pl.pallas_call(
        functools.partial(_glm_multi_stream_kernel, tile=tile,
                          family=family, kind=kind, mxu=mxu),
        grid=(S // tile,),
        in_specs=[
            pl.BlockSpec((tile, d), lambda i: (i, 0)),
            pl.BlockSpec((tile, 1), lambda i: (i, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
            pl.BlockSpec((C, d), lambda i: (0, 0)),
            pl.BlockSpec((1, C), lambda i: (0, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(x, y_codes[:, None], nv, Bm, b0)
    loss = outs[0][0, 0]
    if kind == "val":
        return (loss,)
    grad = outs[1]
    if intercept:
        grad = jnp.concatenate([grad, outs[2].T], axis=1)
    return loss, grad


def _sgd_many_grad_kernel(x_ref, y_ref, nv_ref, w_ref, b0_ref, loss_ref,
                          gw_ref, gb_ref, *, tile, loss, mxu, codes):
    """Multi-weight twin of ``_sgd_grad_kernel``: ONE X pass serves N
    weight rows — the C one-vs-rest rows of a multiclass model
    (``codes=True``: y holds class indices, per-class 0/1 targets derive
    in-kernel) or the N models of a batched-trial cohort (``codes=False``:
    the (tile, 1) target broadcasts across the weight columns). eta is
    one (tile, N) MXU matmul against the stacked coef rows; the (N, d)
    gradient accumulates with a second MXU contraction."""
    i = pl.program_id(0)
    x = x_ref[:]                        # (tile, d)
    yv = y_ref[:]                       # (tile, 1) targets or codes
    W = w_ref[:]                        # (N, d) coef rows
    b0 = b0_ref[:]                      # (1, N) intercept*iflag per row
    N = W.shape[0]
    m = _tile_mask(x, nv_ref, i, tile)
    xd = _mxu_cast(x, mxu)
    eta = jax.lax.dot_general(
        xd, W.astype(xd.dtype), (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) + b0                              # (tile, N)
    if codes:
        iota = jax.lax.broadcasted_iota(
            jnp.int32, (x.shape[0], N), 1
        ).astype(jnp.float32)
        yv = (iota == yv).astype(jnp.float32)
    per, resid = sgd_objective_terms(eta, yv, loss)
    rm = resid * m

    @pl.when(i == 0)
    def _init():
        loss_ref[:] = jnp.zeros_like(loss_ref)
        gw_ref[:] = jnp.zeros_like(gw_ref)
        gb_ref[:] = jnp.zeros_like(gb_ref)

    loss_ref[:] += jnp.sum(per * m, axis=0, keepdims=True)   # (1, N)
    gw_ref[:] += jax.lax.dot_general(
        rm.astype(xd.dtype), xd, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                   # (N, d)
    gb_ref[:] += jnp.sum(rm, axis=0, keepdims=True)          # (1, N)


def fused_sgd_many_block_grad(x, n_valid, y, W_ext, iflags, loss,
                              codes, mxu=None, interpret=False):
    """(Σ pointwise-loss per row (N,), Σ ∂/∂W (N, d+1)) of one streamed
    block in ONE X pass for N stacked weight vectors — the fused flavor
    of the multiclass streamed SGD step (``codes=True``; ``iflags`` a
    scalar) and of the cohort scan's vmapped step (``codes=False``;
    ``iflags`` (N,) per-model). Raw sums — the caller divides by
    n_valid and applies each row's lr/l2/prox epilogue."""
    S, d = x.shape
    N = W_ext.shape[0]
    tile = sgd_many_stream_tile(S, d, N, x.dtype.itemsize)
    nv = jnp.asarray(n_valid, jnp.int32).reshape(1, 1)
    b0 = (W_ext[:, -1] * iflags).astype(jnp.float32)[None, :]
    loss_sums, gw, gb = pl.pallas_call(
        functools.partial(_sgd_many_grad_kernel, tile=tile, loss=loss,
                          mxu=mxu, codes=codes),
        grid=(S // tile,),
        in_specs=[
            pl.BlockSpec((tile, d), lambda i: (i, 0)),
            pl.BlockSpec((tile, 1), lambda i: (i, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
            pl.BlockSpec((N, d), lambda i: (0, 0)),
            pl.BlockSpec((1, N), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, N), lambda i: (0, 0)),
            pl.BlockSpec((N, d), lambda i: (0, 0)),
            pl.BlockSpec((1, N), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, N), jnp.float32),
            jax.ShapeDtypeStruct((N, d), jnp.float32),
            jax.ShapeDtypeStruct((1, N), jnp.float32),
        ],
        interpret=interpret,
    )(x, y[:, None], nv, W_ext[:, :-1], b0)
    grads = jnp.concatenate([gw, gb.T], axis=1)   # (N, d+1)
    return loss_sums[0], grads


def _kmeans_stream_kernel(x_ref, nv_ref, c_ref, c2_ref, sums_ref,
                          counts_ref, inertia_ref, *, tile, mxu):
    """``_lloyd_stats_kernel`` with the streamed blocks' bf16 policy:
    only the cross-term matmul runs at ``mxu`` (f32 accumulation), the
    norms/statistics stay f32 — mirroring
    ``euclidean_distances_sq(mxu_dtype=...)`` on the XLA flavor."""
    i = pl.program_id(0)
    x = x_ref[:]                        # (tile, d)
    c = c_ref[:]                        # (k, d)
    c2 = c2_ref[:]                      # (1, k)
    k = c.shape[0]
    m = _tile_mask(x, nv_ref, i, tile)
    xd, cd = _mxu_cast(x, mxu), _mxu_cast(c, mxu)
    xc = jax.lax.dot_general(
        xd, cd, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    d2 = jnp.sum(x * x, axis=1, keepdims=True) - 2.0 * xc + c2
    d2 = jnp.maximum(d2, 0.0)
    mind = jnp.min(d2, axis=1, keepdims=True)
    iota = jax.lax.broadcasted_iota(
        jnp.int32, (x.shape[0], k), 1
    ).astype(jnp.float32)
    labf = jnp.min(jnp.where(d2 <= mind, iota, float(k)), axis=1,
                   keepdims=True)
    onehot = (iota == labf).astype(jnp.float32) * m

    @pl.when(i == 0)
    def _init():
        sums_ref[:] = jnp.zeros_like(sums_ref)
        counts_ref[:] = jnp.zeros_like(counts_ref)
        inertia_ref[:] = jnp.zeros_like(inertia_ref)

    sums_ref[:] += jax.lax.dot_general(
        onehot, x, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    counts_ref[:] += jnp.sum(onehot, axis=0, keepdims=True)
    inertia_ref[:] += jnp.sum(mind * m, axis=0, keepdims=True)


def fused_kmeans_block_stats(x, n_valid, centers, mxu=None,
                             interpret=False):
    """(Σ x per label (k, d), count per label (k,), Σ min-d² scalar) of
    one streamed block in ONE X pass — the fused flavor of
    ``models/kmeans.py::_block_assign_stats`` (whose XLA form reads X
    twice: distance matmul + segment_sum) with the same prefix-count
    validity as the resident ``fused_lloyd_stats``. No padding: shapes
    must satisfy ``kmeans_stream_tile``."""
    S, d = x.shape
    k = centers.shape[0]
    centers = centers.astype(jnp.float32)
    tile = kmeans_stream_tile(S, d, k, x.dtype.itemsize)
    nv = jnp.asarray(n_valid, jnp.int32).reshape(1, 1)
    c2 = jnp.sum(centers * centers, axis=1)[None, :]
    sums, counts, inertia = pl.pallas_call(
        functools.partial(_kmeans_stream_kernel, tile=tile, mxu=mxu),
        grid=(S // tile,),
        in_specs=[
            pl.BlockSpec((tile, d), lambda i: (i, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
            pl.BlockSpec((k, d), lambda i: (0, 0)),
            pl.BlockSpec((1, k), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((k, d), lambda i: (0, 0)),
            pl.BlockSpec((1, k), lambda i: (0, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((k, d), jnp.float32),
            jax.ShapeDtypeStruct((1, k), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x, nv, centers, c2)
    return sums, counts[0], inertia[0, 0]


@functools.partial(jax.jit, static_argnames=("interpret",))
def fused_assign_update(x, mask, centers, interpret=False):
    """One Lloyd-iteration data pass over a (per-device) block.

    x: (n, d), mask: (n,) row validity, centers: (k, d).
    Returns (labels (n,) int32, min_d2 (n,), sums (k, d), counts (k,),
    inertia scalar) — caller psums the last three across shards.
    """
    n, d = x.shape
    k = centers.shape[0]
    x = x.astype(jnp.float32)
    mask = mask.astype(jnp.float32)
    centers = centers.astype(jnp.float32)
    tile = _pick_tile(n)
    n_pad = -(-n // tile) * tile
    if n_pad != n:
        # masked rows contribute nothing; labels/mind sliced back below
        x = jnp.pad(x, ((0, n_pad - n), (0, 0)))
        mask = jnp.pad(mask, (0, n_pad - n))
    grid = (n_pad // tile,)
    c2 = jnp.sum(centers * centers, axis=1)[None, :]    # (1, k) in XLA
    labels, mind, sums, counts = pl.pallas_call(
        _assign_update_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile, d), lambda i: (i, 0)),
            pl.BlockSpec((tile, 1), lambda i: (i, 0)),
            pl.BlockSpec((k, d), lambda i: (0, 0)),
            pl.BlockSpec((1, k), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((tile, 1), lambda i: (i, 0)),
            pl.BlockSpec((tile, 1), lambda i: (i, 0)),
            pl.BlockSpec((k, d), lambda i: (0, 0)),
            pl.BlockSpec((1, k), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_pad, 1), jnp.int32),
            jax.ShapeDtypeStruct((n_pad, 1), jnp.float32),
            jax.ShapeDtypeStruct((k, d), jnp.float32),
            jax.ShapeDtypeStruct((1, k), jnp.float32),
        ],
        interpret=interpret,
    )(x, mask[:, None], centers, c2)
    mind = mind[:n, 0]
    inertia = jnp.sum(mind)  # XLA fuses this with the kernel output
    return labels[:n, 0], mind, sums, counts[0], inertia
