"""Multi-host distributed runtime.

Reference: the ``distributed`` scheduler/worker/comm stack — TCP frames,
msgpack+pickle serialization, heartbeats (SURVEY.md §2b rows 4-5, §5 comm
row). TPU replacement: intra-slice communication is XLA collectives over
ICI compiled into programs (no serialization layer exists at all);
cross-host control is the JAX distributed runtime over DCN. This module
is the thin bring-up layer: ``initialize()`` wraps
``jax.distributed.initialize`` (no-op single-host), ``global_mesh`` spans
every process's devices, and small host-side control messages ride an
all-gather (``broadcast_host`` / ``barrier``) instead of a socket
protocol.

Single-host sessions exercise the same code paths (process_count == 1),
which is how the test suite covers it; a pod run only changes the
environment variables.

VIRTUAL PROCESSES: the real 2-process bring-up needs a backend that
implements cross-process collectives — some CPU jax builds refuse with
"Multiprocess computations aren't implemented on the CPU backend",
which used to leave the distribution LOGIC (task partitioning, round
merges, failure propagation, local-mesh placement) untestable under
tier-1. :func:`run_virtual_processes` runs N ranks as threads of ONE
process: ``process_count``/``process_index`` answer per-thread, the
host collectives (``allgather_object`` and everything built on it)
rendezvous in-process with the same ordering/bit-exactness guarantees,
``local_mesh`` splits the local devices into per-rank submeshes, and a
rank that dies mid-round fails its peers' collectives fast (the
worker-death detection analog). Device-fabric SPMD (a GSPMD program
psumming across processes) is exactly what this cannot emulate — those
paths keep their real-multiprocess tests, capability-probed.
"""

from __future__ import annotations

import contextlib
import pickle
import threading
import time as _time

import numpy as np

import jax
import jax.numpy as jnp

from .mesh import device_mesh

_initialized = False

# -- virtual process plane ---------------------------------------------------

_vlocal = threading.local()     # .ctx = (rank, world, _VirtualExchange)


def _virtual():
    return getattr(_vlocal, "ctx", None)


class _VirtualExchange:
    """In-process rendezvous allgather shared by one virtual world's
    rank threads. Rounds are generation-counted so back-to-back
    collectives never mix; a failed rank poisons the exchange so peers
    raise instead of waiting out the timeout."""

    def __init__(self, world, timeout=120.0):
        self.world = int(world)
        self.timeout = float(timeout)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._slots = {}
        self._result = None
        self._gen = 0
        self._failed = None     # (rank, repr(exc))

    def fail(self, rank, exc):
        with self._cond:
            if self._failed is None:
                self._failed = (rank, repr(exc))
            self._cond.notify_all()

    def allgather(self, rank, obj):
        with self._cond:
            if self._failed is not None:
                raise RuntimeError(
                    f"virtual peer {self._failed[0]} failed: "
                    f"{self._failed[1]}"
                )
            gen = self._gen
            self._slots[rank] = obj
            if len(self._slots) == self.world:
                self._result = [self._slots[r]
                                for r in range(self.world)]
                self._slots = {}
                self._gen += 1
                self._cond.notify_all()
                return list(self._result)
            deadline = _time.monotonic() + self.timeout
            while self._gen == gen:
                if self._failed is not None:
                    raise RuntimeError(
                        f"virtual peer {self._failed[0]} failed: "
                        f"{self._failed[1]}"
                    )
                left = deadline - _time.monotonic()
                if left <= 0:
                    raise TimeoutError(
                        f"virtual allgather timed out after "
                        f"{self.timeout}s (rank {rank} waiting)"
                    )
                self._cond.wait(min(left, 0.1))
            return list(self._result)


@contextlib.contextmanager
def virtual_process(rank, world, exchange):
    """Make THIS thread virtual rank ``rank`` of ``world`` — every
    process-topology query and host collective in this module answers
    for the virtual rank while the context is open."""
    prev = _virtual()
    _vlocal.ctx = (int(rank), int(world), exchange)
    try:
        yield
    finally:
        if prev is None:
            del _vlocal.ctx
        else:
            _vlocal.ctx = prev


def run_virtual_processes(fn, world=2, timeout=120.0):
    """Run ``fn(rank)`` on ``world`` rank threads of this process with
    the virtual collective plane wired up; returns ``[fn(0), ...,
    fn(world-1)]``. The single-process stand-in for a real
    ``jax.distributed`` bring-up: same partitioning/merge/failure logic,
    no cross-process runtime required. A rank that raises fails the
    others' pending collectives immediately; the first raised exception
    propagates to the caller."""
    exchange = _VirtualExchange(world, timeout=timeout)
    results = [None] * world
    errors = [None] * world

    def body(rank):
        try:
            with virtual_process(rank, world, exchange):
                results[rank] = fn(rank)
        except BaseException as exc:  # noqa: BLE001 - reraised below
            errors[rank] = exc
            exchange.fail(rank, exc)

    threads = [threading.Thread(target=body, args=(r,),
                                name=f"virtual-rank-{r}")
               for r in range(world)]
    for t in threads:
        t.start()
    # one shared deadline (not `timeout` per join — sequential joins
    # would wait up to world x timeout), and an explicit liveness check:
    # a rank hung OUTSIDE a collective never trips exchange.fail, and
    # silently returning its None result would surface as a confusing
    # TypeError in the caller instead of a timeout naming the rank
    deadline = _time.monotonic() + timeout
    for t in threads:
        t.join(max(0.0, deadline - _time.monotonic()))
    for exc in errors:
        if exc is not None and not isinstance(exc, RuntimeError):
            raise exc
    for exc in errors:
        if exc is not None:
            raise exc
    hung = [t.name for t in threads if t.is_alive()]
    if hung:
        raise RuntimeError(
            f"virtual rank(s) still running after {timeout}s: "
            + ", ".join(hung)
        )
    return results


def initialize(coordinator_address=None, num_processes=None,
               process_id=None, local_device_ids=None):
    """Bring up the JAX distributed runtime (DCN control plane).

    No-op when single-process and no coordinator is configured — the same
    script runs on a laptop, one TPU VM, or every host of a pod slice.
    """
    global _initialized
    if _initialized:
        return
    if coordinator_address is None and num_processes is None and \
            "COORDINATOR_ADDRESS" not in __import__("os").environ:
        _initialized = True  # single-process mode
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
    )
    _initialized = True


def process_index() -> int:
    v = _virtual()
    return v[0] if v is not None else jax.process_index()


def process_count() -> int:
    v = _virtual()
    return v[1] if v is not None else jax.process_count()


def in_virtual_world() -> bool:
    """True on a thread running as a virtual rank of a >1 world.
    Topology queries answer for the virtual rank, but every device
    still reports the REAL process — so callers detecting
    cross-process work from device attributes must ask this instead
    (a virtual world is always "cross-process": its twins exist to
    replicate multi-process semantics in one process)."""
    v = _virtual()
    return v is not None and v[1] > 1


def is_coordinator() -> bool:
    """The host that runs search controllers (SURVEY.md §3.5: 'asyncio
    controller on host 0')."""
    return process_index() == 0


def global_mesh(axis_names=("data",), shape=None):
    """Mesh over ALL processes' devices (ICI within a slice, DCN across:
    topology-ordered so the DCN hop is the outer factor of the data
    axis)."""
    return device_mesh(shape=shape, axis_names=axis_names,
                       devices=jax.devices(), topology_order=True)


def local_mesh(axis_names=("data",), shape=None):
    """Mesh over THIS process's devices only. Trials placed here never
    emit cross-host collectives, so different processes can run different
    programs concurrently — the placement unit for distributed
    hyperparameter search (SURVEY.md §3.5: 'trials pinned to
    hosts/mesh-subsets'). Under a virtual world the local devices are
    SPLIT into contiguous per-rank groups, so virtual ranks place their
    trials on disjoint devices exactly like real processes do."""
    devices = jax.local_devices()
    v = _virtual()
    if v is not None:
        rank, world, _ = v
        if len(devices) >= world:
            per = len(devices) // world
            devices = devices[rank * per:(rank + 1) * per]
        else:  # fewer devices than ranks: everyone shares device 0
            devices = devices[:1]
    return device_mesh(shape=shape, axis_names=axis_names,
                       devices=devices, topology_order=True)


def allgather_object(obj):
    """Gather one small picklable host object per process; every process
    receives the list ``[obj_from_proc_0, ..., obj_from_proc_{P-1}]``.
    Variable-size pickles ride the fixed-size device collective by
    padding to the max length (sizes exchanged first) — the control-plane
    result channel for distributed searches, replacing the reference's
    msgpack/pickle frames over TCP (SURVEY.md §5 comm row)."""
    if process_count() == 1:
        return [obj]
    v = _virtual()
    if v is not None:
        rank, _, exchange = v
        # pickle round-trip per rank: same isolation (and same
        # picklability requirement) as the real wire path
        return [pickle.loads(p) for p in
                exchange.allgather(rank, pickle.dumps(obj))]
    buf = np.frombuffer(pickle.dumps(obj), np.uint8)
    sizes = allgather_host(np.array([buf.size], np.int32))[:, 0]
    padded = np.zeros(int(sizes.max()), np.uint8)
    padded[: buf.size] = buf
    stacked = allgather_host(padded)
    return [
        pickle.loads(stacked[i, : sizes[i]].tobytes())
        for i in range(len(sizes))
    ]


def psum_host(*arrays):
    """Sum each small host array across processes; every process gets
    the identical (bit-exact — same gather order everywhere) global sum.
    The cross-process merge plane for streamed fits: per-pass
    loss/gradient/Hessian/moment accumulators are additive, so one
    psum of the local sums turns a per-process stream into a global fit
    (SURVEY.md §1 L2 dd partitions; VERDICT r4 missing #3). No-op
    single-process. Returns one array, or a tuple matching the inputs."""
    if process_count() == 1:
        outs = tuple(np.asarray(a) for a in arrays)
        return outs[0] if len(outs) == 1 else outs
    # ONE packed collective regardless of argument count — hot callers
    # (Lloyd stats, Newton's value/grad/Hessian) psum 3 arrays per data
    # pass, and each allgather pays a full DCN round trip
    arrs = [np.asarray(a, np.float64) for a in arrays]
    flat = (np.concatenate([a.ravel() for a in arrs])
            if arrs else np.zeros(0))
    total = allgather_host(flat).sum(axis=0)
    outs, off = [], 0
    for a in arrs:
        outs.append(total[off:off + a.size].reshape(a.shape))
        off += a.size
    return outs[0] if len(outs) == 1 else tuple(outs)


def allgather_host(value: np.ndarray) -> np.ndarray:
    """Gather a small host array from every process; returns the
    (n_processes, *shape) stack on all of them (shape/dtype must match
    across processes). The score-gather channel of distributed searches —
    replaces the reference's worker→scheduler result messages with one
    device-fabric collective.

    The payload rides the collective as raw bytes: ``jnp.asarray`` would
    silently downcast float64 (x64 disabled by default), and score merges
    must be bit-exact with the single-process run."""
    value = np.ascontiguousarray(value)
    if process_count() == 1:
        return value[None]
    v = _virtual()
    if v is not None:
        rank, _, exchange = v
        parts = exchange.allgather(rank, value.copy())
        if any(p.shape != value.shape or p.dtype != value.dtype
               for p in parts):
            raise ValueError(
                "allgather_host requires identical shape/dtype on "
                f"every rank; got {[(p.shape, str(p.dtype)) for p in parts]}"
            )
        return np.stack(parts)
    from jax.experimental import multihost_utils

    buf = np.frombuffer(value.tobytes(), np.uint8)
    stacked = np.asarray(
        multihost_utils.process_allgather(jnp.asarray(buf), tiled=False)
    )
    return np.stack([
        np.frombuffer(stacked[i].tobytes(), value.dtype).reshape(value.shape)
        for i in range(stacked.shape[0])
    ])


def array_from_process_local(local, mesh=None, dtype=np.float32):
    """Global row-sharded ShardedArray from PER-PROCESS row blocks.

    Each process contributes its OWN rows (global order = process
    order); unlike ``ShardedArray.from_array`` (SPMD: every process
    holds the full array), only the rows that land on a FOREIGN
    process's shards are exchanged — at most one shard's worth per
    process boundary, zero when counts divide evenly. Wire cost note:
    the exchange rides ``allgather_object`` (a broadcast), so each
    boundary parcel reaches every process — O(P x boundary bytes) over
    DCN, fine for the boundary-slice volumes this produces; a
    per-destination channel would be the upgrade if parcels ever grow.
    The reference's analog is dd's partition-locality (a worker's
    partitions stay put; SURVEY.md §1 L2 dd row); here the multi-host
    ingest for PartitionedFrame.to_sharded(mesh=global_mesh())."""
    import jax

    from .mesh import data_shards, row_sharding
    from .sharded import ShardedArray, _padded_rows

    local = np.ascontiguousarray(np.asarray(local, dtype))
    if mesh is None:
        mesh = global_mesh()
    me = process_index()
    shapes = allgather_object(
        (tuple(local.shape[1:]), str(local.dtype))
    )
    if any(s != shapes[0] for s in shapes):
        raise ValueError(
            "array_from_process_local requires identical feature shape "
            f"and dtype on every process; got {shapes}"
        )
    counts = np.asarray(allgather_object(int(local.shape[0])), np.int64)
    n = int(counts.sum())
    off = int(counts[:me].sum())
    n_pad = _padded_rows(n, data_shards(mesh))
    shape = (n_pad,) + local.shape[1:]
    sharding = row_sharding(mesh, local.ndim)
    # exact global row range per device, then per process
    imap = sharding.devices_indices_map(shape)
    proc_ranges = {}
    for dev, idx in imap.items():
        sl = idx[0]
        rng = (sl.start or 0, n_pad if sl.stop is None else sl.stop)
        proc_ranges.setdefault(dev.process_index, set()).add(rng)
    # ship the slices of MY rows that land on foreign shards
    parcels = {}
    for q, ranges in proc_ranges.items():
        if q == me:
            continue
        for a, b in sorted(ranges):
            lo, hi = max(a, off), min(b, off + local.shape[0])
            if lo < hi:
                parcels.setdefault(q, []).append(
                    (lo, local[lo - off:hi - off])
                )
    received = allgather_object(parcels)
    # assemble my shards: own overlap + foreign parcels; rows >= n stay
    # zero (the trailing padding row_mask hides)
    mine = {}
    for a, b in sorted(proc_ranges.get(me, ())):
        buf = np.zeros((b - a,) + local.shape[1:], dtype=local.dtype)
        lo, hi = max(a, off), min(b, off + local.shape[0])
        if lo < hi:
            buf[lo - a:hi - a] = local[lo - off:hi - off]
        for sender in received:
            for g0, arr in sender.get(me, []):
                l2, h2 = max(a, g0), min(b, g0 + arr.shape[0])
                if l2 < h2:
                    buf[l2 - a:h2 - a] = arr[l2 - g0:h2 - g0]
        mine[(a, b)] = buf

    if _virtual() is not None:
        # virtual ranks share one process whose devices ALL report
        # process_index 0, so the shard buffers (own rows + shipped
        # parcels) land wherever the real attribute says — but every
        # rank must build the (fully addressable) global array. One
        # more gather merges the assembled shard buffers everywhere;
        # the parcel-routing logic above still ran for real.
        merged = {}
        for part in allgather_object(mine):
            merged.update(part)
        mine = merged

    def cb(idx):
        sl = idx[0]
        a = sl.start or 0
        return mine[(a, n_pad if sl.stop is None else sl.stop)]

    data = jax.make_array_from_callback(shape, sharding, cb)
    return ShardedArray(data, n, mesh)


_MULTIHOST_CAPABLE = None


def multihost_capability():
    """(ok, reason): can this runtime span processes with a DEVICE
    collective? The runtime twin of tests/_mp_capability's subprocess
    probe: cached, one tiny cross-process barrier on first ask — some
    CPU jax builds bring the distributed runtime up but refuse the
    first collective ("Multiprocess computations aren't implemented on
    the CPU backend"), and a streamed fit must degrade to its host
    psum merge there instead of crashing mid-pass. Virtual worlds
    answer False: their ranks share one real process, so there is
    nothing for ``multihost_utils`` to span."""
    global _MULTIHOST_CAPABLE
    if _MULTIHOST_CAPABLE is not None:
        return _MULTIHOST_CAPABLE
    if process_count() == 1:
        return (False, "single-process")
    if _virtual() is not None:
        return (False, "virtual world (one real process)")
    try:
        barrier("multihost-capability-probe")
        _MULTIHOST_CAPABLE = (True, "")
    except Exception as exc:  # noqa: BLE001 - the probe IS the catch
        _MULTIHOST_CAPABLE = (False, f"{type(exc).__name__}: {exc}")
    return _MULTIHOST_CAPABLE


class StreamSyncTimeout(RuntimeError):
    """The multihost pass barrier did not complete within
    ``config.stream_sync_timeout_s`` — a peer process is likely gone
    (TPU slices fail whole), and without the deadline the surviving
    hosts would hang in the collective forever. Typed so the driver can
    checkpoint-restart the fit (``utils/checkpoint.py`` contract)
    instead of diagnosing a wedged process."""


def run_with_deadline(fn, timeout_s, tag="stream_pass"):
    """Run ``fn`` (a blocking collective body) on a helper thread and
    raise :class:`StreamSyncTimeout` if it hasn't completed within
    ``timeout_s``. The collective itself cannot be interrupted — the
    helper thread is abandoned (daemon) on timeout, which is fine: the
    typed error's whole point is that the process restarts. ``fn``'s
    own exception re-raises in the caller."""
    done = threading.Event()
    err = []

    def runner():
        try:
            fn()
        except BaseException as exc:  # noqa: BLE001 - re-raised below
            err.append(exc)
        finally:
            done.set()

    t = threading.Thread(target=runner, daemon=True,
                         name=f"stream-sync-{tag}")
    t.start()
    if not done.wait(timeout_s):
        raise StreamSyncTimeout(
            f"pass barrier {tag!r} did not complete within "
            f"{timeout_s:g}s — a peer process is likely gone; restart "
            "the fit from its checkpoint"
        )
    if err:
        raise err[0]


def sync_stream_pass(tag="stream_pass", timeout_s=None) -> bool:
    """Process-spanning sync point between streamed passes
    (``multihost_utils.sync_global_devices``): on a live multi-host
    runtime every process streams the same pass sequence over its
    LOCAL shard, and the barrier keeps a fast host from racing ahead
    into pass N+1 transfers while a slow peer still owns the fabric
    for pass N's psum merge. No-op (returns False) single-process, in
    virtual worlds, and on backends whose capability probe failed.

    ``timeout_s`` (default ``config.stream_sync_timeout_s``; 0 = wait
    forever) bounds the barrier: a lost peer raises the typed
    :class:`StreamSyncTimeout` instead of wedging the fit."""
    ok, _ = multihost_capability()
    if not ok:
        return False
    from ..config import get_config

    cfg = get_config()
    if timeout_s is None:
        timeout_s = float(cfg.stream_sync_timeout_s)
    # the fault-plan spec is captured HERE, on the caller's thread: with
    # a deadline armed the body runs on a fresh helper thread whose
    # thread-local config would not carry a config.set override (the
    # same capture rule BlockStream._fault_spec follows)
    spec = cfg.fault_plan

    def body():
        from ..reliability.faults import fire_plan

        fire_plan(spec, "pass_barrier")
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(tag)

    if timeout_s and timeout_s > 0:
        run_with_deadline(body, timeout_s, tag)
    else:
        body()
    return True


def barrier(name="barrier"):
    """Cross-host sync point: a tiny psum over every device (virtual
    ranks rendezvous in-process and report the same device-count sum)."""
    v = _virtual()
    if v is not None:
        rank, _, exchange = v
        exchange.allgather(rank, name)
        return float(len(jax.devices()))
    x = jnp.ones((jax.device_count(),))
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = global_mesh()
    y = jax.jit(
        lambda v: jnp.sum(v),
        in_shardings=NamedSharding(mesh, P("data")),
        out_shardings=NamedSharding(mesh, P()),
    )(x)
    return float(y)


def broadcast_host(value: np.ndarray, root: int = 0) -> np.ndarray:
    """Broadcast a small host array from the coordinator to all processes
    — replaces the reference's scheduler→worker control messages. Rides
    the device fabric (device_put + replication), not a socket."""
    if process_count() == 1:
        return np.asarray(value)
    v = _virtual()
    if v is not None:
        rank, _, exchange = v
        parts = exchange.allgather(rank, np.asarray(value).copy())
        return parts[root]
    from jax.experimental import multihost_utils

    return np.asarray(
        multihost_utils.broadcast_one_to_all(
            jnp.asarray(value), is_source=process_index() == root
        )
    )
