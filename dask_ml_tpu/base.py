"""Estimator base: the scikit-learn contract.

The reference's estimators subclass sklearn bases so that ``get_params`` /
``set_params`` / ``clone`` compose with pipelines and search (SURVEY.md §5
config row: "estimator params stay sklearn-style (MUST)"). We do the same —
sklearn's ``BaseEstimator`` provides the param introspection contract; the
mixins add ``score`` defaults. Fitted state is stored as numpy on the host
(small: coefs, centers, components) with device-resident copies created on
demand, so estimators pickle/clone cleanly.
"""

from __future__ import annotations

import numpy as np
from sklearn.base import (  # re-exported contract, verified sklearn 1.9
    BaseEstimator,
    ClassifierMixin,
    ClusterMixin,
    RegressorMixin,
    TransformerMixin,
    clone,
)

__all__ = [
    "BaseEstimator",
    "ClassifierMixin",
    "RegressorMixin",
    "TransformerMixin",
    "ClusterMixin",
    "clone",
    "to_host",
]


def log_proba(p):
    """log of a probability matrix, sklearn ``predict_log_proba``
    semantics: zero probabilities map to -inf, silently (no runtime
    warning). THE one implementation — every classifier's
    predict_log_proba delegates here so they cannot diverge."""
    with np.errstate(divide="ignore"):
        return np.log(p)


def to_host(x):
    """Move a fitted attribute to host numpy (fitted attrs are small).

    Under a multi-process runtime an array on the global mesh spans
    devices this process cannot address; it is gathered to every host
    with a collective (all processes reach this call in SPMD lockstep —
    the same contract as any other collective op on the global mesh)."""
    import jax

    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(x, tiled=True))
    return np.asarray(x)
