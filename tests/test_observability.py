"""Observability subsystem (ISSUE 1): hierarchical span tracing, the
runtime counter registry, the run-report CLI, back-compat re-exports,
and the zero-overhead guarantee (no callback traced into jitted code
when metrics are disabled)."""

import io
import json
import os
import threading

import numpy as np
import pytest

from dask_ml_tpu import config, observability as obs


def _read_jsonl(path):
    return [json.loads(line) for line in open(path)]


# -- spans ------------------------------------------------------------------

def test_span_nesting_parent_ids_and_attrs(tmp_path):
    trace = str(tmp_path / "t")
    with config.set(trace_dir=trace):
        with obs.span("outer", component="X", n_rows=100) as sp_o:
            assert obs.current_span_id() is not None
            with obs.span("inner") as sp_i:
                sp_i.add(detail=7)
            sp_o.add(n_iter=3)
        assert obs.current_span_id() is None
    recs = _read_jsonl(os.path.join(trace, "trace.jsonl"))
    assert [r["span"] for r in recs] == ["inner", "outer"]  # close order
    inner, outer = recs
    assert inner["parent_id"] == outer["span_id"]
    assert inner["depth"] == 1 and outer["depth"] == 0
    assert outer["parent_id"] is None
    assert inner["detail"] == 7
    assert outer["n_iter"] == 3 and outer["n_rows"] == 100
    assert outer["wall_s"] >= inner["wall_s"] >= 0.0
    assert "sync_s" in outer


def test_span_noop_when_disabled(tmp_path):
    with config.set(trace_dir="", metrics_path=""):
        with obs.span("nothing", a=1) as sp:
            assert sp is obs.NOOP_SPAN
            assert obs.current_span_id() is None
            assert sp.sync(5) == 5  # passthrough
    assert list(tmp_path.iterdir()) == []


def test_span_sync_accumulates(tmp_path):
    import jax.numpy as jnp

    trace = str(tmp_path / "t")
    with config.set(trace_dir=trace):
        with obs.span("s") as sp:
            out = sp.sync(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
    assert float(out[0, 0]) == 8.0
    rec = _read_jsonl(os.path.join(trace, "trace.jsonl"))[-1]
    assert rec["sync_s"] >= 0.0


def test_span_records_error_and_unwinds_stack(tmp_path):
    trace = str(tmp_path / "t")
    with config.set(trace_dir=trace):
        with pytest.raises(ValueError):
            with obs.span("boom"):
                raise ValueError("x")
        assert obs.current_span_id() is None
    rec = _read_jsonl(os.path.join(trace, "trace.jsonl"))[-1]
    assert rec["span"] == "boom" and rec["error"] == "ValueError"


def test_span_prefers_active_logger_sink(tmp_path):
    p = str(tmp_path / "m.jsonl")
    with obs.MetricsLogger(p, extra={"run": "r1"}) as lg, \
            obs.active_logger(lg):
        with obs.span("inside"):
            pass
    recs = _read_jsonl(p)
    assert recs and recs[0]["span"] == "inside"
    assert recs[0]["run"] == "r1"  # went through the bound logger


# -- counters ---------------------------------------------------------------

def test_counter_snapshot_and_reset():
    obs.counters_reset()
    obs.counter_add("widgets", 2)
    obs.counter_add("widgets", 3)
    snap = obs.counters_snapshot()
    assert snap["widgets"] == 5
    snap["widgets"] = 99  # snapshot is a copy
    assert obs.counters_snapshot()["widgets"] == 5
    obs.counters_reset()
    assert obs.counters_snapshot() == {}


def test_record_transfer_gated_by_config():
    obs.counters_reset()
    with config.set(obs_counters=False):
        obs.record_transfer(1024)
    assert "h2d_bytes" not in obs.counters_snapshot()
    with config.set(obs_counters=True):
        obs.record_transfer(1024)
        obs.record_donation(512)
    snap = obs.counters_snapshot()
    assert snap["h2d_bytes"] == 1024 and snap["h2d_transfers"] == 1
    assert snap["donated_bytes_reused"] == 512


def test_recompile_counter_increments_on_fresh_compile():
    import jax

    obs.counters_reset()
    with config.set(obs_counters=True):
        # a jit of a brand-new Python lambda can't hit any cache
        jax.jit(lambda x: x * 3 + 1)(np.float32(2.0))
    snap = obs.counters_snapshot()
    assert snap.get("recompiles", 0) >= 1
    assert snap.get("compile_secs", 0) > 0


def test_stream_h2d_bytes_counted():
    from dask_ml_tpu.parallel.streaming import BlockStream

    X = np.random.RandomState(0).rand(512, 4).astype(np.float32)
    obs.counters_reset()
    with config.set(obs_counters=True):
        for blk in BlockStream((X,), block_rows=128):
            pass
    snap = obs.counters_snapshot()
    # every block: X slab + its row mask, all float32
    assert snap["h2d_bytes"] == X.nbytes + 4 * 512
    assert snap["h2d_transfers"] == 4


def test_span_emits_counter_deltas(tmp_path):
    trace = str(tmp_path / "t")
    obs.counters_reset()
    with config.set(trace_dir=trace, obs_counters=True):
        obs.counter_add("pre_existing", 100)
        with obs.span("work"):
            obs.record_transfer(2048)
    rec = _read_jsonl(os.path.join(trace, "trace.jsonl"))[-1]
    assert rec["ctr_h2d_bytes"] == 2048
    assert "ctr_pre_existing" not in rec  # only deltas, not totals


def test_device_memory_gauges_shape():
    gauges = obs.device_memory_gauges()
    assert isinstance(gauges, dict)  # empty on CPU; keyed dev<i>_* on TPU
    for v in gauges.values():
        assert isinstance(v, int)


def test_log_counters_record(tmp_path):
    p = str(tmp_path / "c.jsonl")
    obs.counters_reset()
    obs.counter_add("recompiles", 4)
    with obs.MetricsLogger(p) as lg:
        snap = obs.log_counters(lg, phase="end")
    rec = _read_jsonl(p)[-1]
    assert rec["counters"] is True and rec["recompiles"] == 4
    assert rec["phase"] == "end"
    assert snap["recompiles"] == 4


# -- ambient logger under concurrency --------------------------------------

def test_active_logger_non_lifo_and_concurrent(tmp_path):
    """Two fits binding/unbinding out of LIFO order must each remove
    exactly their own sink entry; the innermost surviving binding keeps
    receiving jit-step callbacks."""
    from dask_ml_tpu.observability._metrics import _active_loggers, _jit_step_cb

    a = obs.MetricsLogger(str(tmp_path / "a.jsonl"), extra={"who": "a"})
    b = obs.MetricsLogger(str(tmp_path / "b.jsonl"), extra={"who": "b"})
    cm_a = obs.active_logger(a)
    cm_b = obs.active_logger(b)
    cm_a.__enter__()
    cm_b.__enter__()
    cm_a.__exit__(None, None, None)  # non-LIFO exit
    assert _active_loggers == [b]
    _jit_step_cb(0, ("loss",), 1.5)
    cm_b.__exit__(None, None, None)
    assert _active_loggers == []
    recs = _read_jsonl(str(tmp_path / "b.jsonl"))
    assert recs and recs[0]["who"] == "b" and recs[0]["loss"] == 1.5
    assert not os.path.exists(str(tmp_path / "a.jsonl"))


def test_concurrent_fits_span_trees_are_threadlocal(tmp_path):
    """Parallel trial threads trace independent span trees: no thread
    ever parents its span under another thread's open span."""
    trace = str(tmp_path / "t")
    errs = []

    def worker(tag):
        try:
            # config.set is thread-local (like dask.config): each trial
            # thread binds its own override, exactly as the controller's
            # worker threads would
            with config.set(trace_dir=trace):
                with obs.span("outer", tag=tag):
                    with obs.span("inner", tag=tag):
                        pass
        except Exception as e:  # pragma: no cover
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    recs = _read_jsonl(os.path.join(trace, "trace.jsonl"))
    outer = {r["tag"]: r for r in recs if r["span"] == "outer"}
    inner = {r["tag"]: r for r in recs if r["span"] == "inner"}
    assert set(outer) == set(inner) == {0, 1, 2, 3}
    for tag, r in inner.items():
        assert r["parent_id"] == outer[tag]["span_id"]
    for r in outer.values():
        assert r["parent_id"] is None


# -- zero overhead ----------------------------------------------------------

def test_no_debug_callback_in_solver_jaxpr_when_disabled():
    """With metrics disabled the solver trace must contain NO host
    callback — the acceptance criterion that the silent path stays at
    hardware speed."""
    import jax
    import jax.numpy as jnp

    from dask_ml_tpu.models.solvers.solvers import _gd_run

    X = jnp.ones((16, 3))
    y = jnp.zeros(16)
    mask = jnp.ones(16)

    def run(log):
        return jax.make_jaxpr(
            lambda X_, y_, m_, b_: _gd_run(
                X_, y_, m_, 16.0, b_, jnp.float32(0.0), jnp.ones(3), 0.5,
                jnp.asarray(3), jnp.float32(1e-6), 1.0, "logistic", "none",
                log=log,
            )
        )(X, y, mask, jnp.zeros(3))

    assert "debug_callback" not in str(run(False))
    assert "debug_callback" in str(run(True))


def test_program_registry_and_watchdog_add_nothing_when_disabled():
    """ISSUE 4 extension of the zero-overhead contract: with
    obs_programs/watchdog_timeout_s at their defaults, the tracked
    solver entry points trace to the IDENTICAL jaxpr (the tracker lives
    outside jit and must stay there), the program registry stays empty,
    and no watchdog thread exists."""
    import jax
    import jax.numpy as jnp

    from dask_ml_tpu.models.solvers.solvers import _gd_run

    X = jnp.ones((16, 3))
    y = jnp.zeros(16)
    mask = jnp.ones(16)

    def jaxpr():
        return str(jax.make_jaxpr(
            lambda X_, y_, m_, b_: _gd_run(
                X_, y_, m_, 16.0, b_, jnp.float32(0.0), jnp.ones(3), 0.5,
                jnp.asarray(3), jnp.float32(1e-6), 1.0, "logistic",
                "none", log=False,
            )
        )(X, y, mask, jnp.zeros(3)))

    obs.programs_reset()
    with config.set(obs_programs=False, watchdog_timeout_s=0.0):
        baseline = jaxpr()
        assert "debug_callback" not in baseline
        assert obs.programs_snapshot() == []   # tracker never recorded
        assert not obs.watchdog_active()       # no thread armed
        from dask_ml_tpu.observability import watchdog

        with watchdog() as wd:                 # config-gated: a no-op
            assert wd is None
            assert jaxpr() == baseline         # nothing entered the trace
        assert not obs.watchdog_active()
    # the tracker is transparent: the jit object stays reachable and the
    # raw body unwrap (used by super-block reducers) still lands on the
    # plain function
    assert hasattr(_gd_run, "__wrapped_jit__")
    assert not hasattr(_gd_run.__wrapped__, "__wrapped__")


def test_live_plane_adds_nothing_when_port_unset():
    """ISSUE 5 extension of the zero-overhead contract: with
    obs_http_port at its 0 default the live telemetry plane is inert —
    no exporter thread, no span observer, every publish call a bool
    check, the gauge/histogram registry untouched by a streamed SGD
    pass, and the streamed scan kernel's jaxpr byte-identical whether
    or not a server ever existed in the process."""
    import jax
    import jax.numpy as jnp

    from dask_ml_tpu.models.sgd import SGDClassifier, _sgd_sb_scan
    from dask_ml_tpu.observability import live
    from dask_ml_tpu.observability._programs import unwrap
    from dask_ml_tpu.observability._spans import _span_observers

    def scan_jaxpr():
        body = unwrap(_sgd_sb_scan)
        K, S, d = 2, 8, 3
        return str(jax.make_jaxpr(
            lambda W, Xs, ys, c, lrs: body(
                W, Xs, ys, c, lrs, 1e-4, 1.0, 0.0, 1.0, "hinge", None
            )
        )(jnp.zeros(d + 1), jnp.zeros((K, S, d)), jnp.zeros((K, S)),
          jnp.zeros(K, jnp.int32), jnp.zeros(K)))

    assert live.telemetry_server() is None
    assert not live.live_publishing()
    baseline = scan_jaxpr()
    live.metrics_reset()
    rng = np.random.RandomState(0)
    X = rng.randn(4096, 6).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    with config.set(stream_block_rows=512):
        SGDClassifier(max_iter=2, random_state=0).fit(X, y)
    # the fit registered nothing with the live plane...
    from dask_ml_tpu.observability import _spans

    assert live.gauges_snapshot() == {}
    assert live.histograms_snapshot() == {}
    assert _span_observers == [] and _spans._armed_trackers == 0
    assert live.telemetry_server() is None
    # ...and a server's life cycle leaves the traced program unchanged
    # (the plane lives entirely outside jit)
    with obs.TelemetryServer(port=0):
        assert scan_jaxpr() == baseline
    assert scan_jaxpr() == baseline
    live.metrics_reset()


def test_drift_plane_adds_nothing_when_disabled():
    """ISSUE 7 extension of the zero-overhead contract: with
    ``obs_drift`` off, a streamed SGD fit allocates NO sketch, attaches
    no profile, arms no monitor thread, registers nothing with the
    drift engine — and the streamed scan kernel's jaxpr is
    byte-identical (trivially guaranteed: the quality plane is host
    numpy that never imports jax, but the assertion pins it)."""
    import jax
    import jax.numpy as jnp

    from dask_ml_tpu.models.sgd import SGDClassifier, _sgd_sb_scan
    from dask_ml_tpu.observability import drift
    from dask_ml_tpu.observability._programs import unwrap

    def scan_jaxpr():
        body = unwrap(_sgd_sb_scan)
        K, S, d = 2, 8, 3
        return str(jax.make_jaxpr(
            lambda W, Xs, ys, c, lrs: body(
                W, Xs, ys, c, lrs, 1e-4, 1.0, 0.0, 1.0, "hinge", None
            )
        )(jnp.zeros(d + 1), jnp.zeros((K, S, d)), jnp.zeros((K, S)),
          jnp.zeros(K, jnp.int32), jnp.zeros(K)))

    drift.reset()
    baseline = scan_jaxpr()
    rng = np.random.RandomState(0)
    X = rng.randn(4096, 6).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    with config.set(stream_block_rows=512, obs_drift=False):
        est = SGDClassifier(max_iter=2, random_state=0).fit(X, y)
        assert est.training_profile_ is None
        assert scan_jaxpr() == baseline
    assert not drift.monitor_active()
    assert drift.status_block() == {
        "scores": [], "canaries": [], "serving_sketches": [],
        "training_profiles": [],
    }
    # with the default (on), the profile is host-side only: the traced
    # program STILL cannot change — sketch.py/drift.py never import jax
    with config.set(stream_block_rows=512):
        SGDClassifier(max_iter=1, random_state=0).fit(X, y)
        assert scan_jaxpr() == baseline
    drift.reset()


def test_trace_plane_adds_nothing_when_disabled():
    """ISSUE 16 extension of the zero-overhead contract: the request
    trace plane is pure host bookkeeping — a full traced server
    lifecycle (sample=1.0) and an untraced one (the 0 default) leave
    the serving entry point's jaxpr byte-identical, and with the plane
    off no trace is ever allocated and no sampler state moves."""
    import jax
    import jax.numpy as jnp

    from dask_ml_tpu.observability import _requests as rtrace
    from dask_ml_tpu.serving import BucketLadder, ModelServer
    from dask_ml_tpu.wrappers import _linear_core

    def serve_jaxpr():
        core = _linear_core("classify", multi=False)
        p = {"W": jnp.zeros((1, 6)), "b": jnp.zeros(1)}
        return str(jax.make_jaxpr(core)(p, jnp.zeros((8, 6))))

    from dask_ml_tpu.datasets import make_classification
    from dask_ml_tpu.linear_model import LogisticRegression

    X, y = make_classification(
        n_samples=300, n_features=6, n_informative=4, random_state=0
    )
    clf = LogisticRegression(solver="lbfgs", max_iter=20).fit(X, y)
    Xh = X.to_numpy().astype(np.float32)

    rtrace.traces_reset()
    assert not rtrace.tracing_enabled()
    baseline = serve_jaxpr()
    ladder = BucketLadder(8, 64, 2.0)
    # traced lifecycle: the plane records on the host, the program
    # can't see it
    with config.set(obs_trace_sample=1.0):
        assert rtrace.tracing_enabled()
        with ModelServer(clf, ladder=ladder) as srv:
            srv.warmup()
            srv.submit(Xh[:4]).result(10)
            assert serve_jaxpr() == baseline
    assert rtrace.traces_data()["counts"]["completed"] == 1
    rtrace.traces_reset()
    # untraced lifecycle: nothing allocated, nothing counted, same
    # program
    with ModelServer(clf, ladder=ladder) as srv:
        assert srv._trace_on is False
        srv.warmup()
        f = srv.submit(Xh[:4])
        # the queue entry never grew a trace
        f.result(10)
        assert serve_jaxpr() == baseline
    d = rtrace.traces_data()
    assert d["counts"] == {"started": 0, "completed": 0, "sampled": 0,
                           "captured": 0}
    assert d["traces"] == [] and d["stage_histograms"] == {}
    assert serve_jaxpr() == baseline


def test_fleet_plane_adds_nothing_when_disabled():
    """ISSUE 19 extension of the zero-overhead contract: the fleet
    observability plane (trace propagation + metrics federation) is
    host-side bookkeeping riding threads the federation already owns —
    a federated lifecycle with federation ON leaves the serving entry
    point's jaxpr byte-identical and compiles nothing new, and the
    default (federation OFF) builds no federator, registers no
    provider, and spawns no extra thread."""
    import threading

    import jax
    import jax.numpy as jnp

    from dask_ml_tpu.observability import live
    from dask_ml_tpu.serving import (
        BucketLadder,
        FederatedFleet,
        FleetServer,
        LocalEndpoint,
    )
    from dask_ml_tpu.wrappers import _linear_core

    def serve_jaxpr():
        core = _linear_core("classify", multi=False)
        p = {"W": jnp.zeros((1, 6)), "b": jnp.zeros(1)}
        return str(jax.make_jaxpr(core)(p, jnp.zeros((8, 6))))

    from dask_ml_tpu.datasets import make_classification
    from dask_ml_tpu.linear_model import LogisticRegression

    X, y = make_classification(
        n_samples=300, n_features=6, n_informative=4, random_state=0
    )
    clf = LogisticRegression(solver="lbfgs", max_iter=20).fit(X, y)
    Xh = X.to_numpy().astype(np.float32)

    baseline = serve_jaxpr()
    ladder = BucketLadder(8, 64, 2.0)
    fleet = FleetServer(clf, name="zf", replicas=1, ladder=ladder,
                        batch_window_ms=1.0).warmup().start()
    try:
        before = obs.counters_snapshot().get("recompiles", 0)
        # federation + propagation ON: everything stays on the host
        with config.set(obs_fleet_federate=True, obs_trace_sample=1.0):
            with FederatedFleet([LocalEndpoint(fleet, "p0")],
                                name="zf", ladder=ladder) as fed:
                assert fed._federator is not None
                fed._poll_once()
                fed.predict(Xh[:8])
                assert serve_jaxpr() == baseline
        assert obs.counters_snapshot().get("recompiles", 0) == before
        # the default: no federator object, no provider registration,
        # no fleet_ families on /metrics, and no thread beyond the
        # poller + submit pool the federation owns anyway
        names_before = {t.name for t in threading.enumerate()}
        with FederatedFleet([LocalEndpoint(fleet, "p0")],
                            name="zf", ladder=ladder) as fed:
            assert fed._federator is None
            assert not live._fleet_providers
            assert "dask_ml_tpu_fleet_" not in live.render_prometheus()
            new = {t.name for t in threading.enumerate()} - names_before
            assert all(n.startswith(("fed-poller", "fed-submit"))
                       for n in new), new
        assert serve_jaxpr() == baseline
    finally:
        fleet.stop(drain=False)
        from dask_ml_tpu.observability import _requests as rtrace

        rtrace.traces_reset()


def test_jit_callbacks_probe_resettable(monkeypatch):
    from dask_ml_tpu.observability import _metrics

    obs.reset_jit_callbacks_probe()
    assert _metrics._callbacks_supported is None
    first = obs.jit_callbacks_supported()
    assert isinstance(first, bool)
    assert _metrics._callbacks_supported == first
    # a poisoned cache must be clearable (backend swaps in tests)
    monkeypatch.setattr(_metrics, "_callbacks_supported", not first)
    assert obs.jit_callbacks_supported() is (not first)
    obs.reset_jit_callbacks_probe()
    assert obs.jit_callbacks_supported() == first


# -- back-compat shim -------------------------------------------------------

def test_utils_observability_reexports_same_objects():
    from dask_ml_tpu.observability import _metrics
    from dask_ml_tpu.utils import observability as legacy

    assert legacy.MetricsLogger is obs.MetricsLogger
    assert legacy.active_logger is obs.active_logger
    assert legacy.emit_jit_step is obs.emit_jit_step
    assert legacy.fit_logger is obs.fit_logger
    assert legacy.timed is obs.timed
    # the mutable sink registry must be the SAME list object — bench.py
    # and streaming.py bind through different import paths
    assert legacy._active_loggers is _metrics._active_loggers


# -- report CLI -------------------------------------------------------------

@pytest.fixture
def canned_run(tmp_path):
    """A canned JSONL run: two fit spans, stream passes, step records,
    and a final counters snapshot."""
    p = str(tmp_path / "run.jsonl")
    recs = [
        {"time": 0.1, "span": "fit", "span_id": 1, "parent_id": None,
         "depth": 0, "wall_s": 2.0, "sync_s": 0.5,
         "component": "KMeans", "n_rows": 10000, "n_iter": 7},
        {"time": 0.2, "span": "stream.pass", "span_id": 3, "parent_id": 2,
         "depth": 1, "wall_s": 0.5, "sync_s": 0.0},
        {"time": 0.3, "span": "fit", "span_id": 2, "parent_id": None,
         "depth": 0, "wall_s": 1.0, "sync_s": 0.1,
         "component": "LogisticRegression", "n_rows": 5000},
        {"time": 0.4, "component": "KMeans", "step": 0,
         "center_shift2": 9.0},
        {"time": 0.5, "component": "KMeans", "step": 1,
         "center_shift2": 0.25},
        {"time": 0.6, "component": "LogisticRegression", "step": 0,
         "loss": 0.693, "grad_norm": 1.0},
        {"time": 0.7, "component": "LogisticRegression", "step": 1,
         "loss": 0.21, "grad_norm": 0.05},
        {"time": 0.8, "stream_pass": 1, "host_s": 0.2, "put_s": 0.1,
         "wait_s": 0.01, "consume_s": 0.4, "pass_s": 0.71, "n_blocks": 8,
         "block_rows": 1250},
        {"time": 0.9, "counters": True, "recompiles": 12,
         "h2d_bytes": 40960000, "h2d_transfers": 8},
    ]
    with open(p, "w") as fh:
        fh.write("\n".join(json.dumps(r) for r in recs) + "\n")
        fh.write("{corrupt trailing line")  # must be skipped, not fatal
    return p


def test_report_build(canned_run):
    from dask_ml_tpu.observability.report import build_report, load_records

    records = load_records(canned_run)
    assert len(records) == 9  # corrupt line skipped
    out = build_report(records, path=canned_run)
    assert "KMeans.fit" in out
    assert "LogisticRegression.fit" in out
    assert "5,000" in out  # 5000 rows / 1.0s
    assert "center_shift2: 9 -> 0.25" in out
    assert "loss: 0.693 -> 0.21" in out
    assert "recompiles" in out and "12" in out
    assert "39.1MiB" in out  # h2d_bytes rendered human-readable
    assert "streaming overlap" in out


def test_report_cli_main(canned_run, capsys):
    from dask_ml_tpu.observability import report

    rc = report.main([canned_run])
    assert rc == 0
    out = capsys.readouterr().out
    assert "KMeans.fit" in out and "recompiles" in out


def test_report_cli_missing_file(tmp_path, capsys):
    from dask_ml_tpu.observability import report

    rc = report.main([str(tmp_path / "nope.jsonl")])
    assert rc == 1
    assert "cannot read" in capsys.readouterr().err


def test_report_cli_runs_as_module(canned_run):
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, "-m", "dask_ml_tpu.observability.report",
         canned_run],
        capture_output=True, text=True, cwd=repo,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    assert "KMeans.fit" in proc.stdout


# -- end-to-end: spans from a real fit --------------------------------------

def test_fit_emits_span_with_samples_per_sec(tmp_path):
    from dask_ml_tpu.linear_model import LogisticRegression
    from dask_ml_tpu.parallel import as_sharded

    rng = np.random.RandomState(0)
    X = rng.randn(300, 5).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    p = str(tmp_path / "fit.jsonl")
    with config.set(metrics_path=p):
        LogisticRegression(solver="lbfgs", max_iter=10).fit(
            as_sharded(X), as_sharded(y)
        )
    spans = [r for r in _read_jsonl(p) if r.get("span") == "fit"]
    assert len(spans) == 1
    rec = spans[0]
    assert rec["component"] == "LogisticRegression"
    assert rec["n_rows"] == 300 and rec["wall_s"] > 0
    assert rec["n_iter"] >= 1


def test_streamed_fit_nests_pass_spans_under_fit(tmp_path):
    from dask_ml_tpu.linear_model import LinearRegression

    rng = np.random.RandomState(1)
    X = rng.randn(600, 4).astype(np.float32)
    y = (X @ rng.randn(4)).astype(np.float32)
    p = str(tmp_path / "stream.jsonl")
    with config.set(metrics_path=p, stream_block_rows=150):
        LinearRegression(solver="gradient_descent", max_iter=3).fit(X, y)
    recs = _read_jsonl(p)
    fits = [r for r in recs if r.get("span") == "fit"]
    # per-block passes trace stream.pass; super-block passes (the
    # default when K > 1) trace streaming.superblock — both are
    # stream_pass-keyed pass records nested under the fit
    passes = [r for r in recs
              if r.get("span") in ("stream.pass", "streaming.superblock")]
    assert len(fits) == 1 and fits[0]["streamed"] is True
    assert passes, "streamed fit must trace stream pass spans"
    assert all(r["parent_id"] == fits[0]["span_id"] for r in passes)
    assert all("stream_pass" in r for r in passes)


def test_search_round_spans_and_trial_tags(tmp_path):
    from dask_ml_tpu.model_selection import HyperbandSearchCV
    from dask_ml_tpu.models.sgd import SGDClassifier

    rng = np.random.RandomState(3)
    X = rng.randn(300, 5).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    p = str(tmp_path / "hb.jsonl")
    with config.set(metrics_path=p):
        HyperbandSearchCV(
            SGDClassifier(random_state=0),
            {"alpha": [1e-4, 1e-3, 1e-2]},
            max_iter=4, random_state=0,
        ).fit(X, y, classes=[0.0, 1.0])
    recs = _read_jsonl(p)
    rounds = [r for r in recs if r.get("span") == "search.round"]
    assert rounds and all("n_trials" in r for r in rounds)
    trials = [r for r in recs
              if r.get("component") == "adaptive_search"
              and "model_id" in r]
    assert trials
    for r in trials:
        assert "bracket" in r and "partial_fit_calls" in r and "score" in r
