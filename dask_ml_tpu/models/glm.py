"""Generalized linear models: LinearRegression, LogisticRegression,
PoissonRegression.

Reference equivalent: ``dask_ml/linear_model/glm.py`` (SURVEY.md §2a GLMs
row; §3.2 call stack) — sklearn-style wrappers dispatching to dask-glm
solvers, with ``fit_intercept`` via an appended ones column and predict as
blocked matvec. Same surface here; the solvers are the device-resident jax
programs in ``solvers/solvers.py``.

Regularization scaling: the objective is ``mean-NLL + lam * r(coef)`` with
``lam = 1 / (C * n_samples)`` and the intercept unpenalized, matching
sklearn's objective so the §4 parity contract holds. (dask-glm used
``lamduh = 1/C`` against a sum-NLL and penalized the intercept — a known
non-parity we deliberately fix.)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..base import BaseEstimator, to_host
from ..observability import span
from ..parallel.mesh import resolve_mesh
from ..parallel.sharded import ShardedArray
from ..utils.validation import check_X_y, check_array, check_is_fitted
from .solvers import regularizers
from .solvers.solvers import solve


def _check_poisson_targets(ymin):
    """Shared non-negativity gate for BOTH Poisson fit paths (device-
    resident and streamed) — one rule, one message."""
    if ymin < 0:
        raise ValueError(
            "PoissonRegression requires non-negative targets; "
            f"got min(y) = {ymin}"
        )


def add_intercept(X):
    """Append a ones column (ref: dask_ml/linear_model/utils.py::add_intercept).

    Accepts a ShardedArray (ones are zeroed on padding rows so reductions
    stay exact) or any 2-D array.
    """
    if isinstance(X, ShardedArray):
        ones = X.row_mask(dtype=X.data.dtype)[:, None]
        return ShardedArray(
            jnp.concatenate([X.data, ones], axis=1), X.n_rows, X.mesh
        )
    arr = np.asarray(X)
    return np.concatenate([arr, np.ones((arr.shape[0], 1), arr.dtype)], axis=1)


from functools import partial as _partial


@jax.jit
def _matvec_eta(data, coef, intercept):
    """Decision values as ONE program: eager ``X @ w + b`` would pay a
    dispatch round trip per op on a tunneled runtime."""
    return data @ coef.astype(data.dtype) + intercept.astype(data.dtype)


@jax.jit
def _matvec_eta_multi(data, coef, intercept):
    """(n, C) decision values against stacked OvR coefficients (C, d)."""
    return data @ coef.T.astype(data.dtype) + intercept.astype(data.dtype)


@jax.jit
def _onehot_targets(yd, mask, classes_d):
    """(C, n) one-vs-rest targets in one program (module-level jit: a
    per-fit lambda would retrace+recompile every fit). The encoding
    invariant itself lives in solvers/streamed.py::onehot_targets,
    shared with the streamed block kernels."""
    from .solvers.streamed import onehot_targets

    return onehot_targets(yd, mask, classes_d)


@_partial(jax.jit, static_argnames=("fit_intercept", "to_bf16", "encode"))
def _prepare_fit(Xd, yd, mask, fit_intercept, to_bf16, encode):
    """ONE program for all fit prep: intercept column, bf16 cast, binary
    label scan + encoding. Launch count matters: on tunneled runtimes
    every eager op / separate jit call pays a full dispatch round trip,
    and the old prep chain (concat, cast, scan, eq, mul) cost more wall
    clock than the entire 50-iteration solve."""
    if fit_intercept:
        Xd = jnp.concatenate([Xd, mask[:, None].astype(Xd.dtype)], axis=1)
    if to_bf16:
        Xd = Xd.astype(jnp.bfloat16)
    if encode:
        valid = mask > 0
        big = jnp.asarray(jnp.inf, yd.dtype)
        mn = jnp.min(jnp.where(valid, yd, big))
        mx = jnp.max(jnp.where(valid, yd, -big))
        binary = jnp.all(~valid | (yd == mn) | (yd == mx))
        y_enc = (yd == mx).astype(jnp.float32) * mask
        packed = jnp.stack([mn, mx, binary.astype(yd.dtype)])
    else:
        y_enc = yd
        packed = jnp.zeros((3,), yd.dtype)
    return Xd, y_enc, packed


class _GLMBase(BaseEstimator):
    family: str = None  # overridden per subclass

    def __init__(self, penalty="l2", dual=False, tol=1e-4, C=1.0,
                 fit_intercept=True, intercept_scaling=1.0, class_weight=None,
                 random_state=None, solver="admm", max_iter=100,
                 multi_class="ovr", verbose=0, warm_start=False, n_jobs=1,
                 solver_kwargs=None, fit_dtype=None):
        self.penalty = penalty
        self.dual = dual
        self.tol = tol
        self.C = C
        self.fit_intercept = fit_intercept
        self.intercept_scaling = intercept_scaling
        self.class_weight = class_weight
        self.random_state = random_state
        self.solver = solver
        self.max_iter = max_iter
        self.multi_class = multi_class
        self.verbose = verbose
        self.warm_start = warm_start
        self.n_jobs = n_jobs
        self.solver_kwargs = solver_kwargs
        # per-estimator precision override: None follows config.dtype
        # ("auto" = bf16 on TPU for the smooth solvers, f32 elsewhere);
        # "float32" opts out, "bfloat16" forces on. Resolved choice is
        # recorded as fit_dtype_ and in solver_info_ for streamed fits.
        self.fit_dtype = fit_dtype

    # -- internals --------------------------------------------------------
    def _encode_y_host(self, y):
        return np.asarray(y, np.float32), None

    # hooks a family must provide when its _encode_y_host returns >2
    # classes (today: logistic only) — base fits must fail with a clear
    # contract, not an AttributeError deep in _fit_streamed
    def _warm_B0(self, C, d):
        raise NotImplementedError(
            f"{type(self).__name__} does not support multiclass targets"
        )

    def _finish_fit_multi(self, beta, classes, info, n_features):
        raise NotImplementedError(
            f"{type(self).__name__} does not support multiclass targets"
        )

    def _fit_C_grid_multiclass(self, X, y, data, mask, Cs):
        """Multiclass arm of the C-grid fast path; only the logistic
        family overrides it (other families have no multiclass fit)."""
        return None

    def _run_C_grid(self, X, Cs, d, solve_fn, finish, **log_fields):
        """Shared tail of BOTH C-grid arms: per-C (pmask, lam) through
        _penalty_setup (the ONE place the regularization bookkeeping
        lives), one logged stacked solve, then fitted clones in ``Cs``
        order. ``solve_fn(lams, pmask) -> (B, info)``;
        ``finish(est, B_i, info)`` publishes one candidate's result."""
        from ..base import clone
        from ..observability import fit_logger

        per_c = [clone(self).set_params(C=c)._penalty_setup(d, X.n_rows)
                 for c in Cs]
        pmask = per_c[0][0]
        lams = [lam for _, lam in per_c]
        with span("fit", component=type(self).__name__, solver=self.solver,
                  n_rows=X.n_rows, lam_grid=len(Cs)) as sp, \
                fit_logger(type(self).__name__, solver=self.solver,
                           n_rows=X.n_rows, lam_grid=len(Cs),
                           **log_fields) as logger:
            B, info = solve_fn(lams, pmask)
            sp.add(n_iter=info.get("n_iter"))
            if logger is not None:
                logger.log(step=info.get("n_iter"), summary=True,
                           **{k: v for k, v in info.items()
                              if isinstance(v, (int, float))})
        B = np.asarray(B, np.float64)
        per_cand = info.get("n_iter_per_candidate")
        # the C-grid design was prepared under the same rule as the
        # plain lbfgs fit (to_bf16 = resolved mxu dtype; the fast path
        # is lbfgs-only) — every fitted clone records the precision it
        # actually trained at
        from ..config import mxu_dtype as _mxu

        dt_label = "bfloat16" if _mxu(self.fit_dtype) is not None \
            else "float32"
        fitted = []
        for i, c in enumerate(Cs):
            est = clone(self).set_params(C=c)
            est.fit_dtype_ = dt_label
            # the stacked solve shares one iteration budget; publish
            # each clone's OWN convergence point (last iteration its
            # per-block gradient norm exceeded tol) as its n_iter_ —
            # the joint budget stays readable as
            # max(solver_info_["n_iter_per_candidate"])
            info_i = dict(info)
            if per_cand is not None:
                info_i["n_iter"] = int(per_cand[i])
            # a sparse fold the fast path densified under the byte
            # budget is on record, not silent (ISSUE 14 satellite):
            # every clone's solver_info_ names the fallback so reports
            # can tell a direct dense solve from the streamed path
            reason = getattr(self, "_c_grid_sparse_reason", None)
            if reason is not None:
                info_i.setdefault("sparse_stream", False)
                info_i.setdefault("sparse_stream_reason", reason)
            finish(est, B[i], info_i)
            fitted.append(est)
        return fitted

    def _dense_search_solve(self, X):
        """One-shot densify of a sparse fold for the stacked C-grid/OvR
        direct solve, behind the SAME byte budget that guards
        ``to_sharded_dense`` — an over-budget corpus raises the typed
        :class:`DenseBudgetExceeded` (the fast path bails and the
        search keeps streamed per-candidate fits) instead of silently
        allocating the dense matrix."""
        from ..config import get_config
        from ..feature_extraction.text import DenseBudgetExceeded

        n, d = int(X.shape[0]), int(X.shape[1])
        nbytes = 4 * n * d
        budget = int(get_config().to_dense_byte_budget)
        if budget > 0 and nbytes > budget:
            raise DenseBudgetExceeded(
                f"the stacked C-grid/OvR search solve would densify a "
                f"{n} x {d} sparse fold ({nbytes >> 20} MiB > "
                f"config.to_dense_byte_budget {budget >> 20} MiB); "
                "falling back to streamed per-candidate fits"
            )
        # _csr_dense casts the nnz VALUES to f32 before toarray(), so
        # the transient is the one budgeted dense block — a f64 source
        # densified first would peak at ~3x the budget this guard
        # enforces
        from ..parallel.streaming import _csr_dense

        return _csr_dense(X.tocsr(), 0, n, np.float32)

    def _check_unsupported(self):
        """Honest-raise for accepted-but-unimplemented params (same
        policy as SpectralClustering's): silently ignoring
        class_weight="balanced" would return unweighted fits that LOOK
        like weighted ones. The reference wrapper ignores it silently —
        a non-parity we fix on purpose."""
        if self.class_weight is not None:
            raise ValueError(
                "class_weight is not supported; reweight via "
                "sample-level resampling, or leave class_weight=None"
            )

    def _penalty_setup(self, d, n_rows):
        """(pmask, lam): intercept unpenalized, sklearn's 1/(C*n) scaling
        — the ONE place the regularization bookkeeping lives (shared by
        the resident, streamed, and multiclass fit paths)."""
        pmask = np.ones(d, np.float32)
        if self.fit_intercept:
            pmask[-1] = 0.0
        lam = 1.0 / (self.C * n_rows) if self.penalty != "none" else 0.0
        return pmask, lam

    def _warm_beta0(self, d, xp):
        """Shape-guarded warm start: a stale coef_ from a DIFFERENT
        problem shape (e.g. a prior multiclass fit) must not leak into
        this solve — silently starting from a malformed vector crashes
        deep in the jitted loss."""
        if self.warm_start and getattr(self, "coef_", None) is not None:
            single = np.ndim(self.coef_) == 1 or np.shape(self.coef_)[0] == 1
            flat = self._coef_flat()
            if single and flat.shape[0] == d - int(self.fit_intercept):
                b = (np.r_[flat, np.ravel(self.intercept_)[:1]]
                     if self.fit_intercept else flat)
                return xp.asarray(b, dtype=np.float32)
        return xp.zeros(d, np.float32)

    def _finish_fit(self, beta, classes, info, n_features):
        beta = np.asarray(beta, np.float64)
        if self.fit_intercept:
            self.intercept_ = beta[-1]
            coef = beta[:-1]
        else:
            self.intercept_ = 0.0
            coef = beta
        self._set_coef(coef, classes)
        self.n_iter_ = info.get("n_iter")
        self.solver_info_ = info
        if "fit_dtype" in info:  # streamed fits resolve it in the solver
            self.fit_dtype_ = info["fit_dtype"]
        self.n_features_in_ = n_features
        return self

    def _fit_streamed(self, X, y, block_rows):
        """Out-of-core fit: X stays host-resident (np.memmap or large
        ndarray); blocks stream through prefetched device_put into
        per-block loss/grad/Hessian kernels (solvers/streamed.py). The
        reference's analog is dask-glm over host-backed chunks
        (SURVEY.md §3.2); here the optimizer state is the only host-side
        math. y is encoded to a host float32 vector (1/d the size of X).

        Under a live multi-process runtime (``jax.distributed``), X/y are
        the PROCESS-LOCAL shard (per-host memmaps, SURVEY §1 L2 dd
        partitions): per-pass block sums psum across processes, n_rows
        and the class set are global, and every process converges to the
        identical global fit."""
        if self.penalty not in regularizers.KNOWN:
            raise ValueError(f"Unknown penalty {self.penalty!r}")
        from ..parallel import distributed as dist
        from ..parallel.streaming import BlockStream
        from ..observability import fit_logger
        from .solvers.streamed import solve_streamed

        multi_host = dist.process_count() > 1
        reduce = dist.psum_host if multi_host else None
        y_host, classes = self._encode_y_host(y)
        n, d_feat = X.shape[0], X.shape[1]
        if multi_host:
            n = int(dist.psum_host(np.asarray(float(n))))
        d = d_feat + (1 if self.fit_intercept else 0)
        pmask, lam = self._penalty_setup(d, n)
        stream = BlockStream((X, y_host), block_rows=block_rows)
        kwargs = dict(self.solver_kwargs or {})
        l1_ratio = kwargs.pop("l1_ratio", 0.5)
        # pass-granular checkpoint/auto-resume (ISSUE 11): the solver
        # saves its host state each outer iteration under a fingerprint
        # token and clears on completion; None (knobs off, multi-host,
        # warm start) leaves the fit exactly as before
        ckpt = None
        if not (multi_host or getattr(self, "warm_start", False)):
            from ..reliability.stream_ckpt import stream_checkpoint

            ckpt = stream_checkpoint(
                "glm",
                (type(self).__name__, self.solver, self.penalty,
                 getattr(self, "C", None), float(np.asarray(lam)),
                 l1_ratio, self.fit_intercept, self.max_iter, self.tol,
                 self.family, repr(sorted(kwargs.items())), n, d,
                 int(stream.block_rows),
                 None if classes is None
                 else tuple(np.asarray(classes).tolist())),
                arrays=(X, y_host),
            )
        if classes is not None and len(classes) > 2:
            # one-vs-rest out-of-core: y_host carries class CODES; every
            # epoch streams X once for all C classes
            from .solvers.streamed import solve_streamed_multi

            C = len(classes)
            B0 = self._warm_B0(C, d)
            with span("fit", component=type(self).__name__,
                      solver=self.solver, streamed=True, n_rows=n,
                      n_classes=C) as sp, \
                    fit_logger(type(self).__name__, solver=self.solver,
                               streamed=True, n_rows=n,
                               n_classes=C) as logger:
                Beta, info = solve_streamed_multi(
                    self.solver, stream, n, B0, self.family, self.penalty,
                    lam, pmask, l1_ratio=l1_ratio,
                    intercept=self.fit_intercept, max_iter=self.max_iter,
                    tol=self.tol, logger=logger, reduce=reduce,
                    fit_dtype=self.fit_dtype, ckpt=ckpt, **kwargs,
                )
                sp.add(n_iter=info.get("n_iter"),
                       data_passes=info.get("data_passes"))
            self.training_profile_ = stream.profile_snapshot()
            return self._finish_fit_multi(Beta, classes, info, d_feat)
        beta0 = self._warm_beta0(d, np)
        with span("fit", component=type(self).__name__, solver=self.solver,
                  streamed=True, n_rows=n) as sp, \
                fit_logger(type(self).__name__, solver=self.solver,
                           streamed=True, n_rows=n) as logger:
            beta, info = solve_streamed(
                self.solver, stream, n, beta0, self.family, self.penalty,
                lam, pmask, l1_ratio=l1_ratio, intercept=self.fit_intercept,
                max_iter=self.max_iter, tol=self.tol, logger=logger,
                reduce=reduce, fit_dtype=self.fit_dtype, ckpt=ckpt,
                **kwargs,
            )
            sp.add(n_iter=info.get("n_iter"),
                   data_passes=info.get("data_passes"))
        # per-feature training profile for train-vs-serve drift scoring
        self.training_profile_ = stream.profile_snapshot()
        return self._finish_fit(beta, classes, info, d_feat)

    def _fit_C_grid(self, X, y, Cs):
        """Fit ``len(Cs)`` clones differing only in ``C`` as ONE
        stacked-lam L-BFGS program over a shared design matrix
        (GridSearchCV's homogeneous-trial fast path; SURVEY.md §3.4).
        Returns the fitted clones in ``Cs`` order, or None when this fit
        shape isn't eligible (caller falls back to per-candidate
        fits)."""
        from ..parallel.streaming import stream_plan

        # class_weight != None is an ELIGIBILITY bail, not a raise: the
        # caller's general path re-runs est.fit(), which raises the
        # clean unsupported-param error instead of a fast-path warning
        if (self.solver != "lbfgs" or self.penalty not in ("l2", "none")
                or self.solver_kwargs or self.warm_start
                or self.class_weight is not None):
            return None
        from ..parallel.streaming import _is_sparse_source

        self._c_grid_sparse_reason = None
        if _is_sparse_source(X):
            # stacked direct solves need the dense design ONCE; the
            # densify rides the to_sharded_dense byte budget — typed
            # refusal (fast path bails, streamed per-candidate fits
            # carry the search) instead of a silent n x d allocation,
            # and a within-budget densify is recorded in every clone's
            # solver_info_ as sparse_stream_reason="search-dense-solve"
            from ..feature_extraction.text import DenseBudgetExceeded

            try:
                X = self._dense_search_solve(X)
            except DenseBudgetExceeded:
                return None
            self._c_grid_sparse_reason = "search-dense-solve"
        elif stream_plan(X) is not None:
            return None
        mesh = resolve_mesh(getattr(X, "mesh", None))
        X, y = check_X_y(X, y, mesh=mesh, dtype=np.float32)
        from ..config import mxu_dtype

        mask = X.row_mask(dtype=jnp.float32)
        data, y_data, packed = _prepare_fit(
            X.data, y.data, mask, fit_intercept=self.fit_intercept,
            to_bf16=mxu_dtype(self.fit_dtype) is not None,
            encode=self.family == "logistic",
        )
        if self.family == "poisson":
            _check_poisson_targets(
                float(jnp.min(jnp.where(mask > 0, y_data, jnp.inf)))
            )
        classes = None
        if self.family == "logistic":
            pk = np.asarray(packed)
            if not bool(pk[2]) or pk[0] == pk[1]:
                # >2 classes: the grid stacks k*C one-vs-rest blocks in
                # one program (degenerate single-class keeps None — the
                # general path raises the clean error)
                return self._fit_C_grid_multiclass(X, y, data, mask, Cs)
            classes = np.asarray(pk[:2])
        d = data.shape[1]
        from .solvers.solvers import solve_lam_grid

        def finish(est, Bi, info):
            if classes is not None:
                est.classes_ = classes
            est._finish_fit(Bi, classes, dict(info),
                            d - int(self.fit_intercept))

        return self._run_C_grid(
            X, Cs, d,
            lambda lams, pmask: solve_lam_grid(
                data, y_data, mask, X.n_rows, lams, pmask, self.family,
                self.penalty, max_iter=self.max_iter, tol=self.tol,
            ),
            finish,
        )

    def fit(self, X, y):
        from ..parallel.streaming import stream_plan

        self._check_unsupported()
        block_rows = stream_plan(X)
        if block_rows is not None:
            return self._fit_streamed(X, y, block_rows)
        mesh = resolve_mesh(getattr(X, "mesh", None))
        X, y = check_X_y(X, y, mesh=mesh, dtype=np.float32)
        if self.penalty not in regularizers.KNOWN:
            raise ValueError(f"Unknown penalty {self.penalty!r}")
        # bf16 design matrix: the _smooth_loss matvec rides the MXU at
        # bf16 rate with f32 accumulation; solver state / y / mask stay
        # f32. Newton/ADMM are excluded — their Hessian matmuls would
        # silently upcast (no speedup) and bf16 Hessians risk conditioning
        from ..config import mxu_dtype

        use_bf16 = mxu_dtype(self.fit_dtype) is not None and self.solver in (
            "lbfgs", "gradient_descent", "proximal_grad"
        )
        # resolved precision on record: the auto policy's f32 fallback
        # (off-TPU, or a solver whose Hessian math excludes bf16) must
        # be visible, not silent
        self.fit_dtype_ = "bfloat16" if use_bf16 else "float32"
        mask = X.row_mask(dtype=jnp.float32)
        data, y_data, packed = _prepare_fit(
            X.data, y.data, mask, fit_intercept=self.fit_intercept,
            to_bf16=use_bf16, encode=self.family == "logistic",
        )
        if self.family == "poisson":
            _check_poisson_targets(
                float(jnp.min(jnp.where(mask > 0, y_data, jnp.inf)))
            )
        classes = None
        if self.family == "logistic":
            pk = np.asarray(packed)  # one small fetch: (mn, mx, binary)
            if not bool(pk[2]) or pk[0] == pk[1]:
                # >2 (or 1) classes: the one-vs-rest path (vmapped
                # multi-target solve; beyond the reference's binary-only
                # dask-glm logistic family)
                return self._fit_multiclass(X, y, data, mask)
            classes = np.asarray(pk[:2])
            self.classes_ = classes
        d = data.shape[1]
        pmask, lam = self._penalty_setup(d, X.n_rows)
        beta0 = jnp.asarray(self._warm_beta0(d, np))
        kwargs = dict(self.solver_kwargs or {})
        l1_ratio = kwargs.pop("l1_ratio", 0.5)
        from ..observability import (
            active_logger, fit_logger, jit_callbacks_supported,
        )

        with span("fit", component=type(self).__name__, solver=self.solver,
                  n_rows=X.n_rows) as sp, \
                fit_logger(type(self).__name__, solver=self.solver,
                           n_rows=X.n_rows) as logger, active_logger(logger):
            # per-step callbacks need backend support (axon PJRT lacks
            # host callbacks); degrade to one summary record per fit
            log_steps = logger is not None and jit_callbacks_supported()
            beta, info = solve(
                self.solver,
                X=data, y=y_data, mask=mask,
                n_rows=X.n_rows, beta0=beta0, family=self.family,
                reg=self.penalty, lam=jnp.asarray(lam, jnp.float32),
                pmask=jnp.asarray(pmask), l1_ratio=l1_ratio,
                max_iter=self.max_iter, tol=self.tol, mesh=mesh,
                log=log_steps, **kwargs,
            )
            sp.add(n_iter=info.get("n_iter"))
            if logger is not None and not log_steps:
                logger.log(step=info.get("n_iter"), summary=True,
                           **{k: v for k, v in info.items()
                              if isinstance(v, (int, float))})
        return self._finish_fit(to_host(beta), classes, info, X.shape[1])

    def _coef_flat(self):
        return np.ravel(self.coef_)

    def _intercept_scalar(self) -> np.float32:
        """intercept_ as one scalar: binary LogisticRegression stores
        shape (1,), the regressions store a plain float."""
        return np.float32(np.ravel(self.intercept_)[0]
                          if np.ndim(self.intercept_) else self.intercept_)

    def _set_coef(self, coef, classes):
        self.coef_ = coef

    def _eta_host(self, X):
        """Decision values as a host (n,) array; streams block-wise for
        out-of-core inputs instead of materializing X on device."""
        from ..parallel.streaming import stream_plan, streamed_map

        block_rows = stream_plan(X)
        if block_rows is not None:
            coef = jnp.asarray(self._coef_flat(), jnp.float32)
            b0 = jnp.asarray(self._intercept_scalar())
            return streamed_map(
                X, block_rows, lambda blk: blk.arrays[0] @ coef + b0
            )
        X, eta = self._decision(X)
        return to_host(eta)[: X.n_rows]

    def _decision(self, X):
        X = check_array(X, dtype=np.float32)
        eta = _matvec_eta(X.data, np.asarray(self._coef_flat(), np.float32),
                          self._intercept_scalar())
        return X, eta


class LinearRegression(_GLMBase):
    """Ref: dask_ml/linear_model/glm.py::LinearRegression."""

    family = "normal"

    def predict(self, X):
        check_is_fitted(self, "coef_")
        return self._eta_host(X)

    def score(self, X, y):
        from ..metrics import r2_score

        return r2_score(y, self.predict(X))


class PoissonRegression(_GLMBase):
    """Ref: dask_ml/linear_model/glm.py::PoissonRegression."""

    family = "poisson"

    def _encode_y_host(self, y):
        y = np.asarray(y, np.float32)
        if y.size:
            _check_poisson_targets(float(y.min()))
        return y, None

    def predict(self, X):
        check_is_fitted(self, "coef_")
        return np.exp(self._eta_host(X))

    def score(self, X, y):
        from ..metrics import r2_score

        return r2_score(y, self.predict(X))


class LogisticRegression(_GLMBase):
    """Ref: dask_ml/linear_model/glm.py::LogisticRegression. The
    reference (via dask-glm's logistic family) is binary-only; here >2
    classes fit one-vs-rest, with the C per-class solves stacked into a
    single XLA program for smooth solvers."""

    family = "logistic"

    def _fit_multiclass(self, X, y, data, mask):
        self._check_multi_class()
        classes = np.unique(y.to_numpy())
        if len(classes) < 2:
            raise ValueError(
                f"LogisticRegression needs at least 2 classes; got "
                f"{len(classes)}"
            )
        from ..observability import fit_logger
        from .solvers.solvers import solve_multi

        # (C, n) one-vs-rest targets in ONE program; padding rows zeroed
        Y = _onehot_targets(y.data, mask, jnp.asarray(classes, y.dtype))
        d = data.shape[1]
        pmask, lam = self._penalty_setup(d, X.n_rows)
        C = len(classes)
        B0 = jnp.asarray(self._warm_B0(C, d))
        kwargs = dict(self.solver_kwargs or {})
        l1_ratio = kwargs.pop("l1_ratio", 0.5)
        with span("fit", component=type(self).__name__, solver=self.solver,
                  n_rows=X.n_rows, n_classes=C) as sp, \
                fit_logger(type(self).__name__, solver=self.solver,
                           n_rows=X.n_rows, n_classes=C) as logger:
            beta, info = solve_multi(
                self.solver, X=data, Y=Y, mask=mask, n_rows=X.n_rows,
                B0=B0, family=self.family, reg=self.penalty,
                lam=jnp.asarray(lam, jnp.float32), pmask=jnp.asarray(pmask),
                l1_ratio=l1_ratio, max_iter=self.max_iter, tol=self.tol,
                mesh=X.mesh, **kwargs,
            )
            sp.add(n_iter=info.get("n_iter"))
            if logger is not None:
                logger.log(step=info.get("n_iter"), summary=True,
                           **{k: v for k, v in info.items()
                              if isinstance(v, (int, float))})
        return self._finish_fit_multi(to_host(beta), classes, info,
                                      X.shape[1])

    def _fit_C_grid_multiclass(self, X, y, data, mask, Cs):
        """k candidates x C one-vs-rest classes solved as ONE stacked
        program per fold (the multiclass arm of GridSearchCV's pure-C
        fast path). Returns fitted clones in ``Cs`` order, or None for
        degenerate targets (the general path raises cleanly)."""
        if self.multi_class not in ("auto", "ovr"):
            return None  # general path raises the clean error
        classes = np.unique(y.to_numpy())
        if len(classes) < 2:
            return None
        from .solvers.solvers import solve_lam_grid_multi

        Y = _onehot_targets(y.data, mask, jnp.asarray(classes, y.dtype))
        d = data.shape[1]
        return self._run_C_grid(
            X, Cs, d,
            lambda lams, pmask: solve_lam_grid_multi(
                data, Y, mask, X.n_rows, lams, pmask, self.family,
                self.penalty, max_iter=self.max_iter, tol=self.tol,
            ),
            lambda est, Bi, info: est._finish_fit_multi(
                Bi, classes, dict(info), d - int(self.fit_intercept)
            ),
            n_classes=len(classes),
        )

    def _check_multi_class(self):
        if self.multi_class not in ("auto", "ovr"):
            raise ValueError(
                f"multi_class={self.multi_class!r} is not supported; "
                "use 'ovr' (or 'auto')"
            )

    def _warm_B0(self, C, d):
        """(C, d) start: prior stacked OvR coefficients when warm_start
        and the shape matches THIS problem, else zeros."""
        if (self.warm_start and getattr(self, "coef_", None) is not None
                and np.shape(self.coef_)
                == (C, d - (1 if self.fit_intercept else 0))):
            return np.asarray(
                np.c_[self.coef_, np.ravel(self.intercept_)]
                if self.fit_intercept else self.coef_, np.float32,
            )
        return np.zeros((C, d), np.float32)

    def _finish_fit_multi(self, beta, classes, info, n_features):
        beta = np.asarray(beta, np.float64)
        if self.fit_intercept:
            self.intercept_ = beta[:, -1]
            self.coef_ = beta[:, :-1]
        else:
            self.intercept_ = np.zeros(len(classes))
            self.coef_ = beta
        self.classes_ = classes
        self.n_iter_ = info.get("n_iter")
        self.solver_info_ = info
        if "fit_dtype" in info:  # streamed fits resolve it in the solver
            self.fit_dtype_ = info["fit_dtype"]
        self.n_features_in_ = n_features
        return self

    def _is_multiclass(self):
        return getattr(self, "coef_", None) is not None \
            and np.ndim(self.coef_) == 2 and self.coef_.shape[0] > 1

    def _encode_y_host(self, y):
        from ..parallel import distributed as dist

        y = np.asarray(y)
        classes = np.unique(y)
        if dist.process_count() > 1:
            # multi-host streamed fit: the class set is the UNION over
            # every process's local shard (a shard missing a class must
            # not shift the others' codes)
            classes = np.unique(
                np.concatenate(dist.allgather_object(classes))
            )
        if len(classes) < 2:
            raise ValueError(
                f"LogisticRegression needs at least 2 classes; got "
                f"{len(classes)}"
            )
        if len(classes) > 2:
            self._check_multi_class()
            # class CODES 0..C-1 (float32, 1/d the bytes of X) — the
            # streamed block kernels rebuild one-hot targets on device
            self.classes_ = classes
            codes = np.searchsorted(classes, y).astype(np.float32)
            return codes, classes
        self.classes_ = classes
        return (y == classes[1]).astype(np.float32), classes

    def _set_coef(self, coef, classes):
        self.coef_ = coef.reshape(1, -1)
        self.intercept_ = np.atleast_1d(self.intercept_)

    def _eta_multi_host(self, X):
        """(n, C) decision values — one matmul program against the
        stacked OvR coefficient matrix; streams block-wise for
        out-of-core inputs exactly like the binary path."""
        from ..parallel.streaming import stream_plan, streamed_map

        coef = np.asarray(self.coef_, np.float32)
        b = np.asarray(self.intercept_, np.float32)
        block_rows = stream_plan(X)
        if block_rows is not None:
            coef_d = jnp.asarray(coef.T)
            b_d = jnp.asarray(b)
            return streamed_map(
                X, block_rows, lambda blk: blk.arrays[0] @ coef_d + b_d
            )
        X = check_array(X, dtype=np.float32)
        eta = _matvec_eta_multi(X.data, coef, b)
        return to_host(eta)[: X.n_rows]

    def decision_function(self, X):
        check_is_fitted(self, "coef_")
        if self._is_multiclass():
            return self._eta_multi_host(X)
        return self._eta_host(X)

    def predict_proba(self, X):
        from scipy.special import expit

        check_is_fitted(self, "coef_")
        if self._is_multiclass():
            # OvR probabilities: per-class sigmoids normalized to sum 1
            # (sklearn's OvR contract)
            p = expit(self._eta_multi_host(X))
            return p / np.maximum(p.sum(axis=1, keepdims=True), 1e-12)
        p1 = expit(self._eta_host(X))
        return np.stack([1.0 - p1, p1], axis=1)

    def predict_log_proba(self, X):
        """Log of predict_proba (sklearn API; the reference's glm lacks
        it but sklearn users expect it on a classifier)."""
        from ..base import log_proba

        return log_proba(self.predict_proba(X))

    def predict(self, X):
        if self._is_multiclass():
            eta = self._eta_multi_host(X)
            return self.classes_[np.argmax(eta, axis=1)]
        proba = self.predict_proba(X)
        return self.classes_[(proba[:, 1] > 0.5).astype(int)]

    def score(self, X, y):
        from ..metrics import accuracy_score

        return accuracy_score(y, self.predict(X))
