"""ISSUE 18: 2-D ("data", "model") hybrid meshes.

Mesh-resolution edge cases (``config.mesh_shape`` parsing, Dx1/1xM
degenerate shapes, non-power-of-two pools, explicit-mesh override,
cached-Mesh identity), feature-sharded GLM pass-level parity vs the
1-D programs, the typed per-device byte-budget refusal the 2-D mesh
lifts, and the streamed randomized SVD (PCA / TruncatedSVD) parity
across mesh shapes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dask_ml_tpu import config
from dask_ml_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    data_shards,
    default_mesh,
    device_mesh,
    mesh_str,
    model_shards,
    parse_mesh_shape,
    stream_data_mesh,
)
from dask_ml_tpu.parallel.streaming import BlockStream, StreamBudgetExceeded

MESHES_2D = ("1x2", "2x2", "2x4")


class TestMeshResolution:
    def test_auto_forms_return_none(self):
        for s in ("auto", "", "1d", None, "AUTO"):
            assert parse_mesh_shape(s, 8) is None

    def test_bare_and_dxm_forms(self):
        assert parse_mesh_shape("8", 8) == (8, 1)
        assert parse_mesh_shape("4", 8) == (4, 1)
        assert parse_mesh_shape("2x4", 8) == (2, 4)
        assert parse_mesh_shape("1x4", 8) == (1, 4)
        # D*M may undershoot the pool (first D*M devices are used)
        assert parse_mesh_shape("2x2", 8) == (2, 2)

    def test_inferred_axis(self):
        assert parse_mesh_shape("-1x2", 8) == (4, 2)
        assert parse_mesh_shape("4x-1", 8) == (4, 2)
        assert parse_mesh_shape("-1x2", 6) == (3, 2)

    @pytest.mark.parametrize("bad", [
        "5x3",      # needs 15 devices, have 8
        "0x2",      # axes must be >= 1
        "-1x-1",    # only one axis may be inferred
        "-1x3",     # 8 % 3 != 0: data axis not inferable
        "axb",      # not integers
        "2x3x4",    # too many axes
    ])
    def test_rejects_bad_shapes(self, bad):
        with pytest.raises(ValueError):
            parse_mesh_shape(bad, 8)

    def test_dx1_collapses_to_cached_default_mesh(self):
        """A trivial model axis must resolve to the SAME cached 1-D
        Mesh object as "auto" — the lru'd scan programs key on the mesh,
        so identity here IS jaxpr byte-identity of the 1-D programs."""
        with config.set(stream_mesh=0, mesh_shape="8x1"):
            m81 = stream_data_mesh()
        with config.set(stream_mesh=0, mesh_shape="auto"):
            m1d = stream_data_mesh()
        assert m81 is m1d
        assert m81 is default_mesh()
        assert mesh_str(m81) == "8x1"
        assert model_shards(m81) == 1

    def test_m1_reducer_identity(self):
        """mesh_shape="8x1" and "auto" must hand the GLM reducer cache
        the same key — the same compiled program object comes back, so
        the 1-D jaxprs are byte-identical by construction."""
        from dask_ml_tpu.models.solvers.streamed import _sb_reducer

        with config.set(stream_mesh=0, mesh_shape="8x1"):
            m81 = stream_data_mesh()
        with config.set(stream_mesh=0, mesh_shape="auto"):
            m1d = stream_data_mesh()
        r81 = _sb_reducer("vg", "logistic", True, 0, mesh=m81)
        r1d = _sb_reducer("vg", "logistic", True, 0, mesh=m1d)
        assert r81 is r1d
        assert r81.program_name == "superblock.glm.vg.psum"

    def test_1xm_degenerate_shape(self):
        with config.set(stream_mesh=0, mesh_shape="1x4"):
            m = stream_data_mesh()
        assert data_shards(m) == 1
        assert model_shards(m) == 4
        assert mesh_str(m) == "1x4"

    def test_non_power_of_two_pool(self):
        """stream_mesh=6 restricts the pool to 6 devices; "3x2" (and
        the inferred "-1x2") shape it as a 3x2 hybrid mesh."""
        for shape in ("3x2", "-1x2"):
            with config.set(stream_mesh=6, mesh_shape=shape):
                m = stream_data_mesh()
            assert data_shards(m) == 3
            assert model_shards(m) == 2
            assert m.devices.size == 6

    def test_cached_mesh_identity(self):
        """Every BlockStream of a fit must see the SAME Mesh object
        (the scan-program lru keys carry the mesh)."""
        with config.set(stream_mesh=0, mesh_shape="2x4"):
            a = stream_data_mesh()
            b = stream_data_mesh()
        assert a is b

    def test_explicit_mesh_override_beats_config(self):
        explicit = device_mesh((2, 2), (DATA_AXIS, MODEL_AXIS),
                               devices=jax.devices()[:4])
        X = np.zeros((64, 8), np.float32)
        with config.set(stream_mesh=0, mesh_shape="2x4"):
            s = BlockStream((X,), block_rows=16, mesh=explicit)
        assert s.mesh is explicit
        assert s.sb_data_shards() == 2
        assert s.sb_model_shards() == 2

    def test_indivisible_d_degrades_with_reason(self):
        """d=10 doesn't tile over M=4: the stream stays model-unsharded
        (replicated X over the model axis) and records why."""
        X = np.zeros((64, 10), np.float32)
        with config.set(stream_mesh=0, mesh_shape="2x4"):
            s = BlockStream((X,), block_rows=16)
        assert s.sb_model_shards() == 1
        assert "d-not-divisible" in str(s.model_tile_reason)


def _glm_objective(stream, n, d):
    from dask_ml_tpu.models.solvers.streamed import StreamedObjective

    return StreamedObjective(
        stream, n, jnp.asarray(0.1, jnp.float32), jnp.ones(d + 1),
        0.5, "logistic", "l2", True,
    )


class TestFeatureShardedGLM:
    def _xy(self, n=2300, d=8, seed=0):
        rng = np.random.RandomState(seed)
        X = rng.randn(n, d).astype(np.float32)
        y = (X @ rng.randn(d) > 0).astype(np.float32)
        return X, y

    @pytest.mark.parametrize("shape", MESHES_2D)
    def test_pass_level_parity(self, shape):
        """The feature-sharded objective passes must match the 1-D
        single-device programs at a FIXED beta to 1e-6 — same math,
        psums reassociate the sums."""
        n, d = 2300, 8
        X, y = self._xy(n, d)
        beta = np.random.RandomState(3).randn(d + 1)
        with config.set(stream_block_rows=1024, stream_mesh=1):
            o = _glm_objective(BlockStream((X, y), block_rows=1024), n, d)
            base = (*o.value_and_grad(beta),
                    *o.value_and_grad_and_hess(beta))
        with config.set(stream_block_rows=1024, stream_mesh=0,
                        mesh_shape=shape):
            s = BlockStream((X, y), block_rows=1024)
            assert s.sb_model_shards() == int(shape.split("x")[1])
            o2 = _glm_objective(s, n, d)
            got = (*o2.value_and_grad(beta),
                   *o2.value_and_grad_and_hess(beta))
        for a, b in zip(base, got):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6, rtol=1e-6)

    def test_model_psum_program_name(self):
        from dask_ml_tpu.models.solvers.streamed import _sb_reducer

        with config.set(stream_mesh=0, mesh_shape="2x4"):
            m = stream_data_mesh()
        r = _sb_reducer("vg", "logistic", True, 0, mesh=m,
                        model_shards=4)
        assert r.program_name == "superblock.glm.vg.model_psum"

    def test_fit_level_parity(self):
        """A full lbfgs solve accumulates per-pass 1e-6 parity over
        many iterations — compare the fitted coefs relatively."""
        from dask_ml_tpu.linear_model import LogisticRegression

        X, y = self._xy(4096, 8, seed=1)
        fits = {}
        for label, knobs in (
            ("1d", dict(stream_mesh=1)),
            ("2x4", dict(stream_mesh=0, mesh_shape="2x4")),
        ):
            with config.set(stream_block_rows=1024, **knobs):
                fits[label] = LogisticRegression(
                    solver="lbfgs", max_iter=15
                ).fit(X.astype(np.float64), y.astype(np.float64))
        np.testing.assert_allclose(fits["2x4"].coef_, fits["1d"].coef_,
                                   atol=5e-4, rtol=5e-4)

    def test_budget_refusal_lifted_by_2d_mesh(self):
        """The wide-d fit a 1-D stage refuses under the simulated
        per-device byte budget (typed StreamBudgetExceeded) completes
        once mesh_shape adds the model axis — the X slabs then stage
        as (rows/D, d/M) per-device tiles."""
        from dask_ml_tpu.linear_model import LogisticRegression

        rng = np.random.RandomState(7)
        n, d = 2048, 512
        X = rng.randn(n, d).astype(np.float64)
        y = (X[:, 0] > 0).astype(np.float64)
        budget = 1_000_000    # 1-D stages ~4.2MB/device; 2x4 ~0.5MB
        with config.set(stream_block_rows=512, stream_mesh=1,
                        stream_device_byte_budget=budget):
            with pytest.raises(StreamBudgetExceeded) as ei:
                LogisticRegression(solver="lbfgs", max_iter=3).fit(X, y)
            assert "mesh_shape" in str(ei.value)
        with config.set(stream_block_rows=512, stream_mesh=0,
                        mesh_shape="2x4",
                        stream_device_byte_budget=budget):
            clf = LogisticRegression(solver="lbfgs", max_iter=3).fit(X, y)
        assert np.asarray(clf.coef_).reshape(-1).shape == (d,)


def _spectrum_data(n=4096, d=64, seed=0):
    """Data with a decaying spectrum so randomized SVD is well-posed."""
    rng = np.random.default_rng(seed)
    u = np.linalg.qr(rng.normal(size=(n, d)))[0]
    v = np.linalg.qr(rng.normal(size=(d, d)))[0]
    s = 100.0 * (0.7 ** np.arange(d))
    X = (u * s) @ v.T + 0.01 * rng.normal(size=(n, d))
    return (X + 1.5).astype(np.float32)


class TestStreamedRandomizedPCA:
    @pytest.mark.parametrize("shape", MESHES_2D)
    def test_parity_vs_1d_streamed(self, shape):
        from dask_ml_tpu.models.pca import PCA

        X = _spectrum_data()
        fits = {}
        for label, knobs in (
            ("1d", dict(stream_mesh=1)),
            (shape, dict(stream_mesh=0, mesh_shape=shape)),
        ):
            with config.set(stream_block_rows=512, **knobs):
                fits[label] = PCA(n_components=8,
                                  svd_solver="randomized",
                                  random_state=0).fit(X)
        a, b = fits[shape], fits["1d"]
        np.testing.assert_allclose(a.components_, b.components_,
                                   atol=1e-6)
        np.testing.assert_allclose(a.singular_values_,
                                   b.singular_values_, rtol=1e-6)
        np.testing.assert_allclose(a.mean_, b.mean_, atol=1e-6)
        np.testing.assert_allclose(a.explained_variance_ratio_,
                                   b.explained_variance_ratio_,
                                   atol=1e-6)

    def test_parity_vs_resident(self):
        from dask_ml_tpu.models.pca import PCA

        X = _spectrum_data()
        with config.set(stream_block_rows=512, stream_mesh=0,
                        mesh_shape="2x4"):
            st = PCA(n_components=8, svd_solver="randomized",
                     random_state=0).fit(X)
        res = PCA(n_components=8, svd_solver="full").fit(X)
        np.testing.assert_allclose(st.singular_values_,
                                   res.singular_values_, rtol=1e-4)
        np.testing.assert_allclose(st.explained_variance_ratio_,
                                   res.explained_variance_ratio_,
                                   atol=1e-5)
        # subspace alignment: the principal angles between the streamed
        # and resident top-8 subspaces must be ~0
        align = np.linalg.svd(
            np.asarray(st.components_, np.float64)
            @ np.asarray(res.components_, np.float64).T,
            compute_uv=False,
        )
        np.testing.assert_allclose(align, 1.0, atol=1e-5)

    def test_transform_matches_resident(self):
        from dask_ml_tpu.models.pca import PCA

        X = _spectrum_data(n=2048)
        with config.set(stream_block_rows=512, stream_mesh=0,
                        mesh_shape="2x4"):
            st = PCA(n_components=4, svd_solver="randomized",
                     random_state=0).fit(X)
            sc_stream = np.asarray(st.transform(X))
        sc_host = (X - st.mean_) @ np.asarray(st.components_).T
        np.testing.assert_allclose(sc_stream, sc_host, atol=1e-3)

    def test_wide_auto_routes_randomized(self, monkeypatch):
        """svd_solver="auto" beyond the Gram width threshold must take
        the randomized streamed path instead of the d x d Gram."""
        from dask_ml_tpu.models import streamed_svd
        from dask_ml_tpu.models.pca import PCA

        monkeypatch.setattr(streamed_svd, "STREAM_GRAM_MAX_D", 32)
        X = _spectrum_data(n=2048, d=64)
        with config.set(stream_block_rows=512, stream_mesh=0,
                        mesh_shape="2x4"):
            p = PCA(n_components=4, svd_solver="auto",
                    random_state=0).fit(X)
        # the randomized route records its fixed pass plan
        assert p.training_profile_ is not None
        assert p.components_.shape == (4, 64)
        res = PCA(n_components=4, svd_solver="full").fit(X)
        np.testing.assert_allclose(p.singular_values_,
                                   res.singular_values_, rtol=1e-4)


class TestStreamedTruncatedSVD:
    def test_parity_vs_1d_streamed_and_resident_evr(self):
        from dask_ml_tpu.models.pca import TruncatedSVD

        X = _spectrum_data()
        fits = {}
        for label, knobs in (
            ("1d", dict(stream_mesh=1)),
            ("2x4", dict(stream_mesh=0, mesh_shape="2x4")),
        ):
            with config.set(stream_block_rows=512, **knobs):
                fits[label] = TruncatedSVD(
                    n_components=8, algorithm="randomized",
                    random_state=0,
                ).fit(X)
        np.testing.assert_allclose(fits["2x4"].components_,
                                   fits["1d"].components_, atol=1e-6)
        res = TruncatedSVD(n_components=8, algorithm="randomized",
                           random_state=0).fit(X)
        np.testing.assert_allclose(
            fits["2x4"].explained_variance_ratio_,
            res.explained_variance_ratio_, atol=1e-3,
        )

    def test_streamed_requires_randomized(self):
        from dask_ml_tpu.models.pca import TruncatedSVD

        X = _spectrum_data(n=1024)
        with config.set(stream_block_rows=256, stream_mesh=0,
                        mesh_shape="2x4"):
            with pytest.raises(ValueError, match="randomized"):
                TruncatedSVD(n_components=4, algorithm="tsqr").fit(X)

    def test_streamed_transform_shape(self):
        from dask_ml_tpu.models.pca import TruncatedSVD

        X = _spectrum_data(n=1024)
        with config.set(stream_block_rows=256, stream_mesh=0,
                        mesh_shape="2x4"):
            tsvd = TruncatedSVD(n_components=4, algorithm="randomized",
                                random_state=0)
            sc = np.asarray(tsvd.fit_transform(X))
        assert sc.shape == (1024, 4)
        host = X @ np.asarray(tsvd.components_).T
        np.testing.assert_allclose(sc, host, atol=1e-3)
