"""SimpleImputer over sharded arrays.

Reference: ``dask_ml/impute.py`` (SURVEY.md §2a Imputation row). NaN-aware
fit statistics are one jitted masked reduction; the reference limits
strategies on arrays similarly (mean/constant; median approximated — here
median is exact via device nanquantile; most_frequent falls back to a
host pass, as the reference does via DataFrames).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .base import BaseEstimator, TransformerMixin, to_host
from .parallel.sharded import ShardedArray
from .utils.validation import check_array, check_is_fitted

__all__ = ["SimpleImputer"]

_STRATEGIES = ("mean", "median", "most_frequent", "constant")


class SimpleImputer(TransformerMixin, BaseEstimator):
    """Ref: dask_ml/impute.py::SimpleImputer."""

    def __init__(self, missing_values=np.nan, strategy="mean",
                 fill_value=None, copy=True, add_indicator=False):
        self.missing_values = missing_values
        self.strategy = strategy
        self.fill_value = fill_value
        self.copy = copy
        self.add_indicator = add_indicator

    def _missing_mask(self, data):
        if isinstance(self.missing_values, float) and np.isnan(
            self.missing_values
        ):
            return jnp.isnan(data)
        return data == self.missing_values

    def fit(self, X, y=None):
        if self.strategy not in _STRATEGIES:
            raise ValueError(
                f"strategy must be one of {_STRATEGIES}, got "
                f"{self.strategy!r}"
            )
        X = check_array(X, dtype=np.float32, allow_nan=True)
        mask = X.row_mask(X.dtype)
        missing = self._missing_mask(X.data) | (mask[:, None] == 0)
        valid = (~missing).astype(X.dtype)
        if self.strategy == "constant":
            fv = 0.0 if self.fill_value is None else self.fill_value
            stats = np.full(X.shape[1], fv, np.float64)
        elif self.strategy == "mean":
            sums = jnp.sum(jnp.where(missing, 0.0, X.data) * 1.0, axis=0)
            counts = jnp.sum(valid, axis=0)
            stats = to_host(sums / jnp.maximum(counts, 1.0)).astype(np.float64)
        elif self.strategy == "median":
            data = jnp.where(missing, jnp.nan, X.data)
            stats = to_host(
                jnp.nanquantile(data.astype(jnp.float32), 0.5, axis=0)
            ).astype(np.float64)
        else:  # most_frequent: host pass (no device mode primitive)
            host = X.to_numpy()
            stats = np.empty(host.shape[1], np.float64)
            for j in range(host.shape[1]):
                col = host[:, j]
                col = col[~np.isnan(col)] if np.isnan(
                    self.missing_values
                ) else col[col != self.missing_values]
                if len(col) == 0:
                    stats[j] = np.nan
                else:
                    vals, cnt = np.unique(col, return_counts=True)
                    stats[j] = vals[np.argmax(cnt)]
        self.statistics_ = stats
        self.n_features_in_ = X.shape[1]
        return self

    def transform(self, X):
        check_is_fitted(self, "statistics_")
        X = check_array(X, dtype=np.float32, allow_nan=True)
        missing = self._missing_mask(X.data)
        out = jnp.where(
            missing, jnp.asarray(self.statistics_, X.dtype)[None, :], X.data
        )
        out = out * X.row_mask(out.dtype)[:, None]
        return ShardedArray(out, X.n_rows, X.mesh)
