"""Data & model-quality observability (ISSUE 7): streaming sketches,
train-serve drift scores, hot-swap canary deltas, the live-plane label
cardinality guard, and the report/export drift views."""

import json
import os
import re
import threading

import numpy as np
import pytest

from dask_ml_tpu import config, observability as obs
from dask_ml_tpu.observability import drift, live
from dask_ml_tpu.observability.sketch import (
    CategoricalSketch,
    FeatureSketch,
    merge_profiles,
    profile_from_dict,
)


@pytest.fixture(autouse=True)
def _clean():
    drift.reset()
    live.metrics_reset()
    yield
    drift.reset()
    live.metrics_reset()


def _read_jsonl(path):
    return [json.loads(line) for line in open(path)]


# -- sketches ----------------------------------------------------------------

def test_feature_sketch_moments_match_numpy():
    rng = np.random.RandomState(0)
    X = rng.randn(4000, 5) * [1, 10, 0.1, 100, 1] + [0, 5, -2, 0, 1e4]
    sk = FeatureSketch(5)
    for lo in range(0, 4000, 700):       # ragged chunked folds
        sk.fold(X[lo:lo + 700])
    st = sk.stats()
    assert np.allclose(st["mean"], X.mean(axis=0), rtol=1e-12)
    assert np.allclose(st["std"], X.std(axis=0, ddof=1), rtol=1e-12)
    assert np.allclose(st["min"], X.min(axis=0))
    assert np.allclose(st["max"], X.max(axis=0))
    assert sk.rows == 4000


def test_feature_sketch_fold_merge_equivalence():
    rng = np.random.RandomState(1)
    X = rng.randn(3000, 3)
    whole = FeatureSketch(3)
    whole.fold(X)
    a, b = FeatureSketch(3), FeatureSketch(3)
    a.fold(X[:1200])
    b.fold(X[1200:])
    a.merge(b)
    assert np.array_equal(whole.counts(), a.counts())
    sa, sw = a.stats(), whole.stats()
    for k in ("mean", "std", "min", "max"):
        assert np.allclose(sa[k], sw[k], rtol=1e-10), k
    # snapshot round-trip rebuilds an identical sketch
    again = profile_from_dict(whole.to_dict())
    assert np.array_equal(again.counts(), whole.counts())


def test_feature_sketch_quantiles_bucket_accurate():
    rng = np.random.RandomState(2)
    X = rng.randn(20000, 2)
    sk = FeatureSketch(2)
    sk.fold(X)
    med = sk.quantile(0.5)
    p90 = sk.quantile(0.9)
    assert np.all(np.abs(med - np.median(X, axis=0)) < 0.3)
    assert np.all(np.abs(p90 - np.quantile(X, 0.9, axis=0)) < 0.5)


def test_feature_sketch_nonfinite_isolated():
    X = np.array([[1.0, 2.0], [np.nan, 3.0], [np.inf, 4.0]])
    sk = FeatureSketch(2)
    sk.fold(X)
    st = sk.stats()
    assert st["n"][0] == 1 and st["n"][1] == 3   # non-finite excluded
    assert st["mean"][0] == 1.0 and st["mean"][1] == 3.0
    snap = sk.to_dict()
    assert snap["nonfinite"] == 2
    assert json.loads(json.dumps(snap))          # JSON-safe (inf-free)


def test_feature_sketch_thread_safe_folds():
    rng = np.random.RandomState(3)
    X = rng.randn(8000, 4)
    sk = FeatureSketch(4)
    errs = []

    def worker(part):
        try:
            for lo in range(0, len(part), 500):
                sk.fold(part[lo:lo + 500])
        except Exception as e:  # pragma: no cover
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(X[i::4],))
               for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    assert sk.rows == 8000
    assert int(sk.counts().sum()) == 8000 * 4


def test_merge_profiles_handles_none():
    sk = FeatureSketch(2)
    sk.fold(np.ones((10, 2)))
    snap = sk.to_dict()
    assert merge_profiles(None, snap) is snap
    assert merge_profiles(snap, None) is snap
    double = merge_profiles(snap, snap)
    assert double["rows"] == 20


def test_categorical_sketch_topk_bounded():
    cat = CategoricalSketch(k=3)
    vals = ["a"] * 50 + ["b"] * 30 + ["c"] * 10 + [f"x{i}" for i in range(20)]
    cat.fold(np.asarray(vals, dtype=object))
    top = cat.top(2)
    assert top[0][0] == "a" and top[0][1] >= 50    # upper-bound counts
    assert len(cat.to_dict()["counts"]) <= 3
    assert cat.total == len(vals)


# -- drift scores ------------------------------------------------------------

def test_psi_identical_zero_shifted_large():
    rng = np.random.RandomState(4)
    a, b = FeatureSketch(1), FeatureSketch(1)
    a.fold(rng.randn(20000, 1))
    b.fold(rng.randn(20000, 1))
    same = drift.psi_from_counts(a.counts()[0], b.counts()[0])
    assert 0 <= same < 0.02
    c = FeatureSketch(1)
    c.fold(rng.randn(20000, 1) + 2.0)
    shifted = drift.psi_from_counts(a.counts()[0], c.counts()[0])
    assert shifted > 1.0
    ks_same = drift.ks_from_counts(a.counts()[0], b.counts()[0])
    ks_shift = drift.ks_from_counts(a.counts()[0], c.counts()[0])
    assert ks_same < 0.05 < ks_shift
    assert np.isnan(drift.psi_from_counts([0, 0], [1, 2]))


def test_train_serve_scoring_and_alert_latch(tmp_path):
    rng = np.random.RandomState(5)
    base = FeatureSketch(3)
    base.fold(rng.randn(30000, 3))
    obs.counters_reset()
    drift.note_training_profile("m", 1, base.to_dict())
    drift.fold_serving("m", 1, "predict", rng.randn(2000, 3) + 3.0)
    trace = str(tmp_path / "t")
    with config.set(trace_dir=trace):
        recs = drift.compute(publish=False)
    ts = [r for r in recs if r["pair"] == "train_serve"]
    assert ts and max(r["psi"] for r in ts) > 0.2
    assert any(r["alert"] for r in ts)
    alerts = obs.counters_snapshot().get("drift_alerts", 0)
    assert alerts >= 1
    # the latch: a second compute on the SAME state must not re-count
    with config.set(trace_dir=trace):
        drift.compute(publish=False)
    assert obs.counters_snapshot().get("drift_alerts", 0) == alerts
    # drift records landed in the trace sink with wall-clock stamps
    recs_file = _read_jsonl(os.path.join(trace, "trace.jsonl"))
    dr = [r for r in recs_file if r.get("drift")]
    assert dr and all("t_unix" in r for r in dr)


def test_window_vs_window_detects_mid_serve_shift():
    rng = np.random.RandomState(6)
    drift.fold_serving("m", 1, "predict", rng.randn(3000, 2))
    drift.compute(publish=False)          # window cursor 1
    drift.fold_serving("m", 1, "predict", rng.randn(3000, 2))
    drift.compute(publish=False)          # window 1 vs cursor: control
    drift.fold_serving("m", 1, "predict", rng.randn(3000, 2) + 3.0)
    recs = drift.compute(publish=False)   # shifted window vs control
    win = [r for r in recs if r["pair"] == "window"]
    assert win and max(r["psi"] for r in win) > 0.2


def test_serving_fold_rate_budget_bounds_rows():
    rng = np.random.RandomState(7)
    total = 0
    for _ in range(50):
        total += drift.fold_serving("m", 1, "predict",
                                    rng.randn(4096, 2))
    # the token bucket caps the folded sample (burst + a trickle),
    # far below the 200k rows offered
    assert 0 < total <= drift._FOLD_BURST_ROWS + 4096


def test_canary_delta_and_gauges():
    old = np.asarray([0.0] * 90 + [1.0] * 10)
    new = np.asarray([0.0] * 50 + [1.0] * 50)
    verdict = drift.canary_delta(old, new)
    assert verdict["disagreement"] == pytest.approx(0.4)
    obs.counters_reset()
    with obs.TelemetryServer(port=0):
        drift.record_canary("m", 1, 2, "predict", old, new)
        page = live.render_prometheus()
    assert re.search(r'canary_disagreement\{[^}]*from="1"[^}]*to="2"',
                     page)
    # per-version prediction series for BOTH sides of the flip
    assert re.search(r'canary_prediction_p50\{[^}]*version="1"', page)
    assert re.search(r'canary_prediction_p50\{[^}]*version="2"', page)
    sb = drift.status_block()
    assert sb["canaries"] and sb["canaries"][0]["version_to"] == 2


# -- serving integration -----------------------------------------------------

def _fit_pair():
    from dask_ml_tpu.models.sgd import SGDClassifier

    rng = np.random.RandomState(0)
    X = rng.randn(20000, 6).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    y2 = (X[:, 1] > 0).astype(np.float32)
    with config.set(stream_block_rows=2048):
        a = SGDClassifier(max_iter=2, random_state=0).fit(X, y)
        b = SGDClassifier(max_iter=2, random_state=7).fit(X, y2)
    return X, a, b


def test_streamed_fit_attaches_training_profile():
    X, a, _ = _fit_pair()
    prof = a.training_profile_
    assert prof["n_features"] == 6 and prof["rows"] > 0
    st = profile_from_dict(prof).stats()
    assert np.all(np.abs(st["mean"]) < 0.1)       # N(0,1) features
    assert np.all(np.abs(st["std"] - 1.0) < 0.1)


def test_glm_streamed_fit_attaches_training_profile():
    from dask_ml_tpu.linear_model import LinearRegression

    rng = np.random.RandomState(1)
    X = rng.randn(4000, 4).astype(np.float32)
    y = (X @ rng.randn(4)).astype(np.float32)
    with config.set(stream_block_rows=512):
        est = LinearRegression(solver="gradient_descent",
                               max_iter=3).fit(X, y)
    assert est.training_profile_["n_features"] == 4


def test_profile_off_when_disabled():
    from dask_ml_tpu.models.sgd import SGDClassifier

    rng = np.random.RandomState(2)
    X = rng.randn(4000, 3).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    with config.set(stream_block_rows=512, obs_drift=False):
        est = SGDClassifier(max_iter=1, random_state=0).fit(X, y)
    assert est.training_profile_ is None


def test_incremental_wrapper_exposes_inner_profile():
    from dask_ml_tpu.models.sgd import SGDClassifier
    from dask_ml_tpu.wrappers import Incremental

    rng = np.random.RandomState(3)
    X = rng.randn(6000, 4).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    with config.set(stream_block_rows=1024):
        inc = Incremental(SGDClassifier(random_state=0)).fit(
            X, y, classes=[0.0, 1.0]
        )
    assert inc.training_profile_["n_features"] == 4
    assert hasattr(inc, "training_profile_")


def test_server_folds_traffic_and_scores_against_profile():
    from dask_ml_tpu.serving import BucketLadder, ModelServer

    X, a, _ = _fit_pair()
    with config.set(obs_shadow_fraction=0.0, obs_drift_interval_s=0.0):
        srv = ModelServer(a, methods=("predict",), name="clf",
                          ladder=BucketLadder(8, 128, 2.0),
                          batch_window_ms=0.5, timeout_ms=0).warmup()
        with srv:
            for i in range(40):
                srv.predict(X[i * 64:(i + 1) * 64])
    recs = drift.compute(publish=False)
    ts = [r for r in recs if r["pair"] == "train_serve"]
    assert ts, "server must fold traffic into serving sketches"
    assert max(r["psi"] for r in ts) < 0.2        # in-distribution
    entry = drift.serving_sketch("clf", 0, "predict")
    assert entry["features"].rows > 0
    assert entry["classes"] is not None           # predict outputs
    assert entry["predictions"].rows > 0


def test_hot_swap_canary_zero_compiles_and_per_version_series():
    from dask_ml_tpu.serving import BucketLadder, ModelServer

    X, a, b = _fit_pair()
    obs.counters_reset()
    with config.set(obs_shadow_fraction=1.0, obs_drift_interval_s=0.0):
        srv = ModelServer(a, methods=("predict",), name="clf",
                          ladder=BucketLadder(8, 128, 2.0),
                          batch_window_ms=0.5, timeout_ms=0).warmup()
        with srv:
            for i in range(30):
                srv.predict(X[i * 64:(i + 1) * 64])
            before = obs.counters_snapshot().get("recompiles", 0)
            srv.swap_model(b, version=2)
            minted = obs.counters_snapshot().get("recompiles", 0) - before
    assert minted == 0, "canary must ride warmed entry points"
    sb = drift.status_block()
    assert sb["canaries"], "swap must record a canary"
    can = sb["canaries"][0]
    assert can["version_from"] == 0 and can["version_to"] == 2
    # a (hinge) concept change must disagree on the shadow sample
    assert can["disagreement"] > 0.1
    # both versions' training profiles registered for train-vs-serve
    assert drift.training_profile("clf", 0)
    assert drift.training_profile("clf", 2)


def test_drift_monitor_lifecycle():
    with config.set(obs_drift_interval_s=0.05):
        t = drift.ensure_monitor()
        assert t is not None and drift.monitor_active()
        assert drift.ensure_monitor() is t        # idempotent
    drift.stop_monitor()
    assert not drift.monitor_active()
    with config.set(obs_drift=False):
        assert drift.ensure_monitor() is None


# -- label-cardinality guard (live metric registry) ---------------------------

def test_series_cap_drops_and_counts_overflow():
    obs.counters_reset()
    with config.set(obs_max_series=8):
        for i in range(30):
            live.gauge_set("capped_family", float(i),
                           (("feature", f"f{i}"),))
        labeled = [k for k in live.gauges_snapshot()
                   if k[0] == "capped_family"]
        assert len(labeled) == 8
        dropped = obs.counters_snapshot().get(
            "telemetry_series_dropped", 0)
        assert dropped == 22
        # existing series still update past the cap
        live.gauge_set("capped_family", 99.0, (("feature", "f0"),))
        assert live.gauges_snapshot()[("capped_family",
                                       (("feature", "f0"),))] == 99.0
        # unlabeled series are never capped
        live.gauge_set("capped_family_total_view", 1.0)
        # histograms: overflow keys get a working detached sink
        for i in range(30):
            live.histogram("capped_hist",
                           (("feature", f"f{i}"),)).observe(0.01)
        hs = [k for k in live.histograms_snapshot()
              if k[0] == "capped_hist"]
        assert len(hs) == 8


def test_series_drop_counted_once_per_series():
    """The drop counter counts dropped SERIES: a publisher re-setting
    the same over-cap gauges every monitor tick must not inflate it."""
    obs.counters_reset()
    with config.set(obs_max_series=2):
        for _ in range(5):                  # 5 publish ticks
            for i in range(4):              # 4 series, cap 2
                live.gauge_set("once_family", 1.0, (("f", str(i)),))
    assert obs.counters_snapshot().get(
        "telemetry_series_dropped", 0) == 2


def test_version_eviction_bounds_registries_and_drops_series():
    """serve_while_training publishes a version per pass: the drift
    registries keep only the newest ``_VERSIONS_KEEP`` versions per
    model, and an evicted version's per-version gauge series leave
    /metrics (releasing their cardinality-cap slots)."""
    rng = np.random.RandomState(3)
    X = rng.randn(256, 4)
    prof = FeatureSketch(4)
    prof.fold(X)
    for v in range(1, 10):
        drift.note_training_profile("m", v, prof.to_dict())
        assert drift.fold_serving("m", v, "predict", X) > 0
        live.gauge_set(
            "drift_score", 0.5,
            (("model", "m"), ("version", str(v)), ("feature", "f0")),
        )
    keep = list(range(10 - drift._VERSIONS_KEEP, 10))
    with drift._lock:
        assert sorted({k[1] for k in drift._serving}) == keep
        assert sorted({k[1] for k in drift._train}) == keep
    live_versions = sorted(
        int(dict(k[1])["version"]) for k in live.gauges_snapshot()
        if k[0] == "drift_score"
    )
    assert live_versions == keep
    # evicted versions' scores are gone from /status too
    drift.compute(publish=False)
    assert all(s["version"] in keep
               for s in drift.status_block()["scores"])


def test_exposition_parseable_at_cap():
    with config.set(obs_max_series=8):
        for i in range(40):
            live.gauge_set("drift_score", 0.5,
                           (("model", "m"), ("feature", f"f{i}")))
            live.histogram("lat", (("b", str(i)),)).observe(0.001)
        page = live.render_prometheus()
    for line in page.rstrip("\n").split("\n"):
        assert line.startswith("#") or re.match(
            r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? "
            r"(-?\d+(\.\d+)?([eE][+-]?\d+)?|[+-]Inf|NaN)$", line
        ), f"bad exposition line: {line!r}"
    assert len(re.findall(r"^dask_ml_tpu_drift_score\{", page,
                          re.MULTILINE)) == 8


# -- report / export / merge -------------------------------------------------

def _drift_records():
    return [
        {"time": 0.1, "t_unix": 100.0, "drift": True,
         "pair": "train_serve", "model": "clf", "version": 1,
         "method": "predict", "feature": "f0", "psi": 0.31, "ks": 0.2,
         "alert": True},
        {"time": 0.2, "t_unix": 101.0, "drift": True,
         "pair": "train_serve", "model": "clf", "version": 1,
         "method": "predict", "feature": "f1", "psi": 0.01, "ks": 0.02,
         "alert": False},
        {"time": 0.3, "t_unix": 102.0, "drift": True, "pair": "canary",
         "model": "clf", "version_from": 1, "version_to": 2,
         "method": "predict", "n_rows": 128, "disagreement": 0.4,
         "max_quantile_shift": 0.1, "alert": True},
    ]


def test_report_renders_drift_and_canary_tables():
    from dask_ml_tpu.observability.report import build_report, report_data

    recs = _drift_records()
    out = build_report(recs)
    assert "drift (train vs serve / window vs window)" in out
    assert "canary (version vs version prediction deltas)" in out
    assert "1->2" in out and "f0" in out
    data = report_data(recs)
    assert data["drift"]["scores"][0]["max_psi"] == 0.31
    assert data["drift"]["scores"][0]["worst_feature"] == "f0"
    assert data["drift"]["scores"][0]["alerts"] == 1
    assert data["drift"]["canaries"][0]["versions"] == "1->2"


def test_report_merge_keeps_drift_records_on_timeline():
    from dask_ml_tpu.observability.report import merge_records

    a = [{"time": 0.1, "t_unix": 100.0, "span": "fit", "span_id": 1,
          "parent_id": None, "wall_s": 1.0},
         {"time": 5.0, "t_unix": 105.0, "drift": True,
          "pair": "train_serve", "model": "m", "version": 1,
          "method": "predict", "feature": "f0", "psi": 0.5,
          "alert": True}]
    b = [{"time": 0.2, "t_unix": 102.0, "drift": True, "pair": "canary",
          "model": "m", "version_from": 1, "version_to": 2,
          "method": "predict", "disagreement": 0.1,
          "max_quantile_shift": 0.0, "n_rows": 8, "alert": False}]
    merged = merge_records([a, b])
    stamps = [r["t_unix"] for r in merged]
    assert stamps == sorted(stamps)
    # the canary from file b interleaves BETWEEN file a's records
    assert merged[1].get("pair") == "canary"


def test_perfetto_export_lanes_drift_alert_instants():
    from dask_ml_tpu.observability.export import to_chrome_trace

    trace = to_chrome_trace(_drift_records())
    instants = [e for e in trace["traceEvents"] if e.get("ph") == "i"]
    names = [e["name"] for e in instants]
    assert any("drift alert" in n for n in names)
    assert any("canary alert" in n for n in names)
    # quiet drift records stay off the timeline
    assert len(instants) == 2


# -- host-only contract -------------------------------------------------------

def test_sketch_and_drift_never_import_jax():
    """The zero-sync guarantee, structurally: the quality plane is host
    numpy only — no jax import can ever appear in sketch.py/drift.py
    (a device sync or traced callback is impossible by construction)."""
    import dask_ml_tpu.observability.drift as dmod
    import dask_ml_tpu.observability.sketch as smod

    for mod in (smod, dmod):
        src = open(mod.__file__).read()
        assert "import jax" not in src, mod.__name__
