"""PCA/TruncatedSVD/IncrementalPCA parity vs sklearn (SURVEY.md §4)."""

import numpy as np
import pytest
import sklearn.decomposition as skdec

from dask_ml_tpu.decomposition import PCA, IncrementalPCA, TruncatedSVD

RNG = np.random.RandomState(0)
X = (RNG.randn(203, 8) @ RNG.randn(8, 8) + RNG.randn(8)).astype(np.float64)


@pytest.mark.parametrize("solver", ["full", "randomized"])
def test_pca_parity(solver):
    k = 4
    ours = PCA(n_components=k, svd_solver=solver, random_state=0,
               iterated_power=4).fit(X)
    ref = skdec.PCA(n_components=k, svd_solver="full").fit(X)
    np.testing.assert_allclose(ours.mean_, ref.mean_, atol=1e-4)
    np.testing.assert_allclose(
        ours.singular_values_, ref.singular_values_, rtol=1e-3
    )
    np.testing.assert_allclose(
        ours.explained_variance_, ref.explained_variance_, rtol=1e-3
    )
    np.testing.assert_allclose(
        ours.explained_variance_ratio_, ref.explained_variance_ratio_,
        rtol=1e-3,
    )
    np.testing.assert_allclose(
        np.abs(ours.components_), np.abs(ref.components_), atol=2e-3
    )
    np.testing.assert_allclose(ours.noise_variance_, ref.noise_variance_,
                               rtol=1e-2)


def test_pca_transform_roundtrip():
    ours = PCA(n_components=8, svd_solver="full").fit(X)
    t = ours.transform(X)
    back = ours.inverse_transform(t).to_numpy()
    np.testing.assert_allclose(back, X, atol=1e-2)


def test_pca_fit_transform_matches_transform():
    p = PCA(n_components=3, svd_solver="full")
    t1 = p.fit_transform(X).to_numpy()
    t2 = p.transform(X).to_numpy()
    np.testing.assert_allclose(t1, t2, atol=1e-3)


def test_pca_whiten():
    ours = PCA(n_components=4, whiten=True, svd_solver="full").fit(X)
    t = ours.transform(X).to_numpy()
    np.testing.assert_allclose(t.std(axis=0, ddof=1), 1.0, rtol=5e-2)


def test_pca_errors():
    with pytest.raises(ValueError, match="n_components"):
        PCA(n_components=100).fit(X)
    with pytest.raises(ValueError, match="tall"):
        PCA().fit(X[:4])


def test_truncated_svd_parity():
    ours = TruncatedSVD(n_components=4, algorithm="tsqr").fit(X)
    ref = skdec.TruncatedSVD(n_components=4, algorithm="arpack").fit(X)
    np.testing.assert_allclose(
        ours.singular_values_, ref.singular_values_, rtol=1e-3
    )
    np.testing.assert_allclose(
        ours.explained_variance_, ref.explained_variance_, rtol=1e-2
    )
    np.testing.assert_allclose(
        np.abs(ours.components_), np.abs(ref.components_), atol=2e-3
    )


def test_truncated_svd_randomized():
    ours = TruncatedSVD(n_components=4, algorithm="randomized",
                        random_state=0).fit(X)
    ref = skdec.TruncatedSVD(n_components=4, algorithm="arpack").fit(X)
    np.testing.assert_allclose(
        ours.singular_values_, ref.singular_values_, rtol=1e-2
    )


def test_truncated_svd_transform():
    svd = TruncatedSVD(n_components=3, algorithm="tsqr")
    t1 = svd.fit_transform(X).to_numpy()
    t2 = svd.transform(X).to_numpy()
    np.testing.assert_allclose(t1, t2, atol=1e-3)


def test_incremental_pca_close_to_pca():
    ours = IncrementalPCA(n_components=4, batch_size=50).fit(X)
    ref = skdec.PCA(n_components=4, svd_solver="full").fit(X)
    np.testing.assert_allclose(ours.mean_, ref.mean_, atol=1e-3)
    np.testing.assert_allclose(
        ours.singular_values_, ref.singular_values_, rtol=5e-2
    )
    np.testing.assert_allclose(
        np.abs(ours.components_ @ ref.components_.T),
        np.eye(4), atol=0.05,
    )


def test_incremental_pca_partial_fit():
    ipca = IncrementalPCA(n_components=3)
    for i in range(0, 200, 50):
        ipca.partial_fit(X[i:i + 50])
    assert ipca.n_samples_seen_ == 200
    assert ipca.components_.shape == (3, 8)


def test_incremental_pca_no_host_gather(monkeypatch):
    """VERDICT r4 weak #4: fit over a ShardedArray must NOT pull the
    whole array to host (the class exists for out-of-core sizes)."""
    from dask_ml_tpu.parallel import as_sharded

    Xs = as_sharded(X)
    monkeypatch.setattr(
        type(Xs), "to_numpy",
        lambda self: (_ for _ in ()).throw(
            AssertionError("whole-array host gather in IncrementalPCA")
        ),
    )
    ipca = IncrementalPCA(n_components=3, batch_size=50).fit(Xs)
    assert ipca.n_samples_seen_ == len(X)
    ref = IncrementalPCA(n_components=3, batch_size=50).fit(X)
    np.testing.assert_allclose(ipca.mean_, ref.mean_, atol=1e-4)
    np.testing.assert_allclose(
        ipca.explained_variance_ratio_, ref.explained_variance_ratio_,
        rtol=1e-3,
    )


def test_incremental_pca_memmap_streams(tmp_path):
    """memmap input: blocks slice O(block) from disk; the variance pass
    accumulates from the same blocks (no full-X device placement)."""
    p = tmp_path / "x.f32"
    m = np.memmap(p, dtype=np.float32, mode="w+", shape=X.shape)
    m[:] = X
    m.flush()
    ours = IncrementalPCA(n_components=3, batch_size=50).fit(
        np.memmap(p, dtype=np.float32, mode="r", shape=X.shape)
    )
    ref = IncrementalPCA(n_components=3, batch_size=50).fit(X)
    np.testing.assert_allclose(ours.mean_, ref.mean_, atol=1e-4)
    np.testing.assert_allclose(
        ours.singular_values_, ref.singular_values_, rtol=1e-3
    )


def test_incremental_pca_uncentered_variance_device():
    """f32 device sum-of-squares must not cancel for data with a large
    mean: explained_variance_ratio_ on device input must match the f64
    host path (shifted accumulation)."""
    from dask_ml_tpu.parallel import as_sharded

    rng = np.random.RandomState(3)
    Xb = (rng.randn(600, 6) + 1000.0).astype(np.float32)
    dev = IncrementalPCA(n_components=3, batch_size=100).fit(as_sharded(Xb))
    host = IncrementalPCA(n_components=3, batch_size=100).fit(Xb)
    np.testing.assert_allclose(
        dev.explained_variance_ratio_, host.explained_variance_ratio_,
        rtol=2e-2,
    )
    assert np.all(np.isfinite(dev.explained_variance_ratio_))


def test_incremental_pca_sparse_partial_fit_and_empty():
    import scipy.sparse as sp

    blk = sp.random(120, 8, density=0.4, format="csr",
                    random_state=np.random.RandomState(0))
    ipca = IncrementalPCA(n_components=3).partial_fit(blk)
    assert ipca.components_.shape == (3, 8)
    with pytest.raises(ValueError, match="0 sample"):
        IncrementalPCA(n_components=2).fit(np.empty((0, 4), np.float32))
    # COO input streams too (normalized to CSR once)
    coo = IncrementalPCA(n_components=3, batch_size=50).fit(blk.tocoo())
    csr = IncrementalPCA(n_components=3, batch_size=50).fit(blk)
    np.testing.assert_allclose(coo.mean_, csr.mean_)
    # NaN data raises at the source, as check_array used to
    Xbad = np.asarray(X, np.float32).copy()
    Xbad[3, 2] = np.nan
    with pytest.raises(ValueError, match="NaN"):
        IncrementalPCA(n_components=2, batch_size=50).fit(Xbad)


def test_pca_variance_fraction():
    ours = PCA(n_components=0.95, svd_solver="full").fit(X)
    ref = skdec.PCA(n_components=0.95, svd_solver="full").fit(X)
    assert ours.n_components_ == ref.n_components_
    assert ours.components_.shape == ref.components_.shape


def test_incremental_pca_fit_transform_uses_incremental_path():
    ipca = IncrementalPCA(n_components=3, batch_size=50)
    t = ipca.fit_transform(X)
    np.testing.assert_allclose(
        t.to_numpy(), ipca.transform(X).to_numpy(), atol=1e-5
    )
    assert ipca.n_samples_seen_ == len(X)


def test_kmeans_tiny_dataset_oversampling_clamp():
    from dask_ml_tpu.cluster import KMeans

    Xs = np.random.RandomState(0).randn(10, 3)
    km = KMeans(n_clusters=8, oversampling_factor=4, random_state=0).fit(Xs)
    assert km.cluster_centers_.shape == (8, 3)


def test_take_rows_bounds_check():
    import pytest

    from dask_ml_tpu.parallel import ShardedArray
    from dask_ml_tpu.parallel.sharded import take_rows

    sx = ShardedArray.from_array(np.arange(20.0).reshape(10, 2))
    with pytest.raises(IndexError):
        take_rows(sx, np.array([0, 10]))
    with pytest.raises(IndexError):
        take_rows(sx, np.array([-1]))


def test_pca_probabilistic_scoring_parity():
    """get_covariance/get_precision/score_samples/score match sklearn's
    probabilistic-PCA formulas on the same fitted subspace."""
    from sklearn.decomposition import PCA as SkPCA

    from dask_ml_tpu.decomposition import PCA
    from dask_ml_tpu.parallel import as_sharded

    rng = np.random.RandomState(0)
    n, d = 600, 8
    X = (rng.randn(n, d) * np.linspace(3, 0.3, d)).astype(np.float64)

    ours = PCA(n_components=3, svd_solver="full").fit(as_sharded(X))
    sk = SkPCA(n_components=3, svd_solver="full").fit(X)

    np.testing.assert_allclose(ours.get_covariance(), sk.get_covariance(),
                               rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(ours.get_precision(), sk.get_precision(),
                               rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(
        ours.score_samples(as_sharded(X)), sk.score_samples(X),
        rtol=1e-3, atol=1e-3,
    )
    assert ours.score(as_sharded(X)) == pytest.approx(sk.score(X),
                                                      rel=1e-3)


def test_pca_scoring_whiten_and_incremental():
    from sklearn.decomposition import PCA as SkPCA

    from dask_ml_tpu.decomposition import PCA, IncrementalPCA
    from dask_ml_tpu.parallel import as_sharded

    rng = np.random.RandomState(1)
    X = (rng.randn(500, 6) * np.linspace(2, 0.4, 6)).astype(np.float64)
    ours = PCA(n_components=3, whiten=True, svd_solver="full").fit(
        as_sharded(X)
    )
    sk = SkPCA(n_components=3, whiten=True, svd_solver="full").fit(X)
    np.testing.assert_allclose(ours.get_precision(), sk.get_precision(),
                               rtol=1e-3, atol=1e-4)
    assert ours.score(as_sharded(X)) == pytest.approx(sk.score(X),
                                                      rel=1e-3)
    # IncrementalPCA: scoring API usable after fit (noise_variance_ set)
    ipca = IncrementalPCA(n_components=3).fit(as_sharded(X))
    assert np.isfinite(ipca.score(as_sharded(X)))


def test_pca_score_samples_streams_out_of_core(tmp_path):
    from dask_ml_tpu import config
    from dask_ml_tpu.decomposition import PCA

    rng = np.random.RandomState(2)
    X = (rng.randn(2000, 5) * [3, 2, 1, 0.5, 0.2]).astype(np.float32)
    path = str(tmp_path / "X.f32")
    mm = np.memmap(path, dtype=np.float32, mode="w+", shape=X.shape)
    mm[:] = X
    mm.flush()
    mm = np.memmap(path, dtype=np.float32, mode="r", shape=X.shape)
    with config.set(stream_block_rows=512):
        p = PCA(n_components=2).fit(mm)
        ll_stream = p.score_samples(mm)
    ll_res = p.score_samples(X)
    np.testing.assert_allclose(ll_stream, ll_res, rtol=1e-4, atol=1e-4)
