"""SLO-driven replica autoscaling: the admission predictor grows the
fleet instead of only shedding at its door.

The fleet's SLO admission (``serving/policy.py``) already computes the
exact scale signal — queued rows x the windowed per-(method, bucket)
execution quantile = the BEST healthy replica's predicted completion
time for a top-bucket request. :class:`ReplicaAutoscaler` polls that
signal and moves replica count under hysteresis bands:

- predicted completion above the **up band** (default 80% of
  ``serving_slo_ms``) for ``patience`` consecutive ticks ADDS a
  replica: built via the fleet's own ``_make_replica`` (identical
  config, device round-robin), warmed OFF the serving path — with the
  plans plane armed (``plan_cache`` + ``compile_cache_dir``, PR 15) the
  warmup replays cached executables and spin-up is near-instant, zero
  fresh XLA compiles — then installed into the routing tuple under the
  fleet lock;
- predicted completion below the **down band** (default 20% of the
  SLO) for ``patience`` ticks RETIRES the least-loaded replica: removed
  from routing first (no new work), then drained gracefully
  (``stop(drain=True)`` — its queued requests complete), and its
  per-replica gauge series DROPPED so /metrics never latches a phantom;
- a ``cooldown_s`` refractory after every action stops flapping, and
  ``[min, max]`` bound the fleet.

Scale activity is observable: the ``serving_replicas{fleet=...}`` gauge
tracks the live count, ``serving_scale_ups_total`` /
``serving_scale_downs_total`` count the moves, and each action lands in
:attr:`ReplicaAutoscaler.events` (kind, replicas-after, seconds) for
tests and the federation smoke.

Armed by ``FleetServer.start()`` when ``config.serving_autoscale`` is
on (default off — like supervision, scaling is an operational policy).
"""

from __future__ import annotations

import dataclasses
import threading
import time

from . import metrics as smetrics
from .policy import predict_completion_s

__all__ = ["ReplicaAutoscaler"]


class ReplicaAutoscaler:
    """Watch one fleet; scale its replica count to the SLO signal."""

    def __init__(self, fleet, min_replicas=None, max_replicas=None,
                 interval_s=None, up_ms=None, down_ms=None,
                 patience=None, cooldown_s=None):
        from ..config import get_config

        cfg = get_config()
        self.fleet = fleet
        self.min = max(1, int(cfg.serving_autoscale_min
                              if min_replicas is None else min_replicas))
        self.max = max(self.min, int(cfg.serving_autoscale_max
                                     if max_replicas is None
                                     else max_replicas))
        self.interval_s = float(cfg.serving_autoscale_interval_s
                                if interval_s is None else interval_s)
        slo_ms = float(cfg.serving_slo_ms)
        up = float(cfg.serving_autoscale_up_ms if up_ms is None
                   else up_ms)
        down = float(cfg.serving_autoscale_down_ms if down_ms is None
                     else down_ms)
        # 0 = derive the bands from the SLO itself; an explicit band
        # decouples scaling from shedding (scale at 80%, shed at 100%)
        self.up_ms = up if up > 0 else 0.8 * slo_ms
        self.down_ms = down if down > 0 else 0.2 * slo_ms
        self.patience = max(1, int(cfg.serving_autoscale_patience
                                   if patience is None else patience))
        self.cooldown_s = float(cfg.serving_autoscale_cooldown_s
                                if cooldown_s is None else cooldown_s)
        self._cfg = cfg          # the scaler thread re-applies it
        self._above = 0
        self._below = 0
        self._t_last_scale = 0.0
        self.events: list[tuple] = []   # (kind, n_after, seconds)
        self._stop = threading.Event()
        self._thread = None

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="dask-ml-tpu-autoscaler",
            daemon=True,
        )
        self._thread.start()
        return self

    def stop(self, timeout=5.0):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
        self._thread = None

    # -- loop --------------------------------------------------------------
    def _run(self):
        from .. import config

        # thread-local config: warmup compiles, counters, and the plans
        # plane on this thread must follow the fleet creator's config,
        # not daemon-thread defaults (same contract as the supervisor)
        with config.set(**dataclasses.asdict(self._cfg)):
            while not self._stop.wait(self.interval_s):
                try:
                    self.tick()
                except Exception:
                    # scaling must never take the process down; the
                    # next tick retries
                    pass

    # -- signal ------------------------------------------------------------
    def signal_ms(self):
        """The scale signal: the BEST healthy replica's predicted
        completion (ms) for a top-bucket request — exactly what the SLO
        admission door computes, so "the door is about to shed" and
        "the scaler should add a replica" read the same number. None
        while no execution estimate exists (a cold fleet neither grows
        nor shrinks on ignorance)."""
        fleet = self.fleet
        method = fleet._methods[0]
        top = fleet.ladder.max_rows
        best = None
        for r in fleet.replicas:
            if not r.healthy:
                continue
            pred = predict_completion_s(
                r.queue_rows, top, top, r.predict_exec_s(method, top))
            if pred is not None and (best is None or pred < best):
                best = pred
        return None if best is None else best * 1e3

    def tick(self):
        """One evaluation (also callable directly from tests — the
        thread is just this on a timer)."""
        fleet = self.fleet
        if not getattr(fleet, "_started", False) or self.up_ms <= 0:
            return
        n = len(fleet.replicas)
        smetrics.set_replica_count_gauge(fleet.name, n)
        sig = self.signal_ms()
        if sig is None:
            self._above = self._below = 0
            return
        if sig > self.up_ms:
            self._above += 1
            self._below = 0
        elif sig < self.down_ms:
            self._below += 1
            self._above = 0
        else:
            self._above = self._below = 0
        if time.monotonic() - self._t_last_scale < self.cooldown_s:
            return
        if self._above >= self.patience and n < self.max:
            self.scale_up()
        elif self._below >= self.patience and n > self.min:
            self.scale_down()

    # -- actions -----------------------------------------------------------
    def scale_up(self) -> float:
        """Add one replica at the registry's current version, warmed
        BEFORE it joins routing. Returns spin-up seconds (the
        ``autoscale_spinup_seconds`` bench signal — plan-warm runs
        replay cached executables here)."""
        from ..observability.live import unregister_server

        fleet = self.fleet
        t0 = time.perf_counter()
        try:
            mv = fleet.registry.get(fleet.name)
        except KeyError:
            return 0.0
        new_id = max((r.replica_id for r in fleet.replicas),
                     default=-1) + 1
        fresh = fleet._make_replica(new_id, mv.estimator, mv.version)
        q = getattr(mv, "quantize", None)
        if q:
            fresh.rebuild_model(mv.estimator, version=mv.version,
                                warm=False, quantize=q)
        fresh.warmup()          # compiles land HERE, not on traffic
        fresh.start()
        unregister_server(fresh)    # the fleet entry covers it
        with fleet._lock:
            if not fleet._started:
                fresh.stop(drain=False)
                return 0.0
            fleet.replicas = fleet.replicas + (fresh,)
        dt = time.perf_counter() - t0
        self._t_last_scale = time.monotonic()
        self._above = self._below = 0
        smetrics.record_scale_up()
        smetrics.set_replica_gauges(new_id, version=fresh.model_version,
                                    healthy=True)
        smetrics.set_replica_count_gauge(fleet.name,
                                         len(fleet.replicas))
        self.events.append(("up", len(fleet.replicas), round(dt, 6)))
        return dt

    def scale_down(self) -> bool:
        """Retire the least-loaded replica: out of routing FIRST (no
        new work lands on it), then a graceful drain (queued requests
        complete on its worker), then its gauge series dropped."""
        fleet = self.fleet
        t0 = time.perf_counter()
        with fleet._lock:
            if not fleet._started or len(fleet.replicas) <= self.min:
                return False
            victim = min(fleet.replicas,
                         key=lambda r: (r.queue_rows, -r.replica_id))
            fleet.replicas = tuple(r for r in fleet.replicas
                                   if r is not victim)
        victim._accepting = False
        victim.stop(drain=True)
        dt = time.perf_counter() - t0
        self._t_last_scale = time.monotonic()
        self._above = self._below = 0
        smetrics.record_scale_down()
        # a retired replica must not leave stale serving_replica_*/
        # queue gauge series latched on /metrics
        smetrics.drop_replica_gauges(victim.replica_id)
        smetrics.set_replica_count_gauge(fleet.name,
                                         len(fleet.replicas))
        self.events.append(("down", len(fleet.replicas),
                            round(dt, 6)))
        return True
