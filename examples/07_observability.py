"""Observability: record a KMeans + LogisticRegression run as JSONL
(span traces, per-step solver metrics, runtime counters, and the
compiled-program registry), then render the run report and a Perfetto
trace — the "where did this fit spend its time, FLOPs and HBM" answer
the reference got from dask's dashboard.

Everything is ambient: setting ``config.metrics_path`` wires span
records (fit -> stream pass, with wall/device-sync time and counter
deltas) and per-iteration solver telemetry into one append-only file;
``config.obs_programs=True`` additionally attributes each compiled
entry point's XLA-measured FLOPs/compile-time/HBM (the report's
``programs`` table and per-span measured MFU). The report CLI
(``python -m dask_ml_tpu.observability.report``) aggregates it;
``--perfetto`` converts the span tree for ``ui.perfetto.dev``. Unset,
the whole subsystem is a no-op — nothing is traced into jitted code.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import tempfile

import numpy as np

from dask_ml_tpu import config
from dask_ml_tpu.cluster import KMeans
from dask_ml_tpu.linear_model import LogisticRegression
from dask_ml_tpu.observability import (MetricsLogger, log_counters,
                                       log_programs, programs_reset)
from dask_ml_tpu.observability.report import main as report_main

n, d = int(os.environ.get("DASK_ML_TPU_EXAMPLE_N", 50_000)), 16
rng = np.random.RandomState(0)
X = np.concatenate([
    rng.randn(n // 4, d).astype(np.float32) + 3.0 * i for i in range(4)
])
y = (X[:, 0] > X[:, 1]).astype(np.float32)

path = os.path.join(tempfile.mkdtemp(), "metrics.jsonl")
programs_reset()
with config.set(metrics_path=path, obs_programs=True):
    # resident fit: per-iteration Lloyd telemetry out of the jitted loop
    KMeans(n_clusters=4, init="random", random_state=0, max_iter=20).fit(X)
    # streamed fit: stream.pass spans nest under the fit span and carry
    # host<->device transfer bytes + program-FLOP counter deltas
    with config.set(metrics_path=path, obs_programs=True,
                    stream_block_rows=max(len(X) // 8, 1)):
        LogisticRegression(solver="lbfgs", max_iter=30).fit(X, y)
    with MetricsLogger(path) as lg:
        log_counters(lg)   # run totals: recompiles, h2d bytes, memory
        log_programs(lg)   # program registry + the resolved peak table

print(f"recorded {sum(1 for _ in open(path))} records -> {path}\n")
# same as: python -m dask_ml_tpu.observability.report <path>
report_main([path])

# Perfetto/Chrome trace of the same run (open in ui.perfetto.dev)
perfetto = path.replace(".jsonl", ".perfetto.json")
report_main([path, "--perfetto", perfetto])
