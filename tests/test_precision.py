"""ISSUE 8 precision ladder: fused Pallas streamed kernels (interpret
parity), the bf16 "auto" fit policy with its recorded f32 fallback and
per-estimator opt-out, the int8 weight-quantized serving flavor, the
zero-copy CPU staging path, and the dtype-alias config surface.

Tolerance notes: bf16 input rounding is ~0.4% relative, so bf16-vs-f32
fit parity is documented at ~1e-2 relative (matching
tests/test_bf16_policy.py); int8 weights add per-channel <=1/254
rounding, and the serving criterion is prediction agreement >= 99.5%
on a margin-bearing parity suite."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import dask_ml_tpu.config as config
from dask_ml_tpu import observability as obs

rng = np.random.RandomState(0)


# ---------------------------------------------------------------------------
# config: dtype aliases, auto policy, fallback recording
# ---------------------------------------------------------------------------

def test_mxu_dtype_aliases_and_auto():
    assert config.get_config().dtype == "auto"
    # auto on the CPU CI backend resolves to f32 (the recorded fallback)
    assert config.mxu_dtype() is None
    info = config.fit_dtype_info()
    assert info["fit_dtype"] == "float32"
    assert info["fit_dtype_source"].startswith("auto:")
    for alias in ("bfloat16", "bf16", "BF16"):
        with config.set(dtype=alias):
            assert config.mxu_dtype() is jnp.bfloat16
    for alias in ("float32", "f32", "fp32", "FP32"):
        with config.set(dtype=alias):
            assert config.mxu_dtype() is None
    # estimator override beats config
    with config.set(dtype="f32"):
        assert config.mxu_dtype("bf16") is jnp.bfloat16
        assert config.fit_dtype_info("bf16")["fit_dtype_source"] \
            == "estimator"


def test_mxu_dtype_rejects_typos_listing_spellings():
    with pytest.raises(ValueError) as ei:
        with config.set(dtype="b16"):
            config.mxu_dtype()
    msg = str(ei.value)
    for spelling in ("auto", "float32", "f32", "fp32", "bfloat16",
                     "bf16"):
        assert spelling in msg


# ---------------------------------------------------------------------------
# fused Pallas streamed kernels: interpret-mode parity vs XLA flavors
# ---------------------------------------------------------------------------

def _sb_fixture(K=3, S=256, d=8):
    r = np.random.RandomState(7)
    Xs = jnp.asarray(r.randn(K, S, d).astype(np.float32))
    ys = jnp.asarray((r.rand(K, S) > 0.5).astype(np.float32))
    counts = jnp.asarray([S, S - 56, 0], jnp.int32)  # ragged + padding
    return Xs, ys, counts


@pytest.mark.parametrize("loss", ["log_loss", "hinge", "squared_error"])
def test_pallas_sgd_scan_matches_xla(loss):
    from dask_ml_tpu.models.sgd import _sgd_sb_scan, _sgd_sb_scan_pallas

    Xs, ys, counts = _sb_fixture()
    K, _, d = Xs.shape
    lrs = jnp.full((K,), 0.05, jnp.float32)
    w0 = jnp.asarray(np.random.RandomState(1)
                     .randn(d + 1).astype(np.float32) * 0.1)
    args = (counts, lrs, jnp.float32(1e-3), jnp.float32(0.7),
            jnp.float32(0.3), jnp.float32(1.0))
    Wx, lx = _sgd_sb_scan(jnp.array(w0), Xs, ys, *args, loss, None)
    Wp, lp = _sgd_sb_scan_pallas(jnp.array(w0), Xs, ys, *args, loss,
                                 interpret=True)
    np.testing.assert_allclose(Wp, Wx, atol=1e-5)
    np.testing.assert_allclose(lp, lx, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("kind", ["val", "vg", "vgh"])
@pytest.mark.parametrize("intercept", [True, False])
def test_pallas_glm_reducer_matches_xla(kind, intercept):
    from dask_ml_tpu.models.solvers.streamed import _sb_reducer

    Xs, ys, counts = _sb_fixture()
    d = Xs.shape[2]
    p = d + (1 if intercept else 0)
    beta = jnp.asarray(np.random.RandomState(2)
                       .randn(p).astype(np.float32) * 0.1)
    init = [jnp.zeros((), jnp.float32)]
    if kind != "val":
        init.append(jnp.zeros(p, jnp.float32))
    if kind == "vgh":
        init.append(jnp.zeros((p, p), jnp.float32))
    xla = _sb_reducer(kind, "logistic", intercept, 0)
    pal = _sb_reducer(kind, "logistic", intercept, 0, fused=True,
                      interpret=True)
    ax = xla(tuple(jnp.array(a) for a in init), beta, Xs, ys, counts)
    ap = pal(tuple(jnp.array(a) for a in init), beta, Xs, ys, counts)
    for got, want in zip(ap, ax):
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=2e-5)


def test_pallas_kmeans_stream_matches_xla():
    from dask_ml_tpu.models.kmeans import (_sb_assign_stats,
                                           _sb_assign_stats_pallas)

    Xs, _, counts = _sb_fixture()
    d = Xs.shape[2]
    C = jnp.asarray(np.random.RandomState(3)
                    .randn(4, d).astype(np.float32))

    def acc0():
        return (jnp.zeros((4, d), jnp.float32),
                jnp.zeros((4,), jnp.float32),
                jnp.zeros((), jnp.float32))

    ax = _sb_assign_stats(acc0(), Xs, counts, C)
    ap = _sb_assign_stats_pallas(acc0(), Xs, counts, C, interpret=True)
    for got, want in zip(ap, ax):
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_stream_tile_gate():
    """The fused kernels refuse non-128-multiple block heights (they
    cannot pad inside the scan) and overwide designs; the flavor
    selectors then keep the XLA programs."""
    from dask_ml_tpu.ops.pallas_fused import (
        glm_stream_tile, kmeans_stream_tile, sgd_stream_tile,
    )

    assert sgd_stream_tile(256, 8) == 256
    assert sgd_stream_tile(12500, 128) is None      # not a 128-multiple
    assert sgd_stream_tile(512 * 1024, 128) is not None
    assert glm_stream_tile(256, 8, "vgh") == 256
    assert glm_stream_tile(250, 8, "vg") is None
    assert kmeans_stream_tile(256, 8, 4) == 256
    # a design too wide for even a 128-row tile falls back
    assert sgd_stream_tile(128, 3_000_000) is None


def test_xla_flavor_selected_and_unchanged_on_cpu():
    """Zero-overhead contract (ISSUE 8): off-TPU (and with
    pallas_stream off anywhere) the streamed programs are the plain XLA
    flavors — no pallas call, no bf16 casts — so the jaxpr is
    byte-identical to the pre-feature one."""
    from dask_ml_tpu.models.sgd import SGDClassifier, _sgd_sb_scan
    from dask_ml_tpu.observability._programs import unwrap
    from dask_ml_tpu.ops.pallas_fused import use_stream_kernels

    assert jax.default_backend() == "cpu"
    assert not use_stream_kernels()         # backend gate, knob on
    with config.set(pallas_stream=False):
        assert not use_stream_kernels()

    body = unwrap(_sgd_sb_scan)
    K, S, d = 2, 8, 3
    jaxpr = str(jax.make_jaxpr(
        lambda W, Xs, ys, c, lrs: body(
            W, Xs, ys, c, lrs, 1e-4, 1.0, 0.0, 1.0, "log_loss", None
        )
    )(jnp.zeros(d + 1), jnp.zeros((K, S, d)), jnp.zeros((K, S)),
      jnp.zeros(K, jnp.int32), jnp.zeros(K)))
    assert "bf16" not in jaxpr and "pallas" not in jaxpr

    # the estimator-level selector picks the XLA program on this backend
    # and says why the fused flavor was gated off
    class _FakeSB:
        arrays = (jnp.zeros((2, 256, 8)), jnp.zeros((2, 256)))
        counts = jnp.zeros(2, jnp.int32)
        shard_counts = None

    clf = SGDClassifier()
    fused, mxu, interp, reason = clf._sb_scan_flavor(_FakeSB())
    assert not fused and mxu is None and reason == "off-TPU"
    with config.set(pallas_stream=False):
        assert clf._sb_scan_flavor(_FakeSB())[3] == "pallas-stream-off"


# ---------------------------------------------------------------------------
# bf16 fit parity + opt-out + recorded fallback
# ---------------------------------------------------------------------------

def _margin_data(n=6000, d=16, seed=5):
    r = np.random.RandomState(seed)
    X = r.randn(n, d).astype(np.float32)
    w = r.randn(d).astype(np.float32)
    y = (X @ w + 0.5 * r.randn(n) > 0).astype(np.float32)
    return X, y


def _clipped_log_loss(y, proba):
    p = np.clip(np.asarray(proba)[:, 1], 1e-7, 1 - 1e-7)
    return float(-np.mean(y * np.log(p) + (1 - y) * np.log(1 - p)))


def test_logreg_bf16_parity_loss_and_predictions():
    from dask_ml_tpu.linear_model import LogisticRegression

    X, y = _margin_data()
    f32 = LogisticRegression(solver="lbfgs", max_iter=40).fit(X, y)
    with config.set(dtype="bf16"):
        b16 = LogisticRegression(solver="lbfgs", max_iter=40).fit(X, y)
    assert f32.fit_dtype_ == "float32"
    assert b16.fit_dtype_ == "bfloat16"
    # prediction agreement + loss gap within the documented bf16 band
    assert np.mean(b16.predict(X) == f32.predict(X)) >= 0.995
    l32 = _clipped_log_loss(y, f32.predict_proba(X))
    l16 = _clipped_log_loss(y, b16.predict_proba(X))
    assert abs(l16 - l32) <= 2e-2 * max(l32, 1e-6)


def test_streamed_sgd_bf16_parity_and_optout():
    from dask_ml_tpu.models.sgd import SGDClassifier

    X, y = _margin_data(n=4096, d=8)
    with config.set(stream_block_rows=512):
        f32 = SGDClassifier(max_iter=3, random_state=0,
                            shuffle=False).fit(X, y)
        with config.set(dtype="bfloat16"):
            b16 = SGDClassifier(max_iter=3, random_state=0,
                                shuffle=False).fit(X, y)
            # per-estimator opt-out wins over the config policy
            opt = SGDClassifier(max_iter=3, random_state=0,
                                shuffle=False,
                                fit_dtype="fp32").fit(X, y)
    assert b16.fit_dtype_ == "bfloat16"
    assert opt.fit_dtype_ == "float32"
    np.testing.assert_array_equal(opt.coef_, f32.coef_)
    assert np.mean(b16.predict(X) == f32.predict(X)) >= 0.99
    np.testing.assert_allclose(b16.coef_, f32.coef_, rtol=3e-2,
                               atol=3e-2)
    assert abs(float(b16._last_loss) - float(f32._last_loss)) \
        <= 2e-2 * max(float(f32._last_loss), 1e-6)


def test_streamed_glm_records_f32_fallback_in_info():
    from dask_ml_tpu.linear_model import LogisticRegression

    X, y = _margin_data(n=4096, d=8)
    with config.set(stream_block_rows=512, dtype="bfloat16"):
        st = LogisticRegression(solver="lbfgs", max_iter=10).fit(X, y)
    # streamed XLA reducers are f32-only; the bf16 request must be
    # recorded as fallen back, not silently honored
    assert st.solver_info_["fit_dtype"] == "float32"
    assert st.solver_info_["fit_dtype_source"] == "streamed-xla"
    assert st.solver_info_["fused_stream"] is False
    assert st.fit_dtype_ == "float32"


# ---------------------------------------------------------------------------
# int8 serving flavor
# ---------------------------------------------------------------------------

def test_int8_prediction_agreement_across_ladder():
    from dask_ml_tpu.linear_model import LogisticRegression
    from dask_ml_tpu.wrappers import compiled_batch_fn

    X, y = _margin_data(n=8000, d=24, seed=11)
    clf = LogisticRegression(solver="lbfgs", max_iter=40).fit(X, y)
    f32 = compiled_batch_fn(clf, "predict")
    q8 = compiled_batch_fn(clf, "predict", quantize="int8")
    assert q8.quantize == "int8" and f32.quantize is None
    agree = total = 0
    for bucket in (8, 16, 32, 64, 128, 256, 512):   # the ladder shapes
        blk = X[:bucket]
        agree += int(np.sum(f32(blk) == q8(blk)))
        total += bucket
    assert agree / total >= 0.995, agree / total
    # decision_function stays within the combined bf16+int8 band
    d32 = compiled_batch_fn(clf, "decision_function")(X)
    d8 = compiled_batch_fn(clf, "decision_function",
                           quantize="int8")(X)
    assert np.max(np.abs(d32 - d8)) <= 2e-2 * np.max(np.abs(d32))


def test_int8_multiclass_and_regression_and_proba_fallback():
    from dask_ml_tpu.linear_model import (LinearRegression,
                                          LogisticRegression)
    from dask_ml_tpu.wrappers import compiled_batch_fn

    r = np.random.RandomState(13)
    X = r.randn(6000, 12).astype(np.float32)
    ym = np.argmax(X[:, :3] + 0.2 * r.randn(6000, 3), axis=1)
    multi = LogisticRegression(solver="lbfgs", max_iter=40).fit(X, ym)
    q8 = compiled_batch_fn(multi, "predict", quantize="int8")
    assert np.mean(compiled_batch_fn(multi, "predict")(X) == q8(X)) \
        >= 0.995
    # predict_proba refuses the int8 flavor (stays higher precision)
    pp = compiled_batch_fn(multi, "predict_proba", quantize="int8")
    assert pp.quantize is None

    yr = (X @ r.randn(12).astype(np.float32)).astype(np.float32)
    reg = LinearRegression(solver="lbfgs", max_iter=40).fit(X, yr)
    p32 = compiled_batch_fn(reg, "predict")(X)
    p8 = compiled_batch_fn(reg, "predict", quantize="int8")(X)
    scale = np.max(np.abs(p32))
    assert np.max(np.abs(p32 - p8)) <= 2e-2 * scale

    # poisson predict passes eta through exp — it refuses the int8
    # flavor (error would amplify multiplicatively) and falls back
    from dask_ml_tpu.linear_model import PoissonRegression

    yc = np.round(np.exp(0.3 * X[:, 0] + 1.0)).astype(np.float32)
    poi = PoissonRegression(solver="lbfgs", max_iter=30).fit(X, yc)
    pq = compiled_batch_fn(poi, "predict", quantize="int8")
    assert pq.quantize is None


def test_int8_quantization_is_per_channel():
    from dask_ml_tpu.wrappers import _quantize_w

    W = np.array([[1.0, -2.0, 0.5], [100.0, 50.0, -200.0],
                  [0.0, 0.0, 0.0]], np.float32)
    Wq, scale = _quantize_w(W)
    assert Wq.dtype == np.int8
    np.testing.assert_allclose(scale,
                               [2.0 / 127, 200.0 / 127, 1.0])
    # dequantized weights land within half a quantization step of the
    # originals, PER CHANNEL (the step is scale[c])
    assert np.all(np.abs(Wq * scale[:, None] - W)
                  <= scale[:, None] / 2 + 1e-6)
    assert np.all(Wq[2] == 0)


def test_int8_hot_swap_round_trip_zero_compiles():
    """f32 -> int8 -> f32 through a warmed ModelServer with the int8
    flavor pre-built (config.serving_warm_flavors): every flip and
    every served batch after warmup mints ZERO XLA compiles."""
    from dask_ml_tpu.linear_model import LogisticRegression
    from dask_ml_tpu.serving import ModelServer

    X, y = _margin_data(n=4000, d=16, seed=17)
    v1 = LogisticRegression(solver="lbfgs", max_iter=30).fit(X, y)
    v2 = LogisticRegression(solver="lbfgs", max_iter=30,
                            C=0.3).fit(X, y)
    with config.set(serving_warm_flavors="int8", serving_min_batch=8,
                    serving_max_batch=64):
        srv = ModelServer(
            v1, methods=("predict", "decision_function", "predict_proba")
        ).warmup()
        obs.counters_reset()
        with srv:
            base = srv.predict(X[:200])
            srv.swap_model(v2, quantize="int8")
            p_int8 = srv.predict(X[:200])
            assert srv._active_flavor == "int8"
            # proba still serves (higher-precision fallback flavor)
            pr = np.asarray(
                srv.submit(X[:40], method="predict_proba").result()
            )
            srv.swap_model(v1)                      # back to f32
            p_back = srv.predict(X[:200])
        snap = obs.counters_snapshot()
    assert snap.get("recompiles", 0) == 0, snap
    assert np.mean(p_int8 == v2.predict(X[:200])) >= 0.99
    np.testing.assert_array_equal(p_back, base)
    assert pr.shape == (40, 2)


def test_int8_unwarmed_flavor_refuses_swap():
    from dask_ml_tpu.linear_model import LogisticRegression
    from dask_ml_tpu.serving import ModelServer
    from dask_ml_tpu.wrappers import ParamSwapError

    X, y = _margin_data(n=1000, d=8, seed=19)
    clf = LogisticRegression(solver="lbfgs", max_iter=20).fit(X, y)
    srv = ModelServer(clf)                  # no warm flavors configured
    with pytest.raises(ParamSwapError):
        srv.swap_model(clf, quantize="int8")
    # rebuild_model installs the new flavor on the paid path instead
    srv.rebuild_model(clf, quantize="int8")
    assert srv._active_flavor == "int8"
    assert srv._fns["predict"].quantize == "int8"


def test_registry_publish_quantize_reaches_server():
    from dask_ml_tpu.linear_model import LogisticRegression
    from dask_ml_tpu.serving import ModelServer
    from dask_ml_tpu.serving.registry import ModelRegistry

    X, y = _margin_data(n=1000, d=8, seed=23)
    v1 = LogisticRegression(solver="lbfgs", max_iter=20).fit(X, y)
    v2 = LogisticRegression(solver="lbfgs", max_iter=20,
                            C=0.5).fit(X, y)
    with config.set(serving_warm_flavors="int8"):
        srv = ModelServer(v1).warmup()
        regy = ModelRegistry(keep=4)

        def on_publish(mv):
            srv.swap_model(mv.estimator, version=mv.version,
                           quantize=mv.quantize)

        regy.subscribe("m", on_publish)
        obs.counters_reset()
        regy.publish("m", v2, quantize="int8")
        assert srv._active_flavor == "int8"
        assert srv.model_version == regy.current_version("m")
        regy.publish("m", v1)                       # back to f32
        assert srv._active_flavor == ""
        assert obs.counters_snapshot().get("recompiles", 0) == 0
        assert regy.get("m", 1).quantize == "int8"
        snap = regy.status_snapshot()["m"]
        assert snap["quantize"] is None             # current is v2/f32


# ---------------------------------------------------------------------------
# zero-copy CPU staging
# ---------------------------------------------------------------------------

def _one_device_mesh():
    from dask_ml_tpu.parallel.mesh import device_mesh

    return device_mesh(devices=[jax.devices()[0]])


def test_zero_copy_staging_parity_and_counters(tmp_path):
    """On a single-device CPU mesh, aligned full dense blocks stage as
    dlpack ALIASES (zero_copy_bytes counts them; h2d_bytes drops to the
    leftovers) and the fit is bit-identical to the copying path."""
    from dask_ml_tpu.models.sgd import SGDClassifier
    from dask_ml_tpu.parallel.mesh import use_mesh

    n, d = 4096, 16
    path = str(tmp_path / "x.f32")
    mm = np.memmap(path, dtype=np.float32, mode="w+", shape=(n, d))
    mm[:] = rng.randn(n, d)
    mm.flush()
    Xr = np.memmap(path, dtype=np.float32, mode="r", shape=(n, d))
    y = (np.asarray(Xr[:, 0]) > 0).astype(np.float32)

    def run(zc):
        with use_mesh(_one_device_mesh()), \
                config.set(stream_block_rows=512, stream_zero_copy=zc):
            obs.counters_reset()
            clf = SGDClassifier(max_iter=2, random_state=0,
                                shuffle=False).fit(Xr, y)
            return clf, obs.counters_snapshot()

    on, snap_on = run(True)
    off, snap_off = run(False)
    np.testing.assert_array_equal(on.coef_, off.coef_)
    assert snap_on.get("zero_copy_bytes", 0) > 0
    assert snap_off.get("zero_copy_bytes", 0) == 0
    # the aliased bytes were real copies on the off path
    assert snap_on.get("h2d_bytes", 0) < snap_off.get("h2d_bytes", 1)


def test_zero_copy_alias_reads_source_memory():
    """The imported block really is an alias of host memory (no copy):
    64-byte-aligned writeable arrays round-trip a mutation."""
    from dask_ml_tpu.parallel.streaming import _ZC_ALIGN, _dlpack_alias

    raw = np.zeros(1024 + _ZC_ALIGN, np.float32)
    off = (-raw.ctypes.data) % (_ZC_ALIGN * 4)
    a = raw[off // 4: off // 4 + 256].reshape(16, 16)
    if a.ctypes.data % _ZC_ALIGN:
        pytest.skip("could not build an aligned view")
    dev = _dlpack_alias(a)
    if dev is None:
        pytest.skip("backend refuses dlpack import")
    jax.block_until_ready(dev)
    a[0, 0] = 42.0
    assert float(np.asarray(dev)[0, 0]) == 42.0
    # readonly sources (mode="r" memmaps) import through the writeable
    # re-wrap — same memory, still zero-copy. Reuse the SAME aligned
    # buffer: a fresh numpy allocation has no alignment guarantee, and
    # an unaligned copy would (correctly) refuse the zero-copy path
    a.flags.writeable = False
    try:
        dev2 = _dlpack_alias(a)
        assert dev2 is not None
        np.testing.assert_array_equal(np.asarray(dev2), a)
    finally:
        a.flags.writeable = True


def test_zero_copy_disabled_on_multi_device_mesh():
    from dask_ml_tpu.parallel.streaming import BlockStream

    X = rng.randn(1024, 8).astype(np.float32)
    s = BlockStream((X,), block_rows=256)       # conftest: 8-dev mesh
    assert s._zero_copy is False
    from dask_ml_tpu.parallel.mesh import use_mesh

    with use_mesh(_one_device_mesh()):
        s1 = BlockStream((X,), block_rows=256)
        assert s1._zero_copy is True
        with config.set(stream_zero_copy=False):
            s2 = BlockStream((X,), block_rows=256)
            assert s2._zero_copy is False
