"""Incident-plane verify gate (ISSUE 20): an injected SLO breach in a
SUBPROCESS serving fleet must close the detect -> snapshot -> artifact
loop.

The parent launches a child that fits a model, starts a ModelServer
(live exporter on a free port), warms it up, THEN arms the alert
engine (`serving_slo_violations:rate>2/2s` + incident capture) — so
warmup compiles can never count — and drives a breach through an armed
``fault_plan`` (``serving_execute:hang@...``) while holding a span
open. The parent asserts:

- ``/alerts`` shows the SLO rule transitioning firing -> resolved once
  the breach subsides (hysteresis: two clean ticks);
- EXACTLY ONE rate-limited incident bundle lands under the incident
  dir, containing the open-span stack (the breach span), non-empty
  counter + histogram snapshots, and the programs table;
- a second capture attempt inside the rate-limit window returns None
  and bumps ``incidents_rate_limited_total``;
- ZERO post-warmup XLA compiles (the child compares the ``recompiles``
  counter across the breach, and ``builtin:recompiles`` never fires);
- ``POST /profile`` answers the documented no-op-with-reason off-TPU;
- a SEPARATE child SIGKILLed mid-capture-loop never publishes a
  truncated bundle (the save_host atomic-publish contract): every
  ``incident_*.json`` on disk parses.

Prints one JSON line: {"ok": true, "bundles": 1, ...}.
Run: ``python scripts/incident_smoke.py`` (exit 0 = gate holds).
"""

import json
import os
import re
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CHILD = r"""
import json, os, time
import numpy as np
from dask_ml_tpu import config
from dask_ml_tpu.datasets import make_classification
from dask_ml_tpu.linear_model import LogisticRegression
from dask_ml_tpu.observability import alerts, incidents, span
from dask_ml_tpu.observability._counters import counters_snapshot
from dask_ml_tpu.serving import BucketLadder, ModelServer

IDIR = os.environ["INCIDENT_SMOKE_DIR"]
RESULT = os.environ["INCIDENT_SMOKE_RESULT"]

Xs, ys = make_classification(
    n_samples=300, n_features=6, n_informative=4, random_state=0
)
clf = LogisticRegression(solver="lbfgs", max_iter=20).fit(Xs, ys)
Xh = Xs.to_numpy().astype(np.float32)

# the fault plan and SLO are captured at SERVER CONSTRUCTION (the
# worker thread re-applies the creator's config): invocations 0-2 of
# serving_execute run clean, 3-8 hang 0.2s each — far past the 50ms SLO
with config.set(serving_slo_ms=50.0,
                fault_plan="serving_execute:hang@3*6/0.2"):
    with ModelServer(clf, ladder=BucketLadder(8, 64, 2.0)) as srv:
        srv.warmup()
        for i in range(3):          # clean phase: invocations 0-2
            srv.submit(Xh[: 4 + i]).result(30)
        # arm the plane AFTER warmup + clean traffic: the recompiles
        # baseline sample excludes every warmup compile by construction
        with config.set(
            obs_alert_rules="serving_slo_violations:rate>2/2s",
            incident_dir=IDIR,
            obs_alert_interval_s=0.2,
        ):
            assert alerts.ensure_engine() is not None
            time.sleep(0.5)         # ticker takes its baseline samples
            compiles_base = counters_snapshot().get("recompiles", 0)
            with span("incident_smoke.breach"):
                for i in range(6):  # invocations 3-8: the breach
                    srv.submit(Xh[: 4 + i]).result(30)
                # hold the span open across >=2 tick intervals so the
                # firing-triggered capture freezes it mid-breach
                time.sleep(1.0)
            for i in range(4):      # clean again: the rule must resolve
                srv.submit(Xh[: 4 + i]).result(30)
            compiles_end = counters_snapshot().get("recompiles", 0)
            # second capture inside the 30s rate-limit window: must be
            # refused (None) and counted, not written
            second = incidents.capture_incident("smoke-second-attempt")
            tmp = RESULT + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"compiles_base": compiles_base,
                           "compiles_end": compiles_end,
                           "second_capture": second}, f)
            os.replace(tmp, RESULT)
            # linger armed: the parent still needs /alerts to show the
            # resolve transition and /profile to answer
            time.sleep(float(os.environ.get("INCIDENT_SMOKE_LINGER",
                                            "60")))
"""

KILL_CHILD = r"""
import os, time
from dask_ml_tpu import config
from dask_ml_tpu.observability import incidents

with config.set(incident_dir=os.environ["INCIDENT_SMOKE_DIR"],
                incident_keep=8):
    # first bundle lands before READY so the parent's SIGKILL always
    # interrupts a LATER write, never an empty dir
    incidents.capture_incident("kill-test-first", force=True)
    print("READY", flush=True)
    while True:
        incidents.capture_incident("kill-test", force=True)
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _get(url, timeout=3.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, resp.read().decode()


def _post(url, timeout=5.0):
    req = urllib.request.Request(url, data=b"", method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def _wait_dead_or(child, deadline, what):
    if child.poll() is not None or time.time() > deadline:
        if child.poll() is None:
            child.kill()
            child.wait(10)
        raise RuntimeError(
            f"child exited or deadline passed before {what}: "
            + child.stderr.read().decode()[-2000:]
        )
    time.sleep(0.05)


def main():
    out = {"ok": False}
    port = _free_port()
    workdir = tempfile.mkdtemp(prefix="incident_smoke_")
    idir = os.path.join(workdir, "incidents")
    result_path = os.path.join(workdir, "child_result.json")
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "DASK_ML_TPU_OBS_HTTP_PORT": str(port),
           # the bundle must freeze a NON-EMPTY programs table
           "DASK_ML_TPU_OBS_PROGRAMS": "1",
           "INCIDENT_SMOKE_DIR": idir,
           "INCIDENT_SMOKE_RESULT": result_path}
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    child = subprocess.Popen(
        [sys.executable, "-c", CHILD], env=env, cwd=repo,
        stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
    )
    base = f"http://127.0.0.1:{port}"
    deadline = time.time() + 150
    try:
        # 1) exporter up
        while True:
            try:
                status, body = _get(base + "/healthz")
                assert status == 200 and body == "ok\n"
                break
            except AssertionError:
                raise
            except Exception:
                _wait_dead_or(child, deadline, "/healthz answered")
        # 2) the SLO rule fires on /alerts during the injected breach
        rule_name = None
        while True:
            try:
                _, body = _get(base + "/alerts")
                doc = json.loads(body)
                firing = [r for r in doc.get("firing", [])
                          if "serving_slo_violations" in r]
                if firing:
                    rule_name = firing[0]
                    break
            except (OSError, ValueError):
                pass
            _wait_dead_or(child, deadline, "/alerts showed firing")
        # 3) ... and resolves once the breach subsides (hysteresis)
        while True:
            try:
                _, body = _get(base + "/alerts")
                doc = json.loads(body)
                states = [t.get("state") for t in
                          doc.get("transitions", [])
                          if t.get("rule") == rule_name]
                if "resolved" in states and rule_name \
                        not in doc.get("firing", []):
                    break
            except (OSError, ValueError):
                pass
            _wait_dead_or(child, deadline, "/alerts showed resolved")
        assert "firing" in states, states
        # post-warmup recompile tripwire never fired
        fired_rules = {t.get("rule") for t in doc.get("transitions", [])}
        assert "builtin:recompiles" not in fired_rules, fired_rules
        # 4) child-side verdicts: zero post-warmup compiles, second
        #    capture refused by the rate limit
        while not os.path.exists(result_path):
            _wait_dead_or(child, deadline, "child wrote its result")
        with open(result_path) as f:
            res = json.load(f)
        assert res["compiles_base"] == res["compiles_end"], res
        assert res["second_capture"] is None, res
        # 5) EXACTLY ONE bundle, holding the promised context
        bundles = sorted(n for n in os.listdir(idir)
                         if n.startswith("incident_")
                         and n.endswith(".json"))
        assert len(bundles) == 1, bundles
        with open(os.path.join(idir, bundles[0])) as f:
            bundle = json.load(f)
        assert bundle["reason"] == f"alert:{rule_name}", bundle["reason"]
        open_names = {s.get("span") for s in bundle["open_spans"]}
        assert "incident_smoke.breach" in open_names, open_names
        assert bundle["counters"].get("serving_slo_violations"), \
            "no slo violations in the frozen counter snapshot"
        assert isinstance(bundle["histograms"], dict) \
            and bundle["histograms"], "empty histogram snapshot"
        assert isinstance(bundle["programs"], list) \
            and bundle["programs"], "empty programs table"
        assert bundle["config"]["fingerprint"], "missing config print"
        # 6) the capture/rate-limit counters made /metrics
        _, text = _get(base + "/metrics")
        for fam, low in (("incidents_captured", 1),
                         ("incidents_rate_limited", 1),
                         ("alerts_fired", 1)):
            m = re.search(rf"^dask_ml_tpu_{fam}_total (\d+)", text,
                          re.MULTILINE)
            assert m and int(m.group(1)) >= low, (fam, text[-500:])
        # 7) POST /profile: documented no-op-with-reason off-TPU
        code, body = _post(base + "/profile?seconds=1")
        pdoc = json.loads(body)
        assert code == 400 and pdoc["profiled"] is False \
            and "TPU" in pdoc.get("reason", ""), (code, pdoc)
        out.update(
            bundles=len(bundles), rule=rule_name,
            open_spans=len(bundle["open_spans"]),
            programs=len(bundle["programs"]),
            profile_reason=pdoc["reason"][:60],
        )
    except Exception as exc:
        out["error"] = f"{type(exc).__name__}: {exc}"
        print(json.dumps(out))
        child.terminate()
        return 1
    finally:
        child.terminate()
        try:
            child.wait(10)
        except Exception:
            child.kill()

    # 8) atomic-publish contract: SIGKILL a child mid-capture-loop,
    #    then every PUBLISHED bundle must still parse
    kdir = os.path.join(workdir, "kill_incidents")
    kenv = {**os.environ, "JAX_PLATFORMS": "cpu",
            "INCIDENT_SMOKE_DIR": kdir}
    kchild = subprocess.Popen(
        [sys.executable, "-c", KILL_CHILD], env=kenv, cwd=repo,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
    )
    try:
        line = kchild.stdout.readline().decode()
        assert line.strip() == "READY", (line,
                                         kchild.stderr.read()
                                         .decode()[-2000:])
        time.sleep(1.0)             # let the capture loop spin
        os.kill(kchild.pid, signal.SIGKILL)
        kchild.wait(10)
        published = [n for n in os.listdir(kdir)
                     if n.startswith("incident_")
                     and n.endswith(".json")]
        assert published, "kill child published no bundles"
        for n in published:
            with open(os.path.join(kdir, n)) as f:
                b = json.load(f)    # truncated JSON raises here
            assert b.get("incident") == 1, n
        out.update(ok=True, killed_bundles=len(published), port=port)
    except Exception as exc:
        out["error"] = f"{type(exc).__name__}: {exc}"
    finally:
        if kchild.poll() is None:
            kchild.kill()
        shutil.rmtree(workdir, ignore_errors=True)
    print(json.dumps(out))
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
