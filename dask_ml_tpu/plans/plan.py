"""ProgramPlan: the one build path for every compiled specialization.

Every jitted hot path in this repo used to hand-assemble the same four
things at its own call site: ``jax.jit`` flags (donation, statics),
``track_program`` registration, ``config.compile_cache_dir`` arming, and
some ad-hoc warmup bookkeeping. A :class:`ProgramPlan` is the
declarative spec — callable body, donation slots, static axes, a cache
key carrying everything the traced program's identity depends on (mesh,
dtype/mxu, parameter shapes, ladder rung), a program name and a ladder
reference — and :meth:`ProgramPlan.build` is the ONE path that turns it
into a tracked jitted entry point:

1. ``config.compile_cache_dir`` is armed (idempotent, no-op when
   unset) so every plan-built program lands in jax's persistent cache;
2. the process-wide build cache is consulted (``config.plan_cache``):
   two builds of an identical spec return the SAME tracked callable,
   so the second client's warmup hits warm jit caches instead of
   re-tracing — counted as ``plan_cache_hits``;
3. on a miss the body is jitted with exactly the declared donation /
   static flags and wrapped in ``track_program`` — the jaxpr is
   byte-identical to a hand-assembled
   ``track_program(name)(jax.jit(body, ...))`` because it IS that
   call — and the plan registers in the attribution registry so the
   report CLI / ``/status`` can name the plan (and ladder rung) that
   minted any specialization.

Pre-jitted program builders (the super-block scan flavors, which carry
their own ``lru_cache`` build caches keyed on mesh/dtype/fusion) route
through :func:`tracked` instead: same ``track_program`` wrapper, same
attribution registry, scan bodies untouched.
"""

from __future__ import annotations

import dataclasses
import itertools
import threading

__all__ = ["ProgramPlan", "tracked", "register_attr", "note_rung",
           "annotate_programs", "plans_snapshot", "plans_reset"]

_lock = threading.Lock()
# insertion-ordered build cache with a hard cap: a long-running process
# churning through many differently-shaped models must not pin every
# historical entry point (and its compiled executables) forever — past
# the cap the OLDEST spec is evicted (an evicted fn stays alive wherever
# a CompiledBatchFn still holds it; an identical later build just pays
# its compiles again)
_BUILD_CACHE: dict = {}
_BUILD_CACHE_MAX = 256
_tokens = itertools.count(1)

# attribution registry: program name -> {group, ladder, rungs, mesh}
# (which plan family owns a program, which shape ladder feeds it, which
# rungs have minted specializations so far, and — for the sharded
# super-block flavors — the "DxM" mesh the program runs over)
_ATTR: dict = {}


def register_attr(name: str, group: str = "plan",
                  ladder: str | None = None,
                  mesh: str | None = None) -> None:
    with _lock:
        e = _ATTR.get(name)
        if e is None:
            _ATTR[name] = {"group": group, "ladder": ladder,
                           "mesh": mesh, "rungs": set()}
        else:
            if group:
                e["group"] = group
            if ladder:
                e["ladder"] = ladder
            if mesh:
                e["mesh"] = mesh


def note_rung(name: str, rung) -> None:
    """Record that ``rung`` of ``name``'s ladder minted (or warmed) a
    specialization — the report CLI's ladder:rung attribution."""
    if name is None or rung is None:
        return
    with _lock:
        e = _ATTR.setdefault(name, {"group": "plan", "ladder": None,
                                    "rungs": set()})
        e["rungs"].add(int(rung))


def _ladder_rung_str(e: dict) -> str | None:
    if not e.get("ladder"):
        return None
    rungs = sorted(e.get("rungs") or ())
    if rungs:
        return f"{e['ladder']}:{','.join(str(r) for r in rungs)}"
    return str(e["ladder"])


def annotate_programs(rows) -> None:
    """Stamp plan attribution onto program-registry snapshot rows (the
    ``plan`` column the report CLI renders): the owning plan group, and
    ``ladder:rung`` when a shape ladder fed the program."""
    with _lock:
        attr = {k: dict(v, rungs=set(v["rungs"])) for k, v in
                _ATTR.items()}
    for row in rows:
        e = attr.get(row.get("program"))
        if e is None:
            continue
        row["plan"] = e["group"]
        lr = _ladder_rung_str(e)
        if lr:
            row["ladder_rung"] = lr
        if e.get("mesh"):
            # sharded super-block programs carry the "DxM" mesh shape
            # they were built over (ISSUE 18) — the programs-table
            # mesh column
            row["mesh"] = e["mesh"]


def plans_snapshot() -> list:
    """One row per planned program: plan group, ladder, the rungs that
    minted specializations, and the warmup/cache-hit counts — the
    ``plans`` table on ``/status`` and in the report CLI."""
    from .warmup import warmups

    stats = warmups.stats_by_program()
    with _lock:
        names = sorted(_ATTR)
        attr = {k: dict(_ATTR[k], rungs=sorted(_ATTR[k]["rungs"]))
                for k in names}
    rows = []
    for name in names:
        e = attr[name]
        st = stats.get(name, {})
        rows.append({
            "program": name,
            "plan": e["group"],
            "ladder": e.get("ladder") or "-",
            "rungs": ",".join(str(r) for r in e["rungs"]) or "-",
            "warmups": int(st.get("warmups", 0)),
            "warm_hits": int(st.get("hits", 0)),
        })
    return rows


def plans_reset() -> None:
    from .warmup import warmups

    with _lock:
        _ATTR.clear()
        _BUILD_CACHE.clear()
    warmups.reset()


@dataclasses.dataclass
class ProgramPlan:
    """Declarative spec of one compiled program (see module docstring).

    ``key`` must carry everything the traced program's identity depends
    on beyond the body itself — parameter-shape signatures, mesh,
    dtype/mxu, ladder rung — because the build cache treats two plans
    with equal (name, key, donate, statics) as the same program. With
    ``key=None`` the body object itself keys the cache (right for
    module-level bodies, useless for per-call closures — pass an
    explicit key there).
    """

    name: str
    body: object
    donate: tuple = ()
    static_argnums: tuple = ()
    static_argnames: tuple = ()
    key: object = None
    ladder: str | None = None
    group: str = "plan"
    # "DxM" for sharded super-block programs (rendered by the report
    # CLI's programs table); None for mesh-free programs
    mesh: str | None = None

    def cache_key(self):
        key = self.key if self.key is not None else self.body
        try:
            return hash((self.name, key, tuple(self.donate),
                         tuple(self.static_argnums),
                         tuple(self.static_argnames))), \
                (self.name, key, tuple(self.donate),
                 tuple(self.static_argnums),
                 tuple(self.static_argnames))
        except TypeError:
            return None

    def build(self):
        """The tracked jitted entry point for this plan — see the
        module docstring for the one-path contract."""
        from ..config import ensure_compile_cache, get_config

        ensure_compile_cache()
        ck = self.cache_key()
        use_cache = bool(get_config().plan_cache) and ck is not None
        if use_cache:
            with _lock:
                hit = _BUILD_CACHE.get(ck[1])
            if hit is not None:
                from ..observability._counters import record_plan_build

                record_plan_build(cached=True)
                return hit
        import jax

        from ..observability import track_program
        from ..observability._counters import record_plan_build

        kw = {}
        if self.donate:
            kw["donate_argnums"] = tuple(self.donate)
        if self.static_argnums:
            kw["static_argnums"] = tuple(self.static_argnums)
        if self.static_argnames:
            kw["static_argnames"] = tuple(self.static_argnames)
        fn = track_program(self.name)(jax.jit(self.body, **kw))
        fn.plan_token = next(_tokens)
        fn.plan_name = self.name
        register_attr(self.name, group=self.group, ladder=self.ladder,
                      mesh=self.mesh)
        record_plan_build(cached=False)
        if use_cache:
            with _lock:
                _BUILD_CACHE.setdefault(ck[1], fn)
                while len(_BUILD_CACHE) > _BUILD_CACHE_MAX:
                    _BUILD_CACHE.pop(next(iter(_BUILD_CACHE)))
        return fn


def tracked(name, fn=None, *, group="superblock", ladder=None,
            mesh=None):
    """Route a pre-jitted program through the plan layer: registers the
    plan attribution and applies the SAME ``track_program`` wrapper a
    :class:`ProgramPlan` build would — the scan body and its jit flags
    stay exactly the caller's, so the jaxpr is untouched. Usable as a
    decorator (``@tracked("name")``) or a call (``tracked(name, run)``).
    ``mesh`` ("DxM") tags sharded programs for the report CLI.
    """
    if fn is None:
        return lambda f: tracked(name, f, group=group, ladder=ladder,
                                 mesh=mesh)
    from ..observability import track_program

    register_attr(name, group=group, ladder=ladder, mesh=mesh)
    out = track_program(name)(fn)
    out.plan_token = next(_tokens)
    out.plan_name = name
    return out
