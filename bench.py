"""Headline benchmark: LogisticRegression.fit throughput on device.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Metric: samples/sec/chip processed by the device-resident L-BFGS fit
(counting one full data pass per outer iteration — line-search passes are
not counted, so this undercounts true throughput). vs_baseline is the ratio
against scikit-learn's lbfgs LogisticRegression measured the same way on a
subsample on this host's CPU — the reference's per-block compute engine
(SURVEY.md §6: no published in-repo numbers; BASELINE.json configs[0]).

Data is generated ON DEVICE (jax.random) and stays there: the benchmark
measures the compute path, not the host→device tunnel.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# persistent compile cache: repeat driver runs skip the ~40s XLA compile
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"),
)

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    import dask_ml_tpu  # noqa: F401
    from dask_ml_tpu.linear_model import LogisticRegression
    from dask_ml_tpu.parallel import as_sharded

    n_chips = len(jax.devices())
    on_tpu = jax.default_backend() == "tpu"
    n_rows = 4_000_000 if on_tpu else 200_000
    n_feat = 256 if on_tpu else 64

    key = jax.random.PRNGKey(0)
    kb, kx, ky = jax.random.split(key, 3)
    beta_true = jax.random.normal(kb, (n_feat,)) / np.sqrt(n_feat)

    @jax.jit
    def gen():
        X = jax.random.normal(kx, (n_rows, n_feat), jnp.float32)
        p = jax.nn.sigmoid(X @ beta_true)
        y = (jax.random.uniform(ky, (n_rows,)) < p).astype(jnp.float32)
        return X, y

    X, y = jax.block_until_ready(gen())
    Xs, ys = as_sharded(X), as_sharded(y)

    max_iter = 50
    from dask_ml_tpu import config

    # bf16 design matrix on TPU: 1.5x MXU throughput, measured identical
    # converged coef error/score vs f32 on this problem (solver state and
    # accumulation stay f32)
    dtype = "bfloat16" if on_tpu else "float32"
    with config.set(dtype=dtype):
        # warm the compile cache AT FULL SHAPE (XLA programs are
        # shape-specialized) with a 1-iteration fit
        LogisticRegression(solver="lbfgs", max_iter=1, tol=0.0).fit(Xs, ys)

        t0 = time.perf_counter()
        clf = LogisticRegression(solver="lbfgs", max_iter=max_iter, tol=0.0)
        clf.fit(Xs, ys)
        elapsed = time.perf_counter() - t0
    iters = clf.n_iter_ or max_iter
    value = n_rows * iters / elapsed / n_chips

    # sklearn reference on a host subsample of the same data
    from sklearn.linear_model import LogisticRegression as SkLR

    sub = min(n_rows, 100_000)
    Xh = np.asarray(X[:sub])
    yh = np.asarray(y[:sub])
    sk = SkLR(solver="lbfgs", max_iter=max_iter, tol=0.0)
    t0 = time.perf_counter()
    sk.fit(Xh, yh)
    sk_elapsed = time.perf_counter() - t0
    sk_iters = int(np.max(sk.n_iter_)) or max_iter
    sk_value = sub * sk_iters / sk_elapsed

    print(json.dumps({
        "metric": "logreg_fit_samples_per_sec_per_chip",
        "value": round(value, 1),
        "unit": "samples/s/chip",
        "vs_baseline": round(value / sk_value, 3),
    }))


if __name__ == "__main__":
    main()
