"""Platform selection workaround for the axon TPU plugin.

The axon plugin IGNORES the ``JAX_PLATFORMS`` env var and can block
indefinitely during backend init when the tunnel is down, so forcing the
CPU platform needs both the env var (for subprocesses) and an explicit
``jax.config.update`` — and it must happen BEFORE anything touches a
backend. Shared by tests/conftest.py, __graft_entry__.py and bench.py so
the invariant lives in one place.
"""

import os
import re

_COUNT_FLAG = "xla_force_host_platform_device_count"


def force_cpu_platform(n_devices: int | None = None) -> None:
    """Force the CPU platform, optionally with at least ``n_devices``
    virtual devices. Must be called before any JAX backend is initialized —
    calling it later is a silent no-op on already-cached backends.

    An ambient ``--xla_force_host_platform_device_count`` in XLA_FLAGS is
    respected when it is >= n_devices and RAISED when it is smaller, so a
    caller that needs N devices actually gets N.
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    if n_devices is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        m = re.search(rf"--{_COUNT_FLAG}=(\d+)", flags)
        if m is None:
            os.environ["XLA_FLAGS"] = (
                flags + f" --{_COUNT_FLAG}={n_devices}"
            ).strip()
        elif int(m.group(1)) < n_devices:
            os.environ["XLA_FLAGS"] = flags.replace(
                m.group(0), f"--{_COUNT_FLAG}={n_devices}"
            )

    import jax

    jax.config.update("jax_platforms", "cpu")
