"""Model selection with device-resident scoring and threshold metrics.

A C-grid over a Pipeline(scaler -> LogisticRegression) runs as ONE
compiled solve per fold (the transformer prefix fits once per fold, all
candidates' coefficients solve jointly), scored by the device-resident
roc_auc scorer — no test fold ever leaves the device. The fitted model
then feeds the threshold-metric family (roc_curve, PR curve, average
precision), each one device sort + host f64 prefix sums.

Run anywhere: on a TPU VM this uses every chip; on CPU set
XLA_FLAGS=--xla_force_host_platform_device_count=8 for an 8-device mesh.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from sklearn.pipeline import Pipeline

N = int(os.environ.get("DASK_ML_TPU_EXAMPLE_N", 100_000))

from dask_ml_tpu import datasets, metrics
from dask_ml_tpu.linear_model import LogisticRegression
from dask_ml_tpu.model_selection import GridSearchCV, train_test_split
from dask_ml_tpu.preprocessing import StandardScaler

X, y = datasets.make_classification(
    n_samples=N, n_features=32, random_state=0
)
Xtr, Xte, ytr, yte = train_test_split(X, y, test_size=0.25, random_state=0)

search = GridSearchCV(
    Pipeline([
        ("scale", StandardScaler()),
        ("clf", LogisticRegression(solver="lbfgs", max_iter=100)),
    ]),
    {"clf__C": [0.01, 0.1, 1.0, 10.0]},
    cv=3,
    scoring="roc_auc",
)
search.fit(Xtr, ytr)
print(f"best C: {search.best_params_['clf__C']}, "
      f"cv roc_auc: {search.best_score_:.4f}, "
      f"candidates per compiled solve: "
      f"{getattr(search, '_c_grid_vmapped_', 1)}")

# threshold metrics on the held-out quarter, device-resident
scores = search.best_estimator_.decision_function(Xte)
auc = metrics.roc_auc_score(yte, scores)
ap = metrics.average_precision_score(yte, scores)
fpr, tpr, _ = metrics.roc_curve(yte, scores)
prec, rec, _ = metrics.precision_recall_curve(yte, scores)
print(f"test roc_auc: {auc:.4f}  average_precision: {ap:.4f}")
print(f"roc_curve: {len(fpr)} points, PR curve: {len(prec)} points")

assert 0.5 < auc <= 1.0 and 0.5 < ap <= 1.0
print("OK")
