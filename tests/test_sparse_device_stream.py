"""Device-resident sparse blocks (ISSUE 13): bucketed-nnz CSR through
the superblock scan, the data mesh, and the serving ladder.

Contracts under test, per the tentpole:

- the nnz-bucket ladder is deterministic (same corpus → same per-block
  rung sequence) and densify fallbacks are decided at PLAN time
  (over-density corpus, over-bucket-spill block) with reasons recorded;
- sparse-vs-dense parity 1e-6 for streamed GLM/SGD/KMeans on the same
  data/partition at mesh {1, 2} — per-pass sums for GLM (line-search
  trajectories amplify float dust), full-fit weights for SGD/KMeans;
- the superblock contract holds for sparse: one dispatch per
  super-block, zero XLA compiles after pass 1 (one capacity per fit —
  shuffling can't mint shapes), donation intact, and ``solver_info_``
  records the sparse flavor + fallback reason;
- ``config.stream_sparse`` off keeps today's per-block densify path
  (K == 1) and dense inputs are untouched either way;
- serving: the sparse (rows, nnz)-bucketed linear entry points agree
  with dense predict through a warmed grid at zero steady-state
  compiles, over-nnz batches spill to the warm densified rung.
"""

import numpy as np
import pytest
import scipy.sparse as sp

import jax.numpy as jnp

from dask_ml_tpu import config
from dask_ml_tpu import observability as obs
from dask_ml_tpu.parallel.streaming import BlockStream
from dask_ml_tpu.parallel.sparse_stream import (SparseSlab,
                                                plan_sparse_stream)


def _rand_csr(n, d, density=0.1, seed=0):
    rng = np.random.RandomState(seed)
    return sp.random(n, d, density=density, format="csr",
                     random_state=rng, dtype=np.float64)


def _xy(n=660, d=20, density=0.2, seed=3):
    Xs = _rand_csr(n, d, density=density, seed=seed)
    s = np.asarray(Xs.sum(axis=1)).ravel()
    y = (s > np.median(s)).astype(np.float64)
    return Xs, y


class TestPlanAndLadder:
    def test_bucket_sequence_deterministic(self):
        Xs, _ = _xy(500, 16)
        p1 = plan_sparse_stream(Xs, 96, 1, 0.5)
        p2 = plan_sparse_stream(Xs.copy(), 96, 1, 0.5)
        assert p1.block_buckets == p2.block_buckets
        assert p1.cap == p2.cap and p1.engaged
        # rungs are geometric: at most a handful of distinct shapes
        assert len(set(p1.block_buckets)) <= 4

    def test_over_density_falls_back(self):
        Xs, y = _xy(400, 8, density=0.9, seed=1)
        with config.set(stream_sparse=True, stream_mesh=1,
                        stream_block_rows=96):
            s = BlockStream((Xs, y.astype(np.float32)), block_rows=96)
            assert s.sparse_plan is None
            assert "density" in s.sparse_reason
            assert s.resolve_superblock_k() == 1  # today's densify path

    def test_over_bucket_spill_block_falls_back(self):
        # one near-dense block inside an otherwise sparse corpus
        Xs = _rand_csr(300, 16, density=0.02, seed=2).tolil()
        Xs[100:140, :] = 1.0
        Xs = Xs.tocsr()
        plan = plan_sparse_stream(Xs, 96, 1, 0.25)
        assert not plan.engaged
        assert "spill" in plan.reason

    def test_default_on_engages(self):
        # ROADMAP 4a (ISSUE 14 satellite): after the PR-13 parity suite
        # held a round, stream_sparse ships DEFAULT ON — a plain config
        # builds the staging plan with no knobs set
        Xs, y = _xy()
        assert config.get_config().stream_sparse is True
        with config.set(stream_mesh=1, stream_block_rows=96):
            s = BlockStream((Xs, y.astype(np.float32)), block_rows=96)
            assert s.sparse_plan is not None
            assert s.resolve_superblock_k() > 1

    def test_opt_out_keeps_densify_path(self):
        Xs, y = _xy()
        with config.set(stream_mesh=1, stream_block_rows=96,
                        stream_sparse=False):
            s = BlockStream((Xs, y.astype(np.float32)), block_rows=96)
            assert s.sparse_plan is None
            assert s.sparse_reason == "stream-sparse-off"
            assert s.resolve_superblock_k() == 1

    def test_normalizes_to_csr_once(self):
        # satellite: block loops normalize via as_row_sliceable ONCE —
        # the stream holds CSR, never re-converting per slice
        Xs, y = _xy()
        with config.set(stream_sparse=True, stream_mesh=1):
            s = BlockStream((Xs.tocsc(), y.astype(np.float32)),
                            block_rows=96)
            assert sp.isspmatrix_csr(s.arrays[0])
            assert s.sparse_plan is not None


class TestSparseStaging:
    @pytest.mark.parametrize("mesh_n", [1, 2])
    def test_staged_slabs_reconstruct_dense(self, mesh_n):
        # 660 rows / 96-row blocks: ragged tail block AND ragged final
        # super-block both exercised
        Xs, y = _xy(660, 12)
        dense = Xs.toarray().astype(np.float32)
        with config.set(stream_sparse=True, stream_mesh=mesh_n,
                        stream_block_rows=96, superblock_k=3):
            s = BlockStream((Xs, y.astype(np.float32)), block_rows=96)
            D = s.sb_data_shards()
            out = np.zeros_like(dense)
            bi = 0
            for sb in s.superblocks():
                slab = sb.arrays[0]
                assert isinstance(slab, SparseSlab)
                data = np.asarray(slab.data)
                cols = np.asarray(slab.cols)
                rows = np.asarray(slab.rows)
                cts = np.asarray(sb.counts)
                for j in range(sb.n_blocks):
                    blk = np.zeros((s.block_rows, Xs.shape[1]),
                                   np.float32)
                    for sh in range(D):
                        seg = slice(sh * slab.cap, (sh + 1) * slab.cap)
                        np.add.at(
                            blk,
                            (rows[j, seg] + sh * slab.n_rows,
                             cols[j, seg]),
                            data[j, seg],
                        )
                    lo = bi * s.block_rows
                    out[lo:lo + cts[j]] = blk[:cts[j]]
                    bi += 1
            np.testing.assert_allclose(out, dense, atol=1e-6)

    def test_dispatches_and_counters(self):
        Xs, y = _xy(660, 12)
        obs.counters_reset()
        with config.set(stream_sparse=True, stream_mesh=1,
                        stream_block_rows=96, superblock_k=3):
            s = BlockStream((Xs, y.astype(np.float32)), block_rows=96)
            n = sum(1 for _ in s.superblocks())
        assert n == 3 == s.stats["dispatches_per_pass"]
        snap = obs.counters_snapshot()
        assert snap.get("sparse_blocks_staged", 0) == s.n_blocks
        assert snap.get("sparse_nnz_staged", 0) == Xs.nnz

    def test_nonfinite_quarantine_and_raise(self):
        Xs, y = _xy(300, 10)
        Xbad = Xs.copy()
        Xbad.data[5] = np.nan
        from dask_ml_tpu.reliability.faults import NonFiniteBlock

        with config.set(stream_sparse=True, stream_mesh=1,
                        stream_nonfinite="raise"):
            s = BlockStream((Xbad, y.astype(np.float32)), block_rows=96)
            with pytest.raises(NonFiniteBlock):
                list(s.superblocks())
        with config.set(stream_sparse=True, stream_mesh=1,
                        stream_nonfinite="quarantine"):
            s = BlockStream((Xbad, y.astype(np.float32)), block_rows=96)
            counts = np.concatenate([
                np.asarray(sb.counts)[: sb.n_blocks]
                for sb in s.superblocks()
            ])
            assert counts[0] == 0               # poisoned block dropped
            assert (counts[1:] > 0).all()


class TestGLMParity:
    @pytest.mark.parametrize("mesh_n", [1, 2])
    def test_per_pass_sums_match_dense(self, mesh_n):
        from dask_ml_tpu.models.solvers.streamed import StreamedObjective

        Xs, y = _xy(660, 16)
        beta = np.random.RandomState(0).randn(17).astype(np.float64)

        def objective(src, sparse_on):
            with config.set(stream_sparse=sparse_on, stream_mesh=mesh_n,
                            stream_block_rows=96):
                stream = BlockStream((src, y.astype(np.float32)),
                                     block_rows=96)
                o = StreamedObjective(
                    stream, Xs.shape[0], jnp.asarray(0.1, jnp.float32),
                    jnp.ones(17), 0.5, "logistic", "l2", True,
                )
                v, g = o.value_and_grad(beta)
                vv, gg, h = o.value_and_grad_and_hess(beta)
            return v, g, h

        v_d, g_d, h_d = objective(Xs.toarray().astype(np.float32), False)
        v_s, g_s, h_s = objective(Xs, True)
        assert abs(v_d - v_s) <= 1e-6 * max(abs(v_d), 1.0)
        np.testing.assert_allclose(g_s, g_d, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(h_s, h_d, rtol=1e-5, atol=1e-6)

    def test_newton_fit_parity_and_info(self):
        from dask_ml_tpu.linear_model import LogisticRegression

        Xs, y = _xy(600, 14)
        with config.set(stream_block_rows=96, stream_mesh=1):
            ref = LogisticRegression(solver="newton", max_iter=8).fit(
                Xs.toarray(), y
            )
        with config.set(stream_block_rows=96, stream_mesh=1,
                        stream_sparse=True):
            got = LogisticRegression(solver="newton", max_iter=8).fit(
                Xs, y
            )
        np.testing.assert_allclose(got.coef_, ref.coef_, rtol=1e-5,
                                   atol=1e-6)
        info = got.solver_info_
        assert info["sparse_stream"] is True
        assert info["sparse_stream_reason"] is None
        assert info["fused_stream_reason"] == "sparse-stream"

    def test_fallback_reasons_recorded(self):
        from dask_ml_tpu.linear_model import LogisticRegression

        Xs, y = _xy(600, 14)
        # knob off: sparse_stream False, reason names the knob
        with config.set(stream_block_rows=96, stream_mesh=1,
                        stream_sparse=False):
            off = LogisticRegression(solver="lbfgs", max_iter=3).fit(
                Xs, y
            )
        assert off.solver_info_["sparse_stream"] is False
        assert off.solver_info_["sparse_stream_reason"] \
            == "stream-sparse-off"
        # admm keeps the per-block densify loop, reason on record
        with config.set(stream_block_rows=96, stream_mesh=1,
                        stream_sparse=True):
            adm = LogisticRegression(solver="admm", max_iter=3).fit(
                Xs, y
            )
        assert adm.solver_info_["sparse_stream"] is False
        assert adm.solver_info_["sparse_stream_reason"] \
            == "admm-local-newton"

    def test_dense_inputs_untouched_by_knob(self):
        from dask_ml_tpu.linear_model import LogisticRegression

        Xs, y = _xy(600, 14)
        Xd = Xs.toarray()
        with config.set(stream_block_rows=96, stream_mesh=1):
            a = LogisticRegression(solver="lbfgs", max_iter=5).fit(Xd, y)
        with config.set(stream_block_rows=96, stream_mesh=1,
                        stream_sparse=True):
            b = LogisticRegression(solver="lbfgs", max_iter=5).fit(Xd, y)
        np.testing.assert_array_equal(a.coef_, b.coef_)
        assert b.solver_info_["sparse_stream_reason"] == "dense-source"


class TestSGDParity:
    @pytest.mark.parametrize("mesh_n", [1, 2])
    def test_fit_parity(self, mesh_n):
        from dask_ml_tpu.models.sgd import SGDClassifier

        Xs, y = _xy(660, 18)
        kw = dict(loss="log_loss", random_state=0, shuffle=False,
                  max_iter=2)
        with config.set(stream_block_rows=96, stream_mesh=mesh_n,
                        stream_sparse=False):
            ref = SGDClassifier(**kw).fit(
                Xs.toarray().astype(np.float32), y
            )
        with config.set(stream_block_rows=96, stream_mesh=mesh_n,
                        stream_sparse=True):
            got = SGDClassifier(**kw).fit(Xs, y)
        np.testing.assert_allclose(got.coef_, ref.coef_, rtol=1e-6,
                                   atol=1e-6)
        assert got.solver_info_["sparse_stream"] is True

    # the default-flip soak shapes (ISSUE 14 satellite, ROADMAP 4a):
    # a NARROW-d wide-ish corpus at d=2**10 (the profile-fold boundary)
    # and a density right under the 0.25 fallback edge — the parity
    # suite must hold on them before stream_sparse ships default-ON
    @pytest.mark.parametrize("n,d,density", [
        (520, 2 ** 10, 0.05),
        (660, 24, 0.20),
    ])
    def test_fit_parity_flip_shapes(self, n, d, density):
        from dask_ml_tpu.models.sgd import SGDClassifier

        Xs, y = _xy(n, d, density=density, seed=11)
        kw = dict(loss="log_loss", random_state=0, shuffle=False,
                  max_iter=2)
        with config.set(stream_block_rows=96, stream_mesh=1,
                        stream_sparse=False):
            ref = SGDClassifier(**kw).fit(
                Xs.toarray().astype(np.float32), y
            )
        with config.set(stream_block_rows=96, stream_mesh=1):
            got = SGDClassifier(**kw).fit(Xs, y)  # default-ON path
        np.testing.assert_allclose(got.coef_, ref.coef_, rtol=1e-6,
                                   atol=1e-6)
        assert got.solver_info_["sparse_stream"] is True
        assert got.solver_info_["sparse_stream_reason"] is None

    @pytest.mark.parametrize("n,d,density", [
        (520, 2 ** 10, 0.05),
        (660, 24, 0.20),
    ])
    def test_glm_parity_flip_shapes(self, n, d, density):
        from dask_ml_tpu.linear_model import LogisticRegression

        Xs, y = _xy(n, d, density=density, seed=12)
        with config.set(stream_block_rows=96, stream_mesh=1,
                        stream_sparse=False):
            ref = LogisticRegression(solver="gradient_descent",
                                     max_iter=6).fit(
                Xs.toarray().astype(np.float32), y
            )
        with config.set(stream_block_rows=96, stream_mesh=1):
            got = LogisticRegression(solver="gradient_descent",
                                     max_iter=6).fit(Xs, y)
        np.testing.assert_allclose(got.coef_, ref.coef_, rtol=1e-5,
                                   atol=1e-6)
        assert got.solver_info_["sparse_stream"] is True

    def test_multiclass_and_shuffled(self):
        from dask_ml_tpu.models.sgd import SGDClassifier

        Xs, _ = _xy(660, 18)
        s = np.asarray(Xs.sum(axis=1)).ravel()
        y3 = ((s > np.percentile(s, 66)).astype(int)
              + (s > np.percentile(s, 33)).astype(int)).astype(float)
        kw = dict(loss="log_loss", random_state=7, shuffle=True,
                  max_iter=2)
        with config.set(stream_block_rows=96, stream_mesh=1):
            ref = SGDClassifier(**kw).fit(
                Xs.toarray().astype(np.float32), y3
            )
        with config.set(stream_block_rows=96, stream_mesh=1,
                        stream_sparse=True):
            got = SGDClassifier(**kw).fit(Xs, y3)
        np.testing.assert_allclose(got.coef_, ref.coef_, rtol=1e-6,
                                   atol=1e-6)

    def test_grad_accum_sparse_micro(self):
        from dask_ml_tpu.models.sgd import SGDClassifier

        Xs, y = _xy(480, 16)
        kw = dict(loss="log_loss", random_state=0, shuffle=False,
                  max_iter=2)
        with config.set(stream_block_rows=96, stream_mesh=1,
                        stream_grad_accum=2):
            ref = SGDClassifier(**kw).fit(
                Xs.toarray().astype(np.float32), y
            )
        with config.set(stream_block_rows=96, stream_mesh=1,
                        stream_grad_accum=2, stream_sparse=True):
            got = SGDClassifier(**kw).fit(Xs, y)
        np.testing.assert_allclose(got.coef_, ref.coef_, rtol=1e-6,
                                   atol=1e-6)
        assert got.solver_info_["sparse_stream"] is True
        assert got.solver_info_["grad_accum"] == 2

    def test_incremental_stream_pass_sparse(self):
        from dask_ml_tpu.models.sgd import SGDClassifier
        from dask_ml_tpu.wrappers import Incremental

        Xs, y = _xy(480, 16)
        kw = dict(loss="log_loss", random_state=0, shuffle=False,
                  max_iter=2)
        with config.set(stream_block_rows=96, stream_mesh=1):
            ref = Incremental(SGDClassifier(**kw),
                              shuffle_blocks=False).fit(Xs.toarray(), y)
        with config.set(stream_block_rows=96, stream_mesh=1,
                        stream_sparse=True):
            got = Incremental(SGDClassifier(**kw),
                              shuffle_blocks=False).fit(Xs, y)
        np.testing.assert_allclose(
            got.estimator_.coef_, ref.estimator_.coef_, rtol=1e-6,
            atol=1e-6,
        )
        assert getattr(got.estimator_, "_sparse_stream", False)

    def test_zero_compiles_after_pass1_and_dispatches(self):
        from dask_ml_tpu.models.sgd import SGDClassifier

        Xs, y = _xy(660, 18)
        kw = dict(loss="log_loss", random_state=0, shuffle=True,
                  max_iter=1)
        with config.set(stream_block_rows=96, stream_mesh=1,
                        stream_sparse=True, superblock_k=3):
            SGDClassifier(**kw).fit(Xs, y)     # pass 1: warm
            obs.counters_reset()
            clf = SGDClassifier(**dict(kw, max_iter=3)).fit(Xs, y)
            snap = obs.counters_snapshot()
        assert snap.get("recompiles", 0) == 0
        st = clf._last_stream_stats
        assert st["dispatches_per_pass"] == -(-st["n_blocks"] // 3)
        assert snap.get("superblock_dispatches", 0) > 0
        assert snap.get("superblock_donations", 0) > 0


class TestKMeansParity:
    @pytest.mark.parametrize("mesh_n", [1, 2])
    def test_lloyd_parity(self, mesh_n):
        from dask_ml_tpu.models.kmeans import KMeans

        rng = np.random.RandomState(0)
        X = _rand_csr(600, 16, density=0.15, seed=0).toarray()
        X[:200, 0] += 5
        X[200:400, 1] += 5
        X[400:, 2] += 5
        Xs = sp.csr_matrix(X)
        kw = dict(n_clusters=3, init="k-means||", random_state=0,
                  max_iter=6)
        with config.set(stream_block_rows=96, stream_mesh=mesh_n):
            ref = KMeans(**kw).fit(X.astype(np.float32))
        with config.set(stream_block_rows=96, stream_mesh=mesh_n,
                        stream_sparse=True):
            got = KMeans(**kw).fit(Xs)
        np.testing.assert_allclose(
            np.sort(got.cluster_centers_, axis=0),
            np.sort(ref.cluster_centers_, axis=0),
            rtol=1e-5, atol=1e-6,
        )


class TestSparseServing:
    def _fit(self, d=48, n=400, density=0.1):
        from dask_ml_tpu.models.sgd import SGDClassifier

        Xs, y = _xy(n, d, density=density, seed=11)
        clf = SGDClassifier(loss="log_loss", random_state=0,
                            max_iter=3).fit(
            Xs.toarray().astype(np.float32), y
        )
        return clf, Xs

    def test_standalone_agreement(self):
        from dask_ml_tpu.wrappers import sparse_batch_fn

        clf, Xs = self._fit()
        q = Xs[:37].tocsr()
        fn = sparse_batch_fn(clf, "predict")
        np.testing.assert_array_equal(
            fn(q), clf.predict(q.toarray().astype(np.float32))
        )
        df = sparse_batch_fn(clf, "decision_function")
        np.testing.assert_allclose(
            df(q),
            clf.decision_function(q.toarray().astype(np.float32)),
            rtol=1e-5, atol=1e-6,
        )

    def test_unsupported_returns_none(self):
        from sklearn.linear_model import LogisticRegression as SkLR

        from dask_ml_tpu.wrappers import sparse_batch_fn

        clf, _ = self._fit()
        assert sparse_batch_fn(clf, "predict_proba") is None
        host = SkLR()
        assert sparse_batch_fn(host, "predict") is None

    def test_warmed_grid_zero_compiles(self):
        from dask_ml_tpu.serving import ModelServer

        clf, Xs = self._fit()
        rng = np.random.RandomState(0)
        with config.set(serving_min_batch=8, serving_max_batch=64,
                        serving_sparse_nnz_per_row=16):
            srv = ModelServer(clf, methods=("predict",))
            srv.warmup()
            srv.warmup_sparse()
            subs = [
                Xs[rng.randint(0, Xs.shape[0],
                               int(rng.randint(1, 60)))].tocsr()
                for _ in range(25)
            ]
            wants = [
                clf.predict(s.toarray().astype(np.float32))
                for s in subs
            ]
            with srv:
                obs.counters_reset()
                futs = [srv.submit(s, method="predict") for s in subs]
                for f, w in zip(futs, wants):
                    np.testing.assert_array_equal(f.result(30), w)
                snap = obs.counters_snapshot()
        assert snap.get("recompiles", 0) == 0

    def test_over_nnz_spills_to_dense_rung(self):
        from dask_ml_tpu.serving import ModelServer

        clf, Xs = self._fit(density=0.1)
        dense_q = sp.csr_matrix(
            np.random.RandomState(1).rand(32, 48).astype(np.float32)
        )   # nnz = 32*48 > top rung (64 * 16)
        with config.set(serving_min_batch=8, serving_max_batch=64,
                        serving_sparse_nnz_per_row=16):
            srv = ModelServer(clf, methods=("predict",))
            srv.warmup()
            srv.warmup_sparse()
            with srv:
                obs.counters_reset()
                got = srv.submit(dense_q, method="predict").result(30)
                snap = obs.counters_snapshot()
        np.testing.assert_array_equal(
            got, clf.predict(dense_q.toarray())
        )
        assert snap.get("serving_sparse_spills", 0) == 1
        assert snap.get("recompiles", 0) == 0  # dense rung was warm

    def test_swap_keeps_sparse_lane_current(self):
        from dask_ml_tpu.models.sgd import SGDClassifier
        from dask_ml_tpu.serving import ModelServer

        clf, Xs = self._fit()
        y2 = (np.arange(Xs.shape[0]) % 2).astype(np.float64)
        clf2 = SGDClassifier(loss="log_loss", random_state=1,
                             max_iter=2).fit(
            Xs.toarray().astype(np.float32), y2
        )
        with config.set(serving_min_batch=8, serving_max_batch=64,
                        serving_sparse_nnz_per_row=16):
            srv = ModelServer(clf, methods=("predict",))
            srv.warmup()
            srv.warmup_sparse()
            with srv:
                srv.swap_model(clf2)
                got = srv.submit(Xs[:9].tocsr(),
                                 method="predict").result(30)
        np.testing.assert_array_equal(
            got, clf2.predict(Xs[:9].toarray().astype(np.float32))
        )

    def test_sparse_submit_refuses_without_entry_point(self):
        from dask_ml_tpu.models.kmeans import KMeans
        from dask_ml_tpu.serving import ModelServer

        X = np.random.RandomState(0).rand(200, 8).astype(np.float32)
        km = KMeans(n_clusters=3, random_state=0, max_iter=5).fit(X)
        srv = ModelServer(km, methods=("predict",))
        with srv:
            with pytest.raises(ValueError, match="sparse entry point"):
                srv.submit(sp.csr_matrix(X[:5]), method="predict")


class TestProducersAndProfile:
    def test_transform_blocks_and_sparse(self):
        from dask_ml_tpu.feature_extraction.text import HashingVectorizer
        from dask_ml_tpu.parallel.streaming import SparseBlocks

        docs = [f"w{i % 40} w{(i * 7) % 40} w{(i * 3) % 40}"
                for i in range(500)]
        hv = HashingVectorizer(n_features=2 ** 10)
        blocks = list(hv.transform_blocks(docs, block_size=128))
        assert all(sp.isspmatrix_csr(b) for b in blocks)
        assert sum(b.shape[0] for b in blocks) == 500
        sb = hv.transform_sparse(docs, block_size=128)
        assert isinstance(sb, SparseBlocks)
        np.testing.assert_allclose(
            sb.tocsr().toarray(), hv.transform(docs).toarray()
        )

    def test_hashing_to_streamed_fit_device_sparse(self):
        from dask_ml_tpu.feature_extraction.text import HashingVectorizer
        from dask_ml_tpu.models.sgd import SGDClassifier

        rng = np.random.RandomState(7)
        vocab = [f"w{i}" for i in range(300)]
        docs, labels = [], []
        for i in range(400):
            cls = i % 2
            lo = 0 if cls == 0 else 100
            docs.append(" ".join(rng.choice(vocab[lo:lo + 200],
                                            size=12)))
            labels.append(cls)
        y = np.asarray(labels, np.float64)
        hv = HashingVectorizer(n_features=2 ** 12)
        sb = hv.transform_sparse(docs, block_size=100)
        with config.set(stream_sparse=True, stream_mesh=1,
                        stream_block_rows=100):
            clf = SGDClassifier(loss="log_loss", random_state=0,
                                max_iter=5, shuffle=False).fit(sb, y)
            assert clf.solver_info_["sparse_stream"] is True
            # predict streams on the same mesh the fit committed its
            # weights to (the general fit-then-predict mesh contract)
            acc = (clf.predict(sb) == y).mean()
        assert acc > 0.9

    def test_to_sharded_dense_budget_guard(self):
        from dask_ml_tpu.feature_extraction.text import (
            DenseBudgetExceeded, to_sharded_dense)

        wide = _rand_csr(4000, 4096, density=0.001, seed=0)
        with config.set(to_dense_byte_budget=1 << 20):
            with pytest.raises(DenseBudgetExceeded,
                               match="stream_sparse"):
                to_sharded_dense(wide)
        # small corpora still densify
        small = _rand_csr(16, 8, density=0.5, seed=0)
        assert to_sharded_dense(small).shape == (16, 8)

    def test_profile_lifted_for_narrow_sparse(self):
        Xs, y = _xy(480, 16)
        with config.set(stream_sparse=True, stream_mesh=1):
            s = BlockStream((Xs, y.astype(np.float32)), block_rows=96)
            for _ in s.superblocks():
                pass
            prof = s.profile_snapshot()
        assert s.profile_reason is None
        assert prof is not None and prof["rows"] == 480

    def test_profile_wide_sparse_keeps_opt_out(self):
        wide = _rand_csr(200, 4096, density=0.01, seed=1)
        with config.set(stream_sparse=True, stream_mesh=1):
            s = BlockStream((wide,), block_rows=64)
            for _ in s.superblocks():
                pass
        assert s.profile_reason == "sparse-wide(d=4096)"
        assert s.profile_snapshot() is None
