"""SpectralClustering tests (ref: tests/test_spectral_clustering.py)."""

import numpy as np
import pytest
from sklearn.datasets import make_circles
from sklearn.metrics import adjusted_rand_score

from dask_ml_tpu.cluster import KMeans, SpectralClustering
from dask_ml_tpu.datasets import make_blobs


@pytest.mark.slow
def test_spectral_blobs():
    X, y = make_blobs(n_samples=300, n_features=4, centers=3, random_state=0,
                      cluster_std=0.5)
    sc = SpectralClustering(n_clusters=3, n_components=80, gamma=0.5,
                            random_state=0).fit(X)
    ari = adjusted_rand_score(y.to_numpy(), sc.labels_.to_numpy())
    assert ari > 0.9, ari


@pytest.mark.slow
def test_spectral_circles_beats_kmeans():
    """Non-convex clusters: spectral must separate what kmeans cannot."""
    Xh, y = make_circles(n_samples=400, factor=0.4, noise=0.04,
                         random_state=0)
    sc = SpectralClustering(n_clusters=2, n_components=150, gamma=40.0,
                            random_state=0).fit(Xh)
    ari_spectral = adjusted_rand_score(y, sc.labels_.to_numpy())
    ari_kmeans = adjusted_rand_score(
        y, KMeans(n_clusters=2, random_state=0).fit(Xh).labels_.to_numpy()
    )
    assert ari_spectral > 0.85, ari_spectral
    assert ari_spectral > ari_kmeans


def test_spectral_assign_labels_validation():
    X, _ = make_blobs(n_samples=50, n_features=3, centers=2, random_state=1)
    with pytest.raises(ValueError, match="assign_labels"):
        SpectralClustering(n_clusters=2, assign_labels="discretize").fit(X)


def test_spectral_affinity_validation():
    X, _ = make_blobs(n_samples=50, n_features=3, centers=2, random_state=1)
    with pytest.raises(ValueError, match="affinity"):
        SpectralClustering(n_clusters=2, affinity="bogus").fit(X)


@pytest.mark.slow
def test_spectral_linear_affinity_runs():
    X, y = make_blobs(n_samples=120, n_features=4, centers=2, random_state=2)
    sc = SpectralClustering(n_clusters=2, affinity="rbf", gamma=0.3,
                            n_components=60, random_state=0).fit(X)
    assert len(np.unique(sc.labels_.to_numpy())) == 2


@pytest.mark.slow
def test_spectral_callable_affinity():
    """A user-supplied kernel callable is used verbatim (reference
    accepts callables for affinity)."""
    import jax.numpy as jnp

    from dask_ml_tpu.cluster import SpectralClustering
    from dask_ml_tpu.metrics import pairwise

    rng = np.random.RandomState(0)
    X = np.r_[rng.randn(60, 2), rng.randn(60, 2) + 6].astype(np.float32)

    calls = []

    def my_kernel(a, b, gamma=999.0):
        calls.append(gamma)
        return pairwise.rbf_kernel(a, b, gamma=gamma)

    sc = SpectralClustering(n_clusters=2, n_components=24, random_state=0,
                            affinity=my_kernel,
                            kernel_params={"gamma": 0.5})
    labels = np.asarray(sc.fit(X).labels_.to_numpy())
    assert len(calls) >= 2  # B and A blocks both used the callable
    assert set(calls) == {0.5}  # kernel_params forwarded, not defaults
    # the two blobs separate
    first, second = labels[:60], labels[60:]
    assert (first == first[0]).mean() > 0.9
    assert (second == second[0]).mean() > 0.9
    assert first[0] != second[0]


@pytest.mark.slow
def test_spectral_honest_params_raise():
    """Params the TSQR/Nystrom formulation cannot honor raise instead of
    silently no-oping (VERDICT r3 weak #4)."""
    X, _ = make_blobs(n_samples=50, n_features=3, centers=2, random_state=1)
    with pytest.raises(ValueError, match="eigen_solver"):
        SpectralClustering(n_clusters=2, eigen_solver="arpack").fit(X)
    with pytest.raises(ValueError, match="eigen_tol"):
        SpectralClustering(n_clusters=2, eigen_tol=1e-3).fit(X)
    with pytest.raises(ValueError, match="nearest_neighbors"):
        SpectralClustering(n_clusters=2,
                           affinity="nearest_neighbors").fit(X)
    # accepted spellings of the supported solver
    SpectralClustering(n_clusters=2, eigen_solver="tsqr", n_init=1,
                       n_components=30, random_state=0).fit(X)


@pytest.mark.slow
def test_spectral_persist_embedding_and_n_init():
    from dask_ml_tpu.parallel import ShardedArray

    X, _ = make_blobs(n_samples=80, n_features=3, centers=2, random_state=3)
    sc = SpectralClustering(n_clusters=2, n_components=40, n_init=3,
                            persist_embedding=True, random_state=0).fit(X)
    assert isinstance(sc.embedding_, ShardedArray)
    assert sc.embedding_.shape == (80, 2)
    # without the flag the embedding is not retained
    sc2 = SpectralClustering(n_clusters=2, n_components=40, n_init=1,
                             random_state=0).fit(X)
    assert not hasattr(sc2, "embedding_")
