"""Regression metrics. Reference: ``dask_ml/metrics/regression.py``
(SURVEY.md §2a Metrics row)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .classification import _canon


def mean_squared_error(y_true, y_pred, sample_weight=None, squared=True):
    t, p, w, n = _canon(y_true, y_pred, sample_weight)
    mse = jnp.sum(((t - p) ** 2) * w) / jnp.sum(w)
    return float(mse if squared else jnp.sqrt(mse))


def mean_absolute_error(y_true, y_pred, sample_weight=None):
    t, p, w, n = _canon(y_true, y_pred, sample_weight)
    return float(jnp.sum(jnp.abs(t - p) * w) / jnp.sum(w))


def r2_score(y_true, y_pred, sample_weight=None):
    t, p, w, n = _canon(y_true, y_pred, sample_weight)
    wsum = jnp.sum(w)
    mean = jnp.sum(t * w) / wsum
    ss_res = jnp.sum(((t - p) ** 2) * w)
    ss_tot = jnp.sum(((t - mean) ** 2) * w)
    return _force_finite_ratio(ss_res, ss_tot)


def _force_finite_ratio(num, den):
    """1 - num/den with sklearn's force_finite semantics: a constant
    target (den == 0) scores 1.0 when the residual term is also 0
    (perfect fit) and 0.0 otherwise, instead of nan/-inf that would
    poison a CV search."""
    num, den = float(num), float(den)
    if den == 0.0:
        return 1.0 if num == 0.0 else 0.0
    return 1.0 - num / den


def mean_squared_log_error(y_true, y_pred, sample_weight=None):
    t, p, w, n = _canon(y_true, y_pred, sample_weight)
    err = (jnp.log1p(t) - jnp.log1p(p)) ** 2
    return float(jnp.sum(err * w) / jnp.sum(w))


def explained_variance_score(y_true, y_pred, sample_weight=None):
    t, p, w, n = _canon(y_true, y_pred, sample_weight)
    wsum = jnp.sum(w)
    err = t - p
    err_mean = jnp.sum(err * w) / wsum
    var_err = jnp.sum(((err - err_mean) ** 2) * w) / wsum
    t_mean = jnp.sum(t * w) / wsum
    var_t = jnp.sum(((t - t_mean) ** 2) * w) / wsum
    return _force_finite_ratio(var_err, var_t)


def max_error(y_true, y_pred):
    """Largest absolute residual (sklearn takes no sample_weight here);
    padded rows are masked out via the validity weights."""
    t, p, w, n = _canon(y_true, y_pred)
    return float(jnp.max(jnp.abs(t - p) * (w > 0)))


def median_absolute_error(y_true, y_pred, sample_weight=None):
    """Median of |err|, matching sklearn's two conventions exactly: the
    unweighted path is ``np.median`` (middle-two average over valid
    rows), the weighted path is ``_weighted_percentile``'s inverted-cdf
    — the FIRST sorted error whose cumulative weight reaches half the
    total (so an explicit zero-weight row can never contribute its
    error value, and an even split takes the LOWER of the two straddling
    errors, as sklearn does). One device sort + host f64 prefix sums: an
    f32 cumsum of unit weights saturates at 2**24 rows (the same hazard
    the curve metrics guard)."""
    t, p, w, n = _canon(y_true, y_pred, sample_weight)
    err = jnp.abs(t - p)
    order = jnp.argsort(err)
    es = np.asarray(jnp.take(err, order), np.float64)
    ws = np.asarray(jnp.take(w, order), np.float64)
    if sample_weight is None:
        # w holds only the padding-validity mask here
        return float(np.median(es[ws > 0]))
    cw = np.cumsum(ws)
    half = 0.5 * cw[-1]
    return float(es[int(np.argmax(cw >= half))])
