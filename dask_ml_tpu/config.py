"""Runtime configuration.

Reference: ``dask.config`` — layered YAML + ``DASK_*`` env vars + a
``set(...)`` context manager (SURVEY.md §5 config row). Estimator
hyperparameters stay sklearn-style (get_params/set_params — the MUST for
clone/search compat); this module covers *runtime* knobs only: a small
dataclass with env-var overrides (``DASK_ML_TPU_<FIELD>``) and a context
manager, no YAML cascade.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import threading


@dataclasses.dataclass
class Config:
    # fit compute dtype for device estimators ("auto" | "float32" |
    # "bfloat16"; "f32"/"fp32"/"bf16" are accepted aliases). "auto" —
    # the default — resolves to bfloat16 on TPU (where the MXU runs
    # bf16 at full rate and the bf16 fits are benched within the
    # documented parity tolerances, tests/test_bf16_policy.py +
    # tests/test_precision.py) and float32 everywhere else (CPU/GPU pay
    # a software bf16 penalty); the resolved choice and the fallback
    # reason are recorded in each fit's info (solver_info_ /
    # fit_dtype_). Estimators expose a per-instance ``fit_dtype``
    # override that wins over this knob.
    dtype: str = "auto"
    # rows per streamed block in out-of-core paths (0 = auto: n/8)
    stream_block_rows: int = 0
    # prefetch depth of the block streamer (1 = double buffering)
    stream_prefetch: int = 1
    # grow streamed blocks between epochs when transfer time dominates
    # compute (measured per pass; at most 2 doublings, ≥16 blocks).
    # Default OFF: resizing from wall-clock measurements makes the
    # minibatch partition — and hence a seeded fit's weights — depend on
    # machine load, breaking random_state reproducibility. Opt in for
    # throughput-bound production streaming.
    stream_autotune: bool = False
    # -- super-block scan execution (parallel/streaming.py) ---------------
    # blocks per super-block: streamed hot loops stack K fixed-shape
    # blocks into one [K, block_rows, d] device buffer and consume it in
    # ONE jitted lax.scan with a donated carry — one XLA dispatch per K
    # blocks instead of K. 0 = auto (8, capped by the pass length and a
    # device byte budget); 1 = per-block dispatch. Changing K never
    # changes the minibatch partition — only dispatch granularity — so
    # results are identical at any K.
    superblock_k: int = 0
    # opt-out: False forces the per-block dispatch path everywhere even
    # for consumers that support the fused scan
    stream_superblock: bool = True
    # -- data-parallel superblock streaming (ISSUE 9) ---------------------
    # data-axis shards for the STREAMED superblock hot loop: every
    # super-block stages as a batch-sharded jax.Array (per-shard host
    # slabs placed onto their own device by the staging worker, ragged
    # tails padded per shard with zero valid-row counts) and the scan
    # programs run under shard_map with REPLICATED carries — GLM
    # val/vg/vgh reducers and KMeans assign-stats pay one lax.psum over
    # "data" per super-block, streamed SGD one gradient psum per block
    # step. 0 = auto (all local devices — the sharded flavor engages
    # whenever more than one device is visible); 1 = single-device
    # streaming (the sharded machinery never enters the trace and the
    # streamed jaxprs are byte-identical to the pre-mesh programs);
    # N > 1 = shard over the first N local devices
    stream_mesh: int = 0
    # 2-D ("data", "model") mesh shape for the streamed/sharded plane
    # (parallel/mesh.py): "auto" = the 1-D data mesh over the resolved
    # device set (today's behavior — nothing changes); "DxM" = a 2-D
    # hybrid mesh with D data shards and M feature (model) shards, where
    # either factor may be -1 (inferred from the device count); a bare
    # "D" or "Dx1" collapses to the plain 1-D mesh so the 1-D programs
    # stay jaxpr-byte-identical. With M > 1 streamed X slabs stage as
    # (rows/D, d/M) per-device tiles and the GLM reducers / streamed
    # PCA run their feature-sharded flavors (psum over "model" exactly
    # where the math contracts over features) — per-chip HBM then stays
    # flat in d. Composes with stream_mesh: that knob first restricts
    # the device pool, this one shapes it
    mesh_shape: str = "auto"
    # simulated per-device staging byte budget for streamed fits: > 0
    # makes BlockStream refuse (typed StreamBudgetExceeded) any fit
    # whose per-device staged super-block bytes (K x block_rows/D x
    # ceil(d/M) x itemsize) exceed it, pointing at mesh_shape — the
    # CPU-verifiable stand-in for real per-chip HBM limits (bench.py
    # drives the 1-D-refuses / 2-D-completes point through this).
    # 0 = off (no budget enforced)
    stream_device_byte_budget: int = 0
    # zero-copy CPU staging: on a single-device XLA:CPU mesh, full
    # dense 64-byte-aligned blocks import into the runtime as ALIASES
    # of the host memory (dlpack) instead of device_put copies — the
    # staging memcpy that competes with the consumer's compute on small
    # hosts disappears (the streamed hot loop reads X straight from the
    # source/page cache). Safe because streamed data blocks are only
    # ever READ (never donated) and source arrays outlive the stream;
    # disable if the input array is mutated while a fit is running
    stream_zero_copy: bool = True
    # fused Pallas streamed kernels (ops/pallas_fused.py): on real TPU
    # the super-block hot loops (SGD step, GLM val/vg/vgh reducers,
    # KMeans assign-stats) run fused objective+gradient kernels — one
    # VMEM pass over each block instead of separate forward/backward
    # reads. Off-TPU (or when shapes don't fit the VMEM tile budget /
    # the 128-row Mosaic grid) the XLA flavors run unchanged: with the
    # knob off the streamed jaxprs are byte-identical to the
    # pre-feature programs (asserted in tests)
    pallas_stream: bool = True
    # interpret-mode opt-in for the fused Pallas streamed kernels
    # off-TPU: with this on, the fused bodies (including the ones
    # running INSIDE the shard_map scan programs) execute through the
    # Pallas interpreter on CPU/GPU — the fused x sharded composition
    # is then testable/benchable without a chip, at interpreter speed.
    # Off (the default) keeps the off-TPU XLA flavors byte-identical;
    # real-TPU behavior is unaffected either way
    pallas_stream_interpret: bool = False
    # -- device-resident sparse streaming (parallel/sparse_stream.py) -----
    # stream sparse (CSR / SparseBlocks) sources as DEVICE-RESIDENT
    # bucketed-nnz blocks: values/column-indices/row-ids padded to a
    # geometric nnz-bucket ladder and consumed by sparse superblock
    # scan programs (take/segment_sum — nnz-proportional cost) instead
    # of densifying every block on host to n x d. ON by default
    # (ROADMAP 4a — flipped after the PR-13 parity suite held a round
    # and grew two more shapes): a sparse source whose density stays
    # under ``stream_sparse_max_density`` runs GLM val/vg/vgh, streamed
    # SGD (incl. multiclass, grad-accum and the search cohort scans)
    # and KMeans assign-stats through the ``superblock.sparse.*``
    # programs with the same one-dispatch-per-super-block /
    # zero-compiles-after-pass-1 / donation contracts as the dense
    # scan; over-density sources keep the per-block densify path with
    # the reason recorded (solver_info_["sparse_stream_reason"]). Off
    # restores the per-block densify path byte-identically. Dense
    # inputs are untouched either way
    stream_sparse: bool = True
    # streamed adaptive-search cohort rounds (model_selection): a
    # Hyperband/IncrementalSearchCV round over host-resident X advances
    # ALL surviving candidates through ONE BlockStream superblock pass
    # — each super-block is one dispatch whose donated carry holds the
    # stacked cohort weights (padded to the search's candidate count,
    # so shrinking brackets reuse one compiled scan), composing with
    # the stream mesh (shard_map + psum twins), the bucketed-nnz sparse
    # format and the fused Pallas bodies. Off keeps the SAME block
    # partition but executes rounds through the device-resident cohort
    # machinery — the A/B bench.py records
    search_stream: bool = True
    # automatic densify fallback threshold for the sparse streamed
    # path: a source whose overall nnz density exceeds this fraction
    # stages dense (the bucketed-nnz format stops paying for itself
    # around here — padded nnz triples approach the dense block's
    # bytes while paying gather/scatter instead of matmul)
    stream_sparse_max_density: float = 0.25
    # byte budget for one-shot dense materialization of a sparse corpus
    # (feature_extraction.text.to_sharded_dense): a corpus whose dense
    # form exceeds this refuses with the typed DenseBudgetExceeded
    # pointing at the streamed sparse path instead of silently
    # allocating tens of GB of host RAM
    to_dense_byte_budget: int = 1 << 30
    # expected nonzeros per row for the SPARSE serving entry points'
    # nnz-bucket ladder (serving/wrappers sparse_batch_fn): the
    # (rows, nnz) grid's nnz rungs run geometrically from
    # serving_min_batch * this to serving_max_batch * this with
    # serving_bucket_growth — a warmed grid then serves ragged hashed-
    # text traffic at zero steady-state compiles
    serving_sparse_nnz_per_row: int = 64
    # gradient-accumulation streamed SGD (models/sgd.py): 0 = off (the
    # sequential flavor; host-streamed SGD under a multi-process
    # runtime stays refused, because sequential per-block updates
    # cannot psum across process-local streams). A >= 1 accumulates
    # each process's raw gradient sums over A micro-blocks, merges ONCE
    # across processes (psum_host), and applies a single shared update
    # — the documented optimizer variant that lifts the cross-host
    # refusal. Exact parity with the sequential fit at A=1
    # single-process (bit-exact vs the single-device sequential
    # flavor; the sharded sequential scan differs at
    # float-reassociation level); at A>1 (or multi-process) the
    # effective batch per
    # update grows A x processes-fold, so expect fewer, larger steps
    # per pass (see README "Pod-scale streaming" for the convergence
    # caveat). Recorded in solver_info_["grad_accum"]
    stream_grad_accum: int = 0
    # -- reliability / chaos plane (dask_ml_tpu/reliability/) -------------
    # deterministic fault-injection plan ("" = off, the zero-overhead
    # default: every site costs one config read + branch and the
    # streamed jaxprs are byte-identical). Arms named host-side sites
    # by seeded invocation-index schedules — e.g.
    # "staging_read:io@2;replica_worker:crash@40" — so chaos runs
    # replay exactly; see reliability/faults.py for the grammar and
    # the site/kind tables
    fault_plan: str = ""
    # bounded exponential-backoff retries for transient staging/reader
    # IO failures (real disk hiccups and injected "io" faults alike):
    # a failing host block read is re-read positionally up to this many
    # times (stream_retries_total counts attempts) before raising the
    # typed StreamIORetriesExhausted. 0 = fail on first error
    stream_io_retries: int = 3
    # non-finite streamed-block policy: "off" (no check — today's
    # behavior; staging never reads blocks it can zero-copy), "raise"
    # (typed NonFiniteBlock at the staging boundary), "quarantine"
    # (zero the block's data AND its valid-row count so the existing
    # masked prefix-count folds it out — no shape change, no recompile;
    # stream_quarantined_blocks counts). Inference streams treat
    # quarantine as raise (silently dropping prediction rows would
    # corrupt output alignment)
    stream_nonfinite: str = "off"
    # pass-granular checkpoint/auto-resume for streamed GLM/SGD/
    # Incremental fits ("" = off): the carry pytree + pass/lr-clock
    # state persist here (orbax, atomic rename) under a fingerprint
    # token — a killed fit rerun with the same data/knobs resumes at
    # the last saved pass, a wrong-fingerprint checkpoint is ignored,
    # completion clears it. Refused (fit runs uncheckpointed) under a
    # multi-process runtime: resume must be a collective decision
    stream_checkpoint_path: str = ""
    # passes between checkpoint saves when stream_checkpoint_path is
    # set (1 = every pass)
    stream_checkpoint_every: int = 1
    # deadline (seconds) on the multihost pass barrier
    # (distributed.sync_stream_pass): a lost peer turns the barrier
    # hang into a typed StreamSyncTimeout instead of wedging the fit
    # forever. 0 = no deadline
    stream_sync_timeout_s: float = 600.0
    # persistent XLA compilation cache directory ("" = off): repeated
    # runs skip warm-up compiles for programs whose shapes/backends
    # match a cached entry (applies process-wide on first streamed fit
    # or serving warmup after the knob is set; every plans.ProgramPlan
    # build arms it too)
    compile_cache_dir: str = ""
    # -- execution plans (dask_ml_tpu/plans/) -----------------------------
    # process-wide plan build cache: two ProgramPlan builds with an
    # identical spec (name, cache key, donation, static axes) return
    # the SAME tracked jitted entry point, so the second client's
    # warmup hits warm jit caches instead of re-tracing/re-compiling
    # (plan_cache_hits counts). Off = every build constructs a fresh
    # jit (the pre-ISSUE-15 behavior)
    plan_cache: bool = True
    # force the process-wide WarmupRegistry to re-execute every warm
    # request even for keys already registered warm (the executions are
    # semantic no-ops; debugging aid for compile-cache investigations).
    # Off (default) keeps warming idempotent per process
    plan_rewarm: bool = False
    # JSONL metrics path ("" = disabled)
    metrics_path: str = ""
    # span-trace directory: spans append to <trace_dir>/trace.jsonl even
    # outside a metrics_path fit ("" = spans fall back to metrics_path,
    # or no-op when both are unset)
    trace_dir: str = ""
    # runtime counter registry (recompiles, host<->device bytes, donated
    # buffer reuse) — cheap host-side adds; disable to make every
    # counter call site a single config lookup
    obs_counters: bool = True
    # compiled-program registry (observability/_programs.py): tracked jit
    # entry points record compile time + XLA cost/memory analysis per
    # program and feed the `program_flops` counter spans read for
    # measured MFU. Opt-in: the analysis pass re-lowers each program once
    # per fresh compile (an extra, in-memory-cached XLA compile that also
    # shows up in the recompiles counter), so steady-state zero-recompile
    # contracts keep it off by default
    obs_programs: bool = False
    # live telemetry exporter (observability/live.py): port for the
    # background HTTP daemon serving Prometheus /metrics, /healthz and
    # the JSON /status (open-span stack, report tables, serving
    # windows) WHILE a run is going. 0 = off — the exporter thread is
    # never created, no span observer registers, and the hot paths keep
    # today's zero-overhead profile (env DASK_ML_TPU_OBS_HTTP_PORT)
    obs_http_port: int = 0
    # data/model-quality observability (observability/sketch.py +
    # drift.py): streamed fits fold per-feature training profiles on the
    # host staging path, serving folds request/prediction sketches, and
    # hot swaps score a shadow canary — all pure host numpy (never in a
    # jaxpr, never a device sync). Off = no sketch is ever allocated
    obs_drift: bool = True
    # background drift-score cadence (seconds) while a server runs:
    # every tick recomputes PSI/KS over the registered sketch pairs and
    # publishes drift_score gauges / drift_alerts. 0 = no monitor thread
    # (scores still compute on demand via drift.compute())
    obs_drift_interval_s: float = 5.0
    # PSI above this alerts (drift_alerts_total; 0.2 is the classic
    # "significant shift" line); canary disagreement/quantile-shift
    # share it
    obs_drift_threshold: float = 0.2
    # fraction of served rows stashed into the per-method shadow
    # reservoir a hot-swap canary scores against both versions
    # (0 = no shadow sampling, swaps record no canary)
    obs_shadow_fraction: float = 0.05
    # max LABELED series per metric family in the live registry:
    # per-feature drift gauges can mint unbounded label sets; past the
    # cap new series are dropped and counted
    # (telemetry_series_dropped_total)
    obs_max_series: int = 512
    # per-request trace plane (observability/_requests.py): the rolling
    # slowest fraction of ordinary completions tail-sampled with a full
    # stage breakdown (errors, timeouts, sheds, SLO violations,
    # reroutes, and fault-injected requests are ALWAYS kept; every
    # completion folds into the per-stage exemplar histograms either
    # way). 0 = the plane is off: no trace object is ever allocated on
    # the serving hot path and the serving jaxprs are byte-identical
    obs_trace_sample: float = 0.0
    # sampled traces retained in memory for /traces, /status and the
    # report CLI (a bounded deque; oldest sampled traces fall off)
    obs_trace_keep: int = 256
    # cross-process trace continuation (observability/_requests.py +
    # serving/federation.py): the router's trace id rides federated
    # submits as an X-Trace-Context header and the receiving process
    # CONTINUES the same pid-prefixed id through its own stages, so one
    # federated request joins into one Perfetto timeline. Only consulted
    # when the trace plane is on (obs_trace_sample > 0); off = every
    # process mints its own ids, pre-federation behavior
    obs_trace_propagate: bool = True
    # fleet metrics federation (observability/fleet.py): a
    # FederatedFleet router folds every process's scraped counters/
    # gauges/histograms into one fleet registry exposed on the router's
    # own /metrics (dask_ml_tpu_fleet_* families) and /status/fleet.
    # Off by default — no federator is built, no provider registers,
    # and the router's exposition is byte-identical to pre-fleet
    obs_fleet_federate: bool = False
    # minimum seconds between fleet-metrics ingests; 0 = fold on every
    # federation status poll (the federator RIDES the existing poller —
    # it never starts a thread or issues its own /status reads)
    obs_fleet_poll_s: float = 0.0
    # slow-span watchdog (observability/_watchdog.py): any span open past
    # this many seconds dumps all-thread tracebacks + device memory
    # gauges + the open-span stack to the trace sink, without touching
    # the fit. 0 = disabled (no thread, nothing armed)
    watchdog_timeout_s: float = 0.0
    # alert rules engine (observability/alerts.py): ","/";"-separated
    # declarative rules evaluated over the live host-side registry, e.g.
    # "serving_slo_violations:rate>5/60s, drift_score_max:gauge>0.2,
    # fit_eta_seconds:gauge>1800" — counter rate-over-window and gauge
    # threshold forms (ops > < >= <=). The special value "builtin" arms
    # only the built-in rules (watchdog stalls, post-warmup recompiles,
    # fleet SLO burn > 1.0 — always included once the engine is armed).
    # "" + incident_dir unset = no engine, no ticker thread (the
    # zero-overhead default)
    obs_alert_rules: str = ""
    # alert-engine evaluation cadence: seconds between ticker passes
    # over the counter/gauge snapshots (pure host dicts, zero device
    # syncs per tick)
    obs_alert_interval_s: float = 5.0
    # black-box incident capture (observability/incidents.py): any alert
    # transition to firing (plus watchdog stalls and reliability typed
    # errors) writes one rate-limited JSON bundle here — open-span
    # stack, recent span/trace rings, counter/gauge/histogram
    # snapshots, programs table, device memory gauges, armed fault
    # plan, config fingerprint — atomically (tmp + fsync + rename).
    # Setting it arms the alert engine's built-in rules even with
    # obs_alert_rules unset. "" = capture disabled (no bundle dir)
    incident_dir: str = ""
    # incident bundles retained under incident_dir: past the cap the
    # oldest bundles are evicted after each capture
    incident_keep: int = 16
    # capture a bounded jax.profiler trace window into the incident dir
    # on each incident (real device traces on TPU; documented
    # no-op-with-reason off-TPU — see incidents.deep_profile)
    obs_profile_on_incident: bool = False
    # checkpoint directory for adaptive searches ("" = disabled)
    checkpoint_dir: str = ""
    # -- serving (dask_ml_tpu/serving/) ----------------------------------
    # smallest / largest padded batch the micro-batcher emits; the shape
    # ladder is the geometric sequence between them, so steady-state
    # serving uses at most ceil(log_growth(max/min)) + 1 compiled
    # programs per method
    serving_min_batch: int = 8
    serving_max_batch: int = 1024
    # ladder growth factor (must be > 1); 2.0 bounds padding waste at
    # <50% of any emitted batch
    serving_bucket_growth: float = 2.0
    # admission control: max requests waiting in the server queue before
    # submit() sheds load with ServerOverloaded
    serving_max_queue: int = 1024
    # how long the batcher holds an admitted request hoping to coalesce
    # more (milliseconds); 0 = dispatch immediately
    serving_batch_window_ms: float = 2.0
    # per-request deadline (milliseconds) measured from admission; a
    # request still queued past it is shed with RequestTimeout
    # (0 = no deadline)
    serving_timeout_ms: float = 1000.0
    # latency SLO (milliseconds, end-to-end enqueue -> demux) — requests
    # over it increment the serving_slo_violations counter (visible in
    # /metrics and the report counters table). With an SLO set the
    # micro-batcher also switches from the fixed coalescing window to
    # DEADLINE-AWARE release: a partial batch dispatches as soon as the
    # oldest request's SLO budget minus the predicted execution time
    # (windowed per-(method, bucket) histogram quantile) says waiting
    # longer would miss, and may coalesce LONGER than the fixed window
    # when the budget is ample. 0 = no SLO accounting, fixed window
    serving_slo_ms: float = 0.0
    # -- serving fleet (dask_ml_tpu/serving/fleet.py) ---------------------
    # replica count for FleetServer; 0 = auto (one replica per local
    # device when several exist, else 1). More replicas than devices
    # share devices round-robin as thread replicas
    serving_replicas: int = 0
    # SLO-aware admission at the fleet door: when an SLO is configured
    # and every replica's predicted completion (queued rows / predicted
    # batch execution from the live latency histograms) would miss it,
    # shed IMMEDIATELY with SloShed instead of queueing a request that
    # is already doomed — backpressure before the latency collapse, not
    # after
    serving_slo_shed: bool = True
    # replica supervision (reliability/supervisor.py): FleetServer.start
    # arms a background supervisor that REBUILDS a dead replica off the
    # serving path — fresh ModelServer at the registry's current
    # version, warmed before it rejoins routing, its stranded queue
    # drained onto the replacement (serving_replica_restarts counts).
    # Off by default: restart-on-death is an operational policy;
    # failover-only fleets keep today's behavior
    serving_supervise: bool = False
    # max rebuilds per replica slot before it degrades to PERMANENT
    # failover (serving_replica_failures; stale gauges dropped) — a
    # crash-looping replica must not burn the fleet on rebuild loops
    serving_restart_budget: int = 3
    # supervisor sweep cadence (seconds)
    serving_supervise_interval_s: float = 0.5
    # versions a ModelRegistry keeps per model name for rollback (the
    # current version is never evicted)
    serving_registry_keep: int = 8
    # extra serving entry-point flavors to PRE-BUILD and warm alongside
    # the float32 ones (comma/space separated; only "int8" today).
    # ModelServer.warmup() then compiles BOTH flavors' (method, bucket)
    # grids, so a registry publish flagged quantize="int8" (and the
    # rollback to f32) hot-swaps with ZERO new XLA compiles — the
    # two-phase swap contract extended to precision flavors. Unlisted
    # flavors swap via rebuild_model (fresh compiles off the serving
    # path) instead
    serving_warm_flavors: str = ""
    # -- serving federation (dask_ml_tpu/serving/federation.py) -----------
    # how long a FederatedFleet router trusts a cached process /status
    # snapshot before re-polling it (seconds) — routing reads the cache;
    # only a stale cache pays the poll
    serving_federation_poll_s: float = 0.5
    # per-call deadline for one cross-process operation (a /status poll,
    # one routed submit, one publish fan-out push); a process that
    # cannot answer inside it is treated as down and failed over
    serving_federation_timeout_s: float = 10.0
    # how long a process marked down stays out of routing before the
    # router probes it again (seconds) — a rebooted process rejoins on
    # the first successful probe and is re-converged to the control
    # plane's current version
    serving_federation_retry_s: float = 2.0
    # -- serving autoscale (dask_ml_tpu/serving/autoscale.py) -------------
    # FleetServer.start arms a ReplicaAutoscaler: the SLO admission
    # signal (queued rows x windowed exec quantiles) ADDS replicas under
    # sustained predicted pressure and RETIRES them (graceful drain)
    # when it subsides, instead of only shedding. Off by default:
    # elasticity is an operational policy, fixed fleets keep today's
    # behavior
    serving_autoscale: bool = False
    # replica-count bounds the autoscaler never crosses (min also floors
    # scale-down; the fleet's construction-time count seeds the pool)
    serving_autoscale_min: int = 1
    serving_autoscale_max: int = 4
    # autoscaler sweep cadence (seconds)
    serving_autoscale_interval_s: float = 0.25
    # hysteresis bands on the predicted completion signal
    # (milliseconds): scale UP when the best replica's predicted
    # completion for a top-bucket request stays above the up band,
    # DOWN when it stays below the down band. 0 = derive from
    # serving_slo_ms (80% / 20% of the SLO)
    serving_autoscale_up_ms: float = 0.0
    serving_autoscale_down_ms: float = 0.0
    # consecutive over/under-band sweeps required before a scale action
    # fires (debounce: one bursty tick must not mint a replica)
    serving_autoscale_patience: int = 2
    # seconds after any scale action during which no further action
    # fires (the new pool must see traffic before being judged)
    serving_autoscale_cooldown_s: float = 2.0


_ENV_PREFIX = "DASK_ML_TPU_"
_state = threading.local()


def _from_env() -> Config:
    cfg = Config()
    for f in dataclasses.fields(Config):
        env = os.environ.get(_ENV_PREFIX + f.name.upper())
        if env is None:
            continue
        # f.type is the annotation STRING under `from __future__ import
        # annotations` — dispatch on the declared default's type instead
        kind = type(getattr(cfg, f.name))
        if kind is bool:
            value = env.strip().lower() in ("1", "true", "yes", "on")
        elif kind is int:
            value = int(env)
        elif kind is float:
            value = float(env)
        else:
            value = env
        setattr(cfg, f.name, value)
    return cfg


# accepted config.dtype spellings -> canonical names; the error message
# below enumerates them so a typo is a one-line fix, not a spelunk
_DTYPE_ALIASES = {
    "auto": "auto",
    "float32": "float32", "f32": "float32", "fp32": "float32",
    "bfloat16": "bfloat16", "bf16": "bfloat16",
}


def normalize_dtype(dt: str) -> str:
    """Canonical dtype name for a config/estimator dtype string.
    Unknown spellings raise — a typo silently training f32 would
    corrupt every precision and benchmark expectation downstream."""
    canon = _DTYPE_ALIASES.get(str(dt).strip().lower())
    if canon is None:
        raise ValueError(
            f"dtype={dt!r} is not supported; accepted spellings: "
            "'auto', 'float32' (aliases 'f32', 'fp32'), "
            "'bfloat16' (alias 'bf16')"
        )
    return canon


def resolve_dtype(override=None) -> tuple[str, str]:
    """(resolved canonical dtype, why) for a fit: the per-estimator
    ``override`` wins over ``config.dtype``; "auto" resolves to
    bfloat16 on real TPU (benched parity, MXU-rate bf16) and float32
    everywhere else — the automatic f32 fallback the fit info
    records."""
    src = "estimator" if override is not None else "config"
    dt = normalize_dtype(override if override is not None
                         else get_config().dtype)
    if dt != "auto":
        return dt, src
    import jax

    if jax.default_backend() == "tpu":
        return "bfloat16", "auto:tpu"
    return "float32", f"auto:{jax.default_backend()}-fallback"


def mxu_dtype(override=None):
    """The matmul compute dtype the current config (or the estimator's
    ``fit_dtype`` override) asks for, or None for plain f32 — the ONE
    mapping from ``config.dtype`` to the kernels' ``mxu_dtype``/cast
    arguments (KMeans distances, PCA Gram, SGD epoch grids, GLM design
    matrices)."""
    dt, _ = resolve_dtype(override)
    if dt == "bfloat16":
        import jax.numpy as jnp

        return jnp.bfloat16
    return None


def fit_dtype_info(override=None) -> dict:
    """The resolved fit compute dtype as fit-info fields: estimators
    merge this into ``solver_info_`` / expose it as ``fit_dtype_`` so
    an automatic f32 fallback (auto policy off-TPU) is on record, not
    silent."""
    dt, src = resolve_dtype(override)
    return {"fit_dtype": dt, "fit_dtype_source": src}


_compile_cache_applied: str | None = None


def ensure_compile_cache() -> bool:
    """Apply ``config.compile_cache_dir`` to jax's persistent
    compilation cache (idempotent per directory value; process-wide, as
    the cache itself is). Returns True when a cache directory is
    active. Called from the streamed-fit entry (BlockStream) and
    ``serving.warmup()`` — warmup still compiles the full
    (method, bucket) grid, but a second process/run with the same knob
    replays those compiles from disk instead of XLA.

    The thresholds are zeroed so even sub-second streamed-block
    programs are cached: the dispatch-bound hot loops this repo cares
    about are exactly the ones whose many small compiles add up."""
    global _compile_cache_applied
    d = get_config().compile_cache_dir
    if not d:
        return False
    if _compile_cache_applied == d:
        return True
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", d)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        # jax latches the cache backend at the FIRST compile: a process
        # that already compiled anything before this knob was applied
        # holds an initialized no-op cache and silently ignores the new
        # directory — reset so the next compile re-initializes against it
        from jax._src import compilation_cache as _cc

        if getattr(_cc, "_cache_initialized", False):
            _cc.reset_cache()
    except Exception:
        return False  # jax build without the cache knobs: run uncached
    _compile_cache_applied = d
    return True


def get_config() -> Config:
    stack = getattr(_state, "stack", None)
    if stack:
        return stack[-1]
    cached = getattr(_state, "base", None)
    if cached is None:
        cached = _from_env()
        _state.base = cached
    return cached


@contextlib.contextmanager
def set(**overrides):
    """``with config.set(stream_block_rows=1_000_000): ...`` — the
    dask.config.set analog."""
    base = get_config()
    new = dataclasses.replace(base, **overrides)
    stack = getattr(_state, "stack", None)
    if stack is None:
        stack = _state.stack = []
    stack.append(new)
    try:
        yield new
    finally:
        stack.pop()
