"""Serving fleet: train WHILE serving, watch the version flip live.

`examples/08_serving.py` served ONE frozen model from ONE worker.
The fleet layer turns that into the production shape:

- ``ModelRegistry``  — named, versioned fitted-model snapshots
  (publish / rollback, subscribers notified on every flip);
- ``FleetServer``    — N replica ``ModelServer`` workers (one per
  device when several exist) behind least-loaded routing, SLO-aware
  admission, and failover;
- ``publish()``      — a ROLLING zero-recompile hot-swap: compiled
  entry points close over shapes, not values, so pushing new weights
  re-binds the param pytree under the same XLA programs — no compile,
  no dropped request;
- ``serve_while_training`` — an ``Incremental.partial_fit`` driver
  that publishes a fresh snapshot after EVERY pass, so an online model
  refreshes its serving version under live traffic.

This example trains an online SGD classifier while 4 client threads
hammer the fleet, and self-scrapes ``/metrics`` between passes — the
``serving_replica_version`` gauge flips replica by replica as each
rolling swap lands, and the recompile counter stays flat.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import re
import threading
import urllib.request

import numpy as np

from dask_ml_tpu import observability as obs
from dask_ml_tpu.datasets import make_classification
from dask_ml_tpu.models.sgd import SGDClassifier
from dask_ml_tpu.serving import FleetServer, ServingError, serve_while_training
from dask_ml_tpu.wrappers import Incremental

n = int(os.environ.get("DASK_ML_TPU_EXAMPLE_N", 20_000))
X, y = make_classification(n_samples=n, n_features=16, n_informative=8,
                           random_state=0)
Xh = X.to_numpy().astype(np.float32)
yh = y.to_numpy()
classes = np.unique(yh)

# -- v1: two warm passes (first compiles at fresh-zeros placement,
#    second at steady state) so serve-while-train passes are compile-free
inc = Incremental(SGDClassifier(max_iter=1, random_state=0, shuffle=False),
                  shuffle_blocks=False)
inc.partial_fit(Xh, yh, classes=classes)
inc.partial_fit(Xh, yh, classes=classes)

# live exporter so the registry/replica gauges publish; port=0 = ephemeral
server = obs.TelemetryServer(port=0).start()
print(f"telemetry at {server.url}")


def scrape_versions():
    with urllib.request.urlopen(server.url + "/metrics", timeout=5) as r:
        text = r.read().decode()
    return dict(re.findall(
        r'^dask_ml_tpu_serving_replica_version\{[^}]*replica="(\d+)"[^}]*\} '
        r"([\d.e+-]+)$", text, re.MULTILINE))


with FleetServer(inc.estimator_, name="online", replicas=2).warmup() as fleet:
    base = obs.counters_snapshot().get("recompiles", 0)

    stop = threading.Event()
    served, shed = [], []

    def client(seed):
        rng = np.random.RandomState(seed)
        while not stop.is_set():
            k = rng.randint(1, 64)
            i = rng.randint(0, Xh.shape[0] - k)
            try:
                out = fleet.predict(Xh[i:i + k])
            except ServingError:
                shed.append(1)
                continue
            assert out.shape == (k,)
            served.append(k)

    clients = [threading.Thread(target=client, args=(s,)) for s in range(4)]
    for t in clients:
        t.start()

    def on_pass(pass_no, version):
        print(f"pass {pass_no}: published v{version}  "
              f"replica versions on /metrics: {scrape_versions()}  "
              f"served so far: {len(served)} requests")

    serve_while_training(fleet, inc, Xh, yh, passes=4, classes=classes,
                         on_pass=on_pass)

    stop.set()
    for t in clients:
        t.join()

    recompiles = obs.counters_snapshot().get("recompiles", 0) - base
    stats = fleet.stats()
    print(f"\nfleet served {stats['requests']} requests across "
          f"{stats['n_replicas']} replicas through {stats['swaps']} "
          f"rolling swaps; shed {len(shed)}; "
          f"post-warmup XLA compiles: {recompiles} (contract: 0)")
    assert recompiles == 0, "hot-swap must not recompile"

    # the registry keeps history: a bad push is one rollback away
    v = fleet.rollback()
    print(f"rollback → serving v{v} again "
          f"(versions kept: {fleet.registry.versions('online')})")

server.stop()
print("done.")
