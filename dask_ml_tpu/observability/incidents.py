"""Black-box incident capture + on-demand deep profiling.

When an alert fires (or a watchdog stall / reliability typed error
lands), the diagnostic context that explains it — which spans were
open, what the counters had just done, which compiled programs were
live, how much device memory was in use — evaporates within seconds
unless someone happened to be scraping. This module freezes it: every
firing transition writes ONE bounded JSON **incident bundle** under
``config.incident_dir``::

    incident_<t_unix_ms>_<pid>.json
    {
      "incident": 1, "schema": 1, "reason": "alert:builtin:...",
      "open_spans": [...],        # the live span stack, oldest first
      "recent_spans": [...],      # last-N closed-span ring
      "traces": {...},            # sampled request traces + exemplars
      "counters": {...}, "gauges": {...}, "histograms": {...},
      "programs": [...],          # compiled-programs table
      "device_memory": {...},     # per-device bytes gauges
      "fault_plan": {...},        # armed chaos plan, if any
      "alerts": {...},            # engine state at capture time
      "watchdog_stalls": [...],
      "config": {"fingerprint": "sha256...", "values": {...}},
    }

Capture is **rate-limited** (at most one bundle per
``MIN_CAPTURE_INTERVAL_S`` — an alert storm produces one artifact, not
a disk full), **retained under a cap** (``config.incident_keep``:
oldest bundles evicted after each capture) and **atomic**: written
through ``utils.checkpoint.save_host`` with a JSON dumper — temp
sibling, flush+fsync, rename — so a SIGKILL mid-write can never
publish a truncated bundle.

**Deep profiling** (:func:`deep_profile`) runs a bounded
``jax.profiler.trace`` window into the incident dir: real device
traces on TPU (viewable in Perfetto/TensorBoard), and a documented
no-op-with-reason off-TPU — ``{"profiled": False, "reason": ...}`` —
because non-TPU backends under this repo's CI either lack profiler
support or produce host-only traces that mislead more than they help.
Reachable via ``POST /profile?seconds=N`` on the telemetry server and,
when ``config.obs_profile_on_incident`` is set, fired on a daemon
thread from each capture.

With ``incident_dir`` at its "" default every entry point returns
after one config check: no directory, no thread, no bytes written —
the plane's zero-overhead contract.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from collections import deque

from ._counters import counter_add, counters_enabled, counters_snapshot

__all__ = [
    "capture_incident", "incidents_data", "load_bundles",
    "deep_profile", "reset", "MIN_CAPTURE_INTERVAL_S",
]

SCHEMA_VERSION = 1
# alert storms collapse to one bundle per window (force=True bypasses —
# tests, and explicit operator captures)
MIN_CAPTURE_INTERVAL_S = 30.0
# deep-profile windows are clamped to this many seconds
MAX_PROFILE_SECONDS = 60.0

_lock = threading.Lock()
_last_capture_t = 0.0
_captured: deque = deque(maxlen=32)   # {path, reason, rule, t_unix}
_profile_lock = threading.Lock()      # one trace window at a time


def _json_default(o):
    try:
        return float(o)
    except (TypeError, ValueError):
        return str(o)


def _json_dump(obj, f) -> None:
    """``save_host``'s ``dump=`` hook: UTF-8 JSON into the binary temp
    file, degrading non-JSON leaves the way /status does."""
    f.write(json.dumps(obj, default=_json_default,
                       sort_keys=True).encode())


def config_fingerprint(cfg=None) -> tuple[str, dict]:
    """(sha256-of-sorted-JSON, full values dict) for the active config
    — bundles from two fleet processes with different knobs are
    distinguishable at a glance."""
    import dataclasses

    from ..config import get_config

    values = dataclasses.asdict(cfg or get_config())
    blob = json.dumps(values, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest(), values


def _build_bundle(reason, rule, meta, cfg) -> dict:
    """The full diagnostic snapshot — every block independently
    guarded: a failing source degrades to its error string, never the
    whole capture."""
    bundle = {
        "incident": 1,
        "schema": SCHEMA_VERSION,
        "t_unix": round(time.time(), 6),
        "pid": os.getpid(),
        "reason": str(reason),
        "rule": rule,
        "meta": dict(meta) if meta else None,
    }

    def block(key, fn):
        try:
            bundle[key] = fn()
        except Exception as exc:
            bundle[key] = {"error": f"{type(exc).__name__}: {exc}"}

    from . import live
    from ._programs import programs_snapshot
    from ._spans import open_spans_snapshot

    block("open_spans", open_spans_snapshot)
    block("recent_spans", lambda: list(live._recent_spans))
    block("watchdog_stalls", lambda: list(live._recent_stalls))

    def _traces():
        from . import _requests

        return _requests.traces_data()

    block("traces", _traces)
    block("counters", counters_snapshot)
    block("gauges", lambda: {
        f"{name}{dict(labels) or ''}": v
        for (name, labels), v in sorted(live.gauges_snapshot().items())
    })
    block("histograms", lambda: {
        f"{name}{dict(labels) or ''}": h.snapshot()
        for (name, labels), h in sorted(live.histograms_snapshot().items())
    })
    block("programs", programs_snapshot)

    def _devmem():
        from ._counters import device_memory_gauges

        return device_memory_gauges()

    block("device_memory", _devmem)

    def _faults():
        from .. import reliability

        return reliability.status_block()

    block("fault_plan", _faults)

    def _alerts():
        from . import alerts

        return alerts.alerts_data()

    block("alerts", _alerts)

    def _config():
        fp, values = config_fingerprint(cfg)
        return {"fingerprint": fp, "values": values}

    block("config", _config)
    return bundle


def _evict(incident_dir, keep) -> None:
    """Retention: drop the oldest ``incident_*.json`` past the cap
    (filename order == capture order — the name embeds t_unix_ms)."""
    try:
        names = sorted(n for n in os.listdir(incident_dir)
                       if n.startswith("incident_")
                       and n.endswith(".json"))
    except OSError:
        return
    for name in names[:max(len(names) - max(int(keep), 1), 0)]:
        try:
            os.remove(os.path.join(incident_dir, name))
        except OSError:
            pass


def capture_incident(reason, rule=None, meta=None, cfg=None,
                     force=False):
    """Freeze the diagnostic context into one atomic JSON bundle under
    ``config.incident_dir``. Returns the written path, or None when
    capture is disabled (no dir) or rate-limited (one bundle per
    ``MIN_CAPTURE_INTERVAL_S`` unless ``force``). Never raises — this
    runs on alert/error paths that must survive a full disk."""
    global _last_capture_t
    from ..config import get_config

    cfg = cfg or get_config()
    incident_dir = str(cfg.incident_dir).strip()
    if not incident_dir:
        return None
    now = time.time()
    with _lock:
        if not force and now - _last_capture_t < MIN_CAPTURE_INTERVAL_S:
            if counters_enabled():
                counter_add("incidents_rate_limited", 1)
            return None
        _last_capture_t = now
    try:
        bundle = _build_bundle(reason, rule, meta, cfg)
        path = os.path.join(
            incident_dir,
            f"incident_{int(now * 1000)}_{os.getpid()}.json",
        )
        from ..utils.checkpoint import save_host

        save_host(path, bundle, dump=_json_dump)
        _evict(incident_dir, cfg.incident_keep)
    except Exception:
        return None
    if counters_enabled():
        counter_add("incidents_captured", 1)
    rec = {"incident": True, "path": path, "reason": str(reason),
           "rule": rule, "t_unix": round(now, 6)}
    with _lock:
        _captured.append(rec)
    try:
        from ._spans import _trace_sink

        sink = _trace_sink()
        if sink is not None:
            sink.log(**rec)
    except Exception:
        pass
    if cfg.obs_profile_on_incident:
        threading.Thread(
            target=deep_profile, args=(5.0,),
            kwargs={"cfg": cfg, "tag": os.path.basename(path)[:-5]},
            name="dask-ml-tpu-incident-profile", daemon=True,
        ).start()
    return path


def incidents_data() -> dict:
    """The /status ``incidents`` block: captures this process has
    written (newest last) + the rate-limit window."""
    with _lock:
        captured = list(_captured)
    return {"captured": captured,
            "min_interval_s": MIN_CAPTURE_INTERVAL_S}


def load_bundles(incident_dir):
    """Parse every published ``incident_*.json`` under a dir, oldest
    first — the ``report --incidents <dir>`` reader. Unparseable files
    surface as ``{"error": ...}`` rows rather than aborting the
    report."""
    out = []
    try:
        names = sorted(n for n in os.listdir(incident_dir)
                       if n.startswith("incident_")
                       and n.endswith(".json"))
    except OSError as exc:
        return [{"error": f"{type(exc).__name__}: {exc}",
                 "path": str(incident_dir)}]
    for name in names:
        path = os.path.join(incident_dir, name)
        try:
            with open(path, encoding="utf-8") as f:
                bundle = json.load(f)
            bundle["path"] = path
            out.append(bundle)
        except Exception as exc:
            out.append({"error": f"{type(exc).__name__}: {exc}",
                        "path": path})
    return out


def deep_profile(seconds=5.0, cfg=None, tag=None) -> dict:
    """A bounded ``jax.profiler.trace`` window into
    ``<incident_dir>/profile_<tag>``.

    TPU: real device traces (HLO timelines, per-core activity) land in
    the profile dir for TensorBoard/Perfetto. Off-TPU this is a
    documented no-op-with-reason — ``{"profiled": False, "reason":
    ...}`` — CPU/GPU CI backends here either lack profiler plugins or
    emit host-only traces that look like device data but are not.
    Windows are serialized (one at a time) and clamped to
    ``MAX_PROFILE_SECONDS``."""
    try:
        seconds = float(seconds)
    except (TypeError, ValueError):
        return {"profiled": False,
                "reason": f"bad seconds value {seconds!r}"}
    if seconds <= 0:
        return {"profiled": False, "reason": "seconds must be > 0"}
    seconds = min(seconds, MAX_PROFILE_SECONDS)
    import jax

    backend = jax.default_backend()
    if backend != "tpu":
        return {"profiled": False, "backend": backend,
                "reason": f"deep profiling needs TPU (backend is "
                          f"{backend!r}); host-only traces off-chip "
                          f"mislead more than they help — no-op"}
    from ..config import get_config

    cfg = cfg or get_config()
    incident_dir = str(cfg.incident_dir).strip()
    if not incident_dir:
        # config is thread-local and this runs on the HTTP handler
        # thread: the armed engine carries the config that set
        # incident_dir, so POST /profile works wherever capture does
        try:
            from . import alerts

            eng = alerts.engine()
            if eng is not None:
                cfg = eng._cfg
                incident_dir = str(cfg.incident_dir).strip()
        except Exception:
            pass
    if not incident_dir:
        return {"profiled": False, "backend": backend,
                "reason": "config.incident_dir unset — nowhere to "
                          "write the trace"}
    if not _profile_lock.acquire(blocking=False):
        return {"profiled": False,
                "reason": "a profile window is already running"}
    try:
        tag = tag or f"adhoc_{int(time.time() * 1000)}"
        log_dir = os.path.join(incident_dir, f"profile_{tag}")
        os.makedirs(log_dir, exist_ok=True)
        from ._metrics import profile_trace

        t0 = time.time()
        with profile_trace(log_dir):
            time.sleep(seconds)
        if counters_enabled():
            counter_add("deep_profiles", 1)
        return {"profiled": True, "backend": backend,
                "log_dir": log_dir, "seconds": round(time.time() - t0, 3)}
    except Exception as exc:
        return {"profiled": False, "backend": backend,
                "reason": f"{type(exc).__name__}: {exc}"}
    finally:
        _profile_lock.release()


def reset() -> None:
    """Clear the capture ring + rate-limit clock — test isolation."""
    global _last_capture_t
    with _lock:
        _captured.clear()
        _last_capture_t = 0.0
