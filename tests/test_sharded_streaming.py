"""Data-parallel superblock streaming (ISSUE 9): the streamed hot loop
sharded over the mesh's "data" axis.

Contracts under test, per the tentpole:

- per-pass parity: streamed GLM/SGD/KMeans at mesh sizes {1, 2, 8}
  match the single-device path to 1e-6 — per-shard partial sums only
  reassociate float additions, they never change the math;
- staging: super-blocks arrive batch-sharded (every device owns a
  contiguous row slab of every block) with per-shard valid-row counts —
  a ragged tail block pads its trailing SHARDS with zero counts exactly
  like the ragged final super-block pads its missing block slots;
- carries replicate (out spec P()) and stay donated (the input buffer
  dies, the donation counters move), with ONE dispatch per super-block
  (never one per shard) and zero XLA compiles after pass 1;
- the trivial mesh (config.stream_mesh=1) routes through the original
  single-device programs whose jaxprs are BYTE-IDENTICAL with the mesh
  feature present — and contain no collective, while the sharded
  programs psum.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from dask_ml_tpu import config
from dask_ml_tpu import observability as obs
from dask_ml_tpu.parallel.streaming import BlockStream


def _mk_xy(n=1100, d=6, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    y = (X @ rng.randn(d) > 0).astype(np.float32)
    return X, y


MESHES = (1, 2, 8)


def _objective(stream, n, d):
    from dask_ml_tpu.models.solvers.streamed import StreamedObjective

    return StreamedObjective(
        stream, n, jnp.asarray(0.1, jnp.float32), jnp.ones(d + 1),
        0.5, "logistic", "l2", True,
    )


class TestShardedStaging:
    def test_superblocks_stage_batch_sharded_with_shard_counts(self):
        X, y = _mk_xy(1100)
        with config.set(stream_block_rows=96, superblock_k=8):
            s = BlockStream((X, y), block_rows=96)
            assert s.sb_data_shards() == 8 and s.sb_sharded()
            sbs = list(s.superblocks())
        for sb in sbs:
            blk = sb.arrays[0]
            blk = blk[0] if isinstance(blk, tuple) else blk
            # every device owns its own contiguous row slab
            assert len(blk.sharding.device_set) == 8
            sc = np.asarray(sb.shard_counts)
            assert sc.shape == (8, np.asarray(sb.counts).shape[0])
            # per-shard counts repartition the global counts exactly
            np.testing.assert_array_equal(sc.sum(axis=0),
                                          np.asarray(sb.counts))
        assert s.stats["sb_shards"] == 8

    def test_ragged_tail_pads_per_shard_with_zero_counts(self):
        # 1100 rows / 96-row blocks: the tail block holds 44 rows; at
        # D=8 each shard owns 12 rows, so its per-shard counts are
        # [12, 12, 12, 8, 0, 0, 0, 0] — trailing shards all-padding
        X, y = _mk_xy(1100)
        with config.set(stream_block_rows=96, superblock_k=8):
            s = BlockStream((X, y), block_rows=96)
            last = list(s.superblocks())[-1]
        sc = np.asarray(last.shard_counts)
        tail_slot = last.n_blocks - 1
        np.testing.assert_array_equal(
            sc[:, tail_slot], [12, 12, 12, 8, 0, 0, 0, 0]
        )
        # padding block slots are zero on EVERY shard
        np.testing.assert_array_equal(sc[:, last.n_blocks:], 0)

    def test_trivial_mesh_stages_single_device_without_shard_counts(self):
        X, y = _mk_xy(600)
        with config.set(stream_block_rows=96, stream_mesh=1):
            s = BlockStream((X, y), block_rows=96)
            assert s.sb_data_shards() == 1 and not s.sb_sharded()
            sb = next(iter(s.superblocks()))
        assert sb.shard_counts is None
        blk = sb.arrays[0]
        blk = blk[0] if isinstance(blk, tuple) else blk
        assert len(blk.sharding.device_set) == 1

    def test_stream_mesh_n_limits_the_shard_count(self):
        X, y = _mk_xy(600)
        with config.set(stream_block_rows=96, stream_mesh=2):
            s = BlockStream((X, y), block_rows=96)
            assert s.sb_data_shards() == 2


class TestGLMParity:
    def test_objective_per_pass_parity_across_mesh_sizes(self):
        n, d = 1100, 6
        X, y = _mk_xy(n, d)
        beta = np.random.RandomState(3).randn(d + 1)
        out = {}
        for sm in MESHES:
            with config.set(stream_block_rows=96, stream_mesh=sm):
                o = _objective(BlockStream((X, y), block_rows=96), n, d)
                v, g = o.value_and_grad(beta)
                v2, g2, h = o.value_and_grad_and_hess(beta)
                out[sm] = (v, g, v2, g2, h, o.value(beta))
        for sm in MESHES[1:]:
            for a, b in zip(out[sm], out[1]):
                np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), atol=1e-6, rtol=1e-6
                )

    def test_multiclass_objective_parity(self):
        from dask_ml_tpu.models.solvers.streamed import (
            MulticlassStreamedObjective,
        )

        n, d, C = 900, 5, 3
        X, _ = _mk_xy(n, d)
        y = np.random.RandomState(5).randint(0, C, n).astype(np.float32)
        beta = np.random.RandomState(6).randn(C * (d + 1))
        out = {}
        for sm in (1, 8):
            with config.set(stream_block_rows=96, stream_mesh=sm):
                o = MulticlassStreamedObjective(
                    BlockStream((X, y), block_rows=96), n,
                    jnp.asarray(0.1, jnp.float32),
                    jnp.ones(C * (d + 1)), 0.5, "logistic", "l2", True,
                    n_classes=C,
                )
                out[sm] = o.value_and_grad(beta)
        np.testing.assert_allclose(out[8][0], out[1][0], rtol=1e-6)
        np.testing.assert_allclose(out[8][1], out[1][1],
                                   atol=1e-6, rtol=1e-6)

    def test_streamed_lbfgs_fit_records_stream_shards(self):
        from dask_ml_tpu.linear_model import LogisticRegression

        X, y = _mk_xy(1100)
        with config.set(stream_block_rows=96):
            clf = LogisticRegression(solver="lbfgs", max_iter=15).fit(
                X.astype(np.float64), y.astype(np.float64)
            )
        assert clf.solver_info_["streamed"] is True
        assert clf.solver_info_["stream_shards"] == 8
        assert clf.score(X, y) > 0.8


class TestSGDParity:
    def test_fit_weights_parity_across_mesh_sizes(self):
        from dask_ml_tpu.models.sgd import SGDClassifier

        X, y = _mk_xy(1100)
        res = {}
        for sm in MESHES:
            with config.set(stream_block_rows=96, stream_mesh=sm):
                m = SGDClassifier(max_iter=2, random_state=0,
                                  shuffle=True).fit(X, y)
                res[sm] = (m.coef_.copy(), m.intercept_.copy(), m._t)
        for sm in MESHES[1:]:
            assert res[sm][2] == res[1][2]      # identical lr clock
            np.testing.assert_allclose(res[sm][0], res[1][0], atol=1e-6)
            np.testing.assert_allclose(res[sm][1], res[1][1], atol=1e-6)

    def test_multiclass_elasticnet_parity(self):
        from dask_ml_tpu.models.sgd import SGDClassifier

        X, _ = _mk_xy(900)
        y = np.random.RandomState(5).randint(0, 3, len(X)).astype(float)
        res = {}
        for sm in (1, 8):
            with config.set(stream_block_rows=96, stream_mesh=sm):
                m = SGDClassifier(max_iter=2, random_state=0,
                                  shuffle=False, penalty="elasticnet",
                                  l1_ratio=0.4).fit(X, y)
                res[sm] = m.coef_.copy()
        np.testing.assert_allclose(res[8], res[1], atol=1e-6)

    def test_incremental_wrapper_threads_the_mesh(self):
        from dask_ml_tpu.models.sgd import SGDClassifier
        from dask_ml_tpu.wrappers import Incremental

        X, y = _mk_xy(1100)
        res = {}
        for sm in (1, 8):
            with config.set(stream_block_rows=96, stream_mesh=sm):
                inc = Incremental(
                    SGDClassifier(max_iter=1, random_state=0),
                    shuffle_blocks=True, random_state=7,
                ).fit(X, y)
                res[sm] = inc.estimator_.coef_.copy()
        np.testing.assert_allclose(res[8], res[1], atol=1e-6)


class TestKMeansParity:
    def test_streamed_lloyd_parity(self):
        from dask_ml_tpu.models.kmeans import KMeans

        rng = np.random.RandomState(2)
        X = np.concatenate([
            rng.randn(400, 5).astype(np.float32) + c for c in (0, 6, 12)
        ])
        res = {}
        for sm in (1, 8):
            with config.set(stream_block_rows=96, stream_mesh=sm):
                km = KMeans(n_clusters=3, random_state=0,
                            max_iter=20).fit(X)
                res[sm] = (np.sort(km.cluster_centers_, axis=0),
                           km.inertia_)
        np.testing.assert_allclose(res[8][0], res[1][0], atol=1e-5)
        assert res[8][1] == pytest.approx(res[1][1], rel=1e-5)


class TestCarriesAndDispatch:
    def test_carry_replicates_and_donates(self):
        from dask_ml_tpu.models.solvers.streamed import _sb_reducer
        from dask_ml_tpu.parallel.mesh import stream_data_mesh

        mesh = stream_data_mesh()
        assert mesh.devices.size == 8
        d = 4
        run = _sb_reducer("vg", "logistic", True, 0, mesh=mesh)
        X, y = _mk_xy(192, d)
        with config.set(stream_block_rows=96, superblock_k=2):
            s = BlockStream((X, y), block_rows=96)
            sb = next(iter(s.superblocks()))
        rep = NamedSharding(mesh, P())
        beta = jnp.zeros(d + 1, jnp.float32)
        acc = jax.device_put(
            (jnp.zeros((), jnp.float32), jnp.zeros(d + 1, jnp.float32)),
            rep,
        )
        out = run(acc, beta, sb.arrays[0], sb.arrays[1],
                  sb.shard_counts)  # compile once
        # the carry comes back REPLICATED on the stream mesh
        for o in out:
            assert o.sharding == rep, o.sharding
        acc = jax.device_put(
            (jnp.zeros((), jnp.float32), jnp.zeros(d + 1, jnp.float32)),
            rep,
        )
        out = run(acc, beta, sb.arrays[0], sb.arrays[1],
                  sb.shard_counts)
        # ... and the donated input buffer is dead
        with pytest.raises(Exception):
            np.asarray(acc[1])

    def test_sgd_weight_carry_is_replicated(self):
        from dask_ml_tpu.models.sgd import SGDClassifier
        from dask_ml_tpu.parallel.mesh import stream_data_mesh

        X, y = _mk_xy(1100)
        with config.set(stream_block_rows=96):
            m = SGDClassifier(max_iter=1, random_state=0,
                              shuffle=False).fit(X, y)
        rep = NamedSharding(stream_data_mesh(), P())
        assert m._w.sharding == rep, m._w.sharding

    def test_one_dispatch_per_superblock_and_zero_recompiles(self):
        """Sharding must not change the dispatch shape: one scan
        dispatch per super-block (NOT per shard), and pass 2+ pays zero
        new XLA compiles."""
        from dask_ml_tpu.models.sgd import SGDClassifier

        X, y = _mk_xy(1100)
        with config.set(stream_block_rows=96):
            SGDClassifier(max_iter=1, random_state=0,
                          shuffle=False).fit(X, y)  # pass 1 compiles
            obs.counters_reset()
            m = SGDClassifier(max_iter=3, random_state=0,
                              shuffle=False).fit(X, y)
        st = dict(m._last_stream_stats or {})
        k = st["superblock_k"]
        assert st["dispatches_per_pass"] == -(-st["n_blocks"] // k)
        assert st["sb_shards"] == 8
        snap = obs.counters_snapshot()
        assert snap.get("recompiles", 0) == 0, snap
        assert snap.get("superblock_donations", 0) >= 3
        assert snap.get("shard_slab_puts", 0) > 0
        assert snap.get("shard_staging_batches", 0) > 0


class TestTrivialMeshJaxpr:
    def test_trivial_mesh_jaxpr_byte_identical_and_collective_free(self):
        """With config.stream_mesh=1 the streamed SGD scan program is
        the ORIGINAL single-device one: its jaxpr is byte-identical
        whether the knob is set or left at default resolution semantics
        (the mesh feature adds nothing to the trace) and contains no
        psum; the sharded program's jaxpr does psum."""
        from dask_ml_tpu.models.sgd import (_sgd_sb_scan,
                                            _sgd_sb_scan_sharded)
        from dask_ml_tpu.parallel.mesh import stream_data_mesh

        K, S, d = 2, 96, 4

        def trace_xla():
            W = jnp.zeros(d + 1, jnp.float32)
            Xs = tuple(jnp.zeros((S, d), jnp.float32) for _ in range(K))
            ys = tuple(jnp.zeros((S,), jnp.float32) for _ in range(K))
            counts = jnp.zeros((K,), jnp.int32)
            lrs = jnp.ones((K,), jnp.float32)
            z = jnp.float32(0.0)
            return str(jax.make_jaxpr(
                lambda *a: _sgd_sb_scan.__wrapped__(
                    *a, loss="log_loss", n_out=None
                )
            )(W, Xs, ys, counts, lrs, z, z, z, z))

        baseline = trace_xla()
        with config.set(stream_mesh=1):
            assert trace_xla() == baseline
        with config.set(stream_mesh=8):
            assert trace_xla() == baseline
        assert "psum" not in baseline

        mesh = stream_data_mesh()
        run = _sgd_sb_scan_sharded(mesh, "log_loss", None, None)
        W = jnp.zeros(d + 1, jnp.float32)
        Xs = tuple(jnp.zeros((S, d), jnp.float32) for _ in range(K))
        ys = tuple(jnp.zeros((S,), jnp.float32) for _ in range(K))
        sc = jnp.zeros((8, K), jnp.int32)
        counts = jnp.zeros((K,), jnp.int32)
        lrs = jnp.ones((K,), jnp.float32)
        z = jnp.float32(0.0)
        sharded = str(jax.make_jaxpr(run.__wrapped__)(
            W, Xs, ys, sc, counts, lrs, z, z, z, z
        ))
        assert "psum" in sharded

    def test_trivial_mesh_fit_takes_original_program(self):
        from dask_ml_tpu.models.sgd import SGDClassifier

        X, y = _mk_xy(600)
        with config.set(stream_block_rows=96, stream_mesh=1):
            m = SGDClassifier(max_iter=1, random_state=0,
                              shuffle=False).fit(X, y)
        # single-device carry: no mesh sharding entered the fit
        assert len(m._w.sharding.device_set) == 1
