"""Execute every shipped example in a subprocess (VERDICT r4 weak #5:
examples must not rot — the suite fails when one breaks). Sizes shrink
via DASK_ML_TPU_EXAMPLE_N; the child forces the CPU platform exactly as
conftest does (the axon plugin ignores JAX_PLATFORMS)."""

import glob
import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_EXAMPLES = sorted(glob.glob(os.path.join(_REPO, "examples", "[0-9]*.py")))


def test_examples_exist():
    assert len(_EXAMPLES) >= 4


@pytest.mark.slow
@pytest.mark.parametrize(
    "path", _EXAMPLES, ids=[os.path.basename(p) for p in _EXAMPLES]
)
def test_example_runs(path):
    driver = (
        "import sys; sys.path.insert(0, {repo!r})\n"
        "from dask_ml_tpu._platform import force_cpu_platform\n"
        "force_cpu_platform(n_devices=8)\n"
        "import runpy\n"
        "runpy.run_path({path!r}, run_name='__main__')\n"
    ).format(repo=_REPO, path=path)
    env = dict(os.environ)
    env["DASK_ML_TPU_EXAMPLE_N"] = "2048"
    proc = subprocess.run(
        [sys.executable, "-c", driver], capture_output=True, text=True,
        timeout=600, env=env, cwd=_REPO,
    )
    assert proc.returncode == 0, (
        f"{os.path.basename(path)} failed\n--- stdout ---\n"
        f"{proc.stdout[-3000:]}\n--- stderr ---\n{proc.stderr[-3000:]}"
    )
