"""Multi-host OUT-OF-CORE fits (VERDICT r4 missing #3): each process
streams its local memmap shard; per-pass block sums merge over the
psum/allgather plane; the result matches the single-process fit over the
concatenated data. Real 2-process jax.distributed bring-up, 4 virtual
CPU devices per process (2 procs x 4 devices = the dryrun shape).

Ref: SURVEY.md §1 L2 (the reference's dd-from-files ingest with
per-worker partitions feeding one global fit) and §3.2."""

import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from tests._mp_capability import (
    free_port as _free_port,
    require_multiprocess_backend,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


_WORKER = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, {repo!r})
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    pid = int(sys.argv[1]); nproc = int(sys.argv[2]); port = sys.argv[3]
    outdir = sys.argv[4]
    jax.distributed.initialize(
        coordinator_address="127.0.0.1:" + port,
        num_processes=nproc, process_id=pid)
    import dask_ml_tpu.config as config
    from dask_ml_tpu.linear_model import LogisticRegression
    from dask_ml_tpu.cluster import KMeans

    # this process's shard: rows [pid*n_loc, (pid+1)*n_loc) of the
    # deterministic global dataset the parent also generates
    rng = np.random.RandomState(0)
    n_glob, d = 4096, 6
    Xg = rng.randn(n_glob, d).astype(np.float32)
    w = rng.randn(d).astype(np.float32)
    yg = (Xg @ w + 0.3 * rng.randn(n_glob) > 0).astype(np.float32)
    Xg[yg > 0, :2] += 1.5   # separable-ish + cluster structure
    n_loc = n_glob // nproc
    lo, hi = pid * n_loc, (pid + 1) * n_loc
    path = os.path.join(outdir, f"shard{{pid}}.f32")
    m = np.memmap(path, dtype=np.float32, mode="w+", shape=(n_loc, d))
    m[:] = Xg[lo:hi]
    m.flush()
    X = np.memmap(path, dtype=np.float32, mode="r", shape=(n_loc, d))
    y = yg[lo:hi]

    with config.set(stream_block_rows=256):
        for solver in ("lbfgs", "admm"):
            clf = LogisticRegression(solver=solver, max_iter=60).fit(X, y)
            np.save(os.path.join(outdir, f"coef_{{solver}}_{{pid}}.npy"),
                    np.r_[clf.coef_.ravel(), clf.intercept_])
        km = KMeans(n_clusters=2, random_state=0, max_iter=20).fit(X)
        np.save(os.path.join(outdir, f"centers_{{pid}}.npy"),
                km.cluster_centers_)
        np.save(os.path.join(outdir, f"inertia_{{pid}}.npy"),
                np.asarray([km.inertia_]))
        from dask_ml_tpu.decomposition import PCA
        p = PCA(n_components=3).fit(X)
        np.save(os.path.join(outdir, f"pca_{{pid}}.npy"),
                np.r_[p.mean_[None], p.components_])
    print("proc", pid, "OK", flush=True)
""")


@pytest.mark.slow
def test_two_process_streamed_fits_match_single(tmp_path):
    require_multiprocess_backend()
    nproc = 2
    last = None
    for _attempt in range(2):
        port = str(_free_port())
        procs = [
            subprocess.Popen(
                [sys.executable, "-c", _WORKER.format(repo=REPO),
                 str(pid), str(nproc), port, str(tmp_path)],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True,
            )
            for pid in range(nproc)
        ]
        try:
            outs = [p.communicate(timeout=600)[0] for p in procs]
        except subprocess.TimeoutExpired:
            # a hung collective is exactly the failure mode multi-host
            # bugs produce — reap the workers, then retry/fail
            for p in procs:
                p.kill()
            outs = [p.communicate()[0] for p in procs]
        last = outs
        if all(p.returncode == 0 for p in procs):
            break
    else:
        pytest.fail("workers failed:\n" + "\n---\n".join(last))

    # single-process reference over the CONCATENATED data (same blocks
    # per process: each worker streamed 256-row blocks of its shard)
    from dask_ml_tpu._platform import force_cpu_platform  # noqa: F401
    import dask_ml_tpu.config as config
    from dask_ml_tpu.cluster import KMeans
    from dask_ml_tpu.linear_model import LogisticRegression

    rng = np.random.RandomState(0)
    n_glob, d = 4096, 6
    Xg = rng.randn(n_glob, d).astype(np.float32)
    w = rng.randn(d).astype(np.float32)
    yg = (Xg @ w + 0.3 * rng.randn(n_glob) > 0).astype(np.float32)
    Xg[yg > 0, :2] += 1.5

    with config.set(stream_block_rows=256):
        for solver, tol in (("lbfgs", 2e-3), ("admm", 2e-2)):
            ref = LogisticRegression(solver=solver, max_iter=60).fit(
                Xg, yg
            )
            ref_vec = np.r_[ref.coef_.ravel(), ref.intercept_]
            for pid in range(nproc):
                got = np.load(tmp_path / f"coef_{solver}_{pid}.npy")
                np.testing.assert_allclose(
                    got, ref_vec, rtol=tol, atol=tol,
                    err_msg=f"{solver} proc {pid}",
                )
        ref_km = KMeans(n_clusters=2, random_state=0, max_iter=20).fit(Xg)
        # both processes computed identical global centers
        c0 = np.load(tmp_path / "centers_0.npy")
        c1 = np.load(tmp_path / "centers_1.npy")
        np.testing.assert_allclose(c0, c1, atol=1e-6)
        # centers match the single-process fit up to cluster permutation
        ref_sorted = ref_km.cluster_centers_[
            np.argsort(ref_km.cluster_centers_[:, 0])
        ]
        got_sorted = c0[np.argsort(c0[:, 0])]
        np.testing.assert_allclose(got_sorted, ref_sorted, rtol=2e-2,
                                   atol=2e-2)
        i0 = float(np.load(tmp_path / "inertia_0.npy")[0])
        assert abs(i0 - ref_km.inertia_) / ref_km.inertia_ < 2e-2
        # PCA: identical across processes AND matches single-process
        from dask_ml_tpu.decomposition import PCA

        ref_p = PCA(n_components=3).fit(Xg)
        p0 = np.load(tmp_path / "pca_0.npy")
        p1 = np.load(tmp_path / "pca_1.npy")
        np.testing.assert_allclose(p0, p1, atol=1e-7)
        np.testing.assert_allclose(p0[0], ref_p.mean_, atol=1e-4)
        np.testing.assert_allclose(
            np.abs(p0[1:] @ ref_p.components_.T), np.eye(3), atol=1e-3
        )


def test_virtual_streamed_fits_match_single():
    """Single-process twin: 2 virtual rank THREADS each stream HALF the
    rows (256-row blocks); the per-pass block sums merge through the
    in-process psum_host rendezvous; both ranks converge to the
    identical global fit, matching the single-process fit over the
    concatenated data — the same partition/merge logic as the real
    2-process run, minus the cross-process fabric."""
    from dask_ml_tpu._platform import force_cpu_platform  # noqa: F401
    import dask_ml_tpu.config as config
    from dask_ml_tpu.cluster import KMeans
    from dask_ml_tpu.decomposition import PCA
    from dask_ml_tpu.linear_model import LogisticRegression
    from dask_ml_tpu.parallel import distributed as dist

    rng = np.random.RandomState(0)
    n_glob, d = 4096, 6
    Xg = rng.randn(n_glob, d).astype(np.float32)
    w = rng.randn(d).astype(np.float32)
    yg = (Xg @ w + 0.3 * rng.randn(n_glob) > 0).astype(np.float32)
    Xg[yg > 0, :2] += 1.5   # separable-ish + cluster structure

    def body(rank):
        n_loc = n_glob // 2
        lo, hi = rank * n_loc, (rank + 1) * n_loc
        X, y = Xg[lo:hi], yg[lo:hi]
        out = {}
        # config is thread-local: each rank arms its own streaming plan
        with config.set(stream_block_rows=256):
            for solver in ("lbfgs", "admm"):
                clf = LogisticRegression(solver=solver, max_iter=60).fit(
                    X, y
                )
                out[solver] = np.r_[clf.coef_.ravel(), clf.intercept_]
            km = KMeans(n_clusters=2, random_state=0, max_iter=20).fit(X)
            out["centers"] = np.asarray(km.cluster_centers_)
            out["inertia"] = float(km.inertia_)
            p = PCA(n_components=3).fit(X)
            out["pca"] = np.r_[p.mean_[None], p.components_]
        return out

    r0, r1 = dist.run_virtual_processes(body, world=2, timeout=600)

    with config.set(stream_block_rows=256):
        for solver, tol in (("lbfgs", 2e-3), ("admm", 2e-2)):
            ref = LogisticRegression(solver=solver, max_iter=60).fit(
                Xg, yg
            )
            ref_vec = np.r_[ref.coef_.ravel(), ref.intercept_]
            for got in (r0[solver], r1[solver]):
                np.testing.assert_allclose(got, ref_vec, rtol=tol,
                                           atol=tol, err_msg=solver)
        ref_km = KMeans(n_clusters=2, random_state=0, max_iter=20).fit(Xg)
        # both ranks computed identical global centers
        np.testing.assert_allclose(r0["centers"], r1["centers"], atol=1e-6)
        ref_sorted = ref_km.cluster_centers_[
            np.argsort(ref_km.cluster_centers_[:, 0])
        ]
        got_sorted = r0["centers"][np.argsort(r0["centers"][:, 0])]
        np.testing.assert_allclose(got_sorted, ref_sorted, rtol=2e-2,
                                   atol=2e-2)
        assert abs(r0["inertia"] - ref_km.inertia_) / ref_km.inertia_ < 2e-2
        # PCA: identical across ranks AND matches single-process
        ref_p = PCA(n_components=3).fit(Xg)
        np.testing.assert_allclose(r0["pca"], r1["pca"], atol=1e-7)
        np.testing.assert_allclose(r0["pca"][0], ref_p.mean_, atol=1e-4)
        np.testing.assert_allclose(
            np.abs(r0["pca"][1:] @ ref_p.components_.T), np.eye(3),
            atol=1e-3
        )
