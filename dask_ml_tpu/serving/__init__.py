"""Online inference serving for fitted estimators.

The inference side of the ROADMAP north star ("serves heavy traffic from
millions of users"): where ``wrappers.ParallelPostFit`` parallelizes ONE
big offline predict over blocks, this package answers MANY small
concurrent requests without paying a fresh XLA compile per novel shape
or a host→device parameter transfer per call.

- ``_buckets``  — the geometric shape-bucket ladder bounding the
  compiled-program set;
- ``_batching`` — request records, the bounded admission queue,
  ping-pong staging buffers, pack/demux;
- ``_server``   — :class:`ModelServer`: micro-batching worker, warmup,
  backpressure (:class:`ServerOverloaded` / :class:`RequestTimeout`),
  graceful drain;
- ``metrics``   — per-batch spans + serving counters through
  ``dask_ml_tpu/observability``, and the latency-quantile window.

Quick start::

    from dask_ml_tpu.serving import ModelServer

    with ModelServer(fitted_clf,
                     methods=("predict", "predict_proba")).warmup() as srv:
        fut = srv.submit(x_small)        # Future
        proba = srv.predict_proba(x)     # blocking convenience
"""

from ._buckets import BucketLadder
from ._server import (
    ModelServer,
    RequestTimeout,
    ServerClosed,
    ServerOverloaded,
    ServingError,
)

__all__ = [
    "BucketLadder",
    "ModelServer",
    "RequestTimeout",
    "ServerClosed",
    "ServerOverloaded",
    "ServingError",
]
