from . import distributed
from .frames import PartitionedFrame, from_pandas
from .mesh import (DATA_AXIS, MODEL_AXIS, default_mesh, device_mesh,
                   resolve_mesh, use_mesh)
from .sharded import ShardedArray, as_sharded, reshard, row_mask, take_rows
from .streaming import (Block, BlockStream, SparseBlocks, stream_plan,
                        streamed_map)
