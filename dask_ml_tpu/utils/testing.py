"""Testing helpers. Ref: ``dask_ml/utils.py::assert_estimator_equal``
(SURVEY.md §2a Support row) — attribute-wise comparison of fitted
estimators, the §4 parity-harness primitive."""

from __future__ import annotations

import numpy as np

from ..parallel.sharded import ShardedArray


def _to_comparable(v):
    if isinstance(v, ShardedArray):
        return v.to_numpy()
    try:
        import jax

        if isinstance(v, jax.Array):
            return np.asarray(v)
    except ImportError:  # pragma: no cover
        pass
    return v


def assert_estimator_equal(left, right, exclude=None, **kwargs):
    """Check that two fitted estimators have equal learned attributes.

    kwargs are forwarded to np.testing.assert_allclose (rtol/atol).
    """
    exclude = set(exclude or ())
    l_attrs = {a for a in vars(left) if a.endswith("_")
               and not a.startswith("_")}
    r_attrs = {a for a in vars(right) if a.endswith("_")
               and not a.startswith("_")}
    attrs = (l_attrs & r_attrs) - exclude
    assert attrs, "no common fitted attributes to compare"
    for attr in sorted(attrs):
        lv = _to_comparable(getattr(left, attr))
        rv = _to_comparable(getattr(right, attr))
        assert type(lv).__name__ == type(rv).__name__ or (
            np.isscalar(lv) and np.isscalar(rv)
        ) or (isinstance(lv, np.ndarray) == isinstance(rv, np.ndarray)), (
            f"{attr}: type mismatch {type(lv)} vs {type(rv)}"
        )
        if isinstance(lv, np.ndarray):
            np.testing.assert_allclose(
                lv, rv, err_msg=f"attribute {attr}", **kwargs
            )
        elif np.isscalar(lv) and isinstance(lv, (int, float, np.floating)):
            np.testing.assert_allclose(
                lv, rv, err_msg=f"attribute {attr}", **kwargs
            )
        else:
            assert lv == rv, f"attribute {attr}: {lv!r} != {rv!r}"


def copy_learned_attributes(from_estimator, to_estimator):
    """Ref: dask_ml/utils.py::copy_learned_attributes."""
    for attr, v in vars(from_estimator).items():
        if attr.endswith("_") and not attr.startswith("_"):
            setattr(to_estimator, attr, v)
    return to_estimator
