"""Device-native SGD estimators with ``partial_fit``.

The reference has no GLM partial_fit — its ``Incremental`` wrapper streams
blocks through *sklearn's* SGDClassifier (SURVEY.md §3.6), keeping the hot
loop on host CPU. These estimators keep the model AND the update on
device: each ``partial_fit`` is one jitted optax step (or a few) on a
streamed block — the TPU-resident streaming-partial_fit path of
BASELINE.md configs[3]. Same sklearn contract, so they compose with
``Incremental``, ``IncrementalSearchCV`` and Hyperband.

Update rule: full-block gradient steps (minibatch GD), not per-sample SGD
— per-sample loops don't map to the MXU; a block IS the minibatch.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..base import BaseEstimator, ClassifierMixin, RegressorMixin, to_host
from ..metrics import accuracy_score, r2_score
from ..parallel.sharded import ShardedArray, as_sharded
from ..utils.validation import check_is_fitted

_LOSSES = ("log_loss", "hinge", "squared_error")


@partial(jax.jit, static_argnames=("loss",))
def _sgd_step(X, y, mask, n_valid, w, opt_state, lr, alpha, loss):
    def objective(w):
        eta = X @ w[:-1] + w[-1]
        if loss == "log_loss":
            per = jax.nn.softplus(eta) - y * eta
        elif loss == "hinge":
            margins = (2.0 * y - 1.0) * eta
            per = jnp.maximum(0.0, 1.0 - margins)
        else:  # squared_error
            per = 0.5 * (eta - y) ** 2
        data_loss = jnp.sum(per * mask) / jnp.maximum(n_valid, 1.0)
        reg = 0.5 * alpha * jnp.sum(w[:-1] ** 2)  # intercept unpenalized
        return data_loss + reg

    val, grad = jax.value_and_grad(objective)(w)
    w = w - lr * grad
    return w, opt_state, val


class _SGDBase(BaseEstimator):
    loss_default = "squared_error"

    def __init__(self, loss=None, penalty="l2", alpha=1e-4, eta0=0.01,
                 learning_rate="invscaling", power_t=0.25, max_iter=5,
                 tol=1e-3, shuffle=True, random_state=None, warm_start=False,
                 fit_intercept=True):
        self.loss = loss
        self.penalty = penalty
        self.alpha = alpha
        self.eta0 = eta0
        self.learning_rate = learning_rate
        self.power_t = power_t
        self.max_iter = max_iter
        self.tol = tol
        self.shuffle = shuffle
        self.random_state = random_state
        self.warm_start = warm_start
        self.fit_intercept = fit_intercept

    def _loss(self):
        loss = self.loss or self.loss_default
        if loss not in _LOSSES:
            raise ValueError(f"loss must be one of {_LOSSES}, got {loss!r}")
        return loss

    def _lr(self):
        t = max(self._t, 1)
        if self.learning_rate == "constant":
            return self.eta0
        if self.learning_rate == "invscaling":
            return self.eta0 / (t ** self.power_t)
        if self.learning_rate == "optimal":
            return 1.0 / (self.alpha * (1e3 + t))
        raise ValueError(f"Unknown learning_rate {self.learning_rate!r}")

    def _ensure_state(self, d):
        if not hasattr(self, "_w") or self._w is None:
            self._w = jnp.zeros((d + 1,), jnp.float32)
            self._opt_state = ()
            self._t = 0

    def _block(self, X, y):
        X = as_sharded(X, dtype=np.float32)
        y = as_sharded(self._encode_y(y), mesh=X.mesh, dtype=np.float32)
        return X, y

    def partial_fit(self, X, y, classes=None, **kwargs):
        if classes is not None:
            self._set_classes(np.asarray(classes))
        X, y = self._block(X, y)
        self._ensure_state(X.shape[1])
        mask = X.row_mask(jnp.float32)
        self._t += 1
        self._w, self._opt_state, self._last_loss = _sgd_step(
            X.data, y.data, mask, jnp.float32(X.n_rows), self._w,
            self._opt_state, jnp.float32(self._lr()),
            jnp.float32(self.alpha), self._loss(),
        )
        self._publish(X.shape[1])
        return self

    def fit(self, X, y, **kwargs):
        if not self.warm_start:
            self._w = None
        n_blocks = 8
        from ..parallel.streaming import BlockStream

        Xh = X.to_numpy() if isinstance(X, ShardedArray) else np.asarray(X)
        yh = y.to_numpy() if isinstance(y, ShardedArray) else np.asarray(y)
        if hasattr(self, "_set_classes") and kwargs.get("classes") is None:
            uniq = np.unique(yh)
            if getattr(self, "classes_", None) is None or not self.warm_start:
                self._set_classes(uniq)
        stream = BlockStream(
            (Xh, self._encode_y(yh)),
            block_rows=max(len(Xh) // n_blocks, 1),
            shuffle=self.shuffle, seed=self.random_state,
        )
        self._ensure_state(Xh.shape[1])
        for block in stream.epochs(self.max_iter):
            Xb, yb = block.arrays
            self._t += 1
            self._w, self._opt_state, self._last_loss = _sgd_step(
                Xb, yb, block.mask, jnp.float32(block.n_rows), self._w,
                self._opt_state, jnp.float32(self._lr()),
                jnp.float32(self.alpha), self._loss(),
            )
        self._publish(Xh.shape[1])
        self.n_iter_ = self.max_iter
        return self

    def _decision(self, X):
        X = as_sharded(X, dtype=np.float32)
        w = self._w
        return X, X.data @ w[:-1] + w[-1]

    def _encode_y(self, y):
        return np.asarray(y)

    def _publish(self, d):
        pass


class SGDClassifier(ClassifierMixin, _SGDBase):
    """Binary classifier; device analog of sklearn's SGDClassifier for the
    Incremental / adaptive-search streaming paths."""

    loss_default = "log_loss"

    def _set_classes(self, classes):
        if len(classes) != 2:
            raise ValueError("SGDClassifier supports binary targets")
        self.classes_ = classes

    def partial_fit(self, X, y, classes=None, **kwargs):
        # sklearn contract: classes required on the first partial_fit call
        # (adaptive searches pass it through fit_params, as with dask-ml)
        if classes is None and getattr(self, "classes_", None) is None:
            raise ValueError(
                "classes must be passed on the first call to partial_fit."
            )
        return super().partial_fit(X, y, classes=classes, **kwargs)

    def _encode_y(self, y):
        y = np.asarray(y)
        if getattr(self, "classes_", None) is None:
            return y
        return (y == self.classes_[1]).astype(np.float32)

    def _publish(self, d):
        w = to_host(self._w).astype(np.float64)
        self.coef_ = w[:-1].reshape(1, -1)
        self.intercept_ = np.atleast_1d(w[-1])

    def decision_function(self, X):
        check_is_fitted(self, "coef_")
        X, eta = self._decision(X)
        return to_host(eta)[: X.n_rows]

    def predict(self, X):
        scores = self.decision_function(X)
        return self.classes_[(scores > 0).astype(int)]

    def predict_proba(self, X):
        if self._loss() != "log_loss":
            raise AttributeError("predict_proba requires loss='log_loss'")
        check_is_fitted(self, "coef_")
        X, eta = self._decision(X)
        p1 = to_host(jax.nn.sigmoid(eta))[: X.n_rows]
        return np.stack([1 - p1, p1], axis=1)

    def score(self, X, y):
        return accuracy_score(
            y.to_numpy() if isinstance(y, ShardedArray) else np.asarray(y),
            self.predict(X),
        )


class SGDRegressor(RegressorMixin, _SGDBase):
    loss_default = "squared_error"

    def _publish(self, d):
        w = to_host(self._w).astype(np.float64)
        self.coef_ = w[:-1]
        self.intercept_ = float(w[-1])

    def predict(self, X):
        check_is_fitted(self, "coef_")
        X, eta = self._decision(X)
        return to_host(eta)[: X.n_rows]

    def score(self, X, y):
        return r2_score(
            y.to_numpy() if isinstance(y, ShardedArray) else np.asarray(y),
            self.predict(X),
        )
