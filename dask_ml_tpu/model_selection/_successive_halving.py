"""SuccessiveHalvingSearchCV.

Reference: ``dask_ml/model_selection/_successive_halving.py`` (SURVEY.md
§2a, §3.5): rungs of training where after each rung only the top
``1/aggressiveness`` fraction of models survives, and survivors train
``aggressiveness`` times longer — built on the incremental controller's
``additional_calls`` protocol.
"""

from __future__ import annotations

import math

import numpy as np

from ._incremental import BaseIncrementalSearchCV


class SuccessiveHalvingSearchCV(BaseIncrementalSearchCV):
    """Ref: _successive_halving.py::SuccessiveHalvingSearchCV."""

    def __init__(self, estimator, parameters, n_initial_parameters=10,
                 n_initial_iter=None, max_iter=None, aggressiveness=3,
                 test_size=None, patience=False, tol=1e-3,
                 random_state=None, scoring=None, verbose=False, prefix=""):
        super().__init__(estimator, parameters,
                         n_initial_parameters=n_initial_parameters,
                         test_size=test_size, patience=patience, tol=tol,
                         max_iter=max_iter, random_state=random_state,
                         scoring=scoring, verbose=verbose, prefix=prefix)
        self.n_initial_iter = n_initial_iter
        self.aggressiveness = aggressiveness

    def fit(self, X, y=None, **fit_params):
        if self.n_initial_iter is None:
            raise ValueError("n_initial_iter must be specified")
        return super().fit(X, y, **fit_params)

    def _reset_hook(self):
        self._rung = 0

    def _hook_state(self):
        return {"_rung": self._rung}

    def _additional_calls(self, info):
        eta = self.aggressiveness
        # models have all trained r_i = n_initial_iter * eta^rung calls when
        # this fires; promote top 1/eta and triple (eta) their budget
        scores = {mid: recs[-1]["score"] for mid, recs in info.items()}
        calls = {mid: recs[-1]["partial_fit_calls"]
                 for mid, recs in info.items()}
        target = self.n_initial_iter * (eta ** self._rung)
        # first bring everyone to the current rung's budget
        pending = {
            mid: target - calls[mid]
            for mid in scores if calls[mid] < target
        }
        if pending:
            return {mid: max(c, 0) for mid, c in pending.items()}
        # rung complete: cut to top 1/eta
        n_keep = max(1, math.floor(len(scores) / eta))
        keep = sorted(scores, key=scores.get, reverse=True)[:n_keep]
        self._rung += 1
        next_target = self.n_initial_iter * (eta ** self._rung)
        if self.max_iter is not None:
            next_target = min(next_target, self.max_iter)
        out = {mid: next_target - calls[mid] for mid in keep}
        out = {mid: c for mid, c in out.items() if c > 0}
        if len(keep) == 1 and not out:
            return {}
        if not out:
            return {}
        return out
