"""Test harness (SURVEY.md §4): run on a virtual 8-device CPU mesh so
N-way sharding logic is exercised without a pod — the analog of the
reference's in-process ``gen_cluster`` scheduler+workers."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dask_ml_tpu._platform import force_cpu_platform  # noqa: E402

force_cpu_platform(n_devices=8)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def mesh():
    from dask_ml_tpu.parallel import default_mesh

    return default_mesh()


@pytest.fixture(scope="session")
def xy_classification():
    from sklearn.datasets import make_classification

    X, y = make_classification(
        n_samples=500, n_features=10, n_informative=5, random_state=0
    )
    return X.astype(np.float64), y.astype(np.float64)


@pytest.fixture(scope="session")
def xy_regression():
    from sklearn.datasets import make_regression

    X, y = make_regression(
        n_samples=500, n_features=10, n_informative=5, noise=5.0, random_state=0
    )
    return X.astype(np.float64), y.astype(np.float64)
