"""Ref: dask_ml/model_selection/__init__.py."""
from ._hyperband import HyperbandSearchCV
from ._incremental import IncrementalSearchCV, InverseDecaySearchCV
from ._search import GridSearchCV, RandomizedSearchCV, check_cv
from ._split import KFold, ShuffleSplit, train_test_split
from ._successive_halving import SuccessiveHalvingSearchCV
