"""Meta-estimator wrappers: ParallelPostFit and Incremental.

Reference: ``dask_ml/wrappers.py`` + ``dask_ml/_partial.py`` (SURVEY.md
§2a Wrappers row, §3.6):

- ``ParallelPostFit``: train on small in-memory data, parallelize
  predict/transform/score over blocks.
- ``Incremental``: out-of-core fit via a sequential ``partial_fit`` chain
  over blocks (optionally shuffled per call).

TPU mapping: "blocks" are the row ranges of a ShardedArray. A wrapped
dask_ml_tpu estimator predicts device-parallel as-is (no wrapper machinery
needed — GSPMD already parallelizes); the wrapper's job is interop with
*host* (sklearn-style) estimators: post-fit ops stream blocks through the
host estimator, and ``Incremental.fit`` is the streamed training loop the
reference builds as a linear task chain (the model no longer hops
worker-to-worker; blocks stream to it).
"""

from __future__ import annotations

import numpy as np

from .base import BaseEstimator, clone
from .metrics import accuracy_score, r2_score
from .parallel.sharded import ShardedArray, as_sharded

__all__ = ["ParallelPostFit", "Incremental"]


def _data_shards(mesh):
    from .parallel.mesh import data_shards

    return data_shards(mesh)


def _device_headroom_bytes(nbytes, sample, fraction=0.5):
    """True when an extra device allocation of ``nbytes`` (sharded like
    ``sample``) plausibly fits: per-device free bytes (when the runtime
    reports memory_stats — TPU does, CPU returns None and passes) must
    cover the per-device share with ``fraction`` slack."""
    try:
        data = getattr(sample, "data", None)
        if data is None:
            return True  # host sample: no device copy involved
        devs = list(data.devices())
        per_dev = nbytes / max(len(devs), 1)
        for dev in devs:
            stats = dev.memory_stats()
            if not stats:
                continue
            free = stats.get("bytes_limit", 0) - stats.get(
                "bytes_in_use", 0
            )
            if per_dev > fraction * free:
                return False
        return True
    except Exception:
        return True  # no reliable stats: assume fine (host-backed CPU)


def _device_headroom_for_copy(X, fraction=0.5):
    """True when a full second device copy of ``X`` plausibly fits."""
    return _device_headroom_bytes(X.data.nbytes, X, fraction)


def _is_device_estimator(est):
    return est.__class__.__module__.startswith("dask_ml_tpu")


def _host_matrix(X):
    """Host representation supporting arbitrary row slicing: CSR for any
    sparse source (scipy matrix of any format, SparseBlocks), numpy
    otherwise — the ONE sparse/dense coercion point for the block loops."""
    import scipy.sparse as sp

    from .parallel.streaming import SparseBlocks

    if isinstance(X, SparseBlocks) or sp.issparse(X):
        return X.tocsr()
    return X.to_numpy() if isinstance(X, ShardedArray) else np.asarray(X)


def _host_blocks(X, block_size=100_000):
    """Yield host row blocks of a ShardedArray / array. Sparse X stays
    sparse — host (sklearn) estimators consume CSR blocks natively."""
    host = _host_matrix(X)
    for i in range(0, host.shape[0], block_size):
        yield host[i:i + block_size]


class ParallelPostFit(BaseEstimator):
    """Ref: dask_ml/wrappers.py::ParallelPostFit. The ``*_meta``
    parameters are accepted for API parity: the reference uses them to
    declare dask output metadata; here output types are concrete, so they
    only pin the output dtype when given."""

    def __init__(self, estimator=None, scoring=None, predict_meta=None,
                 predict_proba_meta=None, transform_meta=None):
        self.estimator = estimator
        self.scoring = scoring
        self.predict_meta = predict_meta
        self.predict_proba_meta = predict_proba_meta
        self.transform_meta = transform_meta

    # -- fit: plain in-memory fit of the wrapped estimator ---------------
    def fit(self, X, y=None, **kwargs):
        from .parallel.streaming import SparseBlocks

        est = clone(self.estimator)
        if isinstance(X, ShardedArray):
            Xh = X.to_numpy()
        elif isinstance(X, SparseBlocks):
            Xh = X.tocsr()  # host estimators consume CSR, not the view
        else:
            Xh = X
        yh = y.to_numpy() if isinstance(y, ShardedArray) else y
        if yh is None:
            est.fit(Xh, **kwargs)
        else:
            est.fit(Xh, yh, **kwargs)
        self.estimator_ = est
        return self

    @property
    def _est(self):
        # support wrapping an already-fitted estimator without fit()
        return getattr(self, "estimator_", self.estimator)

    @property
    def classes_(self):
        return self._est.classes_

    # -- parallel post-fit ops --------------------------------------------
    def _pin_meta(self, out, method):
        """Pin the output dtype when a *_meta hint was given (the
        reference uses metas to declare dask output metadata; here output
        types are concrete, so only the dtype survives)."""
        import scipy.sparse as sp

        meta = {"predict": self.predict_meta,
                "predict_proba": self.predict_proba_meta,
                "transform": self.transform_meta}.get(method)
        if meta is not None and hasattr(meta, "dtype") \
                and (isinstance(out, np.ndarray) or sp.issparse(out)):
            out = out.astype(meta.dtype, copy=False)
        return out

    def _apply(self, X, method):
        est = self._est
        from .parallel.frames import PartitionedFrame

        if isinstance(X, PartitionedFrame):
            # the reference's dd path: map_partitions(est.<method>) —
            # partitions run concurrently through the frame's thread pool
            parts = X.map_partitions(getattr(est, method))
            if isinstance(parts, PartitionedFrame):  # frame-in, frame-out
                return parts
            return self._pin_meta(
                np.concatenate([np.asarray(p) for p in parts], axis=0),
                method,
            )
        if _is_device_estimator(est):
            return getattr(est, method)(X)
        mesh = X.mesh if isinstance(X, ShardedArray) else None
        # blocks are SLICES of one host buffer (views, not copies), so
        # listing them costs nothing beyond the to_numpy pull a host
        # estimator needs anyway
        blocks = list(_host_blocks(X))
        fn = getattr(est, method)
        if len(blocks) > 1:
            # the reference's map_blocks runs post-fit blocks on parallel
            # workers; here a thread pool over the host estimator's
            # (read-only, GIL-releasing sklearn C kernels) per-block calls
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(
                max_workers=min(8, len(blocks))
            ) as pool:
                parts = list(pool.map(fn, blocks))
        else:
            parts = [fn(b) for b in blocks]
        import scipy.sparse as sp

        if any(sp.issparse(p) for p in parts):
            # sparse estimator output (e.g. a transformer): stays sparse
            return self._pin_meta(sp.vstack(parts).tocsr(), method)
        out = self._pin_meta(np.concatenate(parts, axis=0), method)
        return as_sharded(out, mesh=mesh) if mesh is not None else out

    def predict(self, X):
        return self._apply(X, "predict")

    def predict_proba(self, X):
        return self._apply(X, "predict_proba")

    def predict_log_proba(self, X):
        return self._apply(X, "predict_log_proba")

    def decision_function(self, X):
        return self._apply(X, "decision_function")

    def transform(self, X):
        return self._apply(X, "transform")

    def score(self, X, y, compute=True):
        if self.scoring:
            from .metrics.scorer import get_scorer

            return get_scorer(self.scoring)(self, X, y)
        pred = self.predict(X)
        if hasattr(self._est, "classes_") or hasattr(self._est, "predict_proba"):
            return accuracy_score(y, pred)
        return r2_score(y, pred)


class Incremental(ParallelPostFit):
    """Ref: dask_ml/wrappers.py::Incremental +
    dask_ml/_partial.py::fit."""

    def __init__(self, estimator=None, scoring=None, shuffle_blocks=True,
                 random_state=None, assume_equal_chunks=True,
                 predict_meta=None, predict_proba_meta=None,
                 transform_meta=None):
        self.estimator = estimator
        self.scoring = scoring
        self.shuffle_blocks = shuffle_blocks
        self.random_state = random_state
        self.assume_equal_chunks = assume_equal_chunks
        self.predict_meta = predict_meta
        self.predict_proba_meta = predict_proba_meta
        self.transform_meta = transform_meta

    def _partial_fit_pass(self, est, X, y, block_size, rng, **fit_kwargs):
        if _is_device_estimator(est) and isinstance(X, ShardedArray):
            # device estimator + device data: blocks are the fused-epoch
            # grid's contiguous S-row ranges (fused_blocks), so the
            # fused and per-block paths train identical minibatches.
            # Blocks materialize as sharded gathers (take_rows); the
            # dataset never round-trips through host (VERDICT r2 #4 —
            # the reference's partial_fit chain runs on worker-resident
            # chunks the same way, SURVEY §3.6)
            from .models.sgd import fused_blocks
            from .parallel.sharded import take_rows

            ys = y if isinstance(y, ShardedArray) or y is None \
                else np.asarray(y)
            B, S = fused_blocks(X)
            # the last grid block always holds ≥1 real row (padding < D
            # and S*(B-1) is a multiple of D), so B IS the block count
            order = list(range(B))
            if self.shuffle_blocks:
                rng.shuffle(order)
            if (hasattr(est, "_fused_epoch") and ys is not None
                    and B > 1
                    and set(fit_kwargs) <= {"classes"}
                    and _device_headroom_for_copy(X)):
                # fused-epoch fast path: the whole pass compiles into ONE
                # scan program (same updates/order/lr clock as the block
                # loop) — per-block dispatch round trips vanish. The
                # grid is a second device copy of X for the epoch, hence
                # the headroom gate (the loop gathers one block at a
                # time and stays the fallback near HBM capacity).
                est._fused_epoch(
                    X, ys, order, n_blocks=B,
                    classes=fit_kwargs.get("classes"),
                )
                return est
            for b in order:
                idx = np.arange(b * S, min((b + 1) * S, X.n_rows))
                Xb = take_rows(X, idx)
                if ys is None:
                    est.partial_fit(Xb, **fit_kwargs)
                else:
                    yb = take_rows(ys, idx) if isinstance(ys, ShardedArray) \
                        else ys[idx]
                    est.partial_fit(Xb, yb, **fit_kwargs)
            return est
        # sparse X blocks stay CSR host-side: a device estimator's
        # partial_fit densifies ONE block at placement (as_sharded), a
        # host estimator consumes the CSR block natively — either way
        # peak memory is O(block), never the dense corpus
        Xh = _host_matrix(X)
        yh = y.to_numpy() if isinstance(y, ShardedArray) else np.asarray(y)
        starts = list(range(0, Xh.shape[0], block_size))
        if self.shuffle_blocks:
            rng.shuffle(starts)
        for s in starts:
            est.partial_fit(Xh[s:s + block_size], yh[s:s + block_size],
                            **fit_kwargs)
        return est

    def fit(self, X, y=None, **fit_kwargs):
        est = clone(self.estimator)
        if not hasattr(est, "partial_fit"):
            raise ValueError(
                f"{type(est).__name__} has no partial_fit; Incremental "
                "requires a partial_fit-capable estimator"
            )
        # classifiers need `classes` on the first partial_fit; the
        # reference makes callers pass classes= explicitly (y is a lazy
        # dask array there, a global unique is a cluster job) — here y is
        # concrete, so infer it when omitted (explicit classes= still wins)
        from sklearn.base import is_classifier

        if (y is not None and "classes" not in fit_kwargs
                and is_classifier(est)):
            if isinstance(y, ShardedArray):
                # binary: a three-scalar device scan, no column gather
                from .utils.validation import device_classes

                fit_kwargs["classes"] = device_classes(y)
            else:
                fit_kwargs["classes"] = np.unique(np.asarray(y))
        rng = np.random.RandomState(self.random_state)
        self.estimator_ = self._partial_fit_pass(
            est, X, y, self._block_size(X), rng, **fit_kwargs
        )
        return self

    def partial_fit(self, X, y=None, **fit_kwargs):
        est = getattr(self, "estimator_", None)
        if est is None:
            est = clone(self.estimator)
        rng = np.random.RandomState(self.random_state)
        self.estimator_ = self._partial_fit_pass(
            est, X, y, self._block_size(X), rng, **fit_kwargs
        )
        return self

    @staticmethod
    def _block_size(X):
        if isinstance(X, ShardedArray):
            # the device branch of _partial_fit_pass derives its own
            # contiguous fused_blocks partition and ignores this value;
            # report that partition's row count for consistency
            from .models.sgd import fused_blocks

            return max(fused_blocks(X)[1], 1)
        # host inputs: the SAME grid partition the device path uses
        # (capped by the byte budget for sparse/memmap sources), so
        # host- and device-input fits train identical blocks
        from .parallel.streaming import fit_block_rows

        return fit_block_rows(X)
