"""Multi-host runtime tests (SURVEY.md §5 distributed-comm row).

Single-process paths run in-process; the REAL 2-process bring-up
(jax.distributed.initialize + cross-process collective over the gloo/DCN
control plane) runs in subprocesses — the analog of the reference's
``gen_cluster`` in-process scheduler+workers, but with actual separate
processes. Fault injection: one worker is killed and the survivor's
checkpoint-restart path is exercised (SURVEY.md §5 failure row)."""

import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from tests._mp_capability import (
    free_port as _free_port,
    require_multiprocess_backend,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_single_process_runtime():
    from dask_ml_tpu.parallel import distributed as dist

    dist.initialize()  # no coordinator configured -> single-process no-op
    assert dist.process_count() == 1
    assert dist.process_index() == 0
    assert dist.is_coordinator()
    assert dist.barrier() == float(len(__import__("jax").devices()))
    out = dist.broadcast_host(np.arange(3.0))
    np.testing.assert_array_equal(out, np.arange(3.0))


_WORKER = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, {repo!r})
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import jax.numpy as jnp
    pid = int(sys.argv[1]); nproc = int(sys.argv[2]); port = sys.argv[3]
    jax.distributed.initialize(
        coordinator_address="127.0.0.1:" + port,
        num_processes=nproc, process_id=pid)
    assert jax.process_count() == nproc
    from dask_ml_tpu.parallel import distributed as dist
    # global mesh spans both processes' devices
    mesh = dist.global_mesh()
    assert mesh.shape["data"] == 2 * nproc, mesh.shape
    # cross-process collective: barrier psum over every device
    total = dist.barrier()
    assert total == 2 * nproc, total
    # control-plane broadcast from the coordinator
    val = np.array([42.0, 7.0]) if dist.is_coordinator() else np.zeros(2)
    got = dist.broadcast_host(val)
    assert np.allclose(got, [42.0, 7.0]), got
    print("proc", pid, "OK", flush=True)
""")


@pytest.mark.slow
def test_two_process_collectives(tmp_path):
    """Real 2-process jax.distributed bring-up: global mesh, psum barrier,
    coordinator broadcast. One retry: the free-port probe can race with
    another process binding it between probe and bring-up."""
    require_multiprocess_backend()
    last = None
    for _attempt in range(2):
        port = str(_free_port())
        procs = [
            subprocess.Popen(
                [sys.executable, "-c", _WORKER.format(repo=REPO),
                 str(i), "2", port],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
                env={**os.environ, "JAX_PLATFORMS": "cpu"},
            )
            for i in range(2)
        ]
        try:
            outs = []
            for p in procs:
                out, _ = p.communicate(timeout=180)
                outs.append(out)
            ok = all(p.returncode == 0 for p in procs) and all(
                f"proc {i} OK" in out for i, out in enumerate(outs)
            )
            if ok:
                return
            last = "\n---\n".join(outs)
        finally:
            for p in procs:  # no orphans on timeout/assert failure
                if p.poll() is None:
                    p.kill()
    raise AssertionError(f"both attempts failed:\n{last}")


_DYING_WORKER = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, {repo!r})
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    pid = int(sys.argv[1]); port = sys.argv[2]
    if pid == 1:
        # fault injection: worker 1 dies before joining the runtime
        sys.exit(17)
    jax.distributed.initialize(
        coordinator_address="127.0.0.1:" + port,
        num_processes=2, process_id=pid,
        initialization_timeout=15)
    print("unexpected success", flush=True)
    sys.exit(3)
""")


@pytest.mark.slow
def test_worker_death_detected(tmp_path):
    """Fault injection: a worker dies during bring-up. The survivor's
    coordination service DETECTS the loss (deadline heartbeat) and
    terminates the process — the SPMD whole-slice failure mode whose
    recovery path is checkpoint-restart (utils/checkpoint.py), not
    dask-style lineage recompute (SURVEY.md §5 failure row)."""
    port = str(_free_port())
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _DYING_WORKER.format(repo=REPO), str(i), port],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        for i in range(2)
    ]
    try:
        out0, _ = procs[0].communicate(timeout=120)
        procs[1].communicate(timeout=30)
        assert procs[1].returncode == 17  # the injected death
        # survivor must NOT hang or report success: it terminates after
        # detecting the dead peer (abort or raised deadline error)
        assert procs[0].returncode != 3, out0
        assert "Deadline" in out0 or "DEADLINE" in out0 or "died" in out0, out0
    finally:
        for p in procs:  # no orphans on timeout/assert failure
            if p.poll() is None:
                p.kill()


_SEARCH_WORKER = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, {repo!r})
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    pid = int(sys.argv[1]); port = sys.argv[2]; expected_path = sys.argv[3]
    jax.distributed.initialize(
        coordinator_address="127.0.0.1:" + port,
        num_processes=2, process_id=pid)
    from sklearn.datasets import make_classification
    from dask_ml_tpu.linear_model import LogisticRegression
    from dask_ml_tpu.model_selection import GridSearchCV
    X, y = make_classification(n_samples=400, n_features=8,
                               n_informative=4, random_state=0)
    X = X.astype(np.float32); y = y.astype(np.float32)
    search = GridSearchCV(
        LogisticRegression(solver="lbfgs", max_iter=25),
        {{"C": [0.01, 0.1, 1.0, 10.0]}}, cv=2,
        scheduler="synchronous", refit=True,
    )
    search.fit(X, y)
    n_local, n_total, proc, n_proc = search._dist_stats
    assert n_proc == 2 and proc == pid
    assert n_local < n_total, (n_local, n_total)   # fitted a strict subset
    assert n_local == len(range(pid, n_total, 2))
    scores = search.cv_results_["mean_test_score"]
    assert not np.isnan(scores).any(), scores      # merge filled every cell
    expected = np.load(expected_path)
    assert np.allclose(scores, expected, atol=1e-4), (scores, expected)
    # refit happened locally and the final state is usable
    assert search.best_estimator_.score(X, y) > 0.7
    print("proc", pid, "search OK", flush=True)
""")


@pytest.mark.slow
def test_two_process_distributed_search(tmp_path):
    """Real 2-process Grid search: each process fits a disjoint trial
    subset on its local-device mesh; the allgather merge reassembles
    cv_results_ identical to the sequential single-process run
    (SURVEY.md §3.5 'trials pinned to hosts', VERDICT r2 #2)."""
    require_multiprocess_backend()
    import numpy as np
    from sklearn.datasets import make_classification

    from dask_ml_tpu.linear_model import LogisticRegression
    from dask_ml_tpu.model_selection import GridSearchCV

    # sequential reference in THIS (single-)process
    X, y = make_classification(n_samples=400, n_features=8,
                               n_informative=4, random_state=0)
    X = X.astype(np.float32)
    y = y.astype(np.float32)
    # cv=2/max_iter=25: one fold shape means ONE lbfgs compile per
    # process; the distribution semantics under test are unchanged
    seq = GridSearchCV(
        LogisticRegression(solver="lbfgs", max_iter=25),
        {"C": [0.01, 0.1, 1.0, 10.0]}, cv=2,
        scheduler="synchronous", refit=False,
    ).fit(X, y)
    expected_path = str(tmp_path / "expected.npy")
    np.save(expected_path, np.asarray(seq.cv_results_["mean_test_score"]))

    port = str(_free_port())
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _SEARCH_WORKER.format(repo=REPO),
             str(i), port, expected_path],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        for i in range(2)
    ]
    try:
        outs = []
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
        for i, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"proc {i} failed:\n{out}"
            assert f"proc {i} search OK" in out
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()


_HB_BODY = textwrap.dedent("""
    import numpy as np
    from scipy.stats import loguniform
    from dask_ml_tpu.models.sgd import SGDClassifier
    from dask_ml_tpu.model_selection import HyperbandSearchCV
    rng = np.random.RandomState(0)
    X = rng.randn(600, 6).astype(np.float32)
    w = rng.randn(6)
    y = (X @ w > 0).astype(np.float32)
    params = {{"alpha": [1e-5, 1e-4, 1e-3, 1e-2],
              "eta0": [0.05, 0.5]}}
    search = HyperbandSearchCV(
        SGDClassifier(tol=1e-3, random_state=0), params,
        max_iter=9, aggressiveness=3, random_state=0,
    )
    search.fit(X, y, classes=[0.0, 1.0])
""")

_HB_SOLO = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, {repo!r})
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax
    jax.config.update("jax_platforms", "cpu")
""") + _HB_BODY + textwrap.dedent("""
    import numpy as np
    np.savez(sys.argv[1],
             test_score=np.asarray(search.cv_results_["test_score"],
                                   np.float64),
             best_score=search.best_score_,
             n_history=len(search.history_))
    print("solo OK", flush=True)
""")

_HB_WORKER = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, {repo!r})
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax
    jax.config.update("jax_platforms", "cpu")
    pid = int(sys.argv[1]); port = sys.argv[2]
    jax.distributed.initialize(
        coordinator_address="127.0.0.1:" + port,
        num_processes=2, process_id=pid)
""") + _HB_BODY + textwrap.dedent("""
    import numpy as np
    assert search._dist_stats == (pid, 2)
    exp = np.load(sys.argv[3])
    got = np.asarray(search.cv_results_["test_score"], np.float64)
    assert got.shape == exp["test_score"].shape, (got.shape,
                                                 exp["test_score"].shape)
    assert np.allclose(got, exp["test_score"], atol=1e-5), (
        got, exp["test_score"])
    assert abs(search.best_score_ - float(exp["best_score"])) < 1e-5
    assert len(search.history_) == int(exp["n_history"])
    assert {{r["bracket"] for r in search.history_}} == {{0, 1, 2}}
    # the gathered best model is usable on every process
    assert 0.0 <= search.best_estimator_.score(X, y) <= 1.0
    print("proc", pid, "hyperband OK", flush=True)
""")


@pytest.mark.slow
def test_two_process_hyperband_brackets(tmp_path):
    """Hyperband brackets distributed over 2 real processes reassemble
    history_/cv_results_/best identical to the single-process run
    (BASELINE configs[4]; VERDICT r2 #2)."""
    require_multiprocess_backend()
    exp = str(tmp_path / "expected.npz")
    solo = subprocess.run(
        [sys.executable, "-c", _HB_SOLO.format(repo=REPO), exp],
        capture_output=True, text=True, timeout=240,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert solo.returncode == 0, solo.stdout + solo.stderr

    port = str(_free_port())
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _HB_WORKER.format(repo=REPO),
             str(i), port, exp],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        for i in range(2)
    ]
    try:
        outs = []
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
        for i, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"proc {i} failed:\n{out}"
            assert f"proc {i} hyperband OK" in out
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()


_ADAPT_BODY = textwrap.dedent("""
    import numpy as np
    from sklearn.linear_model import SGDClassifier as SkSGD
    from dask_ml_tpu.model_selection import IncrementalSearchCV
    rng = np.random.RandomState(0)
    X = rng.randn(500, 6).astype(np.float32)
    w = rng.randn(6)
    y = (X @ w > 0).astype(np.float32)
    # random_state pinned ON THE ESTIMATOR: sklearn's SGD draws a seed
    # from the GLOBAL numpy RNG per partial_fit when unseeded, and the
    # number of draws per process differs under distribution
    search = IncrementalSearchCV(
        SkSGD(tol=None, random_state=7),
        {{"alpha": [1e-5, 1e-4, 1e-3, 1e-2, 1e-1, 1.0]}},
        n_initial_parameters="grid", decay_rate=1.0, max_iter=6,
        random_state=0,
    )
    search.fit(X, y, classes=[0.0, 1.0])
""")

_ADAPT_SOLO = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, {repo!r})
    # 4 devices: the 2-process run sees 4 GLOBAL devices, and block count
    # derives from the global mesh — the solo reference must match
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax
    jax.config.update("jax_platforms", "cpu")
""") + _ADAPT_BODY + textwrap.dedent("""
    import numpy as np
    np.savez(sys.argv[1],
             scores=np.asarray(search.cv_results_["test_score"], np.float64),
             calls=np.asarray(search.cv_results_["partial_fit_calls"]),
             best_score=search.best_score_, n_history=len(search.history_))
    print("solo OK", flush=True)
""")

_ADAPT_WORKER = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, {repo!r})
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax
    jax.config.update("jax_platforms", "cpu")
    pid = int(sys.argv[1]); port = sys.argv[2]
    jax.distributed.initialize(
        coordinator_address="127.0.0.1:" + port,
        num_processes=2, process_id=pid)
""") + _ADAPT_BODY + textwrap.dedent("""
    import numpy as np
    assert search._dist_stats == (pid, 2)
    exp = np.load(sys.argv[3])
    got = np.asarray(search.cv_results_["test_score"], np.float64)
    assert np.allclose(got, exp["scores"], atol=1e-6), (got, exp["scores"])
    assert np.array_equal(
        np.asarray(search.cv_results_["partial_fit_calls"]), exp["calls"])
    assert abs(search.best_score_ - float(exp["best_score"])) < 1e-6
    assert len(search.history_) == int(exp["n_history"])
    # ownership evidence: this process trained ONLY mid % 2 == pid, and
    # the merged history covers both owners
    owners = {{r["model_id"] % 2 for r in search.history_
              if r["owner"] == pid}}
    assert owners == {{pid}}, owners
    assert {{r["owner"] for r in search.history_}} == {{0, 1}}
    # the gathered best model is usable everywhere
    assert 0.0 <= search.best_estimator_.score(X, (X @ w > 0)) <= 1.0
    print("proc", pid, "adaptive OK", flush=True)
""")


@pytest.mark.slow
def test_two_process_adaptive_search(tmp_path):
    """IncrementalSearchCV candidates distributed over 2 real processes:
    per-round record allgather keeps the adaptive decisions identical, and
    cv_results_/history_ match the single-process run exactly."""
    require_multiprocess_backend()
    exp = str(tmp_path / "expected.npz")
    solo = subprocess.run(
        [sys.executable, "-c", _ADAPT_SOLO.format(repo=REPO), exp],
        capture_output=True, text=True, timeout=240,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert solo.returncode == 0, solo.stdout + solo.stderr

    port = str(_free_port())
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _ADAPT_WORKER.format(repo=REPO),
             str(i), port, exp],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        for i in range(2)
    ]
    try:
        outs = []
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
        for i, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"proc {i} failed:\n{out}"
            assert f"proc {i} adaptive OK" in out
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()


_GLOBAL_FIT_WORKER = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, {repo!r})
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    pid = int(sys.argv[1]); port = sys.argv[2]; expected_path = sys.argv[3]
    jax.distributed.initialize(
        coordinator_address="127.0.0.1:" + port,
        num_processes=2, process_id=pid)
    from dask_ml_tpu.linear_model import LogisticRegression
    from dask_ml_tpu.parallel import distributed as dist
    from dask_ml_tpu.parallel.mesh import use_mesh
    from dask_ml_tpu.parallel.sharded import ShardedArray
    rng = np.random.RandomState(0)
    X = rng.randn(400, 8).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    mesh = dist.global_mesh()          # 4 devices over 2 processes
    assert mesh.shape["data"] == 4
    with use_mesh(mesh):
        Xs = ShardedArray.from_array(X, mesh=mesh)
        ys = ShardedArray.from_array(y, mesh=mesh)
        # every process holds only its 2 addressable shards
        assert not Xs.data.is_fully_addressable
        assert len(Xs.data.addressable_shards) == 2
        clf = LogisticRegression(solver="lbfgs", max_iter=60)
        clf.fit(Xs, ys)                # GSPMD psum spans BOTH processes
        # the cross-host replicating gather reassembles the full array
        np.testing.assert_allclose(Xs.to_numpy(), X, atol=0)
        # row gathers (CV fold extraction) also work on the global mesh
        from dask_ml_tpu.parallel.sharded import take_rows
        sub = take_rows(Xs, np.arange(37))
        np.testing.assert_allclose(sub.to_numpy(), X[:37], atol=0)
    expected = np.load(expected_path)
    assert np.allclose(clf.coef_.ravel(), expected, atol=5e-3), (
        clf.coef_.ravel(), expected)
    print("proc", pid, "globalfit OK", flush=True)
""")


@pytest.mark.slow
def test_two_process_global_mesh_fit(tmp_path):
    """DATA-PLANE multi-host: one LogisticRegression fit whose design
    matrix is sharded across TWO processes' devices on the global mesh —
    the loss/grad psum rides the cross-process collective fabric, the
    SPMD analog of the reference's multi-machine training
    (SURVEY.md §2b comm row, §5 'DCN'; completes VERDICT r2 #2's data
    plane half)."""
    require_multiprocess_backend()
    import numpy as np

    from dask_ml_tpu.linear_model import LogisticRegression
    from dask_ml_tpu.parallel import as_sharded

    rng = np.random.RandomState(0)
    X = rng.randn(400, 8).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    ref = LogisticRegression(solver="lbfgs", max_iter=60).fit(
        as_sharded(X), as_sharded(y)
    )
    expected_path = str(tmp_path / "coef.npy")
    np.save(expected_path, ref.coef_.ravel())

    port = str(_free_port())
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _GLOBAL_FIT_WORKER.format(repo=REPO),
             str(i), port, expected_path],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        for i in range(2)
    ]
    try:
        outs = []
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
        for i, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"proc {i} failed:\n{out}"
            assert f"proc {i} globalfit OK" in out
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()


_FRAME_WORKER = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, {repo!r})
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import pandas as pd
    pid = int(sys.argv[1]); port = sys.argv[2]
    jax.distributed.initialize(
        coordinator_address="127.0.0.1:" + port,
        num_processes=2, process_id=pid)
    from dask_ml_tpu.parallel import distributed as dist
    from dask_ml_tpu.parallel.frames import from_pandas
    # each process holds a DIFFERENT local frame (uneven row counts so
    # shard boundaries straddle the process boundary and parcels ship)
    rows = [37, 23][pid]
    rng = np.random.RandomState(pid)
    df = pd.DataFrame({{
        "a": np.arange(rows, dtype=np.float32) + 100.0 * pid,
        "b": rng.randn(rows).astype(np.float32),
        "s": ["x"] * rows,                       # non-numeric: dropped
    }})
    pf = from_pandas(df, npartitions=3)
    mesh = dist.global_mesh()
    sa = pf.to_sharded(mesh=mesh)
    assert sa.n_rows == 60, sa.n_rows
    assert sa.shape == (60, 2), sa.shape
    assert not sa.data.is_fully_addressable   # genuinely cross-process
    # global order = process order, content exact (column "a" encodes
    # process + row index)
    host = sa.to_numpy()
    expect_a = np.concatenate([np.arange(37.0), np.arange(23.0) + 100.0])
    assert np.allclose(host[:, 0], expect_a), host[:10]
    # the ingested array feeds a real global-mesh fit
    from dask_ml_tpu.linear_model import LinearRegression
    y = host[:, 0] * 0.5 + 1.0
    from dask_ml_tpu.parallel.sharded import ShardedArray
    ys = ShardedArray.from_array(y, mesh=mesh)
    est = LinearRegression(solver="lbfgs", max_iter=50).fit(sa, ys)
    pred = est.predict(host[:5])
    assert np.allclose(pred, y[:5], atol=1e-2), pred
    print("proc", pid, "frames OK", flush=True)
""")


@pytest.mark.slow
def test_two_process_frame_ingest(tmp_path):
    """Cross-process frame ingest (VERDICT r3 missing #3): each process
    contributes ITS local PartitionedFrame partitions to one global-mesh
    ShardedArray via array_from_process_local, then fits on it."""
    require_multiprocess_backend()
    last = None
    for _attempt in range(2):
        port = str(_free_port())
        procs = [
            subprocess.Popen(
                [sys.executable, "-c", _FRAME_WORKER.format(repo=REPO),
                 str(i), port],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
                env={**os.environ, "JAX_PLATFORMS": "cpu"},
            )
            for i in range(2)
        ]
        try:
            outs = []
            for p in procs:
                out, _ = p.communicate(timeout=240)
                outs.append(out)
            ok = all(p.returncode == 0 for p in procs) and all(
                f"proc {i} frames OK" in out for i, out in enumerate(outs)
            )
            if ok:
                return
            last = "\n---\n".join(outs)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
    raise AssertionError(f"both attempts failed:\n{last}")

# -- single-process virtual-rank twins ---------------------------------------
# Each real 2-process test above has a twin that runs the SAME
# partitioning/merge/failure logic as 2 rank THREADS of this process
# (``distributed.run_virtual_processes``): topology queries answer
# per-rank, host collectives rendezvous in-process, local_mesh splits
# the devices. The capability-gated subprocess tests keep covering the
# real collective fabric; these keep the logic under tier-1 everywhere.


def test_virtual_collectives():
    import jax

    from dask_ml_tpu.parallel import distributed as dist

    def body(rank):
        assert dist.process_count() == 2
        assert dist.process_index() == rank
        assert dist.is_coordinator() == (rank == 0)
        # object gather comes back in rank order on every rank
        got = dist.allgather_object({"rank": rank, "x": rank * 10})
        assert [g["rank"] for g in got] == [0, 1]
        assert [g["x"] for g in got] == [0, 10]
        # additive merge plane (the streamed-fit channel)
        s = dist.psum_host(np.full(3, float(rank + 1)))
        np.testing.assert_allclose(s, np.full(3, 3.0))
        # stacked host gather
        stack = dist.allgather_host(np.arange(4.0) + rank)
        assert stack.shape == (2, 4)
        np.testing.assert_allclose(stack[1] - stack[0], np.ones(4))
        # coordinator broadcast
        val = np.array([42.0, 7.0]) if rank == 0 else np.zeros(2)
        np.testing.assert_allclose(dist.broadcast_host(val), [42.0, 7.0])
        # barrier reports the same device-count sum as the real psum
        assert dist.barrier() == float(len(jax.devices()))
        # per-rank placement: disjoint submeshes of the local devices
        return [d.id for d in dist.local_mesh().devices.ravel()]

    ids = dist.run_virtual_processes(body, world=2)
    assert len(ids[0]) == len(ids[1]) == len(jax.devices()) // 2
    assert not (set(ids[0]) & set(ids[1])), ids


def test_virtual_worker_death():
    """Twin of test_worker_death_detected: a rank dying mid-round fails
    its peers' pending collectives FAST (poisoned exchange), and the
    injected exception — not the peers' collateral — reaches the
    caller."""
    from dask_ml_tpu.parallel import distributed as dist

    witnessed = {}

    def body(rank):
        if rank == 1:
            raise ValueError("injected death")
        try:
            dist.allgather_object("round-1")
        except RuntimeError as exc:
            witnessed["err"] = str(exc)
            raise
        raise AssertionError("survivor's collective must fail fast")

    with pytest.raises(ValueError, match="injected death"):
        dist.run_virtual_processes(body, world=2)
    assert "virtual peer 1 failed" in witnessed["err"]


def test_virtual_distributed_search():
    """Twin of test_two_process_distributed_search: strided
    (candidate, fold) shares on disjoint local meshes, one allgather
    merge, results identical to the sequential run."""
    from sklearn.datasets import make_classification

    from dask_ml_tpu.linear_model import LogisticRegression
    from dask_ml_tpu.model_selection import GridSearchCV
    from dask_ml_tpu.parallel import distributed as dist

    X, y = make_classification(n_samples=400, n_features=8,
                               n_informative=4, random_state=0)
    X = X.astype(np.float32)
    y = y.astype(np.float32)
    seq = GridSearchCV(
        LogisticRegression(solver="lbfgs", max_iter=25),
        {"C": [0.01, 0.1, 1.0, 10.0]}, cv=2,
        scheduler="synchronous", refit=False,
    ).fit(X, y)
    expected = np.asarray(seq.cv_results_["mean_test_score"])

    def body(rank):
        search = GridSearchCV(
            LogisticRegression(solver="lbfgs", max_iter=25),
            {"C": [0.01, 0.1, 1.0, 10.0]}, cv=2,
            scheduler="synchronous", refit=True,
        ).fit(X, y)
        n_local, n_total, proc, n_proc = search._dist_stats
        assert n_proc == 2 and proc == rank
        assert n_local < n_total, (n_local, n_total)
        assert n_local == len(range(rank, n_total, 2))
        scores = np.asarray(search.cv_results_["mean_test_score"])
        assert not np.isnan(scores).any(), scores  # merge filled every cell
        assert search.best_estimator_.score(X, y) > 0.7
        return scores

    for scores in dist.run_virtual_processes(body, world=2, timeout=600):
        np.testing.assert_allclose(scores, expected, atol=1e-4)


def test_virtual_hyperband_brackets():
    """Twin of test_two_process_hyperband_brackets: brackets strided
    over 2 virtual ranks, payload allgather merge, results identical to
    the single-process interleaved fit."""
    from dask_ml_tpu.model_selection import HyperbandSearchCV
    from dask_ml_tpu.models.sgd import SGDClassifier
    from dask_ml_tpu.parallel import distributed as dist

    rng = np.random.RandomState(0)
    X = rng.randn(600, 6).astype(np.float32)
    w = rng.randn(6)
    y = (X @ w > 0).astype(np.float32)
    params = {"alpha": [1e-5, 1e-4, 1e-3, 1e-2], "eta0": [0.05, 0.5]}

    def run():
        search = HyperbandSearchCV(
            SGDClassifier(tol=1e-3, random_state=0), params,
            max_iter=9, aggressiveness=3, random_state=0,
        )
        search.fit(X, y, classes=[0.0, 1.0])
        return search

    # the virtual ranks fit on half-meshes (local_mesh splits the
    # devices 2 ways) and SGD block math depends on shard count, so the
    # solo reference must run on a same-size mesh — exactly like the
    # real test, where solo and each worker process both saw 2 devices
    import jax

    from dask_ml_tpu.parallel.mesh import device_mesh, use_mesh

    half = device_mesh(devices=jax.devices()[:len(jax.devices()) // 2])
    with use_mesh(half):
        solo = run()
    exp = np.asarray(solo.cv_results_["test_score"], np.float64)

    def body(rank):
        search = run()
        assert search._dist_stats == (rank, 2)
        assert {r["bracket"] for r in search.history_} == {0, 1, 2}
        # the gathered best model is usable on every rank
        assert 0.0 <= search.best_estimator_.score(X, y) <= 1.0
        return (np.asarray(search.cv_results_["test_score"], np.float64),
                search.best_score_, len(search.history_))

    for got, best, n_hist in dist.run_virtual_processes(
            body, world=2, timeout=600):
        assert got.shape == exp.shape, (got.shape, exp.shape)
        np.testing.assert_allclose(got, exp, atol=1e-5)
        assert abs(best - solo.best_score_) < 1e-5
        assert n_hist == len(solo.history_)


def test_virtual_adaptive_search():
    """Twin of test_two_process_adaptive_search: mid%2 ownership,
    per-round record allgather, identical adaptive decisions."""
    from sklearn.linear_model import SGDClassifier as SkSGD

    from dask_ml_tpu.model_selection import IncrementalSearchCV
    from dask_ml_tpu.parallel import distributed as dist

    rng = np.random.RandomState(0)
    X = rng.randn(500, 6).astype(np.float32)
    w = rng.randn(6)
    y = (X @ w > 0).astype(np.float32)

    def make():
        return IncrementalSearchCV(
            SkSGD(tol=None, random_state=7),
            {"alpha": [1e-5, 1e-4, 1e-3, 1e-2, 1e-1, 1.0]},
            n_initial_parameters="grid", decay_rate=1.0, max_iter=6,
            random_state=0,
        )

    solo = make()
    solo.fit(X, y, classes=[0.0, 1.0])
    exp_scores = np.asarray(solo.cv_results_["test_score"], np.float64)
    exp_calls = np.asarray(solo.cv_results_["partial_fit_calls"])

    def body(rank):
        search = make()
        search.fit(X, y, classes=[0.0, 1.0])
        assert search._dist_stats == (rank, 2)
        # ownership evidence: this rank trained ONLY mid % 2 == rank,
        # and the merged history covers both owners
        owners = {r["model_id"] % 2 for r in search.history_
                  if r["owner"] == rank}
        assert owners == {rank}, owners
        assert {r["owner"] for r in search.history_} == {0, 1}
        assert 0.0 <= search.best_estimator_.score(X, y) <= 1.0
        return (np.asarray(search.cv_results_["test_score"], np.float64),
                np.asarray(search.cv_results_["partial_fit_calls"]),
                search.best_score_, len(search.history_))

    for scores, calls, best, n_hist in dist.run_virtual_processes(
            body, world=2, timeout=600):
        np.testing.assert_allclose(scores, exp_scores, atol=1e-6)
        np.testing.assert_array_equal(calls, exp_calls)
        assert abs(best - solo.best_score_) < 1e-6
        assert n_hist == len(solo.history_)


def test_virtual_frame_ingest():
    """Twin of test_two_process_frame_ingest: per-rank PartitionedFrames
    with UNEVEN row counts merge through array_from_process_local
    (parcel routing runs for real; the final assembly gather stands in
    for foreign-shard placement), then feed a fit."""
    import pandas as pd

    from dask_ml_tpu.linear_model import LinearRegression
    from dask_ml_tpu.parallel import distributed as dist
    from dask_ml_tpu.parallel.frames import from_pandas
    from dask_ml_tpu.parallel.sharded import ShardedArray

    def body(rank):
        rows = [37, 23][rank]
        rng = np.random.RandomState(rank)
        df = pd.DataFrame({
            "a": np.arange(rows, dtype=np.float32) + 100.0 * rank,
            "b": rng.randn(rows).astype(np.float32),
            "s": ["x"] * rows,                     # non-numeric: dropped
        })
        pf = from_pandas(df, npartitions=3)
        mesh = dist.global_mesh()
        sa = pf.to_sharded(mesh=mesh)
        assert sa.n_rows == 60, sa.n_rows
        assert sa.shape == (60, 2), sa.shape
        host = sa.to_numpy()
        # global order = rank order, content exact ("a" encodes
        # rank + row index)
        expect_a = np.concatenate([np.arange(37.0),
                                   np.arange(23.0) + 100.0])
        np.testing.assert_allclose(host[:, 0], expect_a)
        # the ingested array feeds a real fit on the same mesh
        yh = host[:, 0] * 0.5 + 1.0
        ys = ShardedArray.from_array(yh, mesh=mesh)
        est = LinearRegression(solver="lbfgs", max_iter=50).fit(sa, ys)
        pred = est.predict(host[:5])
        assert np.allclose(pred, yh[:5], atol=1e-2), pred
        return host

    h0, h1 = dist.run_virtual_processes(body, world=2, timeout=600)
    np.testing.assert_allclose(h0, h1)
