"""Streamed-cohort adaptive search (ISSUE 14): one data pass trains
the whole bracket.

Contracts under test, per the tentpole:

- the streamed plane (config.search_stream=True, the default for
  host-X searches over streamed-cohort-capable estimators) produces
  IDENTICAL history/scores/best to the device-resident cohort path run
  over the same block partition (search_stream=False) — including
  Hyperband's heterogeneous rounds, which ride per-model step masks in
  ONE scan instead of one sub-cohort per (n_calls, cursor) group;
- parity holds at stream mesh {1, 2, 8} (weight parity at the sharded
  psum flavors' float-reassociation level, same winner) and on a
  sparse corpus WITHOUT densify (the bucketed-nnz cohort scans);
- zero XLA compiles after round 1 across shrinking brackets: the slot
  RUNG ladder is warmed in round 1 and bracket halving reuses compiled
  scans via padded slot masks, never a recompile per surviving N;
- a search interrupted and resumed through the round-granular
  checkpoint plane reproduces the uninterrupted bracket bit-for-bit
  (stacked cohort carries round-trip exactly).
"""

import os

import numpy as np
import pytest
import scipy.sparse as sp

from dask_ml_tpu import config
from dask_ml_tpu import observability as obs
from dask_ml_tpu.model_selection import (HyperbandSearchCV,
                                         IncrementalSearchCV)
from dask_ml_tpu.models.sgd import SGDClassifier, SGDRegressor


def _xy(n=4096, d=12, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    y = (X[:, 0] + 0.3 * rng.randn(n) > 0).astype(np.float64)
    return X, y


def _hist_scores(search):
    recs = sorted(search.history_,
                  key=lambda r: (r["model_id"], r["partial_fit_calls"]))
    return np.asarray([r["score"] for r in recs])


PARAMS = {"alpha": list(np.logspace(-4, -1, 8)),
          "eta0": [0.05, 0.2]}


class TestStreamedVsDevicePlane:
    def test_incremental_bit_parity(self):
        X, y = _xy()

        def run(on):
            with config.set(search_stream=on, stream_block_rows=256,
                            stream_mesh=1):
                s = IncrementalSearchCV(
                    SGDClassifier(learning_rate="constant"), PARAMS,
                    n_initial_parameters=8, max_iter=12,
                    random_state=0,
                )
                s.fit(X, y, classes=[0.0, 1.0])
            return s

        s_on, s_off = run(True), run(False)
        meta = s_on.metadata_["stream"]
        assert meta["streamed"] is True and meta["rounds"] > 1
        assert s_off.metadata_["stream"] == {"streamed": False}
        np.testing.assert_array_equal(_hist_scores(s_on),
                                      _hist_scores(s_off))
        assert s_on.best_params_ == s_off.best_params_
        assert s_on.best_index_ == s_off.best_index_
        assert s_on.best_score_ == s_off.best_score_
        np.testing.assert_allclose(
            s_on.best_estimator_.coef_, s_off.best_estimator_.coef_,
            rtol=1e-6, atol=1e-7,
        )

    def test_hyperband_heterogeneous_rounds(self):
        # Hyperband's interleaved rounds request DIFFERENT n_calls per
        # bracket — the streamed plane folds them onto one block-step
        # timeline with per-model activity masks; parity must be exact
        X, y = _xy(6000, 16, seed=3)

        def run(on):
            with config.set(search_stream=on, stream_block_rows=512,
                            stream_mesh=1):
                h = HyperbandSearchCV(
                    SGDClassifier(), PARAMS, max_iter=9,
                    aggressiveness=3, random_state=0,
                )
                h.fit(X, y, classes=[0.0, 1.0])
            return h

        h_on, h_off = run(True), run(False)
        np.testing.assert_array_equal(_hist_scores(h_on),
                                      _hist_scores(h_off))
        assert h_on.best_params_ == h_off.best_params_
        assert h_on.best_score_ == h_off.best_score_
        # heterogeneous rounds collapsed: strictly fewer cohort
        # dispatches than the sum of per-(bracket, n_calls) groups
        assert h_on.metadata_["stream"]["dispatches"] >= 1

    def test_regressor_cohort(self):
        rng = np.random.RandomState(2)
        X = rng.randn(2048, 8).astype(np.float32)
        y = (X @ rng.randn(8) + 0.1 * rng.randn(2048)).astype(np.float64)

        def run(on):
            with config.set(search_stream=on, stream_block_rows=256,
                            stream_mesh=1):
                s = IncrementalSearchCV(
                    SGDRegressor(learning_rate="constant", eta0=0.01),
                    {"alpha": list(np.logspace(-5, -2, 6))},
                    n_initial_parameters=6, max_iter=8, random_state=0,
                )
                s.fit(X, y)
            return s

        s_on, s_off = run(True), run(False)
        np.testing.assert_allclose(_hist_scores(s_on),
                                   _hist_scores(s_off),
                                   rtol=1e-6, atol=1e-6)
        assert s_on.best_params_ == s_off.best_params_


class TestMeshAndSparse:
    @pytest.mark.parametrize("mesh_n", [2, 8])
    def test_sharded_cohort_parity(self, mesh_n):
        X, y = _xy(8192, 16, seed=1)

        def run(mesh):
            with config.set(stream_block_rows=1024, stream_mesh=mesh):
                s = IncrementalSearchCV(
                    SGDClassifier(learning_rate="constant"), PARAMS,
                    n_initial_parameters=8, max_iter=16,
                    fits_per_score=8, random_state=0,
                )
                s.fit(X, y, classes=[0.0, 1.0])
            return s

        s1, sm = run(1), run(mesh_n)
        assert sm.metadata_["stream"]["shards"] == mesh_n
        # per-shard partial sums reassociate float additions only —
        # drift accumulates over the round's sequential steps; the
        # stable contract is the winner plus weight closeness
        np.testing.assert_allclose(
            s1.best_estimator_.coef_, sm.best_estimator_.coef_,
            rtol=5e-2, atol=1e-3,
        )
        assert sm.best_params_ == s1.best_params_

    def test_fused_interpret_cohort(self):
        # fused Pallas cohort bodies (pallas.sgd_cohort[.psum]) through
        # the interpreter on CPU: parity + engagement recorded
        X, y = _xy(16384, 16, seed=4)

        def run(interp):
            with config.set(stream_block_rows=1024, stream_mesh=8,
                            pallas_stream_interpret=interp):
                s = IncrementalSearchCV(
                    SGDClassifier(learning_rate="constant"), PARAMS,
                    n_initial_parameters=8, max_iter=16,
                    fits_per_score=8, random_state=0,
                )
                s.fit(X, y, classes=[0.0, 1.0])
            return s

        ref, fused = run(False), run(True)
        assert fused.metadata_["stream"]["fused"] is True
        assert fused.metadata_["stream"]["fused_reason"] is None
        assert ref.metadata_["stream"]["fused"] is False
        np.testing.assert_allclose(
            ref.best_estimator_.coef_, fused.best_estimator_.coef_,
            rtol=1e-4, atol=1e-5,
        )
        assert fused.best_params_ == ref.best_params_

    def test_sparse_search_no_densify(self):
        rng = np.random.RandomState(5)
        Xs = sp.random(4096, 48, density=0.05, format="csr",
                       random_state=rng, dtype=np.float64)
        s = np.asarray(Xs.sum(axis=1)).ravel()
        y = (s > np.median(s)).astype(np.float64)

        with config.set(stream_block_rows=512, stream_mesh=1):
            hs = HyperbandSearchCV(SGDClassifier(), PARAMS, max_iter=9,
                                   aggressiveness=3, random_state=0)
            hs.fit(Xs, y, classes=[0.0, 1.0])
            hd = HyperbandSearchCV(SGDClassifier(), PARAMS, max_iter=9,
                                   aggressiveness=3, random_state=0)
            hd.fit(Xs.toarray().astype(np.float32), y,
                   classes=[0.0, 1.0])
        assert hs.metadata_["stream"]["sparse"] is True
        np.testing.assert_allclose(_hist_scores(hs), _hist_scores(hd),
                                   rtol=1e-5, atol=1e-6)
        assert hs.best_params_ == hd.best_params_

    def test_sparse_sharded_cohort(self):
        rng = np.random.RandomState(6)
        Xs = sp.random(4096, 32, density=0.08, format="csr",
                       random_state=rng, dtype=np.float64)
        s = np.asarray(Xs.sum(axis=1)).ravel()
        y = (s > np.median(s)).astype(np.float64)

        def run(mesh):
            with config.set(stream_block_rows=512, stream_mesh=mesh):
                h = IncrementalSearchCV(
                    SGDClassifier(), PARAMS, n_initial_parameters=8,
                    max_iter=8, fits_per_score=4, random_state=0,
                )
                h.fit(Xs, y, classes=[0.0, 1.0])
            return h

        h1, h2 = run(1), run(2)
        assert h2.metadata_["stream"]["sparse"] is True
        assert h2.metadata_["stream"]["shards"] == 2
        np.testing.assert_allclose(
            h1.best_estimator_.coef_, h2.best_estimator_.coef_,
            rtol=1e-4, atol=1e-5,
        )

    def test_sparse_over_density_refuses_loud(self):
        # an over-density corpus cannot take the streamed plane and the
        # device cohort path would densify it — the search refuses with
        # the recorded reason instead of silently materializing
        rng = np.random.RandomState(7)
        Xs = sp.random(1000, 8, density=0.9, format="csr",
                       random_state=rng, dtype=np.float64)
        y = (np.asarray(Xs.sum(axis=1)).ravel() > 0).astype(np.float64)
        with config.set(stream_block_rows=128, stream_mesh=1):
            with pytest.raises(ValueError, match="sparse"):
                IncrementalSearchCV(
                    SGDClassifier(), PARAMS, n_initial_parameters=4,
                    max_iter=4, random_state=0,
                ).fit(Xs, y, classes=[0.0, 1.0])


class TestDispatchAndCompileContract:
    def test_zero_compiles_after_round1_across_shrinks(self):
        # the slot rung ladder is warmed during round 1; every later
        # round of a shrinking candidate set (decay 8 -> 4 -> 2 -> 1)
        # must reuse compiled scans — the padded-N mask, not a
        # recompile per N
        X, y = _xy(16384, 16, seed=8)
        marks = []

        class Probe(IncrementalSearchCV):
            def _additional_calls(self, info):
                marks.append(
                    obs.counters_snapshot().get("recompiles", 0)
                )
                return super()._additional_calls(info)

        with config.set(stream_block_rows=2048, stream_mesh=1):
            p = Probe(SGDClassifier(learning_rate="constant"), PARAMS,
                      n_initial_parameters=8, decay_rate=1.0,
                      max_iter=48, fits_per_score=8, random_state=0)
            obs.counters_reset()
            p.fit(X, y, classes=[0.0, 1.0])
        assert len(marks) >= 3  # several shrinking rounds ran
        assert marks[-1] == marks[0], (
            f"{marks[-1] - marks[0]} new XLA compiles after round 1 "
            f"across shrinking rounds (marks={marks})"
        )

    def test_one_dispatch_per_superblock_per_round(self):
        X, y = _xy(16384, 16, seed=9)
        with config.set(stream_block_rows=2048, stream_mesh=1):
            s = IncrementalSearchCV(
                SGDClassifier(learning_rate="constant"), PARAMS,
                n_initial_parameters=8, decay_rate=None, max_iter=16,
                fits_per_score=8, random_state=0,
            )
            s.fit(X, y, classes=[0.0, 1.0])
        meta = s.metadata_["stream"]
        # every round advanced all 8 models by the same n_calls, so
        # each round's timeline is `fits_per_score` steps (round 1: 1)
        # and its dispatch count is exactly ceil(steps / K) — recover K
        # from the recorded totals
        n_rounds = meta["rounds"]
        dispatches = meta["dispatches"]
        assert n_rounds >= 2
        # round 1 = 1 step = 1 dispatch; later rounds 8 steps each
        k = max(2, -(-meta["n_blocks"] // 4))
        expect = 1 + (n_rounds - 1) * -(-8 // k)
        assert dispatches == expect, (meta, expect)


class TestResume:
    def test_resumed_search_bit_parity(self, tmp_path):
        # satellite: a streamed cohort round interrupted and resumed
        # via the round-granular checkpoint plane must reproduce the
        # uninterrupted bracket bit-for-bit — the stacked cohort
        # carries (weights + lr clocks + cursors) round-trip exactly
        X, y = _xy(4096, 12, seed=10)
        ckpt = os.path.join(tmp_path, "ck")

        def make():
            return HyperbandSearchCV(SGDClassifier(), PARAMS,
                                     max_iter=9, aggressiveness=3,
                                     random_state=0)

        with config.set(stream_block_rows=512, stream_mesh=1):
            ref = make().fit(X, y, classes=[0.0, 1.0])

        boom = {"armed": True}

        class Interrupted(HyperbandSearchCV):
            def _additional_calls(self, info):
                out = super()._additional_calls(info)
                if boom["armed"] and self._rungs and \
                        max(self._rungs.values()) >= 1:
                    boom["armed"] = False
                    raise RuntimeError("injected mid-search kill")
                return out

        with config.set(stream_block_rows=512, stream_mesh=1,
                        checkpoint_dir=ckpt):
            killed = Interrupted(SGDClassifier(), PARAMS, max_iter=9,
                                 aggressiveness=3, random_state=0)
            with pytest.raises(RuntimeError, match="injected"):
                killed.fit(X, y, classes=[0.0, 1.0])
            assert os.listdir(ckpt)  # a round checkpoint survived
            resumed = make()
            with config.set(checkpoint_dir=ckpt):
                resumed.fit(X, y, classes=[0.0, 1.0])

        np.testing.assert_array_equal(_hist_scores(resumed),
                                      _hist_scores(ref))
        assert resumed.best_params_ == ref.best_params_
        assert resumed.best_score_ == ref.best_score_
        np.testing.assert_array_equal(
            np.asarray(resumed.best_estimator_.coef_),
            np.asarray(ref.best_estimator_.coef_),
        )


class TestFallbacks:
    def test_device_input_keeps_device_plane(self):
        from dask_ml_tpu.parallel import as_sharded

        X, y = _xy(2048, 8, seed=11)
        Xs, ys = as_sharded(X), as_sharded(y)
        s = IncrementalSearchCV(
            SGDClassifier(learning_rate="constant"), PARAMS,
            n_initial_parameters=4, max_iter=4, random_state=0,
        )
        s.fit(Xs, ys, classes=[0.0, 1.0])
        assert s.metadata_["stream"] == {"streamed": False}

    def test_host_sklearn_estimator_untouched(self):
        from sklearn.linear_model import SGDClassifier as SkSGD

        X, y = _xy(1024, 8, seed=12)
        s = IncrementalSearchCV(
            SkSGD(tol=None), {"alpha": [1e-4, 1e-3]},
            n_initial_parameters=2, max_iter=3, random_state=0,
        )
        s.fit(X, y, classes=[0.0, 1.0])
        assert s.metadata_["stream"] == {"streamed": False}
        assert hasattr(s, "best_estimator_")
