"""Input validation / canonicalization.

Reference equivalent: ``dask_ml/utils.py::check_array / check_X_y /
check_chunks`` (SURVEY.md §2a "Support" row). Here canonicalization means:
accept numpy / jax arrays / ShardedArray, end with a row-sharded padded
device array on the estimator's mesh.
"""

from __future__ import annotations

import numpy as np

from ..parallel.mesh import resolve_mesh
from ..parallel.sharded import ShardedArray, as_sharded


def _assert_all_finite(arr, name="Input", allow_nan=False):
    """sklearn-parity finiteness gate for HOST float arrays (the
    reference inherits it from sklearn's check_array force_all_finite;
    ``allow_nan`` is its 'allow-nan' mode — NaN passes, inf never does).
    Device-resident inputs skip this — the solver-loop sanitizers
    (SURVEY.md §5 row 2) guard those without an extra device pass."""
    if not (isinstance(arr, np.ndarray)
            and np.issubdtype(arr.dtype, np.floating)):
        return
    if allow_nan:
        if np.isinf(arr).any():
            raise ValueError(f"{name} contains infinity.")
    elif not np.isfinite(arr).all():
        raise ValueError(f"{name} contains NaN or infinity.")


def check_array(x, mesh=None, dtype=None, ensure_2d=True, copy=False,
                allow_nan=False) -> ShardedArray:
    if not isinstance(x, ShardedArray):
        arr = np.asarray(x)
        if arr.ndim == 1 and ensure_2d:
            raise ValueError(
                f"Expected 2D array, got 1D array instead: shape {arr.shape}."
            )
        if arr.ndim > 2:
            raise ValueError(f"Expected <=2D array, got shape {arr.shape}.")
        if dtype is not None and np.issubdtype(np.dtype(dtype), np.floating):
            # validate AFTER the target-dtype cast: a finite float64 can
            # overflow to inf in float32 (sklearn checks post-conversion)
            arr = arr.astype(dtype, copy=False)
        _assert_all_finite(arr, "X", allow_nan=allow_nan)
        x = arr
    return as_sharded(x, mesh=resolve_mesh(mesh), dtype=dtype)


def check_X_y(X, y, mesh=None, dtype=None):
    mesh = resolve_mesh(mesh)
    n_X = X.n_rows if isinstance(X, ShardedArray) else np.asarray(X).shape[0]
    n_y = y.n_rows if isinstance(y, ShardedArray) else np.asarray(y).shape[0]
    if n_X != n_y:
        raise ValueError(f"X and y have inconsistent lengths: {n_X} vs {n_y}")
    X = check_array(X, mesh=mesh, dtype=dtype)
    if not isinstance(y, ShardedArray):
        yh = np.asarray(y)
        if dtype is not None and np.issubdtype(np.dtype(dtype), np.floating):
            # same post-cast rule as X: a finite float64 can overflow to
            # inf in float32 and must be caught HERE, not by the solver
            # sanitizer mid-fit
            yh = yh.astype(dtype, copy=False)
        _assert_all_finite(yh, "y")
        y = yh
    y = as_sharded(y, mesh=mesh, dtype=dtype)
    return X, y


def check_chunks(n_samples, n_features, chunks=None, mesh=None):
    """Normalize a dask-ml-style ``chunks`` argument to a flat
    ``(rows_per_shard, n_features)`` tuple.

    Ref: ``dask_ml/utils.py::check_chunks``. On TPU the row partitioning is
    dictated by the mesh's data axis, so when ``chunks`` is None the default
    is ``ceil(n_samples / data_shards)`` rows per shard with unchunked
    columns — the layout ``ShardedArray.from_array`` produces on ``mesh``
    (default mesh when None).
    """
    from ..parallel.mesh import data_shards

    if chunks is None:
        shards = data_shards(resolve_mesh(mesh))
        rows = max(int(np.ceil(n_samples / shards)), 1)
        return (rows, n_features)
    if isinstance(chunks, (int, np.integer)):
        # an integer is the NUMBER of blocks (reference semantics), with a
        # 100-row floor per block — not a rows-per-block count
        return (max(100, n_samples // max(int(chunks), 1)), n_features)
    if isinstance(chunks, (tuple, list)) and len(chunks) == 2:
        r, c = chunks
        # dask-ml also accepts per-dimension block-size tuples,
        # e.g. ((500, 500), (16,))
        if isinstance(r, (tuple, list)):
            r = max(int(v) for v in r) if len(r) else 0
        if isinstance(c, (tuple, list)):
            if len(c) != 1:
                raise AssertionError(
                    f"Column chunks must be a single block on TPU (got {c})"
                )
            c = c[0]
        if isinstance(r, (int, np.integer)) and isinstance(c, (int, np.integer)):
            if int(c) != n_features:
                raise AssertionError(
                    "Column chunks must span all n_features on TPU "
                    f"(got {c}, need {n_features})"
                )
            return (max(int(r), 1), n_features)
    raise AssertionError(f"Unexpected chunks value: {chunks!r}")


def data_fingerprint(a, n_sample=96) -> str:
    """Cheap content fingerprint of an array for checkpoint identity:
    same-shape different-content data must not resume stale state.
    Samples head, evenly strided middle, AND tail rows; for a
    ShardedArray that is one small device gather, never a full pull.
    Sample-based by design — collisions need identical values at every
    probed row."""
    import hashlib

    if a is None:
        return "none"
    n = a.shape[0] if hasattr(a, "shape") else len(a)
    k = max(n_sample // 3, 1)
    idx = np.unique(np.concatenate([
        np.arange(min(k, n)),
        np.linspace(0, n - 1, num=min(k, n), dtype=np.int64),
        np.arange(max(n - k, 0), n),
    ]))
    if isinstance(a, ShardedArray):
        from ..parallel.sharded import take_rows

        sample = take_rows(a, idx).to_numpy()
    else:
        from ..parallel.streaming import (_is_sparse_source, _slice_dense,
                                          as_row_sliceable)

        if _is_sparse_source(a):
            # sampled rows densify one at a time — O(sample), not O(n·d)
            a = as_row_sliceable(a)  # once, not per sampled row
            sample = np.concatenate([
                _slice_dense(a, int(i), int(i) + 1, np.float32)
                for i in idx
            ]) if len(idx) else np.empty((0,) + a.shape[1:], np.float32)
        else:
            sample = np.asarray(a)[idx]
    return hashlib.sha1(
        np.ascontiguousarray(sample).tobytes()
    ).hexdigest()


import functools as _functools


@_functools.lru_cache(maxsize=1)
def _binary_class_scan():
    """Module-cached jitted scan — defining the jit inside
    ``device_binary_classes`` recompiled it (~0.3 s) on EVERY call,
    which dominated every Incremental fit's wall clock."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def _scan(data, mask):
        valid = mask > 0
        if data.dtype == jnp.bool_:
            data = data.astype(jnp.int32)
        # dtype-native sentinels: a float32 cast would corrupt integer
        # labels beyond 2^24 (ID-like class codes)
        if jnp.issubdtype(data.dtype, jnp.floating):
            big = jnp.asarray(jnp.inf, data.dtype)
            small = -big
        else:
            info = jnp.iinfo(data.dtype)
            big = jnp.asarray(info.max, data.dtype)
            small = jnp.asarray(info.min, data.dtype)
        mn = jnp.min(jnp.where(valid, data, big))
        mx = jnp.max(jnp.where(valid, data, small))
        binary = jnp.all(~valid | (data == mn) | (data == mx))
        # one stacked f32 output = ONE device→host fetch; three separate
        # scalar pulls cost three round trips (hundreds of ms each over a
        # tunneled runtime). Integer class values ride BIT-PRESERVED
        # (bitcast), not value-cast — f32 cannot represent ints > 2^24.
        vals = jnp.stack([mn, mx])
        if jnp.issubdtype(vals.dtype, jnp.floating):
            if vals.dtype != jnp.float32:
                # f64 under x64: a value-cast would round class values —
                # fall back to native-dtype scalars (extra fetches, but
                # the non-default mode pays for its precision)
                return mn, mx, binary
        elif vals.dtype.itemsize > 4:
            # i64/u64 under x64: an int32 bitcast would WRAP wide class
            # ids — same native-dtype fallback
            return mn, mx, binary
        else:
            vals = jax.lax.bitcast_convert_type(
                vals.astype(jnp.int32), jnp.float32
            )
        return jnp.concatenate(
            [vals.astype(jnp.float32), binary.astype(jnp.float32)[None]]
        )

    return _scan


def device_binary_classes(y: ShardedArray) -> np.ndarray:
    """The two class values of a device label vector, WITHOUT pulling the
    column to host (VERDICT r2 #4: ``_encode_y`` full-column round-trip).
    One jitted masked reduction; only three scalars cross to host. Raises
    ValueError for non-binary targets (the error path falls back to a
    host ``np.unique`` for an exact class count in the message)."""
    import jax
    import jax.numpy as jnp

    out = _binary_class_scan()(y.data, y.row_mask(jnp.float32))
    if isinstance(out, tuple):  # wide-dtype (f64/i64) fallback path
        mn_h, mx_h, binary = np.asarray(out[0]), np.asarray(out[1]),             bool(out[2])
    else:
        out = np.asarray(out)
        binary = bool(out[2])
        # mirror the scan's branch: bool was cast to int32 there, so only
        # genuinely-floating labels come back as values (ints bitcast)
        if np.issubdtype(np.dtype(str(y.dtype)), np.floating):
            mn_h, mx_h = out[0], out[1]
        else:
            mn_h, mx_h = np.ascontiguousarray(out[:2]).view(np.int32)
    if not binary or mn_h == mx_h:
        classes = np.unique(y.to_numpy())  # error path only
        err = ValueError(
            f"expected binary targets; got {len(classes)} classes"
        )
        # callers falling back to a host unique (the multiclass path)
        # reuse this instead of a second full-column gather + sort
        err.classes = classes
        raise err
    # classes keep the label dtype (np.unique parity: int labels give
    # int classes, so predict() returns the caller's dtype)
    return np.stack([mn_h, mx_h]).astype(np.dtype(str(y.dtype)))


def device_classes(y: ShardedArray) -> np.ndarray:
    """All class values of a device label vector: the three-scalar
    device scan when binary, falling back to the host unique the scan's
    error path already computed (ONE column gather total, never two).
    The ``err.classes`` handoff stays private to this module."""
    try:
        return device_binary_classes(y)
    except ValueError as e:
        c = getattr(e, "classes", None)
        return c if c is not None else np.unique(y.to_numpy())


def check_is_fitted(est, attr: str):
    if not hasattr(est, attr):
        raise AttributeError(
            f"This {type(est).__name__} instance is not fitted yet; call 'fit' first."
        )
