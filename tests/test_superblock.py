"""Super-block scan execution (ISSUE 3): K streamed blocks consumed by
one donated-carry XLA dispatch.

Covers the tentpole's contracts: ragged final super-block (fewer than K
blocks AND a short last block) pads with zero counts and contributes
nothing; sparse sources fall back to the per-block path; the donated
carry actually reuses buffers (no reallocation per dispatch, zero new
compiles after the first pass); and the super-block path's numbers match
the per-block path's to 1e-6 per pass.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dask_ml_tpu import config
from dask_ml_tpu import observability as obs
from dask_ml_tpu.parallel.streaming import BlockStream, SparseBlocks


def _mk_xy(n=1100, d=6, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    y = (X @ rng.randn(d) > 0).astype(np.float32)
    return X, y


def _stack(part):
    """SuperBlock array part as a host (K, S, ...) stack — the CPU
    layout keeps K separate block buffers (superblock_unrolled), the
    TPU/GPU layout one stacked buffer."""
    if isinstance(part, tuple):
        return np.stack([np.asarray(b) for b in part])
    return np.asarray(part)


class TestSuperBlockIterator:
    def test_ragged_final_superblock_pads_with_zero_counts(self):
        # 1100 rows / 96-row blocks = 12 blocks; K=8 -> super-blocks of
        # 8 and 4 real slots, the last real block holding 44 rows
        X, y = _mk_xy(1100)
        with config.set(stream_block_rows=96, superblock_k=8):
            s = BlockStream((X, y), block_rows=96)
            sbs = list(s.superblocks())
        assert [sb.n_blocks for sb in sbs] == [8, 4]
        last = sbs[-1]
        counts = np.asarray(last.counts)
        assert counts.shape == (8,)                      # fixed K shape
        assert _stack(last.arrays[0]).shape == \
            _stack(sbs[0].arrays[0]).shape
        assert list(counts[4:]) == [0, 0, 0, 0]          # padding slots
        assert counts[3] == 1100 - 11 * s.block_rows     # ragged rows
        # padding slots are zeroed, so masked kernels can't read junk
        assert float(np.abs(_stack(last.arrays[0])[4:]).sum()) == 0.0
        # every row round-trips exactly once, in order
        rows = []
        for sb in sbs:
            yb = _stack(sb.arrays[1])
            for j in range(sb.n_blocks):
                rows.append(yb[j][: np.asarray(sb.counts)[j]])
        np.testing.assert_array_equal(np.concatenate(rows), y)

    def test_k_resolution_and_opt_out(self):
        X, y = _mk_xy()
        with config.set(stream_block_rows=96):
            s = BlockStream((X, y), block_rows=96)
            assert s.resolve_superblock_k() > 1
            assert s.use_superblocks()
        with config.set(stream_block_rows=96, stream_superblock=False):
            s = BlockStream((X, y), block_rows=96)
            assert s.resolve_superblock_k() == 1
            assert not s.use_superblocks()
        with config.set(stream_block_rows=96, superblock_k=3):
            s = BlockStream((X, y), block_rows=96)
            assert s.resolve_superblock_k() == 3
        # K never exceeds the pass length
        with config.set(stream_block_rows=96, superblock_k=64):
            s = BlockStream((X, y), block_rows=96)
            assert s.resolve_superblock_k() == s.n_blocks

    def test_sparse_source_falls_back(self):
        import scipy.sparse as sp

        X, y = _mk_xy(400)
        Xs = SparseBlocks([sp.csr_matrix(X[:200]), sp.csr_matrix(X[200:])])
        with config.set(stream_block_rows=96):
            s = BlockStream((Xs,), block_rows=96)
            assert s.resolve_superblock_k() == 1
            assert not s.use_superblocks()

    def test_dispatch_stats_and_counters(self):
        X, y = _mk_xy(1100)
        obs.counters_reset()
        with config.set(stream_block_rows=96, superblock_k=4):
            s = BlockStream((X, y), block_rows=96)
            n = sum(1 for _ in s.superblocks())
        assert n == 3 == s.stats["dispatches_per_pass"]
        assert s.stats["superblock_k"] == 4
        assert s.stats["n_blocks"] == 12
        snap = obs.counters_snapshot()
        assert snap.get("superblock_dispatches") == 3
        assert snap.get("superblock_blocks") == 12

    def test_autotune_grows_k_when_consumer_stalls(self):
        X, y = _mk_xy(2000)
        with config.set(stream_block_rows=96, superblock_k=2):
            s = BlockStream((X, y), block_rows=96)
            list(s.superblocks())
            # synthesize a data-bound pass: the consumer stalled >10%
            # of the pass waiting on staged super-blocks
            s.stats["wait_s"] = 0.5
            s.stats["pass_s"] = 1.0
            s._maybe_grow_superblock()
            assert s.resolve_superblock_k() == 4
            # fully-overlapped passes leave K alone — worker busy time
            # (host_s/put_s) is NOT a growth signal for super-blocks
            s.stats["wait_s"] = 0.0
            s.stats["host_s"] = 1.0
            s.stats["put_s"] = 1.0
            s.stats["consume_s"] = 0.0
            s._maybe_grow_superblock()
            assert s.resolve_superblock_k() == 4


class TestObjectiveParity:
    def _objective(self, stream, n, d):
        from dask_ml_tpu.models.solvers.streamed import StreamedObjective

        return StreamedObjective(
            stream, n, jnp.asarray(0.1, jnp.float32), jnp.ones(d + 1),
            0.5, "logistic", "l2", True,
        )

    def test_per_pass_sums_match_per_block_to_1e6(self):
        n, d = 1100, 6
        X, y = _mk_xy(n, d)
        beta = np.random.RandomState(3).randn(d + 1)
        out = {}
        for sb in (True, False):
            with config.set(stream_block_rows=96, stream_superblock=sb):
                objective = self._objective(
                    BlockStream((X, y), block_rows=96), n, d
                )
                v, g = objective.value_and_grad(beta)
                v2, g2, h = objective.value_and_grad_and_hess(beta)
                out[sb] = (v, g, v2, g2, h, objective.value(beta))
        for a, b in zip(out[True], out[False]):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6, rtol=1e-6)

    def test_glm_streamed_solvers_run_superblocked(self):
        from dask_ml_tpu.linear_model import LogisticRegression

        n, d = 1100, 6
        X, y = _mk_xy(n, d)
        for solver in ("lbfgs", "newton", "admm"):
            with config.set(stream_block_rows=96):
                clf = LogisticRegression(solver=solver, max_iter=20,
                                         tol=1e-5).fit(X.astype(np.float64),
                                                       y.astype(np.float64))
            assert clf.solver_info_["streamed"] is True
            assert clf.score(X, y) > 0.8


class TestSGDParity:
    def test_epoch_weights_match_per_block_to_1e6(self):
        from dask_ml_tpu.models.sgd import SGDClassifier

        X, y = _mk_xy(1100)
        res = {}
        for sb in (True, False):
            with config.set(stream_block_rows=96, stream_superblock=sb):
                m = SGDClassifier(max_iter=2, random_state=0,
                                  shuffle=True).fit(X, y)
                res[sb] = (m.coef_.copy(), m.intercept_.copy(), m._t)
        assert res[True][2] == res[False][2]  # identical lr clock
        np.testing.assert_allclose(res[True][0], res[False][0], atol=1e-6)
        np.testing.assert_allclose(res[True][1], res[False][1], atol=1e-6)

    def test_multiclass_and_l1_parity(self):
        from dask_ml_tpu.models.sgd import SGDClassifier

        X, _ = _mk_xy(900)
        y = np.random.RandomState(5).randint(0, 3, len(X)).astype(float)
        res = {}
        for sb in (True, False):
            with config.set(stream_block_rows=96, stream_superblock=sb):
                m = SGDClassifier(max_iter=2, random_state=0, shuffle=False,
                                  penalty="elasticnet", l1_ratio=0.4,
                                  ).fit(X, y)
                res[sb] = m.coef_.copy()
        np.testing.assert_allclose(res[True], res[False], atol=1e-6)

    def test_incremental_wrapper_host_data_parity(self):
        from dask_ml_tpu.models.sgd import SGDClassifier
        from dask_ml_tpu.wrappers import Incremental

        X, y = _mk_xy(1100)
        res = {}
        for sb in (True, False):
            with config.set(stream_block_rows=96, stream_superblock=sb):
                inc = Incremental(
                    SGDClassifier(max_iter=1, random_state=0),
                    shuffle_blocks=True, random_state=7,
                ).fit(X, y)
                res[sb] = inc.estimator_.coef_.copy()
        np.testing.assert_allclose(res[True], res[False], atol=1e-6)


class TestKMeansParity:
    def test_streamed_lloyd_matches_per_block(self):
        from dask_ml_tpu.models.kmeans import KMeans

        rng = np.random.RandomState(2)
        X = np.concatenate([
            rng.randn(400, 5).astype(np.float32) + c for c in (0, 6, 12)
        ])
        res = {}
        for sb in (True, False):
            with config.set(stream_block_rows=96, stream_superblock=sb):
                km = KMeans(n_clusters=3, random_state=0, max_iter=30).fit(X)
                res[sb] = (np.sort(km.cluster_centers_, axis=0),
                           km.inertia_)
        np.testing.assert_allclose(res[True][0], res[False][0], atol=1e-5)
        assert res[True][1] == pytest.approx(res[False][1], rel=1e-6)


class TestDonationAndCompiles:
    def test_donated_carry_reuses_buffer_and_no_recompiles_after_pass1(self):
        """The scan carry is donated: across a pass the accumulator
        advances in place (on backends honoring donation the buffer
        pointer survives), and pass 2+ of identical shapes pays ZERO new
        XLA compiles — the steady-state contract the verify.sh perf gate
        enforces."""
        from dask_ml_tpu.models.sgd import SGDClassifier

        X, y = _mk_xy(1100)
        with config.set(stream_block_rows=96):
            warm = SGDClassifier(max_iter=1, random_state=0,
                                 shuffle=False).fit(X, y)  # pass 1 compiles
            obs.counters_reset()
            m = SGDClassifier(max_iter=3, random_state=0,
                              shuffle=False).fit(X, y)
        snap = obs.counters_snapshot()
        assert snap.get("recompiles", 0) == 0, snap
        assert snap.get("superblock_dispatches", 0) >= 3
        assert snap.get("superblock_donations", 0) >= 3
        assert warm.coef_.shape == m.coef_.shape

    def test_donation_reuses_buffer_pointer(self):
        """XLA:CPU honors donation: the carry handed to the scan is the
        same allocation the result comes back in."""
        from dask_ml_tpu.models.solvers.streamed import _sb_reducer

        d = 4
        run = _sb_reducer("vg", "logistic", True, 0)
        beta = jnp.zeros(d + 1, jnp.float32)
        Xs = jnp.ones((2, 8, d), jnp.float32)
        ys = jnp.zeros((2, 8), jnp.float32)
        counts = jnp.asarray([8, 8], jnp.int32)
        acc = (jnp.zeros((), jnp.float32), jnp.zeros(d + 1, jnp.float32))
        run(acc, beta, Xs, ys, counts)  # compile once
        acc = (jnp.zeros((), jnp.float32), jnp.zeros(d + 1, jnp.float32))
        ptr = acc[1].unsafe_buffer_pointer()
        out = run(acc, beta, Xs, ys, counts)
        assert out[1].unsafe_buffer_pointer() == ptr
        with pytest.raises(Exception):
            np.asarray(acc[1])  # the donated input buffer is dead


class TestSparseAndHostFallback:
    def test_sparse_sgd_fit_still_streams_per_block(self):
        import scipy.sparse as sp

        from dask_ml_tpu.models.sgd import SGDClassifier

        X, y = _mk_xy(600)
        Xs = sp.csr_matrix(X)
        with config.set(stream_block_rows=96):
            m = SGDClassifier(max_iter=1, random_state=0).fit(Xs, y)
            ref = SGDClassifier(max_iter=1, random_state=0).fit(X, y)
        # the sparse per-block path trains the same minibatches
        np.testing.assert_allclose(m.coef_, ref.coef_, atol=1e-5)

    def test_host_estimator_keeps_per_block_loop(self):
        from sklearn.linear_model import SGDClassifier as SkSGD

        from dask_ml_tpu.wrappers import Incremental

        X, y = _mk_xy(600)
        with config.set(stream_block_rows=96):
            inc = Incremental(SkSGD(max_iter=5, random_state=0),
                              shuffle_blocks=False).fit(X, y)
        assert inc.estimator_.coef_.shape == (1, X.shape[1])


class TestStackedLayout:
    """The TPU/GPU layout — one stacked [K, S, d] buffer consumed by a
    lax.scan — must stay correct even though CPU CI defaults to the
    unrolled layout; force it and re-check parity end to end."""

    def test_stacked_scan_parity(self, monkeypatch):
        import dask_ml_tpu.parallel.streaming as streaming
        from dask_ml_tpu.models.sgd import SGDClassifier

        X, y = _mk_xy(1100)
        with config.set(stream_block_rows=96, stream_superblock=False):
            ref = SGDClassifier(max_iter=2, random_state=0,
                                shuffle=False).fit(X, y)
        monkeypatch.setattr(streaming, "superblock_unrolled",
                            lambda: False)
        with config.set(stream_block_rows=96):
            s = BlockStream((X, y), block_rows=96)
            sb = next(iter(s.superblocks()))
            assert not isinstance(sb.arrays[0], tuple)
            assert sb.arrays[0].shape == (8, s.block_rows, X.shape[1])
            m = SGDClassifier(max_iter=2, random_state=0,
                              shuffle=False).fit(X, y)
        np.testing.assert_allclose(m.coef_, ref.coef_, atol=1e-6)
        np.testing.assert_allclose(m.intercept_, ref.intercept_,
                                   atol=1e-6)

    def test_stacked_glm_objective_parity(self, monkeypatch):
        import dask_ml_tpu.parallel.streaming as streaming
        from dask_ml_tpu.models.solvers.streamed import StreamedObjective

        n, d = 1100, 6
        X, y = _mk_xy(n, d)
        beta = np.random.RandomState(3).randn(d + 1)

        def run():
            with config.set(stream_block_rows=96):
                objective = StreamedObjective(
                    BlockStream((X, y), block_rows=96), n,
                    jnp.asarray(0.1, jnp.float32), jnp.ones(d + 1), 0.5,
                    "logistic", "l2", True,
                )
                return objective.value_and_grad(beta)

        v_unrolled, g_unrolled = run()
        monkeypatch.setattr(streaming, "superblock_unrolled",
                            lambda: False)
        v_stacked, g_stacked = run()
        np.testing.assert_allclose(v_stacked, v_unrolled, atol=1e-6)
        np.testing.assert_allclose(g_stacked, g_unrolled, atol=1e-6)


def test_compile_cache_knob(tmp_path):
    """config.compile_cache_dir routes jax's persistent compilation
    cache; entries land on disk after a streamed fit warms up."""
    import os

    from dask_ml_tpu.config import ensure_compile_cache
    from dask_ml_tpu.models.sgd import SGDRegressor

    d = str(tmp_path / "xla-cache")
    X, y = _mk_xy(600)
    with config.set(compile_cache_dir=d, stream_block_rows=96):
        assert ensure_compile_cache() is True
        SGDRegressor(max_iter=1, random_state=0).fit(X, y[: len(X)])
    assert os.path.isdir(d)
    assert os.listdir(d), "persistent cache wrote no entries"
