"""Train-serve drift scoring, hot-swap canary deltas, and the drift
monitor — the model-quality half of the live telemetry plane.

``sketch.py`` gives cheap, mergeable distribution summaries; this
module pairs them up and turns the pairs into operator-facing signal:

- **train vs serve** — every streamed fit attaches a per-feature
  training profile to its estimator (``training_profile_``, folded by
  ``BlockStream``); ``ModelServer`` registers it per (model, version)
  and folds admitted request rows into per-(model, version, method)
  serving sketches. PSI + KS over the fixed-boundary histogram pairs is
  the covariate-shift score.
- **window vs window** — consecutive snapshots of one serving sketch
  subtract into windows (fixed boundaries make the delta exact);
  scoring window N against window N-1 catches a shift that develops
  AFTER serving started, which the all-time sketch dilutes.
- **version vs version (canary)** — during a two-phase hot swap the
  server scores a shadow sample of recent traffic against BOTH the
  outgoing and incoming parameters through the SAME warmed compiled
  entry points (zero new compiles), recording per-method
  prediction-delta sketches: disagreement rate + max quantile shift.

Scores publish as ``drift_score{model=,version=,method=,feature=,
kind=}`` gauges (cardinality-capped by ``config.obs_max_series``),
alerts latch into the ``drift_alerts`` counter
(``dask_ml_tpu_drift_alerts_total`` on /metrics) once per
below→above-threshold crossing, every computation emits a JSONL
``drift`` record for the report CLI's drift tables, and ``/status``
carries the :func:`status_block`.

Everything is gated by ``config.obs_drift`` at the CALL SITES (the
streamer, the serving worker, the swap path); this module itself is
host-only — it never imports jax, so no drift computation can add a
device sync or touch a jaxpr.
"""

from __future__ import annotations

import math
import threading
import time

import numpy as np

from ._counters import record_drift_alert
from .sketch import CategoricalSketch, FeatureSketch, profile_from_dict

__all__ = ["psi_from_counts", "ks_from_counts", "score_pair",
           "note_training_profile", "fold_serving", "serving_sketch",
           "record_canary", "compute", "status_block", "ShadowBuffer",
           "ensure_monitor", "stop_monitor", "monitor_active", "reset"]

# smoothing floor for PSI proportions: an empty bucket on one side must
# contribute a finite, bounded term, not log(0)
_PSI_EPS = 1e-4

# serving-fold rate budget (token bucket per sketch key): the fold runs
# ON the serving worker thread, and an uncapped fold of every admitted
# row would tax throughput by tens of percent (a 10k x 32 fold costs
# ~20 ms of searchsorted). A fresh key gets a burst (tests and the
# drift smoke fold their whole control window immediately); steady
# state is rate-limited so fold cost stays ~1-2% of a core — the
# sketch is a uniform row sample either way, and a few thousand rows
# already pin the drift scores
_FOLD_BURST_ROWS = 4096
_FOLD_ROWS_PER_SEC = 10_000.0

# widest model a per-feature serving sketch covers: past this the
# sketch matrix (d x ~80 int64) and the shadow reservoir (256 x d f32)
# stop being cheap host state — hashed/ultra-wide feature spaces skip
# quality capture rather than tax the serving worker's memory
_MAX_SKETCH_FEATURES = 1024

_lock = threading.Lock()
# serializes whole scoring passes (compute()) without blocking folds
_compute_lock = threading.Lock()
# (model, version) -> training-profile snapshot dict
_train: dict = {}
# (model, version, method) -> {"features": FeatureSketch,
#   "predictions": FeatureSketch|None, "classes": CategoricalSketch|None}
_serving: dict = {}
# (model, version, method) -> previous cumulative feature-counts matrix
# (the window-vs-window cursor)
_window_prev: dict = {}
# latched alert keys: (key..., feature, kind) currently above threshold
_alerted: set = set()
# versions per model the registries keep: serve_while_training publishes
# a version per partial_fit pass, and without eviction the sketch
# matrices, the per-tick scoring loop, and the per-version /metrics
# series would all grow forever with the version counter
_VERSIONS_KEEP = 4
# recent canary verdicts (swap-time deltas), newest last
_canaries: list = []
_CANARY_KEEP = 32
# last computed scores per (model, version, method): the /status block
_last_scores: dict = {}


# -- scores -------------------------------------------------------------------

def _proportions(counts):
    counts = np.asarray(counts, np.float64)
    tot = counts.sum()
    if tot <= 0:
        return None
    return np.maximum(counts / tot, _PSI_EPS)


def _coarsen(ref, cur, min_frac=0.05):
    """Merge adjacent fine buckets until each coarse bucket holds at
    least ``min_frac`` of the REFERENCE mass (the same merge applied to
    both sides). The sketches keep ~80 fine buckets so KS and quantiles
    stay sharp; PSI on buckets that fine is dominated by small-count
    noise and the smoothing floor — coarsening to ~deciles restores the
    classic, stable PSI (0.2 alarm line) without re-binning raw data."""
    ref = np.asarray(ref, np.float64)
    cur = np.asarray(cur, np.float64)
    tot = ref.sum()
    out_r, out_c = [], []
    acc_r = acc_c = 0.0
    for r, c in zip(ref, cur):
        acc_r += r
        acc_c += c
        if acc_r >= min_frac * tot:
            out_r.append(acc_r)
            out_c.append(acc_c)
            acc_r = acc_c = 0.0
    if not out_r:
        return np.asarray([acc_r]), np.asarray([acc_c])
    out_r[-1] += acc_r
    out_c[-1] += acc_c
    return np.asarray(out_r), np.asarray(out_c)


def psi_from_counts(p_counts, q_counts) -> float:
    """Population stability index between two aligned histogram count
    vectors (same fixed boundaries; ``p`` is the reference side). The
    fine buckets coarsen to >=5%-of-reference-mass bins first — the
    classic decile PSI — so an in-distribution pair scores near 0 even
    at modest sample sizes. 0 = identical; > 0.2 is the classic
    "significant shift" alarm line."""
    p_counts, q_counts = _coarsen(p_counts, q_counts)
    p = _proportions(p_counts)
    q = _proportions(q_counts)
    if p is None or q is None:
        return float("nan")
    return float(np.sum((p - q) * np.log(p / q)))


def ks_from_counts(p_counts, q_counts) -> float:
    """Kolmogorov–Smirnov statistic (max CDF gap) between two aligned
    count vectors — scale-free companion to PSI."""
    p = np.asarray(p_counts, np.float64)
    q = np.asarray(q_counts, np.float64)
    if p.sum() <= 0 or q.sum() <= 0:
        return float("nan")
    return float(np.max(np.abs(
        np.cumsum(p) / p.sum() - np.cumsum(q) / q.sum()
    )))


def score_pair(ref_counts, cur_counts) -> list:
    """Per-feature [(psi, ks)] over two (n_features, n_buckets) count
    matrices with identical boundaries."""
    ref = np.asarray(ref_counts)
    cur = np.asarray(cur_counts)
    return [(psi_from_counts(ref[f], cur[f]),
             ks_from_counts(ref[f], cur[f]))
            for f in range(ref.shape[0])]


# -- registries ---------------------------------------------------------------

def note_training_profile(model, version, profile) -> None:
    """Register a (model, version)'s training profile snapshot (a
    ``FeatureSketch.to_dict``) — called by ModelServer on start / swap /
    rebuild with whatever ``training_profile_`` the estimator carries.
    None clears nothing and registers nothing."""
    if not profile:
        return
    with _lock:
        _train[(str(model), int(version))] = profile
        evicted = _evict_versions_locked(str(model))
    _drop_version_series(str(model), evicted)


def _evict_versions_locked(model):
    """Caller holds ``_lock``: drop every registry entry for ``model``
    whose version trails the newest by more than ``_VERSIONS_KEEP``;
    returns the evicted versions (the caller drops their /metrics
    series OUTSIDE the lock — live's lock nests inside ours, never
    while we hold it)."""
    versions = {v for (m, v) in _train if m == model}
    versions.update(v for (m, v, _meth) in _serving if m == model)
    doomed = set(sorted(versions)[:-_VERSIONS_KEEP])
    if not doomed:
        return ()
    for reg in (_train, _serving, _window_prev, _last_scores):
        for k in [k for k in reg if k[0] == model and k[1] in doomed]:
            del reg[k]
    for k in [k for k in _alerted if k[0] == model and k[1] in doomed]:
        _alerted.discard(k)
    return tuple(sorted(doomed))


def _drop_version_series(model, evicted) -> None:
    """Unlatch an evicted version's per-version gauge series (stale
    drift scores / canary quantiles must not sit on /metrics forever)."""
    if not evicted:
        return
    try:
        from .live import drop_labeled_series

        for v in evicted:
            for fam in ("drift_score", "canary_prediction"):
                drop_labeled_series(
                    fam, (("model", model), ("version", str(v)))
                )
    except Exception:
        pass


def training_profile(model, version):
    with _lock:
        return _train.get((str(model), int(version)))


def serving_sketch(model, version, method, n_features=None,
                   bounds=None):
    """Create-or-get the serving sketch set for (model, version,
    method). Returns None until the first call that supplies
    ``n_features``."""
    key = (str(model), int(version), str(method))
    if n_features and n_features > _MAX_SKETCH_FEATURES:
        return None
    evicted = ()
    with _lock:
        entry = _serving.get(key)
        if entry is None and n_features:
            entry = _serving[key] = {
                "features": FeatureSketch(n_features, bounds=bounds),
                "predictions": None,
                "classes": None,
                # fold rate-limiter state (token bucket)
                "credit": float(_FOLD_BURST_ROWS),
                "t_credit": time.monotonic(),
            }
            evicted = _evict_versions_locked(key[0])
    _drop_version_series(key[0], evicted)
    return entry


def fold_serving(model, version, method, X_rows, outputs=None,
                 max_rows=256) -> int:
    """Fold one served batch's admitted rows (and its outputs) into the
    (model, version, method) serving sketches. ``max_rows`` strides the
    batch down so a busy server's fold cost stays bounded (the sketch
    is a sample either way — the stride keeps it a uniform one).
    Returns rows folded. Never raises into the serving worker."""
    try:
        X_rows = np.asarray(X_rows)
        if X_rows.ndim != 2 or X_rows.shape[0] == 0:
            return 0
        # align the training profile's bounds when one exists, so the
        # PSI/KS pair subtracts bucket-for-bucket
        prof = training_profile(model, version)
        entry = serving_sketch(
            model, version, method, n_features=X_rows.shape[1],
            bounds=prof["bounds"] if prof else None,
        )
        if entry is None:
            return 0
        # token bucket: replenish, then take at most the credit (and
        # the per-call cap). Racy-but-benign across fleet replicas
        # sharing one key — it is a rate limiter, not an invariant.
        now = time.monotonic()
        with _lock:
            credit = min(
                entry["credit"]
                + (now - entry["t_credit"]) * _FOLD_ROWS_PER_SEC,
                float(_FOLD_BURST_ROWS),
            )
            entry["t_credit"] = now
            take = min(int(credit), X_rows.shape[0], int(max_rows))
            entry["credit"] = credit - take
        if take <= 0:
            return 0
        stride = max(int(math.ceil(X_rows.shape[0] / take)), 1)
        folded = entry["features"].fold(X_rows[::stride])
        if outputs is not None:
            _fold_predictions(entry, np.asarray(outputs), stride, method)
        return folded
    except Exception:
        return 0


def _fold_predictions(entry, out, stride, method):
    if out.ndim == 0:
        return
    numeric = out.dtype.kind in "fiu"
    if numeric:
        cols = out[:, None] if out.ndim == 1 else out
        with _lock:
            pred = entry["predictions"]
            if pred is None or pred.n_features != cols.shape[1]:
                pred = entry["predictions"] = FeatureSketch(cols.shape[1])
        pred.fold(cols[::stride])
    if method == "predict":
        with _lock:
            cat = entry["classes"]
            if cat is None:
                cat = entry["classes"] = CategoricalSketch()
        cat.fold(out[::stride])


# -- shadow sampling + canary -------------------------------------------------

class ShadowBuffer:
    """Bounded reservoir of recent request rows (one per served method):
    the sample a hot-swap canary scores against both versions. A
    credit-based fraction keeps the sampling rate proportional to
    traffic without an RNG on the hot path; the ring overwrites oldest
    rows so the sample tracks RECENT traffic."""

    __slots__ = ("cap", "_buf", "_pos", "_count", "_credit", "_lock")

    def __init__(self, cap=256):
        self.cap = int(cap)
        self._buf = None
        self._pos = 0
        self._count = 0
        self._credit = 0.0
        self._lock = threading.Lock()

    def offer(self, rows, fraction) -> int:
        """Stash ~``fraction`` of ``rows`` (strided, so the take spreads
        across the batch). Returns rows taken."""
        if fraction <= 0:
            return 0
        rows = np.asarray(rows)
        if rows.ndim != 2 or rows.shape[0] == 0:
            return 0
        with self._lock:
            self._credit += rows.shape[0] * float(fraction)
            take = min(int(self._credit), rows.shape[0], self.cap)
            if take <= 0:
                return 0
            self._credit -= take
            if self._buf is None or self._buf.shape[1] != rows.shape[1]:
                self._buf = np.zeros((self.cap, rows.shape[1]),
                                     np.float32)
                self._pos = self._count = 0
            picks = rows[:: max(rows.shape[0] // take, 1)][:take]
            for r in picks:
                self._buf[self._pos] = r
                self._pos = (self._pos + 1) % self.cap
            self._count = min(self._count + take, self.cap)
            return take

    def sample(self):
        """A copy of the stashed rows (None when empty)."""
        with self._lock:
            if self._buf is None or self._count == 0:
                return None
            return self._buf[: self._count].copy()


def canary_delta(old_out, new_out) -> dict:
    """Prediction-delta verdict between two versions' outputs on one
    shadow sample: exact disagreement rate plus — for numeric outputs —
    the max quantile shift across p10/p50/p90, normalized by the old
    outputs' scale (max(std, |p90-p10|, eps))."""
    old = np.asarray(old_out)
    new = np.asarray(new_out)
    n = min(old.shape[0], new.shape[0])
    old, new = old[:n], new[:n]
    if old.ndim == 1:
        old, new = old[:, None], new[:, None]
    if old.dtype.kind in "fiu" and new.dtype.kind in "fiu":
        disagree = float(np.mean(
            ~np.isclose(old.astype(np.float64), new.astype(np.float64),
                        rtol=1e-5, atol=1e-6).all(axis=1)
        ))
        qs = (0.10, 0.50, 0.90)
        oq = np.quantile(old.astype(np.float64), qs, axis=0)
        nq = np.quantile(new.astype(np.float64), qs, axis=0)
        scale = max(float(old.std()), float(np.max(oq[2] - oq[0])), 1e-9)
        shift = float(np.max(np.abs(nq - oq)) / scale)
    else:
        disagree = float(np.mean(np.any(old != new, axis=1)))
        shift = disagree
    return {"disagreement": round(disagree, 6),
            "max_quantile_shift": round(shift, 6), "n_rows": int(n)}


def record_canary(model, v_old, v_new, method, old_out, new_out) -> dict:
    """Record one hot-swap canary: prediction sketches for BOTH
    versions' outputs on the shadow sample, the delta verdict, the
    /metrics gauges (per-version series + the delta), and a JSONL
    ``drift`` record. Returns the verdict dict."""
    verdict = canary_delta(old_out, new_out)
    rec = {
        "drift": True, "pair": "canary", "model": str(model),
        "version_from": int(v_old), "version_to": int(v_new),
        "method": str(method), "t_unix": round(time.time(), 6),
        **verdict,
    }
    from ..config import get_config

    threshold = float(get_config().obs_drift_threshold)
    rec["alert"] = bool(verdict["disagreement"] > threshold
                        or verdict["max_quantile_shift"] > threshold)
    if rec["alert"]:
        record_drift_alert()
        # one crossing = one event: the alert engine's builtin:drift
        # rule fires off THIS call alone (never also polled), so the
        # counter above and the engine can't double-count
        from . import alerts as _alerts

        _alerts.note_event("drift", value=verdict["disagreement"], meta={
            "pair": "canary", "model": str(model),
            "version_from": int(v_old), "version_to": int(v_new),
        })
    with _lock:
        _canaries.append(rec)
        del _canaries[:-_CANARY_KEEP]
    _publish_canary(model, v_old, v_new, method, old_out, new_out,
                    verdict)
    _emit(rec)
    return verdict


def _publish_canary(model, v_old, v_new, method, old_out, new_out,
                    verdict):
    from .live import gauge_set, live_publishing

    if not live_publishing():
        return
    base = (("model", str(model)), ("method", str(method)))
    pair = base + (("from", str(v_old)), ("to", str(v_new)))
    gauge_set("canary_disagreement", verdict["disagreement"], pair)
    gauge_set("canary_quantile_shift", verdict["max_quantile_shift"],
              pair)
    # per-VERSION prediction-delta series: the outgoing and incoming
    # versions each expose their shadow-sample prediction quantiles, so
    # a scrape sees both sides of the flip
    for v, out in ((v_old, old_out), (v_new, new_out)):
        out = np.asarray(out)
        if out.dtype.kind not in "fiu" or out.size == 0:
            continue
        flat = out.astype(np.float64).ravel()
        labels = base + (("version", str(v)),)
        gauge_set("canary_prediction_p50", float(np.quantile(flat, 0.5)),
                  labels)
        gauge_set("canary_prediction_p99", float(np.quantile(flat, 0.99)),
                  labels)
        gauge_set("canary_prediction_mean", float(flat.mean()), labels)


# -- the drift computation ----------------------------------------------------

def _emit(rec) -> None:
    """One JSONL drift record through the ambient trace sink (bound fit
    logger / config.trace_dir / config.metrics_path) — the report CLI's
    drift tables read these. Silently no-op without a sink."""
    try:
        from ._spans import _trace_sink

        sink = _trace_sink()
        if sink is not None:
            sink.log(**rec)
    except Exception:
        pass


def _pair_sources(key, cur_counts):
    """The (kind, ref, cur) score pairs for one sketch key — the
    training profile and the window delta — advancing the window
    cursors to ``cur_counts``. The only part of a scoring pass that
    needs ``_lock``, and it is O(copy), not O(scoring): the serving
    worker's fold path contends on this lock, so the PSI/KS math must
    happen outside it."""
    model, version, method = key
    pairs = []
    with _lock:
        prof = _train.get((model, version))
        if prof is not None and prof["n_features"] == cur_counts.shape[0] \
                and len(prof["bounds"]) + 1 == cur_counts.shape[1]:
            pairs.append(("train_serve",
                          np.asarray(prof["counts"], np.int64),
                          cur_counts))
        prev = _window_prev.get(key)
        if prev is not None and prev.shape == cur_counts.shape:
            window = cur_counts - prev
            prev_window = _window_prev.get(key + ("window",))
            if prev_window is not None and window.sum() > 0 \
                    and prev_window.sum() > 0:
                pairs.append(("window", prev_window, window))
            _window_prev[key + ("window",)] = window
        _window_prev[key] = cur_counts
    return pairs


def _score_key(key, pairs, rows, threshold, now):
    """Score one key's pairs (lock-free — the pure-Python coarsen loop
    over up to 1024 features is the expensive part) and then latch
    alerts + the /status summary under one brief ``_lock``."""
    model, version, method = key
    records = []
    summary = {"model": model, "version": version, "method": method,
               "t_unix": round(now, 3), "rows": rows,
               "max_psi": None, "max_ks": None, "alerts": 0}
    scored = [(kind, score_pair(ref, cur)) for kind, ref, cur in pairs]
    new_alerts = 0
    crossings = []
    with _lock:
        for kind, scores in scored:
            psis = [p for p, _ in scores if not math.isnan(p)]
            kss = [k for _, k in scores if not math.isnan(k)]
            if not psis:
                continue
            summary["max_psi"] = max(summary["max_psi"] or 0.0,
                                     max(psis))
            summary["max_ks"] = max(summary["max_ks"] or 0.0,
                                    max(kss) if kss else 0.0)
            for f, (p, k) in enumerate(scores):
                if math.isnan(p):
                    continue
                alert = p > threshold
                latch = key + (f, kind)
                if alert and latch not in _alerted:
                    _alerted.add(latch)
                    summary["alerts"] += 1
                    new_alerts += 1
                    crossings.append((kind, f, p))
                elif not alert:
                    _alerted.discard(latch)
                records.append({
                    "drift": True, "pair": kind, "model": model,
                    "version": version, "method": method,
                    "feature": f"f{f}", "psi": round(p, 6),
                    "ks": round(k, 6) if not math.isnan(k) else None,
                    "alert": alert, "t_unix": round(now, 6),
                })
        _last_scores[key] = summary
    for _ in range(new_alerts):
        record_drift_alert()
    # the same below→above latch drives the alert engine: the _alerted
    # set is the single dedupe source, so a crossing mints exactly one
    # event (builtin:drift is event-only — it is never also polled)
    if crossings:
        from . import alerts as _alerts

        for kind, f, p in crossings:
            _alerts.note_event("drift", value=p, meta={
                "pair": kind, "model": model, "version": version,
                "method": method, "feature": f"f{f}",
            })
    return records


def compute(publish=True) -> list:
    """Score every registered sketch pair now; returns the drift
    records. Publishes gauges when a live telemetry server is up,
    increments ``drift_alerts`` on below→above-threshold crossings,
    and emits each record to the ambient JSONL sink. Called by the
    background monitor on its cadence and directly by tests/smokes."""
    from ..config import get_config

    # live servers batch their fold samples (pending lists amortize the
    # fold's fixed cost off the hot loop) — flush them first so an
    # on-demand compute scores CURRENT traffic, not traffic as of the
    # last flush tick
    try:
        from .live import _server_set

        for srv in list(_server_set()):
            flush = getattr(srv, "_flush_quality", None)
            if flush is not None:
                flush()
    except Exception:
        pass
    threshold = float(get_config().obs_drift_threshold)
    # one scorer at a time: concurrent computes (monitor tick racing an
    # on-demand call) would double-count latch crossings and interleave
    # window-cursor advances; folds are NOT serialized by this — they
    # only touch the brief _lock sections
    with _compute_lock:
        now = time.time()
        with _lock:
            items = list(_serving.items())
        all_records = []
        for key, entry in items:
            cur_counts = entry["features"].counts()
            pairs = _pair_sources(key, cur_counts)
            all_records.extend(_score_key(
                key, pairs, entry["features"].rows, threshold, now
            ))
    if publish:
        _publish_scores(all_records)
    for rec in all_records:
        _emit(rec)
    return all_records


def _publish_scores(records) -> None:
    from .live import gauge_set, live_publishing

    if not live_publishing():
        return
    per_key_max: dict = {}
    for r in records:
        labels = (("model", r["model"]), ("version", str(r["version"])),
                  ("method", r["method"]), ("feature", r["feature"]),
                  ("kind", r["pair"]))
        gauge_set("drift_score", r["psi"], labels)
        mk = (r["model"], r["version"], r["method"], r["pair"])
        per_key_max[mk] = max(per_key_max.get(mk, 0.0), r["psi"])
    for (model, version, method, kind), v in per_key_max.items():
        gauge_set("drift_score_max", v,
                  (("model", model), ("version", str(version)),
                   ("method", method), ("kind", kind)))


def status_block() -> dict:
    """The /status drift view: last computed scores per (model,
    version, method), recent canaries, and the registered sketch keys."""
    with _lock:
        scores = [dict(v) for v in _last_scores.values()]
        canaries = [dict(c) for c in _canaries]
        tracked = [{"model": m, "version": v, "method": meth,
                    "rows": e["features"].rows}
                   for (m, v, meth), e in _serving.items()]
        profiles = [{"model": m, "version": v, "rows": p.get("rows")}
                    for (m, v), p in _train.items()]
    return {"scores": scores, "canaries": canaries,
            "serving_sketches": tracked, "training_profiles": profiles}


# -- background monitor -------------------------------------------------------

_monitor_lock = threading.Lock()
_monitor_thread = None
_monitor_stop = threading.Event()


def monitor_active() -> bool:
    t = _monitor_thread
    return t is not None and t.is_alive()


def ensure_monitor(cfg=None):
    """Start the background drift monitor (idempotent, daemon): every
    ``config.obs_drift_interval_s`` it calls :func:`compute` under the
    ARMING caller's config (config is thread-local — the monitor must
    see the trace sink and thresholds of the fit/server that armed it,
    not the env defaults). No-op when ``obs_drift`` is off or the
    interval is 0."""
    global _monitor_thread
    from .. import config as _config

    cfg = cfg or _config.get_config()
    if not cfg.obs_drift or cfg.obs_drift_interval_s <= 0:
        return None
    with _monitor_lock:
        if monitor_active():
            return _monitor_thread
        _monitor_stop.clear()

        def _loop():
            import dataclasses

            with _config.set(**dataclasses.asdict(cfg)):
                while not _monitor_stop.wait(cfg.obs_drift_interval_s):
                    try:
                        compute()
                    except Exception:
                        pass  # the monitor must never die mid-run

        _monitor_thread = threading.Thread(
            target=_loop, name="dask-ml-tpu-drift", daemon=True
        )
        _monitor_thread.start()
    return _monitor_thread


def stop_monitor() -> None:
    global _monitor_thread
    with _monitor_lock:
        t, _monitor_thread = _monitor_thread, None
        _monitor_stop.set()
    if t is not None:
        t.join(5.0)


def reset() -> None:
    """Clear every registry and stop the monitor — test isolation."""
    stop_monitor()
    with _lock:
        _train.clear()
        _serving.clear()
        _window_prev.clear()
        _alerted.clear()
        _canaries.clear()
        _last_scores.clear()
