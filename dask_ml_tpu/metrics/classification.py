"""Classification metrics over (possibly sharded) arrays.

Reference: ``dask_ml/metrics/classification.py`` (SURVEY.md §2a Metrics
row) — blocked reductions with per-block sklearn kernels. Here each metric
is one jitted masked reduction; XLA inserts the psum when inputs are
sharded.
"""

from __future__ import annotations

import warnings
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..parallel.sharded import ShardedArray, as_sharded


from sklearn.exceptions import (  # noqa: E402 - re-export base
    UndefinedMetricWarning as _SkUndefinedMetricWarning,
)


class UndefinedMetricWarning(_SkUndefinedMetricWarning):
    """A metric is ill-defined for this input (e.g. a single-class fold)
    and a degenerate value was returned instead of raising.

    Subclasses ``sklearn.exceptions.UndefinedMetricWarning`` (itself a
    UserWarning), so code ported from sklearn that filters or catches
    sklearn's class specifically (CV loops skipping degenerate folds,
    ``pytest.warns`` assertions) behaves identically against these
    metrics — an independent same-named class would silently slip those
    filters."""


def _canon(y_true, y_pred, sample_weight=None):
    """Co-shard the pair (and sample_weight, padded alike); returns
    (a, b, weights, n) where weights = row-validity mask * sample_weight."""
    if isinstance(y_true, ShardedArray) or isinstance(y_pred, ShardedArray):
        mesh = (y_true.mesh if isinstance(y_true, ShardedArray) else y_pred.mesh)
        t = as_sharded(y_true, mesh=mesh)
        p = as_sharded(y_pred, mesh=mesh)
        w = t.row_mask()
        if sample_weight is not None:
            w = w * as_sharded(sample_weight, mesh=mesh).data
        return t.data, p.data, w, t.n_rows
    t = np.asarray(y_true)
    p = np.asarray(y_pred)
    w = np.ones(t.shape[0], np.float32)
    if sample_weight is not None:
        w = w * np.asarray(sample_weight)
    return t, p, w, t.shape[0]


def accuracy_score(y_true, y_pred, normalize=True, sample_weight=None):
    t, p, w, n = _canon(y_true, y_pred, sample_weight)
    hits = jnp.sum((t == p) * w)
    if not normalize:
        return float(hits)
    return float(hits / jnp.sum(w))


def _resolve_labels(y_true, y_pred, labels):
    """Sorted class values as a host array. Prefers explicit ``labels``
    (scorers forward ``estimator.classes_`` — zero device pulls); else
    the UNION of y_true and y_pred uniques (sklearn semantics — a fold
    whose y_true misses a class the model still predicts must score,
    not raise). Each is an n-vector, 1/d the bytes of the fold."""
    if labels is not None:
        return np.sort(np.asarray(labels))

    def host(a):
        return a.to_numpy() if isinstance(a, ShardedArray) \
            else np.asarray(a)

    u = np.unique(host(y_true))
    if y_pred is not None:
        u = np.union1d(u, np.unique(host(y_pred)))
    return u


def _codes(values, classes_host, w, what):
    """Map class VALUES to codes 0..C-1 by device searchsorted in the
    values' native dtype (float32 equality would collapse >2**24 integer
    ids); rows with w=0 (padding) are exempt from the membership check."""
    classes_d = jnp.asarray(
        classes_host.astype(np.dtype(str(values.dtype)), copy=False)
    )
    idx = jnp.clip(jnp.searchsorted(classes_d, values),
                   0, len(classes_host) - 1)
    ok = jnp.all((jnp.take(classes_d, idx) == values) | (w == 0))
    if not bool(ok):
        raise ValueError(f"{what} contains values not in labels")
    return idx


@partial(jax.jit, static_argnames=("C",))
def _class_counts(t_codes, p_codes, w, C):
    """Per-class (tp, true, pred) weighted counts in ONE program — the
    sufficient statistics for precision/recall/F1/balanced accuracy.
    ``segment_sum`` lowers to scatter-adds XLA shards with the data."""
    tp = jax.ops.segment_sum(w * (t_codes == p_codes), t_codes, C)
    true_c = jax.ops.segment_sum(w, t_codes, C)
    pred_c = jax.ops.segment_sum(w, p_codes, C)
    return tp, true_c, pred_c


# device segment sums run f32 (TPU-native); per-chunk sums stay ≤ 2**22
# so unit weights accumulate EXACTLY (f32 is exact to 2**24), and the
# cross-chunk accumulation is f64 on host — counts don't saturate at
# 16.7M rows per class
_COUNT_CHUNK = 1 << 22


def _chunked_f64(kernel, n, *arrays):
    """Run ``kernel(*chunk_slices)`` over ≤_COUNT_CHUNK-row chunks and
    accumulate the outputs in f64 on host."""
    acc = None
    for i in range(0, max(n, 1), _COUNT_CHUNK):
        outs = kernel(*(a[i:i + _COUNT_CHUNK] for a in arrays))
        outs = [np.asarray(o, np.float64) for o in outs]
        acc = outs if acc is None else [a + o for a, o in zip(acc, outs)]
    return acc


def _counts(y_true, y_pred, labels, sample_weight):
    t, p, w, _ = _canon(y_true, y_pred, sample_weight)
    classes = _resolve_labels(y_true, y_pred, labels)
    C = len(classes)
    tc = _codes(t, classes, w, "y_true")
    pc = _codes(p, classes, w, "y_pred")
    tp, true_c, pred_c = _chunked_f64(
        lambda a, b, c: _class_counts(a, b, c, C), t.shape[0], tc, pc, w
    )
    return tp, true_c, pred_c, classes


def _averaged(num, den_p, den_r, classes, average, pos_label, what):
    """sklearn's averaging semantics over per-class statistics;
    ``num``=tp, ``den_p``=pred counts, ``den_r``=true counts."""
    true_c = den_r
    def safe(a, b):
        return np.where(b > 0, a / np.maximum(b, 1e-300), 0.0)

    prec, rec = safe(num, den_p), safe(num, den_r)
    f1 = safe(2 * prec * rec, prec + rec)
    per_class = {"precision": prec, "recall": rec, "f1": f1}[what]
    if average == "binary":
        if len(classes) > 2:
            raise ValueError(
                "average='binary' requires binary targets; use "
                "average='macro'|'micro'|'weighted'"
            )
        where = np.flatnonzero(classes == pos_label)
        if len(where) == 0:
            # sklearn: a pos_label the data never mentions is an error,
            # not a silent 0 — {-1,+1}/{2,3} encodings without pos_label=
            # would otherwise rank every candidate equal
            raise ValueError(
                f"pos_label={pos_label} is not a valid label: "
                f"{classes.tolist()}"
            )
        return float(per_class[where[0]])
    if average == "micro":
        tp_s, fp_s = num.sum(), (den_p - num).sum()
        fn_s = (den_r - num).sum()
        p_ = tp_s / max(tp_s + fp_s, 1e-300)
        r_ = tp_s / max(tp_s + fn_s, 1e-300)
        if what == "precision":
            return float(p_) if (tp_s + fp_s) > 0 else 0.0
        if what == "recall":
            return float(r_) if (tp_s + fn_s) > 0 else 0.0
        return float(2 * p_ * r_ / max(p_ + r_, 1e-300))
    if average == "macro":
        return float(per_class.mean())
    if average == "weighted":
        wts = true_c / max(true_c.sum(), 1e-300)
        return float((per_class * wts).sum())
    if average is None:
        return per_class
    raise ValueError(f"Unknown average {average!r}")


def _prf(y_true, y_pred, what, average, pos_label, labels, sample_weight):
    tp, true_c, pred_c, classes = _counts(y_true, y_pred, labels,
                                          sample_weight)
    return _averaged(tp, pred_c, true_c, classes, average, pos_label,
                     what)


def precision_score(y_true, y_pred, average="binary", pos_label=1,
                    labels=None, sample_weight=None):
    """Device-side precision (one jitted counts program + host scalars).
    Ref: the reference exposes sklearn's scorer table dask-aware
    (dask_ml/metrics/scorer.py); this is its device-resident metric."""
    return _prf(y_true, y_pred, "precision", average, pos_label, labels,
                sample_weight)


def recall_score(y_true, y_pred, average="binary", pos_label=1,
                 labels=None, sample_weight=None):
    return _prf(y_true, y_pred, "recall", average, pos_label, labels,
                sample_weight)


def f1_score(y_true, y_pred, average="binary", pos_label=1, labels=None,
             sample_weight=None):
    return _prf(y_true, y_pred, "f1", average, pos_label, labels,
                sample_weight)


def balanced_accuracy_score(y_true, y_pred, sample_weight=None,
                            labels=None):
    """Mean per-class recall over the classes PRESENT in y_true
    (sklearn semantics)."""
    tp, true_c, _, _ = _counts(y_true, y_pred, labels, sample_weight)
    present = true_c > 0
    rec = tp[present] / true_c[present]
    return float(rec.mean())


def confusion_matrix(y_true, y_pred, labels=None, sample_weight=None):
    """(C, C) weighted confusion counts — one segment-sum over the
    flattened (true, pred) code pairs."""
    t, p, w, _ = _canon(y_true, y_pred, sample_weight)
    classes = _resolve_labels(y_true, y_pred, labels)
    C = len(classes)
    tc = _codes(t, classes, w, "y_true")
    pc = _codes(p, classes, w, "y_pred")
    (flat,) = _chunked_f64(
        lambda a, b, c: (jax.ops.segment_sum(c, a * C + b, C * C),),
        t.shape[0], tc, pc, w,
    )
    cm = flat.reshape(C, C)
    return cm.astype(np.int64) if sample_weight is None else cm


@jax.jit
def _auc_stat(s, yt, w):
    """Tie-corrected weighted AUC sufficient statistics in ONE program.
    Sort by score; positives earn the negative weight strictly below
    their tie group + half the group's (rank-statistic / Mann-Whitney U
    with average ranks). Tie groups via a segment-sum over the group ids
    (cumsum of score-change flags) — static shapes, no host loop."""
    n = s.shape[0]
    order = jnp.argsort(s)
    ss = jnp.take(s, order)
    yy = jnp.take(yt, order)
    ww = jnp.take(w, order)
    posw = ww * yy
    negw = ww * (1.0 - yy)
    gid = jnp.concatenate([
        jnp.zeros(1, jnp.int32),
        jnp.cumsum((ss[1:] != ss[:-1]).astype(jnp.int32)),
    ])
    gneg = jax.ops.segment_sum(negw, gid, n)
    bneg = jnp.cumsum(gneg) - gneg  # negatives strictly below the group
    contrib = posw * (jnp.take(bneg, gid) + 0.5 * jnp.take(gneg, gid))
    return jnp.sum(contrib), jnp.sum(posw), jnp.sum(negw)


def roc_auc_score(y_true, y_score, sample_weight=None, labels=None):
    """Binary ROC-AUC as one jitted rank statistic (no threshold sweep;
    AUC == normalized Mann-Whitney U, ties at half credit — exactly
    sklearn's trapezoidal value). Multiclass needs ovr/ovo averaging the
    reference never shipped either — raise rather than guess."""
    t, s, w, _ = _canon(y_true, y_score, sample_weight)
    if s.ndim == 2:
        if s.shape[1] != 2:
            raise ValueError(
                "roc_auc_score supports binary targets; got "
                f"{s.shape[1]}-column scores"
            )
        s = s[:, 1]
    yt = _binary_targets(t, w, labels)
    num, wp, wn = _auc_stat(jnp.asarray(s, jnp.float32), yt,
                            jnp.asarray(w, jnp.float32))
    wp, wn = float(wp), float(wn)
    if wp == 0.0 or wn == 0.0:
        raise ValueError(
            "Only one class present in y_true. ROC AUC score is not "
            "defined in that case."
        )
    return float(num) / (wp * wn)


def _binary_targets(t, w, labels, what="roc_auc_score"):
    """0/1 targets from arbitrary binary labels (device scan for the
    class pair; explicit ``labels`` wins), shared by the rank-statistic
    metrics."""
    if labels is not None:
        lab = np.asarray(labels, dtype=np.float64)
        if len(lab) != 2:
            raise ValueError(f"{what} needs exactly 2 labels")
        if lab[0] == lab[1]:
            # labels=[1, 1] passes the length check but would map EVERY
            # row positive below (t == mx_h matches both "classes") —
            # a silently perfect curve on garbage input
            raise ValueError(
                f"{what} labels must be two distinct values, got "
                f"{list(np.asarray(labels))}"
            )
        # POSITIONAL: labels=[neg, pos] — the order is honored (not
        # sorted), so a positive class numerically smaller than the
        # negative is expressible, as the ambiguity errors below promise
        mx_h = float(lab[1])
        ok = jnp.all((t == float(lab[0])) | (t == mx_h) | (w == 0))
        if not bool(ok):
            raise ValueError("y_true contains values not in labels")
    else:
        valid = w > 0
        mn_h = float(jnp.min(jnp.where(valid, t, jnp.inf)))
        mx_h = float(jnp.max(jnp.where(valid, t, -jnp.inf)))
        if not bool(jnp.all((t == mn_h) | (t == mx_h) | (w == 0))):
            raise ValueError(
                f"multiclass format is not supported by {what}; "
                "pass binary targets (or labels= with 2 classes)"
            )
        # positive-class inference is caller-dependent, matching sklearn:
        # roc_auc_score label-binarizes (larger label = positive, any
        # binary coding), but the pos_label-style curve metrics refuse to
        # guess outside the conventional {0,1} / {-1,1} codings — AP/PR
        # are strongly asymmetric in that guess, so e.g. {1,2} must be
        # spelled out via labels=
        strict = what != "roc_auc_score"
        if mn_h == mx_h:
            if mx_h in (0.0, -1.0) or (not strict and mx_h != 1.0):
                # lone non-positive class: NO positives — mapping the
                # lone class to positive would score a perfect curve on
                # all-negative data
                return jnp.zeros_like(t, jnp.float32)
            if strict and mx_h != 1.0:
                raise ValueError(
                    f"y_true takes the value {{{mx_h}}} and the positive "
                    f"class is ambiguous; pass labels=[neg, pos] to {what}"
                )
        elif strict and (mn_h, mx_h) not in ((0.0, 1.0), (-1.0, 1.0)):
            raise ValueError(
                f"y_true takes values in {{{mn_h}, {mx_h}}} and the "
                "positive class is ambiguous; pass labels=[neg, pos] "
                f"to {what}"
            )
    return (t == mx_h).astype(jnp.float32)


@jax.jit
def _curve_sorted(s, yt, w):
    """Score-descending (scores, positive weight, negative weight,
    valid flag) — the sort half of the curve statistics; prefix sums run
    on host in chunked f64 (f32 cumsum saturates at 2**24, the same
    hazard ``_chunked_f64`` guards in the count metrics)."""
    order = jnp.argsort(-s)
    ss = jnp.take(s, order)
    yy = jnp.take(yt, order)
    ww = jnp.take(w, order)
    return ss, ww * yy, ww * (1.0 - yy), (ww != 0).astype(jnp.float32)


def _curve_host(y_true, y_score, sample_weight, labels, what):
    t, s, w, _ = _canon(y_true, y_score, sample_weight)
    if s.ndim == 2:
        if s.shape[1] != 2:
            raise ValueError(f"{what} supports binary targets")
        s = s[:, 1]
    yt = _binary_targets(t, w, labels, what)
    if isinstance(s, np.ndarray):
        # host inputs: sort + prefix-sum entirely in f64 numpy, so the
        # returned thresholds are EXACT y_score values (sklearn's
        # documented contract) and near-equal f64 scores keep distinct
        # threshold groups
        order = np.argsort(-np.asarray(s, np.float64), kind="stable")
        ss = np.asarray(s, np.float64)[order]
        yo = np.asarray(yt, np.float64)[order]
        wo = np.asarray(w, np.float64)[order]
        pw, nw, vf = wo * yo, wo * (1.0 - yo), (wo != 0).astype(float)
    else:
        # sharded inputs: device sort (data is f32-native, so the
        # thresholds ARE exact score values at the data's precision)
        ss, pw, nw, vf = _curve_sorted(jnp.asarray(s, jnp.float32), yt,
                                       jnp.asarray(w, jnp.float32))
        ss = np.asarray(ss, np.float64)
        pw, nw, vf = (np.asarray(a, np.float64) for a in (pw, nw, vf))
    # f64 prefix sums on host — f32 cumsum would saturate at 2**24, the
    # same hazard _chunked_f64 guards in the count metrics
    tp, fp, cv = np.cumsum(pw), np.cumsum(nw), np.cumsum(vf)
    P, N = float(tp[-1]), float(fp[-1])
    # keep only the LAST index of each distinct score (the cumulative
    # counts AT that threshold — sklearn's threshold de-dup) ...
    keep = np.r_[ss[1:] != ss[:-1], True]
    ss, tp, fp, cv = ss[keep], tp[keep], fp[keep], cv[keep]
    # ... and drop threshold groups made ONLY of padding rows (w=0):
    # their plateaus don't change the curve, but their scores are
    # fabricated values no real sample has
    real = np.diff(np.r_[0.0, cv]) > 0
    return ss[real], tp[real], fp[real], P, N


def _pr_points(tp, fp, P):
    """(precision, recall) at each kept threshold — the ONE place the
    zero-division guard lives (precision_recall_curve and
    average_precision_score share it)."""
    prec = tp / np.maximum(tp + fp, 1e-300)
    rec = tp / P
    return prec, rec


def roc_curve(y_true, y_score, sample_weight=None, labels=None):
    """(fpr, tpr, thresholds) — one jitted sort + prefix-sum program.
    Matches sklearn's dropped-collinear-points behavior only in that
    endpoints are present; intermediate collinear points are KEPT (the
    curve is identical as a function)."""
    ss, tp, fp, P, N = _curve_host(y_true, y_score, sample_weight,
                                   labels, "roc_curve")
    # sklearn: a single-class fold warns and returns a NaN axis (so a CV
    # or plotting loop can skip it) — same warn-don't-abort stance as the
    # PR metrics below
    if P == 0.0:
        warnings.warn(
            "No positive samples in y_true; true positive rate is "
            "meaningless", UndefinedMetricWarning,
        )
        tpr = np.full(tp.shape[0] + 1, np.nan)
    else:
        tpr = np.r_[0.0, tp / P]
    if N == 0.0:
        warnings.warn(
            "No negative samples in y_true; false positive rate is "
            "meaningless", UndefinedMetricWarning,
        )
        fpr = np.full(fp.shape[0] + 1, np.nan)
    else:
        fpr = np.r_[0.0, fp / N]
    thresholds = np.r_[np.inf, ss]
    return fpr, tpr, thresholds


def precision_recall_curve(y_true, y_score, sample_weight=None,
                           labels=None):
    """(precision, recall, thresholds), sklearn orientation (recall
    descending to 0, final precision pinned to 1)."""
    ss, tp, fp, P, _ = _curve_host(y_true, y_score, sample_weight,
                                   labels, "precision_recall_curve")
    if P == 0.0:
        # sklearn: warn and return the degenerate curve (recall pinned
        # to 1, precision 0) rather than abort a CV fold
        warnings.warn(
            "No positive samples in y_true; recall is meaningless",
            UndefinedMetricWarning,
        )
        prec = np.zeros_like(tp)
        rec = np.ones_like(tp)
        return (np.r_[prec[::-1], 1.0], np.r_[rec[::-1], 0.0], ss[::-1])
    prec, rec = _pr_points(tp, fp, P)
    # sklearn orientation: thresholds ascending, trailing (P=1, R=0)
    prec = np.r_[prec[::-1], 1.0]
    rec = np.r_[rec[::-1], 0.0]
    thresholds = ss[::-1]
    return prec, rec, thresholds


def average_precision_score(y_true, y_score, sample_weight=None,
                            labels=None):
    """AP = Σ (R_i − R_{i−1}) · P_i over descending-score thresholds —
    sklearn's step-wise integral, as one device program + a host fold."""
    ss, tp, fp, P, _ = _curve_host(y_true, y_score, sample_weight,
                                   labels, "average_precision_score")
    if P == 0.0:
        # sklearn: AP over a fold with no positives scores 0 with a
        # warning — a raising scorer would abort the whole search
        warnings.warn(
            "No positive samples in y_true; average precision is 0",
            UndefinedMetricWarning,
        )
        return 0.0
    prec, rec = _pr_points(tp, fp, P)
    rec_prev = np.r_[0.0, rec[:-1]]
    return float(np.sum((rec - rec_prev) * prec))


def log_loss(y_true, y_prob, eps=1e-15, sample_weight=None, labels=None):
    t, p, w, n = _canon(y_true, y_prob, sample_weight)
    p = jnp.clip(p, eps, 1.0 - eps)
    if p.ndim == 2 and p.shape[1] > 2:
        # multiclass: cross-entropy of the true-class probability, rows
        # renormalized as sklearn does. Column c of y_prob corresponds to
        # the c-th SORTED class — when a fold is missing a class that
        # inference is ambiguous, so (like sklearn) explicit labels are
        # required rather than silently misaligning columns
        if labels is not None:
            classes = np.sort(np.asarray(labels))
        else:
            host_t = (y_true.to_numpy() if isinstance(y_true, ShardedArray)
                      else np.asarray(y_true))
            classes = np.unique(host_t)
        if len(classes) != p.shape[1]:
            raise ValueError(
                f"y_true has {len(classes)} classes but y_prob has "
                f"{p.shape[1]} columns; pass labels= with every class"
            )
        p = p / jnp.sum(p, axis=1, keepdims=True)
        # cast on HOST: jnp.asarray(host_float64, ...) would request x64
        # and warn on every call in a scoring loop
        classes_d = jnp.asarray(classes.astype(np.dtype(str(t.dtype))))
        idx = jnp.clip(jnp.searchsorted(classes_d, t), 0, p.shape[1] - 1)
        # membership check: a y value absent from the classes (or falling
        # between them) must raise, not silently score a neighbor class
        ok = jnp.all((jnp.take(classes_d, idx) == t) | (w == 0))
        if not bool(ok):
            raise ValueError("y_true contains values not in labels")
        p_true = jnp.take_along_axis(p, idx[:, None], axis=1)[:, 0]
        ll = -jnp.log(jnp.clip(p_true, eps, 1.0))
        return float(jnp.sum(ll * w) / jnp.sum(w))
    if p.ndim == 2:  # (n, 2) probabilities: take class-1 column
        p = p[:, 1]
    # binary labels need not be 0/1 (e.g. {10, 20}): map the POSITIVE
    # (larger) class to 1 by a device min/max scan — one scalar fetch
    if labels is not None:
        lab = np.sort(np.asarray(labels))
        if len(lab) != 2:
            raise ValueError("binary y_prob needs exactly 2 labels")
        mn_h, mx_h = float(lab[0]), float(lab[1])
    else:
        valid = w > 0
        mn = jnp.min(jnp.where(valid, t, jnp.inf))
        mx = jnp.max(jnp.where(valid, t, -jnp.inf))
        mn_h, mx_h = float(mn), float(mx)
        if mn_h == mx_h:
            # single observed class: the 0/1 mapping is ambiguous and a
            # silent guess scores the WRONG class half the time
            raise ValueError(
                "y_true contains a single class; pass labels= to fix "
                "the class order"
            )
    ok = jnp.all((t == mn_h) | (t == mx_h) | (w == 0))
    if not bool(ok):
        raise ValueError("y_true contains values not in labels")
    if (mn_h, mx_h) != (0.0, 1.0):
        t = (t == mx_h).astype(jnp.float32)
    ll = -(t * jnp.log(p) + (1.0 - t) * jnp.log1p(-p))
    return float(jnp.sum(ll * w) / jnp.sum(w))
