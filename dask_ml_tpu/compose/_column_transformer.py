"""ColumnTransformer / make_column_transformer.

Reference: ``dask_ml/compose/`` (SURVEY.md §2a Compose row) —
ColumnTransformer semantics over distributed frames/arrays. Columns are
names (pandas DataFrame) or integer indices (arrays / ShardedArray);
transformer outputs are horizontally concatenated, on device when every
branch returns device arrays.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pandas as pd

from ..base import BaseEstimator, TransformerMixin, clone
from ..parallel.sharded import ShardedArray, as_sharded
from ..utils.validation import check_is_fitted


def _is_partitioned(X):
    from ..parallel.frames import PartitionedFrame

    return isinstance(X, PartitionedFrame)


def _concat_positional(frames, index):
    """hstack frames BY POSITION onto ``index``. pd.concat(axis=1) aligns
    on index, so a user transformer returning a reset-index frame would
    silently produce NaN-padded misaligned output; rows here correspond
    positionally by construction (every branch transformed the same X)."""
    out = []
    for f in frames:
        if len(f) != len(index):
            raise ValueError(
                f"transformer output has {len(f)} rows, expected "
                f"{len(index)}"
            )
        if not f.index.equals(index):
            f = f.set_axis(index, axis=0)
        out.append(f)
    return pd.concat(out, axis=1)


def _select(X, cols):
    if isinstance(X, pd.DataFrame):
        return X[cols] if isinstance(cols, list) else X[[cols]]
    if _is_partitioned(X):
        return X[cols if isinstance(cols, list) else [cols]]
    if isinstance(X, ShardedArray):
        idx = np.atleast_1d(np.asarray(cols, dtype=int))
        return ShardedArray(X.data[:, idx], X.n_rows, X.mesh)
    X = np.asarray(X)
    idx = np.atleast_1d(np.asarray(cols, dtype=int))
    return X[:, idx]


def _to_stackable(out):
    if isinstance(out, ShardedArray) or isinstance(out, pd.DataFrame) \
            or _is_partitioned(out):
        return out
    return np.asarray(out)


class ColumnTransformer(TransformerMixin, BaseEstimator):
    """Ref: dask_ml/compose::ColumnTransformer."""

    def __init__(self, transformers, remainder="drop", sparse_threshold=0.3,
                 n_jobs=None, transformer_weights=None, preserve_dataframe=True):
        self.transformers = transformers
        self.remainder = remainder
        self.sparse_threshold = sparse_threshold
        self.n_jobs = n_jobs
        self.transformer_weights = transformer_weights
        self.preserve_dataframe = preserve_dataframe

    def _all_columns(self, X):
        if isinstance(X, pd.DataFrame) or _is_partitioned(X):
            return list(X.columns)
        return list(range(X.shape[1]))

    def _remainder_cols(self, X):
        used = []
        for _, _, cols in self.transformers:
            used.extend(cols if isinstance(cols, list) else [cols])
        return [c for c in self._all_columns(X) if c not in used]

    def fit(self, X, y=None):
        self.fit_transform(X, y)
        return self

    def fit_transform(self, X, y=None):
        if self.remainder not in ("drop", "passthrough"):
            raise ValueError("remainder must be 'drop' or 'passthrough'")
        self.transformers_ = []
        outs = []
        for name, trans, cols in self.transformers:
            sub = _select(X, cols)
            if trans == "drop":
                self.transformers_.append((name, "drop", cols))
                continue
            if trans == "passthrough":
                outs.append(_to_stackable(sub))
                self.transformers_.append((name, "passthrough", cols))
                continue
            t = clone(trans)
            out = t.fit_transform(sub, y) if hasattr(t, "fit_transform") \
                else t.fit(sub, y).transform(sub)
            outs.append(_to_stackable(out))
            self.transformers_.append((name, t, cols))
        if self.remainder == "passthrough":
            rem = self._remainder_cols(X)
            if rem:
                outs.append(_to_stackable(_select(X, rem)))
        self._rem_cols = (
            self._remainder_cols(X) if self.remainder == "passthrough" else []
        )
        return self._hstack(outs, X)

    def transform(self, X):
        check_is_fitted(self, "transformers_")
        outs = []
        for name, t, cols in self.transformers_:
            if t == "drop":
                continue
            sub = _select(X, cols)
            if t == "passthrough":
                outs.append(_to_stackable(sub))
            else:
                outs.append(_to_stackable(t.transform(sub)))
        if self._rem_cols:
            outs.append(_to_stackable(_select(X, self._rem_cols)))
        return self._hstack(outs, X)

    def _hstack(self, outs, X):
        if not outs:
            raise ValueError("no transformer outputs")
        if all(isinstance(o, ShardedArray) for o in outs):
            data = jnp.concatenate([o.data for o in outs], axis=1)
            first = outs[0]
            return ShardedArray(data, first.n_rows, first.mesh)
        frame_in = isinstance(X, pd.DataFrame) or _is_partitioned(X)
        if frame_in and self.preserve_dataframe and all(
            isinstance(o, pd.DataFrame) or _is_partitioned(o) for o in outs
        ):
            stacked = self._hstack_frames(outs, X)
            if stacked is not None:
                return stacked
        host = []
        for o in outs:
            if isinstance(o, ShardedArray):
                host.append(o.to_numpy())
            elif _is_partitioned(o):
                host.append(o.compute().to_numpy())
            elif isinstance(o, pd.DataFrame):
                host.append(o.to_numpy())
            else:
                host.append(o)
        out = np.concatenate(host, axis=1)
        if isinstance(X, ShardedArray):
            return as_sharded(out, mesh=X.mesh)
        return out

    def _hstack_frames(self, outs, X):
        """Column-concatenate frame branch outputs preserving the input's
        frame type, index, and (for PartitionedFrame) partition boundaries
        — the reference's dd frame-in/frame-out ColumnTransformer path.
        Returns None when partition boundaries diverge (caller then falls
        back to the host concat path)."""
        if isinstance(X, pd.DataFrame):
            frames = [
                o if isinstance(o, pd.DataFrame) else o.compute()
                for o in outs
            ]
            return _concat_positional(frames, X.index)
        from ..parallel.frames import PartitionedFrame

        bounds = [len(p) for p in X.partitions]
        parts_per = []
        for o in outs:
            if isinstance(o, pd.DataFrame):
                chunks, off = [], 0
                for n in bounds:
                    chunks.append(o.iloc[off:off + n])
                    off += n
                parts_per.append(chunks)
            else:
                if [len(p) for p in o.partitions] != bounds:
                    return None
                parts_per.append(list(o.partitions))
        x_parts = list(X.partitions)
        return PartitionedFrame(
            [_concat_positional(list(ps), x_parts[i].index)
             for i, ps in enumerate(zip(*parts_per))]
        )

    @property
    def named_transformers_(self):
        return {name: t for name, t, _ in self.transformers_}


def make_column_transformer(*transformers, remainder="drop",
                            sparse_threshold=0.3, n_jobs=None,
                            preserve_dataframe=True):
    """Ref: dask_ml/compose::make_column_transformer."""
    named = [
        (f"{type(t).__name__.lower()}-{i}" if not isinstance(t, str)
         else f"{t}-{i}", t, cols)
        for i, (t, cols) in enumerate(transformers, 1)
    ]
    return ColumnTransformer(named, remainder=remainder,
                             sparse_threshold=sparse_threshold, n_jobs=n_jobs,
                             preserve_dataframe=preserve_dataframe)
