"""Property-based tests (hypothesis). The reference's suite is purely
example-based (SURVEY.md §4 "Hypothesis/property tests: essentially
none") — these go beyond it: algebraic invariants over arbitrary values.

Shapes come from a SMALL fixed pool so XLA's shape-specialized programs
hit the jit cache across examples (a fresh shape per example would pay a
compile each time on one CPU)."""

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # hypothesis fuzz: full-suite only

hypothesis = pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402

from dask_ml_tpu.parallel import ShardedArray, as_sharded
from dask_ml_tpu.parallel.sharded import take_rows

SHAPES = [(13, 3), (40, 5), (64, 2)]

# no subnormals: XLA (CPU and TPU alike) flushes denormals to zero in
# fused multiply paths — standard accelerator semantics, not a defect
finite = st.floats(min_value=-1e4, max_value=1e4, allow_nan=False,
                   allow_infinity=False, allow_subnormal=False, width=32)


def matrices():
    return st.sampled_from(SHAPES).flatmap(
        lambda s: st.lists(
            st.lists(finite, min_size=s[1], max_size=s[1]),
            min_size=s[0], max_size=s[0],
        ).map(lambda rows: np.asarray(rows, np.float32))
    )


@settings(max_examples=12, deadline=None)
@given(matrices())
def test_sharded_roundtrip_identity(x):
    np.testing.assert_array_equal(as_sharded(x).to_numpy(), x)


@settings(max_examples=12, deadline=None)
@given(matrices(), st.randoms(use_true_random=False))
def test_take_rows_matches_fancy_indexing(x, rnd):
    xs = as_sharded(x)
    n = x.shape[0]
    idx = np.asarray([rnd.randrange(n) for _ in range(n // 2 + 1)],
                     np.int64)
    got = take_rows(xs, idx).to_numpy()
    np.testing.assert_array_equal(got, x[idx])


@settings(max_examples=10, deadline=None)
@given(matrices())
def test_scaler_inverse_is_identity(x):
    from dask_ml_tpu.preprocessing import StandardScaler

    sc = StandardScaler().fit(x)
    out = sc.transform(x)
    back = sc.inverse_transform(out).to_numpy()
    scale = np.maximum(np.abs(x).max(), 1.0)
    assert np.abs(back - x).max() <= 1e-3 * scale


@settings(max_examples=10, deadline=None)
@given(matrices(), st.integers(min_value=0, max_value=2**31 - 1))
def test_train_test_split_partitions(x, seed):
    from dask_ml_tpu.model_selection import train_test_split

    tr, te = train_test_split(x, test_size=0.25, random_state=seed)
    n_tr = tr.shape[0] if hasattr(tr, "shape") else len(tr)
    n_te = te.shape[0] if hasattr(te, "shape") else len(te)
    assert n_tr + n_te == x.shape[0]
    # determinism: the same seed reproduces the same split
    tr2, te2 = train_test_split(x, test_size=0.25, random_state=seed)
    a = tr.to_numpy() if hasattr(tr, "to_numpy") else np.asarray(tr)
    b = tr2.to_numpy() if hasattr(tr2, "to_numpy") else np.asarray(tr2)
    np.testing.assert_array_equal(a, b)


@settings(max_examples=15, deadline=None)
@given(st.lists(st.integers(min_value=-50, max_value=50), min_size=2,
                max_size=60))
def test_label_encoder_roundtrip_any_labels(labels):
    from dask_ml_tpu.preprocessing import LabelEncoder

    y = np.asarray(labels, np.float64)
    le = LabelEncoder().fit(y)
    codes = le.transform(y)
    np.testing.assert_array_equal(le.inverse_transform(codes), y)
    assert codes.min() >= 0 and codes.max() < len(le.classes_)


@settings(max_examples=10, deadline=None)
@given(matrices())
def test_add_intercept_appends_ones(x):
    from dask_ml_tpu.linear_model import add_intercept

    out = add_intercept(as_sharded(x))
    assert isinstance(out, ShardedArray)
    h = out.to_numpy()
    np.testing.assert_array_equal(h[:, :-1], x)
    np.testing.assert_array_equal(h[:, -1], np.ones(x.shape[0]))


def _binary_scored(draw, n_min=8, n_max=40):
    """(y, s) with both classes present and strictly distinct scores."""
    n = draw(st.integers(n_min, n_max))
    y = np.asarray(draw(st.lists(st.integers(0, 1), min_size=n,
                                 max_size=n)), np.float64)
    if y.min() == y.max():
        y[0] = 1.0 - y[0]
    s = np.asarray(draw(st.lists(finite, min_size=n, max_size=n,
                                 unique=True)), np.float64)
    return y, s


@settings(max_examples=15, deadline=None)
@given(st.data())
def test_auc_invariant_under_monotone_score_transform(data):
    """AUC is a rank statistic: any strictly increasing transform of the
    scores leaves it unchanged; negating the scores complements it."""
    from dask_ml_tpu.metrics import roc_auc_score

    y, s = _binary_scored(data.draw)
    auc = roc_auc_score(y, s)
    assert 0.0 <= auc <= 1.0
    # rank substitution is the canonical strictly-increasing transform,
    # and stays exactly representable at the device's f32 (a smooth
    # squash like tanh can collapse near-equal scores in f32)
    s2 = np.empty_like(s)
    s2[np.argsort(s)] = np.arange(len(s), dtype=np.float64)
    s2 = 0.5 * s2 - 3.0
    np.testing.assert_allclose(roc_auc_score(y, s2), auc, atol=1e-9)
    np.testing.assert_allclose(roc_auc_score(y, -s), 1.0 - auc,
                               atol=1e-9)


@settings(max_examples=15, deadline=None)
@given(st.data())
def test_curve_invariants(data):
    """roc_curve axes are monotone in [0,1] ending at (1,1); PR curve
    recall is monotone with AP inside [0,1]."""
    from dask_ml_tpu.metrics import (average_precision_score,
                                     precision_recall_curve, roc_curve)

    y, s = _binary_scored(data.draw)
    fpr, tpr, thr = roc_curve(y, s)
    assert np.all(np.diff(fpr) >= 0) and np.all(np.diff(tpr) >= 0)
    assert fpr[0] == 0.0 and tpr[0] == 0.0
    assert fpr[-1] == 1.0 and tpr[-1] == 1.0
    assert np.all(np.diff(thr) < 0)  # strictly decreasing thresholds
    prec, rec, _ = precision_recall_curve(y, s)
    assert np.all(np.diff(rec) <= 0)  # sklearn orientation: descending
    assert 0.0 <= average_precision_score(y, s) <= 1.0
