"""SLO policy plane: execution-latency prediction, deadline-aware batch
release inputs, and fleet admission decisions.

Two consumers ride the same windowed per-(method, bucket) histograms:

- the micro-batcher's **deadline-aware release** (``_batching.
  release_deadline``): how long may this partial batch keep coalescing
  before the oldest request's SLO budget minus the predicted execution
  time says "dispatch now";
- the fleet's **SLO-aware admission** (:func:`predict_completion_s` /
  :func:`admission_verdict`): given each replica's queued rows and its
  predicted per-batch execution time, would this request complete
  inside ``config.serving_slo_ms``? If no replica can, shed at the door
  (typed ``SloShed``) — backpressure lands BEFORE the queue builds the
  latency collapse, not after requests have already burned their budget
  waiting.

Predictions are WINDOWED quantiles (``observability._hist``
delta-snapshots, rotated every :data:`WINDOW_S` seconds), not lifetime
averages: a model swap or a noisy neighbor changes execution time NOW,
and routing/admission must see the change within a window, undiluted by
hours of healthy history.
"""

from __future__ import annotations

import math
import threading
import time

from ..observability._hist import (
    Histogram,
    percentiles_from,
    snapshot_delta,
)

__all__ = ["ExecStats", "predict_completion_s", "admission_verdict",
           "WINDOW_S"]

# windowed-quantile rotation period: predictions read the delta since a
# snapshot at most 2 windows old
WINDOW_S = 10.0
# a window needs this many observations before its quantile outranks
# the lifetime one (tiny windows estimate wildly)
_MIN_WINDOW_N = 8


class ExecStats:
    """Per-(method, bucket) batch EXECUTION seconds (pack -> demux of
    one dispatched micro-batch — not queue wait) with windowed quantile
    prediction.

    ``observe`` is the serving worker's per-batch write: one histogram
    observe. ``predict_s`` answers "how long will the next batch of
    this shape take" from the freshest window with enough mass, falling
    back to the lifetime histogram, then to any sibling bucket's
    estimate (a bucket never executed yet borrows its nearest measured
    neighbor — still better than no admission control at all), then to
    ``None`` (caller keeps the fixed-window rule).
    """

    __slots__ = ("_hists", "_cursors", "_lock")

    def __init__(self):
        self._hists: dict[tuple, Histogram] = {}
        # key -> (snapshot, t_taken): the rotation cursor windows read
        self._cursors: dict[tuple, tuple] = {}
        self._lock = threading.Lock()

    def observe(self, method: str, bucket: int, seconds: float) -> None:
        key = (method, int(bucket))
        h = self._hists.get(key)
        if h is None:
            with self._lock:
                h = self._hists.setdefault(key, Histogram())
        h.observe(seconds)

    def _window(self, key):
        """Delta snapshot since the rotation cursor (rotating it when
        stale); None when the key was never observed."""
        h = self._hists.get(key)
        if h is None:
            return None
        cur = h.snapshot()
        now = time.perf_counter()
        with self._lock:
            prev = self._cursors.get(key)
            if prev is None or now - prev[1] > WINDOW_S:
                self._cursors[key] = (cur, now)
            prev_snap = prev[0] if prev is not None else None
        delta = snapshot_delta(cur, prev_snap)
        return delta if delta["count"] >= _MIN_WINDOW_N else cur

    def predict_s(self, method: str, bucket: int, q: float = 90):
        """Predicted execution seconds for a (method, bucket) batch, or
        None when nothing was ever measured for the method."""
        key = (method, int(bucket))
        snap = self._window(key)
        if snap is not None and snap["count"] > 0:
            return next(iter(percentiles_from(snap, (q,)).values()))
        # nearest measured sibling bucket of the same method
        best, best_dist = None, math.inf
        for (m, b), h in list(self._hists.items()):
            if m != method or h.count == 0:
                continue
            dist = abs(math.log(max(b, 1)) - math.log(max(bucket, 1)))
            if dist < best_dist:
                best, best_dist = (m, b), dist
        if best is None:
            return None
        snap = self._window(best)
        if snap is None or snap["count"] == 0:
            return None
        return next(iter(percentiles_from(snap, (q,)).values()))

    def snapshot(self) -> dict:
        """{"method:bucket": {count, p50, p90}} — the stats()/status
        rendering of the prediction state."""
        out = {}
        for (m, b), h in sorted(self._hists.items()):
            if h.count == 0:
                continue
            pct = h.percentiles((50, 90))
            out[f"{m}:{b}"] = {
                "count": h.count,
                "p50_s": round(pct["p50"], 6),
                "p90_s": round(pct["p90"], 6),
            }
        return out


def predict_completion_s(queue_rows: int, n_rows: int, top_bucket: int,
                         exec_s) -> float | None:
    """Predicted end-to-end seconds for a request of ``n_rows`` joining
    a replica with ``queue_rows`` already queued: the queued work packs
    into ``ceil(rows / top_bucket)`` full batches ahead of (or around)
    this request, each costing one predicted execution. None when no
    execution estimate exists yet (admission then stays open — never
    shed on ignorance)."""
    if exec_s is None:
        return None
    batches = max(math.ceil((queue_rows + n_rows) / max(top_bucket, 1)),
                  1)
    return batches * exec_s


def admission_verdict(predicted_s, slo_s: float) -> bool:
    """True = admit. Shed only on a CONFIDENT predicted miss: an SLO is
    configured, a prediction exists, and the predicted completion
    exceeds the full budget."""
    if slo_s <= 0 or predicted_s is None:
        return True
    return predicted_s <= slo_s
